"""GLM: generalized linear models with elastic-net regularization.

Reference: ``hex/glm/GLM.java:1573`` (GLMDriver; IRLSM:2143, L-BFGS:2757,
COD:2840), ``hex/glm/GLMTask.java`` (gradient/Hessian MRTasks),
``hex/gram/Gram.java:1017`` (distributed X'X accumulation, reduce = matrix
add, Cholesky on the driver), families/links in ``hex/glm/GLMModel.java:978``.

TPU-native redesign: the per-iteration hot loop — Gram accumulation — is one
jit-compiled pass: ``X^T diag(w) X`` over the row-sharded design matrix runs
on the MXU and GSPMD inserts the ``psum`` that replaces GramTask's MRTask
reduce.  The small P x P solve (Cholesky for L2, coordinate descent on the
Gram for L1 — exactly the reference's IRLSM+COD strategy) happens on host.
Multinomial runs block-wise per-class Newton steps on softmax probabilities
(the COD-multinomial analog, GLM.java:1643).
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from ..metrics.core import make_metrics


# ------------------------------------------------------------------- families
class _Family:
    name = "gaussian"

    def linkinv(self, eta):
        return eta

    def variance(self, mu):
        return jnp.ones_like(mu)

    def dlinkinv(self, eta, mu):
        """d mu / d eta."""
        return jnp.ones_like(eta)

    def deviance(self, y, mu, w):
        return jnp.sum(w * (y - mu) ** 2)

    def init_eta(self, y, w):
        mean = jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12)
        return jnp.full_like(y, mean)


class _Gaussian(_Family):
    pass


class _Binomial(_Family):
    name = "binomial"

    def linkinv(self, eta):
        return jax.nn.sigmoid(eta)

    def variance(self, mu):
        return mu * (1 - mu)

    def dlinkinv(self, eta, mu):
        return mu * (1 - mu)

    def deviance(self, y, mu, w):
        mu = jnp.clip(mu, 1e-15, 1 - 1e-15)
        return -2 * jnp.sum(w * (y * jnp.log(mu) + (1 - y) * jnp.log1p(-mu)))

    def init_eta(self, y, w):
        p = jnp.clip(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12),
                     1e-6, 1 - 1e-6)
        return jnp.full_like(y, jnp.log(p / (1 - p)))


class _Quasibinomial(_Binomial):
    name = "quasibinomial"


class _Poisson(_Family):
    name = "poisson"

    def linkinv(self, eta):
        return jnp.exp(jnp.clip(eta, -30, 30))

    def variance(self, mu):
        return mu

    def dlinkinv(self, eta, mu):
        return mu

    def deviance(self, y, mu, w):
        mu = jnp.maximum(mu, 1e-15)
        t = jnp.where(y > 0, y * jnp.log(y / mu), 0.0)
        return 2 * jnp.sum(w * (t - (y - mu)))

    def init_eta(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.full_like(y, jnp.log(m))


class _Gamma(_Family):
    name = "gamma"

    def linkinv(self, eta):
        return jnp.exp(jnp.clip(eta, -30, 30))

    def variance(self, mu):
        return mu * mu

    def dlinkinv(self, eta, mu):
        return mu

    def deviance(self, y, mu, w):
        mu = jnp.maximum(mu, 1e-15)
        ys = jnp.maximum(y, 1e-15)
        return 2 * jnp.sum(w * (-jnp.log(ys / mu) + (ys - mu) / mu))

    def init_eta(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.full_like(y, jnp.log(m))


class _Tweedie(_Family):
    name = "tweedie"

    def __init__(self, p: float):
        self.p = float(p)

    def linkinv(self, eta):
        return jnp.exp(jnp.clip(eta, -30, 30))

    def variance(self, mu):
        return jnp.power(jnp.maximum(mu, 1e-15), self.p)

    def dlinkinv(self, eta, mu):
        return mu

    def deviance(self, y, mu, w):
        p = self.p
        mu = jnp.maximum(mu, 1e-15)
        if p == 1.0:
            return _Poisson().deviance(y, mu, w)
        if p == 2.0:
            return _Gamma().deviance(y, mu, w)
        ys = jnp.maximum(y, 0.0)
        a = jnp.where(ys > 0,
                      jnp.power(jnp.maximum(ys, 1e-15), 2 - p) / ((1 - p) * (2 - p)),
                      0.0)
        b = ys * jnp.power(mu, 1 - p) / (1 - p)
        c = jnp.power(mu, 2 - p) / (2 - p)
        return 2 * jnp.sum(w * (a - b + c))

    def init_eta(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.full_like(y, jnp.log(m))


class _NegativeBinomial(_Family):
    name = "negativebinomial"

    def __init__(self, theta: float):
        self.theta = float(theta)          # inverse dispersion

    def linkinv(self, eta):
        return jnp.exp(jnp.clip(eta, -30, 30))

    def variance(self, mu):
        return mu + self.theta * mu * mu

    def dlinkinv(self, eta, mu):
        return mu

    def deviance(self, y, mu, w):
        mu = jnp.maximum(mu, 1e-15)
        th = self.theta
        ys = jnp.maximum(y, 0.0)
        t1 = jnp.where(ys > 0, ys * jnp.log(ys / mu), 0.0)
        t2 = (ys + 1.0 / th) * jnp.log((1 + th * mu) / (1 + th * ys))
        return 2 * jnp.sum(w * (t1 + t2))

    def init_eta(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.full_like(y, jnp.log(m))


def _make_family(name: str, params) -> _Family:
    if name == "tweedie":
        return _Tweedie(params.tweedie_variance_power)
    if name == "negativebinomial":
        return _NegativeBinomial(params.theta)
    return {"gaussian": _Gaussian, "binomial": _Binomial,
            "quasibinomial": _Quasibinomial, "poisson": _Poisson,
            "gamma": _Gamma}[name]()


# ------------------------------------------------------------------- kernels
def _ledger(name, jitted, orig=None):
    """Register a compiled GLM seam with the compile ledger (runtime/xprof)."""
    from ..runtime import xprof
    return xprof.register_program(name, jitted, orig=orig)


def _gram_kernel_impl(X, w):
    """Weighted Gram X'WX — the GramTask analog (gram/Gram.java:1017)."""
    Xw = X * w[:, None]
    return Xw.T @ X


_gram_kernel = _ledger("glm_gram", jax.jit(_gram_kernel_impl),
                       orig=_gram_kernel_impl)


def _make_irls_step(family: _Family):
    def step(X, y, w, beta, offset):
        eta = X @ beta + offset
        mu = family.linkinv(eta)
        g = jnp.maximum(family.dlinkinv(eta, mu), 1e-10)
        var = jnp.maximum(family.variance(mu), 1e-10)
        z = (eta - offset) + (y - mu) / g
        wi = w * g * g / var
        Xw = X * wi[:, None]
        gram = Xw.T @ X
        xtwz = Xw.T @ z
        dev = family.deviance(y, mu, w)
        return gram, xtwz, dev
    return _ledger("glm_irls", jax.jit(step), orig=step)


def _make_path_runner(family: _Family, l1_mode: bool, max_iter: int,
                      max_inner: int = 100):
    """The WHOLE regularization path as one device program.

    The host loop pays a device->host round trip per IRLS iteration
    (~67 ms on a tunnelled backend — measured 18.7 s for a 100-lambda
    path at 2M rows, entirely fetch-bound).  Here lambdas run under
    ``lax.scan`` with warm-started betas, IRLS under ``lax.while_loop``
    (beta_epsilon early exit), and the penalized solve on device: one
    linear solve for pure L2, cyclic coordinate descent (the reference's
    COD, GLM.java:2840) under a while_loop for any L1.  One fetch at the
    end returns per-lambda betas/deviances/iteration counts + the final
    Gram (p-values).
    """

    def irls_gram(X, y, w, beta, offset):
        eta = X @ beta + offset
        mu = family.linkinv(eta)
        g = jnp.maximum(family.dlinkinv(eta, mu), 1e-10)
        var = jnp.maximum(family.variance(mu), 1e-10)
        z = (eta - offset) + (y - mu) / g
        wi = w * g * g / var
        Xw = X * wi[:, None]
        return Xw.T @ X, Xw.T @ z, family.deviance(y, mu, w)

    def run(X, y, w, offset, lambdas, alpha, penalize, beta0, n,
            beta_eps):
        P = beta0.shape[0]

        def solve(G, c, lam, warm):
            l2 = lam * (1 - alpha) * penalize
            if not l1_mode:
                A = G + jnp.diag(l2 + 1e-10)
                return jnp.linalg.solve(A, c)
            l1 = lam * alpha * penalize
            d = jnp.diag(G)

            def sweep(state):
                beta, _, it = state

                def upd(j, bd):
                    b, delta = bd
                    r = c[j] - (G[j] @ b - d[j] * b[j])
                    bj = jnp.where(
                        penalize[j] > 0,
                        jnp.sign(r) * jnp.maximum(jnp.abs(r) - l1[j], 0.0)
                        / (d[j] + l2[j] + 1e-12),
                        r / (d[j] + 1e-12))
                    delta = jnp.maximum(delta, jnp.abs(bj - b[j]))
                    return b.at[j].set(bj), delta

                beta2, delta = jax.lax.fori_loop(
                    0, P, upd, (beta, jnp.float32(0.0)))
                return beta2, delta, it + 1

            def cond(state):
                _, delta, it = state
                return (it < max_inner) & (delta > 1e-8)

            beta, _, _ = jax.lax.while_loop(
                cond, sweep, (warm, jnp.float32(jnp.inf), 0))
            return beta

        def per_lambda(beta, lam):
            def body(state):
                beta, _, it, _ = state
                gram, xtwz, dev = irls_gram(X, y, w, beta, offset)
                nb = solve(gram / n, xtwz / n, lam, beta)
                delta = jnp.max(jnp.abs(nb - beta))
                return nb, delta, it + 1, dev

            def cond(state):
                _, delta, it, _ = state
                return (it < max_iter) & (delta >= beta_eps)

            beta, _, iters, dev = jax.lax.while_loop(
                cond, body, (beta, jnp.float32(jnp.inf), 0,
                             jnp.float32(0.0)))
            return beta, (beta, dev, iters)

        beta_fin, (betas, devs, iters) = jax.lax.scan(
            per_lambda, beta0, lambdas)
        gram_fin, _, dev_fin = irls_gram(X, y, w, beta_fin, offset)
        return betas, devs, iters, gram_fin, dev_fin

    return _ledger("glm_path", jax.jit(run), orig=run)


def _make_softmax_stats(nclasses: int):
    def stats(X, y, w, beta, offset):
        """Per-class diagonal-block Newton quantities for multinomial."""
        eta = X @ beta + offset[:, None]
        probs = jax.nn.softmax(eta, axis=1)
        yi = jnp.clip(y.astype(jnp.int32), 0, nclasses - 1)
        Y = jax.nn.one_hot(yi, nclasses)
        p_true = jnp.clip(probs[jnp.arange(probs.shape[0]), yi], 1e-15, 1.0)
        ll = -jnp.sum(w * jnp.log(p_true))
        grams, xtwz = [], []
        for k in range(nclasses):
            mu = probs[:, k]
            wk = jnp.maximum(w * mu * (1 - mu), 1e-10 * w)
            zk = eta[:, k] - offset + (Y[:, k] - mu) / jnp.maximum(
                mu * (1 - mu), 1e-10)
            Xw = X * wk[:, None]
            grams.append(Xw.T @ X)
            xtwz.append(Xw.T @ zk)
        return jnp.stack(grams), jnp.stack(xtwz).T, ll, probs
    return _ledger("glm_softmax", jax.jit(stats), orig=stats)


# -------------------------------------------------------------------- solver
def _solve_penalized(gram: np.ndarray, xtwz: np.ndarray, n: float,
                     lam: float, alpha: float, beta0: np.ndarray,
                     penalize: np.ndarray, max_inner: int = 100,
                     tol: float = 1e-8,
                     nonneg: Optional[np.ndarray] = None) -> np.ndarray:
    """Solve 0.5 b'Gb - c'b + lam*(alpha*|b|_1 + (1-alpha)/2 |b|_2^2).

    G = gram/n, c = xtwz/n.  Pure L2 -> one Cholesky solve; any L1 or
    sign constraint -> cyclic coordinate descent on the Gram (the
    reference's COD, GLM.java:2840).  ``penalize`` masks out the
    intercept; ``nonneg`` marks coefficients clamped to >= 0 (the GLM
    ``non_negative`` option — per-coordinate projection, which for CD is
    the exact constrained minimizer).
    """
    G = gram / n
    c = xtwz / n
    # ``penalize`` is a per-coefficient penalty FACTOR (glmnet-style):
    # 0 = unpenalized (intercept, spline null space), 1 = standard, other
    # values scale both the L1 and L2 shares (GAM penalty eigenvalues)
    l2 = lam * (1 - alpha) * penalize
    l1 = lam * alpha * penalize
    constrained = nonneg is not None and bool(np.any(nonneg))
    if np.all(l1 == 0.0) and not constrained:
        A = G + np.diag(l2 + 1e-10)
        try:
            return np.linalg.solve(A, c)
        except np.linalg.LinAlgError:
            return np.linalg.lstsq(A, c, rcond=None)[0]
    beta = beta0.copy()
    if constrained:
        beta[nonneg] = np.maximum(beta[nonneg], 0.0)
    d = np.diag(G).copy()
    Gb = G @ beta
    for _ in range(max_inner):
        delta = 0.0
        for j in range(len(beta)):
            r = c[j] - (Gb[j] - d[j] * beta[j])
            if penalize[j] > 0:
                bj = np.sign(r) * max(abs(r) - l1[j], 0.0) \
                    / (d[j] + l2[j] + 1e-12)
            else:
                bj = r / (d[j] + 1e-12)
            if constrained and nonneg[j]:
                bj = max(bj, 0.0)
            diff = bj - beta[j]
            if diff != 0.0:
                Gb += G[:, j] * diff
                delta = max(delta, abs(diff))
                beta[j] = bj
        if delta < tol:
            break
    return beta


# ---------------------------------------------------------------- parameters
@dataclasses.dataclass
class GLMParameters(Parameters):
    family: str = "auto"                  # auto|gaussian|binomial|quasibinomial|
    # poisson|gamma|tweedie|negativebinomial|multinomial
    alpha: float = 0.5
    lambda_: Union[float, Sequence[float], None] = None   # None -> 0 / search
    lambda_search: bool = False
    nlambdas: int = 30
    lambda_min_ratio: float = 1e-4
    solver: str = "irlsm"
    # sign constraint (GLMParameters._non_negative): True = every
    # non-intercept coefficient >= 0; a list of column names constrains
    # only those columns (monotone GAM splines ride this)
    non_negative: Union[bool, Sequence[str]] = False
    # per-column penalty factors {column: factor}; cat columns apply the
    # factor to every one-hot slot (glmnet penalty.factor / GAM penalties)
    penalty_factors: Optional[dict] = None
    tweedie_variance_power: float = 1.5
    theta: float = 1.0                    # negative binomial
    beta_epsilon: float = 1e-5
    compute_p_values: bool = False
    intercept: bool = True
    max_iterations: int = 50


class GLMModel(Model):
    algo = "glm"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        beta = jnp.asarray(self.output["beta_std"])
        family = self.output["family"]
        if family == "multinomial":
            probs = jax.nn.softmax(X @ beta, axis=1)
            return probs
        if family == "ordinal":
            thetas = jnp.asarray(self.output["ordinal_thresholds"])
            eta = X @ beta                    # intercept col has beta 0
            cdf = jax.nn.sigmoid(thetas[None, :] - eta[:, None])
            cdf = jnp.concatenate(
                [jnp.zeros((cdf.shape[0], 1)), cdf,
                 jnp.ones((cdf.shape[0], 1))], axis=1)
            return jnp.clip(jnp.diff(cdf, axis=1), 0.0, 1.0)
        eta = X @ beta
        fam = _make_family(family, self.params)
        mu = fam.linkinv(eta)
        if self.datainfo.is_classifier:
            return jnp.stack([1 - mu, mu], axis=1)
        return mu

    @property
    def coef(self) -> dict:
        return dict(zip(self.output["coef_names"], self.output["beta"]))

    @property
    def coef_norm(self) -> dict:
        return dict(zip(self.output["coef_names"], self.output["beta_std_flat"]))


class GLM(ModelBuilder):
    """GLM builder — h2o.glm / H2OGeneralizedLinearEstimator analog."""

    algo = "glm"
    model_class = GLMModel

    def __init__(self, params: Optional[GLMParameters] = None, **kw):
        super().__init__(params or GLMParameters(**kw))

    def _resolve_family(self, di: DataInfo) -> str:
        fam = self.params.family
        if fam in ("auto", None):
            if di.is_classifier:
                fam = "binomial" if di.nclasses == 2 else "multinomial"
            else:
                fam = "gaussian"
        if fam in ("binomial", "quasibinomial") and not di.is_classifier:
            raise ValueError(f"family={fam} needs a categorical response")
        if fam == "multinomial" and di.nclasses < 3:
            fam = "binomial"
        if fam == "ordinal" and (not di.is_classifier or di.nclasses < 3):
            raise ValueError("family=ordinal needs a categorical response "
                             "with 3+ ordered levels")
        return fam

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GLMModel:
        p: GLMParameters = self.params
        fam_name = self._resolve_family(di)
        X = di.make_matrix(frame)
        y = di.response(frame)
        w = di.weights(frame)
        y = jnp.nan_to_num(y)
        offset = di.offsets(frame)
        offset = offset if offset is not None else jnp.zeros_like(y)
        n = float(jnp.sum(w))
        P = di.nfeatures
        penalize = np.ones(P)
        if di.add_intercept:
            penalize[-1] = 0.0
        if p.penalty_factors:
            for spec in di.specs:
                f = p.penalty_factors.get(spec.name)
                if f is not None:
                    penalize[spec.offset: spec.offset + spec.width] = f
        nonneg = np.zeros(P, dtype=bool)
        if p.non_negative is True:
            nonneg[:] = True
            if di.add_intercept:
                nonneg[-1] = False
        elif p.non_negative:
            want = set(p.non_negative)
            matched = set()
            for spec in di.specs:
                if spec.name in want:
                    nonneg[spec.offset: spec.offset + spec.width] = True
                    matched.add(spec.name)
            if want - matched:
                raise ValueError(
                    f"non_negative names not in the design: "
                    f"{sorted(want - matched)}")
        if nonneg.any() and (fam_name in ("multinomial", "ordinal")
                             or p.solver.lower() in ("l_bfgs", "lbfgs")):
            raise ValueError("non_negative requires the IRLSM/COD solver "
                             "on a non-multinomial family")
        self._nonneg = nonneg if nonneg.any() else None

        if fam_name == "ordinal":
            lam0 = 0.0 if p.lambda_ is None else float(np.max(p.lambda_))
            return self._fit_ordinal(job, frame, di, X, y, w, offset, n,
                                     lam0, valid)
        lambdas = self._lambda_path(p, X, y, w, di, fam_name)
        if fam_name == "multinomial":
            model = self._fit_multinomial(job, frame, di, X, y, w, offset, n,
                                          penalize, lambdas, valid)
        else:
            model = self._fit_single(job, frame, di, X, y, w, offset, n,
                                     penalize, lambdas, fam_name, valid)
        return model

    # -------------------------------------------------------- lambda path
    def _lambda_path(self, p: GLMParameters, X, y, w, di, fam_name) -> List[float]:
        if p.lambda_ is not None and not p.lambda_search:
            return list(np.atleast_1d(np.asarray(p.lambda_, dtype=np.float64)))
        if not p.lambda_search:
            return [0.0]
        # lambda_max: smallest lambda zeroing all coefs = max |X'(y-ybar)|/(n*alpha)
        fam = _make_family(fam_name, p)
        eta0 = fam.init_eta(y, w)
        mu0 = fam.linkinv(eta0)
        grad = np.asarray(jnp.abs((X * w[:, None]).T @ (y - mu0)))
        if di.add_intercept:
            grad = grad[:-1]
        n = max(float(jnp.sum(w)), 1.0)
        lmax = float(grad.max()) / max(p.alpha, 1e-3) / n
        lmin = lmax * p.lambda_min_ratio
        return list(np.geomspace(lmax, lmin, p.nlambdas))

    # ------------------------------------------------------------- l-bfgs
    def _fit_lbfgs(self, job, frame, di, X, y, w, offset, n, penalize,
                   lam, fam_name, valid) -> "GLMModel":
        """L-BFGS solver — GLM.java:2757's solver=L_BFGS analog.

        Minimizes deviance/(2n) + lam*(1-alpha)/2 |b|_2^2 with optax's
        L-BFGS inside one jit-compiled scan (the whole optimization is a
        single device program).  Like the reference without ADMM, L1 is
        not supported on this solver — use IRLSM/COD for alpha > 0.
        """
        import optax
        from ..runtime.observability import log
        p: GLMParameters = self.params
        if p.alpha > 0 and (np.asarray(lam) > 0).any():
            # reference behavior: L_BFGS defaults alpha to 0 (no L1 without
            # ADMM); drop the L1 component rather than failing
            log.warning("solver='lbfgs' ignores the L1 component "
                        "(alpha=%s); keeping the L2 share", p.alpha)
        fam = _make_family(fam_name, p)
        pen = jnp.asarray(penalize, jnp.float32)
        lamf = float(lam)

        def obj(beta):
            eta = X @ beta + offset
            mu = fam.linkinv(eta)
            dev = fam.deviance(y, mu, w)
            return dev / (2 * n) + 0.5 * lamf * jnp.sum(pen * beta ** 2)

        opt = optax.lbfgs()
        vg = optax.value_and_grad_from_state(obj)

        iters = int(min(p.max_iterations, 100))

        @jax.jit
        def run(beta0):
            state = opt.init(beta0)

            def step_fn(carry, _):
                params, st = carry
                value, grad = vg(params, state=st)
                updates, st = opt.update(grad, st, params, value=value,
                                         grad=grad, value_fn=obj)
                params = optax.apply_updates(params, updates)
                return (params, st), value
            (beta, _), values = jax.lax.scan(step_fn, (beta0, state),
                                             None, length=iters)
            return beta, values

        P = di.nfeatures
        beta0 = jnp.zeros(P, jnp.float32)
        if di.add_intercept:
            beta0 = beta0.at[-1].set(fam.init_eta(y, w)[0])
        beta_j, values = run(beta0)
        beta = np.asarray(beta_j, np.float64)
        hist = [{"lambda": lamf, "iteration": i,
                 "deviance": float(v) * 2 * n, "delta": float("nan")}
                for i, v in enumerate(np.asarray(values))]
        # gram at the solution (p-values / std errors in _finalize)
        step = _make_irls_step(fam)
        gram, _, dev = step(X, y, w, jnp.asarray(beta, jnp.float32), offset)
        model = GLMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        self._finalize(model, di, beta, fam_name, X, y, w, offset, n,
                       float(dev), hist, lamf, frame, valid,
                       gram_last=np.asarray(gram, np.float64))
        return model

    # ----------------------------------------------------------- ordinal
    def _fit_ordinal(self, job, frame, di, X, y, w, offset, n, lam,
                     valid) -> "GLMModel":
        """Proportional-odds (cumulative logit) — GLM.java family=ordinal.

        P(y <= j) = sigmoid(theta_j - X beta) with ordered thresholds,
        fit jointly by L-BFGS on the NLL inside one jit scan; thresholds
        are parameterized as theta_0 + cumulative softplus gaps so the
        ordering constraint holds by construction.
        """
        import optax
        p: GLMParameters = self.params
        K = di.nclasses
        P = di.nfeatures
        # drop the intercept column (absorbed into the thresholds)
        has_icpt = di.add_intercept
        Xf = X[:, :-1] if has_icpt else X
        Pf = Xf.shape[1]
        yi = jnp.clip(y.astype(jnp.int32), 0, K - 1)
        lamf = float(lam)

        def unpack(params):
            beta = params[:Pf]
            t0 = params[Pf]
            gaps = jax.nn.softplus(params[Pf + 1:])
            thetas = t0 + jnp.concatenate(
                [jnp.zeros(1), jnp.cumsum(gaps)])
            return beta, thetas

        def nll_fn(params):
            beta, thetas = unpack(params)
            eta = Xf @ beta + offset
            # cdf_j = P(y <= j), j = 0..K-2; boundaries 0 and 1 appended
            cdf = jax.nn.sigmoid(thetas[None, :] - eta[:, None])
            cdf = jnp.concatenate(
                [jnp.zeros((cdf.shape[0], 1)), cdf,
                 jnp.ones((cdf.shape[0], 1))], axis=1)
            probs = jnp.clip(jnp.diff(cdf, axis=1), 1e-12, 1.0)
            pick = jnp.take_along_axis(probs, yi[:, None], 1)[:, 0]
            return -jnp.sum(w * jnp.log(pick)) / n

        def obj(params):
            beta, _ = unpack(params)
            return nll_fn(params) + 0.5 * lamf * jnp.sum(beta ** 2)

        opt = optax.lbfgs()
        vg = optax.value_and_grad_from_state(obj)
        iters = int(min(p.max_iterations * 4, 200))

        @jax.jit
        def run(p0):
            state = opt.init(p0)

            def step(carry, _):
                prm, st = carry
                value, grad = vg(prm, state=st)
                upd, st = opt.update(grad, st, prm, value=value, grad=grad,
                                     value_fn=obj)
                return (optax.apply_updates(prm, upd), st), value
            (prm, _), values = jax.lax.scan(step, (p0, state), None,
                                            length=iters)
            return prm, values

        p0 = jnp.concatenate([jnp.zeros(Pf),
                              jnp.asarray([-1.0]),
                              jnp.full(K - 2, 0.5)]).astype(jnp.float32)
        prm, values = run(p0)
        beta, thetas = unpack(prm)
        final_nll = float(nll_fn(prm))     # penalty-free, at the FINAL point

        model = GLMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        beta_full = np.zeros(P)
        beta_full[:Pf] = np.asarray(beta, np.float64)
        # destandardize for reporting (what _finalize does elsewhere)
        beta_orig = beta_full.copy()
        if di.standardize:
            ci = 0
            for spec in di.specs:
                if spec.type != "cat" and spec.width == 1 \
                        and ci < Pf and spec.sigma:
                    beta_orig[ci] = beta_full[ci] / spec.sigma
                ci += spec.width
        model.output.update({
            "family": "ordinal",
            "beta_std": beta_full,
            "ordinal_thresholds": np.asarray(thetas, np.float64),
            "coef_names": di.coef_names,
            "beta_std_flat": beta_full.tolist(),
            "beta": beta_orig.tolist(),
            "iterations": iters,
            "residual_deviance": final_nll * 2 * n,
        })
        model.scoring_history = [
            {"iteration": i, "deviance": float(v) * 2 * n}
            for i, v in enumerate(np.asarray(values[-5:]))]
        from ..metrics.core import make_metrics
        raw = model._predict_raw(X)
        model.training_metrics = make_metrics(di, raw, y, w)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    # ------------------------------------------------------- single-class
    def _fit_single(self, job, frame, di, X, y, w, offset, n, penalize,
                    lambdas, fam_name, valid) -> GLMModel:
        p: GLMParameters = self.params
        if p.solver.lower() in ("l_bfgs", "lbfgs"):
            return self._fit_lbfgs(job, frame, di, X, y, w, offset, n,
                                   penalize, lambdas[-1], fam_name, valid)
        fam = _make_family(fam_name, p)
        step = _make_irls_step(fam)
        P = di.nfeatures
        beta = np.zeros(P, dtype=np.float64)
        if di.add_intercept:
            eta0 = fam.init_eta(y, w)
            beta[-1] = float(eta0[0])
        if getattr(self, "_nonneg", None) is None:
            # every fit (single lambda included) runs as one fused device
            # program — the host loop below pays a device->host round trip
            # per IRLS iteration (~67 ms on a tunnelled backend; VERDICT r5
            # measured the plain fit 5x slower than the 100-lambda path
            # because only lambda_search took this route).  The host loop
            # remains only for non_negative (per-coordinate projection).
            # l1_mode only when L1 is actually active: the CD sweep costs
            # a while_loop per IRLS step that a plain solve doesn't.
            from ..runtime import failure
            failure.maybe_inject("glm_lambda")
            runner = _make_path_runner(
                fam, l1_mode=p.alpha > 0 and float(np.max(lambdas)) > 0,
                max_iter=p.max_iterations)
            betas, devs, iters, gram_fin, dev_fin = jax.device_get(runner(
                X, y, w, offset, jnp.asarray(lambdas, jnp.float32),
                jnp.float32(p.alpha), jnp.asarray(penalize, jnp.float32),
                jnp.asarray(beta, jnp.float32), jnp.float32(n),
                jnp.float32(p.beta_epsilon)))
            hist = [{"lambda": float(lam), "iteration": int(iters[li]),
                     "deviance": float(devs[li]), "delta": float("nan")}
                    for li, lam in enumerate(lambdas)]
            for li, lam in enumerate(lambdas):
                job.update((li + 1) / len(lambdas),
                           f"lambda={lam:.3g} dev={float(devs[li]):.4g}")
            model = GLMModel(job.dest_key or dkv.make_key(self.algo), p, di)
            self._finalize(model, di, np.asarray(betas[-1], np.float64),
                           fam_name, X, y, w, offset, n, float(devs[-1]),
                           hist, lambdas[-1], frame, valid,
                           gram_last=np.asarray(gram_fin, np.float64))
            return model
        best = None
        hist = []
        dev = np.inf
        from ..runtime import failure, scheduler, snapshot
        for li, lam in enumerate(lambdas):
            # the host lambda loop journals its position: the in-progress
            # state (warm-start beta) is not a loadable model, so this is
            # a cursor-only progress record (bounded-rework accounting +
            # the /3/Recovery status view), throttled like full snapshots
            failure.maybe_inject("glm_lambda")
            # per-lambda device-lease yield: co-resident jobs interleave
            # here (the tree drivers yield at chunk boundaries)
            scheduler.DEVICE_LEASE.yield_turn()
            snapshot.progress(job, {"lambda_index": li,
                                    "lambda": float(lam)})
            for it in range(p.max_iterations):
                # one batched fetch per iteration (each separate fetch is a
                # full round trip on a tunnelled backend)
                gram, xtwz, dev_new = jax.device_get(step(
                    X, y, w, jnp.asarray(beta, dtype=jnp.float32), offset))
                gram = np.asarray(gram, np.float64)
                xtwz = np.asarray(xtwz, np.float64)
                new_beta = _solve_penalized(gram, xtwz, n, lam, p.alpha,
                                            beta, penalize,
                                            nonneg=getattr(self, "_nonneg",
                                                           None))
                delta = float(np.max(np.abs(new_beta - beta)))
                beta = new_beta
                dev_new = float(dev_new)
                hist.append({"lambda": lam, "iteration": it,
                             "deviance": dev_new, "delta": delta})
                job.update((li + it / p.max_iterations) / len(lambdas),
                           f"lambda={lam:.3g} iter={it} dev={dev_new:.4g}")
                if delta < p.beta_epsilon:
                    break
            dev = hist[-1]["deviance"]
            best = beta.copy()

        model = GLMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        self._finalize(model, di, best, fam_name, X, y, w, offset, n,
                       dev, hist, lambdas[-1], frame, valid,
                       gram_last=gram)
        return model

    # -------------------------------------------------------- multinomial
    def _fit_multinomial(self, job, frame, di, X, y, w, offset, n, penalize,
                         lambdas, valid) -> GLMModel:
        p: GLMParameters = self.params
        K = di.nclasses
        P = di.nfeatures
        stats = _make_softmax_stats(K)
        beta = np.zeros((P, K), dtype=np.float64)
        hist = []
        lam = lambdas[-1]
        ll_prev = np.inf
        from ..runtime import failure, scheduler, snapshot
        for it in range(p.max_iterations):
            failure.maybe_inject("glm_lambda")
            scheduler.DEVICE_LEASE.yield_turn()
            snapshot.progress(job, {"iteration": it})
            # batched fetch of the SMALL outputs only — [:3] keeps the
            # [N, K] probs (4th return) on device
            grams, xtwz, ll = jax.device_get(stats(
                X, y, w, jnp.asarray(beta, jnp.float32), offset)[:3])
            grams = np.asarray(grams, np.float64)
            xtwz = np.asarray(xtwz, np.float64)
            delta = 0.0
            for k in range(K):
                bk = _solve_penalized(grams[k], xtwz[:, k], n, lam, p.alpha,
                                      beta[:, k], penalize)
                delta = max(delta, float(np.max(np.abs(bk - beta[:, k]))))
                beta[:, k] = bk
            ll = float(ll)
            hist.append({"lambda": lam, "iteration": it, "logloss": ll / n,
                         "delta": delta})
            job.update(it / p.max_iterations, f"iter={it} ll={ll:.4g}")
            if delta < p.beta_epsilon or abs(ll_prev - ll) < 1e-8 * n:
                break
            ll_prev = ll
        model = GLMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        self._finalize(model, di, beta, "multinomial", X, y, w, offset, n,
                       2 * ll, hist, lam, frame, valid)
        return model

    # ------------------------------------------------------------ finalize
    def _finalize(self, model, di, beta_std, fam_name, X, y, w, offset, n,
                  deviance, hist, lam, frame, valid, gram_last=None):
        p: GLMParameters = self.params
        # de-standardize coefficients back to the original data scale
        means = np.zeros(di.nfeatures)
        sigmas = np.ones(di.nfeatures)
        i = 0
        for s in di.specs:
            if s.type == "cat":
                i += s.width
            else:
                if di.standardize:
                    means[i], sigmas[i] = s.mean, s.sigma
                i += 1
        b = np.asarray(beta_std, np.float64)
        multi = b.ndim == 2
        bo = b / sigmas[:, None] if multi else b / sigmas
        if di.add_intercept:
            bo[-1] = b[-1] - (means[:-1] / sigmas[:-1]) @ b[:-1]

        model.output.update({
            "family": fam_name, "beta_std": np.asarray(beta_std, np.float32),
            "beta_std_flat": b.ravel().tolist(), "beta": bo,
            "coef_names": di.coef_names, "lambda": lam, "alpha": p.alpha,
            "iterations": len(hist), "residual_deviance": float(deviance),
            "rank": int(np.count_nonzero(np.atleast_2d(b))) ,
        })
        # null deviance
        fam = _make_family(fam_name if fam_name != "multinomial" else "binomial", p)
        if fam_name != "multinomial":
            mu0 = fam.linkinv(fam.init_eta(y, w))
            model.output["null_deviance"] = float(fam.deviance(y, mu0, w))
        model.scoring_history = hist
        # p-values for unpenalized fits (GLM.java compute_p_values path)
        if p.compute_p_values and lam == 0.0 and not multi and gram_last is not None:
            try:
                inv = np.linalg.inv(gram_last)
                disp = (deviance / max(n - len(b), 1.0)
                        if fam_name in ("gaussian", "gamma", "tweedie") else 1.0)
                se = np.sqrt(np.maximum(np.diag(inv) * disp, 0.0))
                zval = np.where(se > 0, b / np.maximum(se, 1e-30), np.nan)
                from scipy.stats import norm  # pragma: no cover
                pval = 2 * (1 - norm.cdf(np.abs(zval)))
            except Exception:
                se = zval = pval = None
            if se is not None:
                model.output.update({"std_errs": se, "z_values": zval,
                                     "p_values": pval})
        # training + validation metrics
        raw = model._predict_raw(X)
        model.training_metrics = make_metrics(di, raw, y, w)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
