"""PSVM: kernel support vector machine on a low-rank feature map.

Reference: ``hex/psvm/PSVM.java`` (2.1k LoC) — binary SVM with a gaussian
kernel, solved distributed via ICF (incomplete Cholesky factorization, a
rank-r kernel approximation) + an interior-point method; per-class
weights, sv threshold reporting.

TPU-native redesign: the reference's ICF is a low-rank approximation of
the kernel matrix; here the same role is played by a random Fourier
feature map (Rahimi-Recht) of rank ``rank`` — z(x) = sqrt(2/m) cos(Wx+b),
E[z(x).z(y)] = exp(-gamma ||x-y||^2) — which turns the kernel SVM into a
linear squared-hinge problem solved by one jit-compiled L-BFGS scan on
the MXU.  Same model family (low-rank gaussian-kernel SVM), an
approximation axis that scales with chips instead of the ICF's sequential
pivoting.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters


@dataclasses.dataclass
class PSVMParameters(Parameters):
    hyper_param: float = 1.0             # C
    kernel_type: str = "gaussian"
    gamma: float = -1.0                  # -1 -> 1/nfeatures
    rank_ratio: float = -1.0             # -1 -> auto rank
    positive_weight: float = 1.0
    negative_weight: float = 1.0
    sv_threshold: float = 1e-4
    max_iterations: int = 200


class PSVMModel(Model):
    algo = "psvm"

    def _feature_map(self, X: jax.Array) -> jax.Array:
        W = jnp.asarray(self.output["rff_w"], jnp.float32)
        b = jnp.asarray(self.output["rff_b"], jnp.float32)
        m = W.shape[1]
        return jnp.sqrt(2.0 / m) * jnp.cos(X @ W + b[None, :])

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        Z = self._feature_map(X)
        beta = jnp.asarray(self.output["beta"], jnp.float32)
        f = Z @ beta[:-1] + beta[-1]
        p1 = jax.nn.sigmoid(2.0 * f)     # decision -> pseudo-probability
        return jnp.stack([1 - p1, p1], axis=1)

    def decision_function(self, frame: Frame) -> np.ndarray:
        X = self._score_matrix(frame)
        Z = self._feature_map(X)
        beta = jnp.asarray(self.output["beta"], jnp.float32)
        return np.asarray(Z @ beta[:-1] + beta[-1])[: frame.nrows]


class PSVM(ModelBuilder):
    """PSVM builder — H2OSupportVectorMachineEstimator analog."""

    algo = "psvm"
    model_class = PSVMModel
    _force_classification = True

    def __init__(self, params: Optional[PSVMParameters] = None, **kw):
        super().__init__(params or PSVMParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di, valid) -> PSVMModel:
        import optax
        p: PSVMParameters = self.params
        if p.kernel_type != "gaussian":
            raise ValueError("psvm supports kernel_type='gaussian'")
        if di.nclasses != 2:
            raise ValueError("psvm is a binary classifier")
        X = di.make_matrix(frame)
        y01 = jnp.nan_to_num(di.response(frame))
        ysvm = 2.0 * y01 - 1.0                       # {-1, +1}
        w = di.weights(frame)
        w = w * jnp.where(ysvm > 0, p.positive_weight, p.negative_weight)
        F = X.shape[1]
        gamma = (1.0 / max(F, 1)) if p.gamma <= 0 else p.gamma
        n = frame.nrows
        rank = int(min(max(64, np.sqrt(n) * 4), 1024)) \
            if p.rank_ratio <= 0 else int(max(p.rank_ratio * n, 16))
        rng = np.random.default_rng(p.effective_seed())
        W = rng.normal(0.0, np.sqrt(2.0 * gamma), size=(F, rank))
        b = rng.uniform(0, 2 * np.pi, rank)

        model = PSVMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["rff_w"] = W
        model.output["rff_b"] = b
        model.output["gamma"] = gamma
        model.output["rank"] = rank
        Z = model._feature_map(X)
        C = p.hyper_param

        def obj(beta):
            f = Z @ beta[:-1] + beta[-1]
            margin = jnp.maximum(0.0, 1.0 - ysvm * f)
            return 0.5 * jnp.sum(beta[:-1] ** 2) \
                + C * jnp.sum(w * margin ** 2)

        opt = optax.lbfgs()
        vg = optax.value_and_grad_from_state(obj)
        iters = int(p.max_iterations)

        @jax.jit
        def run(beta0):
            state = opt.init(beta0)

            def step(carry, _):
                params, st = carry
                value, grad = vg(params, state=st)
                updates, st = opt.update(grad, st, params, value=value,
                                         grad=grad, value_fn=obj)
                params = optax.apply_updates(params, updates)
                return (params, st), value
            (beta, _), values = jax.lax.scan(step, (beta0, state), None,
                                             length=iters)
            return beta, values

        beta, values = run(jnp.zeros(rank + 1, jnp.float32))
        f = Z @ beta[:-1] + beta[-1]
        margins = ysvm * f
        mask = jnp.arange(X.shape[0]) < n
        n_sv = int(jnp.sum((margins < 1.0 - p.sv_threshold) & mask
                           & (w > 0)))
        model.output.update({
            "beta": np.asarray(beta, np.float64),
            "svs_count": n_sv,
            "objective": float(values[-1]),
            "iterations": iters,
        })
        from ..metrics.core import make_metrics
        raw = model._predict_raw(X)
        model.training_metrics = make_metrics(di, raw, y01, di.weights(frame))
        return model
