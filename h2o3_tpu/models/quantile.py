"""Quantile: distributed quantiles via device sort over the sharded column.

Reference: ``hex/quantile/Quantile.java:15`` — its own ModelBuilder; per
numeric column, iterative histogram refinement MRTasks converge on each
requested probability; ``combine_method`` INTERPOLATE / AVERAGE / LOW / HIGH
resolves non-integer ranks; weighted rows supported.

TPU-native redesign: a single ``jnp.sort`` of the padded column (TPU sort is
a fast bitonic network; NaN/padding sort to +inf) replaces the multi-pass
histogram refinement — one device pass per column instead of ~log(range)
MRTask rounds.  Weighted quantiles use the sorted cumulative-weight vector.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo

DEFAULT_PROBS = (0.001, 0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9,
                 0.99, 0.999)


@dataclasses.dataclass
class QuantileParameters(Parameters):
    probs: Sequence[float] = DEFAULT_PROBS
    combine_method: str = "interpolate"   # interpolate | average | low | high


@jax.jit
def _sorted_with_weights(x, w):
    """Sort x ascending (invalid rows to +inf), carrying weights along."""
    invalid = jnp.isnan(x) | (w <= 0)
    key = jnp.where(invalid, jnp.inf, x)
    order = jnp.argsort(key)
    return key[order], jnp.where(invalid, 0.0, w)[order]


def _quantile_from_sorted(xs: np.ndarray, ws: np.ndarray, prob: float,
                          method: str) -> float:
    wsum = ws.sum()
    if wsum <= 0:
        return float("nan")
    unweighted = bool(np.all((ws == 0) | (ws == ws[ws > 0][0])))
    n = int((ws > 0).sum())
    if unweighted:
        # exact rank arithmetic on the n valid (sorted-first) entries
        h = prob * (n - 1)
        lo = int(np.floor(h))
        hi = min(lo + 1, n - 1)
        frac = h - lo
        if method == "interpolate":
            return float(xs[lo] * (1 - frac) + xs[hi] * frac)
        if method == "average":
            return float((xs[lo] + xs[hi]) / 2) if frac else float(xs[lo])
        if method == "low":
            return float(xs[lo])
        if method == "high":
            return float(xs[hi] if frac else xs[lo])
        raise ValueError(f"unknown combine_method {method!r}")
    # weighted: rank along the cumulative-weight axis
    cw = np.cumsum(ws)
    target = prob * wsum
    idx = min(int(np.searchsorted(cw, target, side="left")), n - 1)
    on_boundary = np.isclose(cw[idx], target) and idx + 1 < n
    if method == "low" or not on_boundary:
        return float(xs[idx])
    if method == "high":
        return float(xs[idx + 1])
    return float((xs[idx] + xs[idx + 1]) / 2)


class QuantileModel(Model):
    algo = "quantile"

    def model_performance(self, frame=None):
        return self.training_metrics


class Quantile(ModelBuilder):
    """Quantile builder — h2o.quantile analog (also used by frame.quantile)."""

    algo = "quantile"
    model_class = QuantileModel
    supervised = False

    def __init__(self, params: Optional[QuantileParameters] = None, **kw):
        super().__init__(params or QuantileParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            weights_column=p.weights_column, standardize=False,
            add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> QuantileModel:
        p: QuantileParameters = self.params
        w = di.weights(frame)
        table = {}
        skip = set(p.ignored_columns) | {p.weights_column}
        numeric = [nm for nm, v in zip(frame.names, frame.vecs)
                   if v.is_numeric and nm not in skip]
        for i, name in enumerate(numeric):
            xs, ws = _sorted_with_weights(frame.vec(name).numeric_data(), w)
            xs = np.asarray(xs, np.float64)
            ws = np.asarray(ws, np.float64)
            table[name] = [_quantile_from_sorted(xs, ws, q, p.combine_method)
                           for q in p.probs]
            job.update((i + 1) / len(numeric), f"quantiles: {name}")
        model = QuantileModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({"probs": list(p.probs), "quantiles": table})
        model.training_metrics = table
        return model


def quantile(frame: Frame, probs: Sequence[float] = DEFAULT_PROBS,
             combine_method: str = "interpolate",
             weights_column: Optional[str] = None) -> dict:
    """Frame-level quantiles — the ``h2o.frame.quantile`` convenience path."""
    m = Quantile(probs=tuple(probs), combine_method=combine_method,
                 weights_column=weights_column).train(frame)
    return m.output["quantiles"]
