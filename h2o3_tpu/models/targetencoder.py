"""Target encoding: CV-aware categorical mean-target transform.

Reference: ``h2o-extensions/target-encoder`` —
``ai/h2o/targetencoding/TargetEncoder.java:23``: per-level response means
with blending (k/f smoothing toward the prior), leave-one-out / k-fold
holdout strategies to avoid leakage, optional noise; both a ModelBuilder
and an AutoML preprocessor.

TPU-native redesign: per-level sums are one one-hot matmul per column
(level counts and response sums from the same product); holdout corrections
are elementwise.  The fitted state is a small host-side table per column.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class TargetEncoderParameters(Parameters):
    columns: Optional[List[str]] = None        # None -> all cat features
    data_leakage_handling: str = "none"        # none | leave_one_out | k_fold
    blending: bool = True
    inflection_point: float = 10.0             # k in k/f smoothing
    smoothing: float = 20.0                    # f
    noise: float = 0.0
    fold_column: Optional[str] = None


class TargetEncoderModel(Model):
    algo = "targetencoder"

    def transform(self, frame: Frame, as_training: bool = False) -> Frame:
        """Append ``<col>_te`` columns (training mode applies holdout)."""
        p: TargetEncoderParameters = self.params
        tables = self.output["encoding_tables"]
        prior = self.output["prior_mean"]
        names = list(frame.names)
        vecs = list(frame.vecs)
        rng = np.random.default_rng(self.params.effective_seed())
        y = wrow = folds = None
        if as_training and p.data_leakage_handling == "leave_one_out":
            y = np.asarray(self.datainfo.response(frame))[: frame.nrows]
            wrow = np.ones(frame.nrows)
            if p.weights_column and p.weights_column in frame.names:
                wrow = np.nan_to_num(
                    frame.vec(p.weights_column).to_numpy())
        if as_training and p.data_leakage_handling == "k_fold":
            if p.fold_column is None or p.fold_column not in frame.names:
                raise ValueError(
                    "k_fold leakage handling requires fold_column")
            fc = frame.vec(p.fold_column).to_numpy()
            fold_ids = self.output["fold_ids"]
            lookup = {f: i for i, f in enumerate(fold_ids)}
            folds = np.asarray([lookup.get(f, -1) for f in fc])
        for col, tbl in tables.items():
            if col not in frame.names:
                continue
            v = frame.vec(col)
            codes = v.to_numpy() if v.type == T_CAT else \
                v.to_numpy().astype(np.int64)
            sums = tbl["sums"]
            counts = tbl["counts"]
            s = np.where((codes >= 0) & (codes < len(sums)),
                         sums[np.clip(codes, 0, len(sums) - 1)], 0.0)
            c = np.where((codes >= 0) & (codes < len(counts)),
                         counts[np.clip(codes, 0, len(counts) - 1)], 0.0)
            if y is not None:               # leave-one-out (weight-aware)
                s = s - np.nan_to_num(y) * wrow
                c = np.maximum(c - wrow, 0)
            if folds is not None:           # k_fold: drop own fold's stats
                fs = tbl["fold_sums"]       # [nfolds, K]
                fcnt = tbl["fold_counts"]
                cc = np.clip(codes, 0, len(sums) - 1)
                ff = np.clip(folds, 0, len(fs) - 1)
                own_s = np.where((codes >= 0) & (folds >= 0),
                                 fs[ff, cc], 0.0)
                own_c = np.where((codes >= 0) & (folds >= 0),
                                 fcnt[ff, cc], 0.0)
                s = s - own_s
                c = np.maximum(c - own_c, 0)
            mean = np.where(c > 0, s / np.maximum(c, 1e-12), prior)
            if p.blending:
                lam = 1.0 / (1.0 + np.exp(-(c - p.inflection_point)
                                          / max(p.smoothing, 1e-6)))
                mean = lam * mean + (1 - lam) * prior
            if as_training and p.noise > 0:
                mean = mean + rng.uniform(-p.noise, p.noise, len(mean))
            names.append(f"{col}_te")
            vecs.append(Vec.from_numpy(mean, T_NUM))
        return Frame(names, vecs)

    def _predict_raw(self, X):
        raise NotImplementedError("targetencoder transforms, not predicts")

    def model_performance(self, frame=None):
        return self.training_metrics


class TargetEncoder(ModelBuilder):
    """TE builder — H2OTargetEncoderEstimator analog."""

    algo = "targetencoder"
    model_class = TargetEncoderModel

    def __init__(self, params: Optional[TargetEncoderParameters] = None,
                 **kw):
        super().__init__(params or TargetEncoderParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> TargetEncoderModel:
        p: TargetEncoderParameters = self.params
        y = di.response(frame)
        w = di.weights(frame)
        yz = jnp.nan_to_num(y)
        cols = p.columns or [s.name for s in di.specs if s.type == T_CAT]
        fold_ids = []
        fold_mask = None
        if p.data_leakage_handling == "k_fold" and p.fold_column:
            fc = frame.vec(p.fold_column).to_numpy()
            fold_ids = sorted(set(fc.tolist()))
            pad = frame.padded_rows - frame.nrows
            fm = np.stack([(fc == f) for f in fold_ids]).astype(np.float32)
            fold_mask = jnp.asarray(np.pad(fm, [(0, 0), (0, pad)]))
        tables: Dict[str, dict] = {}
        # encoding tables accumulate in float64 on host (bincount): the
        # tables are tiny but the transform subtracts near-equal quantities
        # (LOO / fold corrections), which loses precision in f32 matmuls
        yz64 = np.asarray(yz, np.float64)
        w64 = np.asarray(w, np.float64)
        fold_mask_np = np.asarray(fold_mask, np.float64) \
            if fold_mask is not None else None
        for i, col in enumerate(cols):
            v = frame.vec(col)
            if v.type != T_CAT:
                continue
            K = len(v.domain or [])
            if K == 0:
                continue
            codes = np.asarray(v.data)
            ok = (codes >= 0) * w64
            cc = np.clip(codes, 0, K - 1)
            sums = np.bincount(cc, weights=yz64 * ok, minlength=K)[:K]
            counts = np.bincount(cc, weights=ok, minlength=K)[:K]
            tables[col] = {"sums": sums, "counts": counts,
                           "domain": list(v.domain or [])}
            if fold_mask_np is not None:
                tables[col]["fold_sums"] = np.stack(
                    [np.bincount(cc, weights=yz64 * ok * fm,
                                 minlength=K)[:K] for fm in fold_mask_np])
                tables[col]["fold_counts"] = np.stack(
                    [np.bincount(cc, weights=ok * fm,
                                 minlength=K)[:K] for fm in fold_mask_np])
            job.update((i + 1) / max(len(cols), 1), f"encoding {col}")
        n = float(jnp.sum(w))
        prior = float(jnp.sum(yz * w)) / max(n, 1e-12)
        model = TargetEncoderModel(job.dest_key or dkv.make_key(self.algo),
                                   p, di)
        model.output.update({"encoding_tables": tables, "prior_mean": prior,
                             "fold_ids": fold_ids})
        model.training_metrics = {"columns": list(tables),
                                  "prior_mean": prior}
        return model
