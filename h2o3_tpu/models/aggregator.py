"""Aggregator: exemplar-based data reduction.

Reference: ``hex/aggregator/Aggregator.java`` — reduces a frame to
exemplars + member counts by single-pass radius-based assignment.

TPU-native redesign: exemplar discovery via Lloyd iterations (kmeans.py's
MXU distance kernels) with k = target_num_exemplars — radius-scan
assignment is inherently sequential, while Lloyd exemplars give the same
counts-weighted summary with whole-dataset device passes.  Exemplars are
de-standardized medoid-like centers; counts come from the final assignment.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from .kmeans import KMeans, _lloyd_step


@dataclasses.dataclass
class AggregatorParameters(Parameters):
    target_num_exemplars: int = 100
    rel_tol_num_exemplars: float = 0.5
    standardize: bool = True


class AggregatorModel(Model):
    algo = "aggregator"

    def _predict_raw(self, X):
        raise NotImplementedError("aggregator reduces, not predicts")

    @property
    def aggregated_frame(self) -> Frame:
        return dkv.get(self.output["output_frame_key"])

    def model_performance(self, frame=None):
        return self.training_metrics


class Aggregator(ModelBuilder):
    """Aggregator builder — H2OAggregatorEstimator analog."""

    algo = "aggregator"
    model_class = AggregatorModel
    supervised = False

    def __init__(self, params: Optional[AggregatorParameters] = None, **kw):
        super().__init__(params or AggregatorParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            standardize=p.standardize, use_all_factor_levels=True,
            add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> AggregatorModel:
        p: AggregatorParameters = self.params
        k = min(p.target_num_exemplars, frame.nrows)
        km = KMeans(k=k, standardize=False, seed=p.effective_seed(),
                    max_iterations=10, init="plus_plus")
        # reuse this builder's datainfo so standardization matches
        X = di.make_matrix(frame)
        w = di.weights(frame)
        rng = np.random.default_rng(p.effective_seed())
        c0 = km._init_centers(X, w, k, rng, di)
        centers, withinss, counts, tot, iters = km._run_lloyd(
            job, X, w, np.asarray(c0), f"exemplars k={k}")
        assign, _, counts_j, _ = _lloyd_step(
            X, w, jnp.asarray(centers, jnp.float32))
        counts = np.asarray(counts_j, np.float64)
        keep = counts > 0

        # de-standardize exemplar coordinates back to input space
        cols = {}
        ci = 0
        for s in di.specs:
            if s.width == 1:
                vals = centers[keep, ci]
                if di.standardize:
                    vals = vals * s.sigma + s.mean
                cols[s.name] = vals
            else:
                codes = np.argmax(centers[keep, ci:ci + s.width - 1], axis=1)
                lo = 0 if di.use_all_factor_levels else 1
                cols[s.name] = np.asarray(
                    [s.domain[min(c + lo, len(s.domain) - 1)]
                     for c in codes], dtype=object)
            ci += s.width
        cols["counts"] = counts[keep]
        out = Frame.from_numpy(cols, key=dkv.make_key("aggregated"))

        model = AggregatorModel(job.dest_key or dkv.make_key(self.algo),
                                p, di)
        model.output.update({
            "output_frame_key": out.key,
            "num_exemplars": int(keep.sum()),
            "mapping_counts": counts[keep],
        })
        model.training_metrics = {"num_exemplars": int(keep.sum()),
                                  "rows_in": frame.nrows}
        return model
