"""Concurrent model building — the ParallelModelBuilder analog.

Reference: ``hex/ParallelModelBuilder.java`` (bounded-pool fork of model
builds with a completer callback) and ``hex/CVModelBuilder.java:16-28``
(CV fold models built N-at-a-time).  There, parallelism wins by using many
JVM cores; here the device serializes compute, so concurrency wins by
PIPELINING: while one build blocks on a device fetch or runs host-side
prep (numpy, tokenization, metric assembly), another thread keeps the
accelerator queue full.  Small/dispatch-bound models (CV folds, grid
points, AutoML steps) see near-linear wall-clock wins; a single
compute-walled 10M-row build does not regress because it was never
waiting on the host.

Builds run on a short-lived bounded ``ThreadPoolExecutor`` owned by the
caller, NOT on the shared JobScheduler: a parent build occupying a
scheduler worker while its children queue behind it is the classic
fork/join starvation the reference solves with 127 priority levels
(H2O.java:1470) — a private pool per parallel phase sidesteps the problem
outright.

Thread-safety contract: builders must not share mutable per-build state
(each thunk constructs its own builder/Frame); JAX tracing/dispatch, the
DKV, and the lru-cached program factories are all safe to use from
worker threads.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time
from typing import Callable, List, Optional, Sequence

# Cooperative max_runtime_secs deadline, thread-local so concurrent grids
# don't see each other's budgets.  ``map_builds`` (and the batched cohort
# trainer) arm it per worker thread; ``chunk_schedule`` polls it at every
# tree-chunk fence via ``check_deadline`` — an in-flight member therefore
# stops within one chunk of the budget instead of finishing its build.
_DEADLINE = threading.local()


class DeadlineExceeded(Exception):
    """Raised at a chunk fence once the cooperative deadline passes."""


def set_deadline(deadline: Optional[float]) -> None:
    """Arm (monotonic-clock timestamp) or clear (None) this thread's
    cooperative deadline."""
    _DEADLINE.at = deadline


def get_deadline() -> Optional[float]:
    return getattr(_DEADLINE, "at", None)


def check_deadline() -> None:
    """Raise ``DeadlineExceeded`` if this thread's deadline has passed."""
    at = getattr(_DEADLINE, "at", None)
    if at is not None and time.monotonic() > at:
        raise DeadlineExceeded(
            f"max_runtime_secs deadline passed (cooperative cancel at "
            f"chunk fence, {time.monotonic() - at:.1f}s over)")


def effective_parallelism(requested: int, n_tasks: int) -> int:
    """Resolve the ``parallelism`` parameter (0 auto / 1 sequential / n).

    Auto is capped by the host core count: on a single-core host the
    pipelining win does not exist, and concurrent eager dispatch from
    several build threads has been observed to stall XLA:CPU's single
    execution stream for minutes (explicit ``parallelism`` requests are
    still honored as given).
    """
    if n_tasks <= 1 or requested == 1:
        return 1
    if requested and requested > 1:
        return min(int(requested), n_tasks)
    auto = int(os.environ.get("H2O3_PARALLEL_BUILDS", 0)) \
        or min(4, os.cpu_count() or 1)
    return max(1, min(n_tasks, auto))


def map_builds(thunks: Sequence[Callable[[], object]],
               parallelism: int,
               deadline: Optional[float] = None) -> List[object]:
    """Run build thunks, at most ``parallelism`` concurrently; results in
    input order.  The first raised exception propagates (after letting
    in-flight builds finish — matching reference CV semantics where a
    failed fold cancels the CV job but not mid-build siblings).

    ``deadline`` (monotonic timestamp) arms the cooperative
    max_runtime_secs cancel around each thunk: tree drivers poll it at
    chunk fences (``check_deadline``), so a slow wave stops within one
    chunk of the budget instead of overshooting by whole builds."""
    def run(t):
        prev = get_deadline()
        set_deadline(deadline)
        try:
            return t()
        finally:
            set_deadline(prev)

    if parallelism <= 1:
        return [run(t) for t in thunks]
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=parallelism,
            thread_name_prefix="parallel-build") as ex:
        futures = [ex.submit(run, t) for t in thunks]
        return [f.result() for f in futures]
