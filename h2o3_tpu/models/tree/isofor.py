"""Isolation Forest + Extended Isolation Forest: random isolation trees.

Reference: ``hex/tree/isofor/IsolationForest.java:33`` (random-split trees on
row subsamples, anomaly score from average isolation depth) and
``hex/tree/isoforextended/ExtendedIsolationForest.java`` (random-hyperplane
splits, ``extension_level``).

TPU-native redesign: a level of an isolation tree needs only per-leaf
min/max/count of the currently-routed rows — ``jax.ops.segment_min/max/sum``
over the row-sharded matrix (no histograms, no gradients).  Split choices
(random feature, uniform threshold, random hyperplane) are host RNG draws;
routing is the same gather-compare partition the other trees use.  Scoring
reuses the stacked-tree traversal: each leaf's "value" is its isolation path
length, so the ensemble sum is one compiled pass and the anomaly score
``2^(-E[h]/c(n))`` is a scalar epilogue.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...frame.vec import Vec, T_NUM
from ...runtime import dkv
from ...runtime.job import Job
from ..base import Model, ModelBuilder
from ..datainfo import DataInfo
from .shared import (SharedTreeModel, SharedTreeParameters, Tree, stack_trees,
                     traverse_jit)


def _avg_path_length(n) -> float:
    """c(n): expected path length of an unsuccessful BST search (iForest eq.1)."""
    if n <= 1:
        return 0.0
    if n == 2:
        return 1.0
    h = math.log(n - 1) + 0.5772156649015329
    return 2.0 * h - 2.0 * (n - 1) / n


@dataclasses.dataclass
class IsolationForestParameters(SharedTreeParameters):
    ntrees: int = 50
    sample_size: int = 256
    max_depth: int = 8
    contamination: float = -1.0          # optional threshold quantile


@dataclasses.dataclass
class ExtendedIsolationForestParameters(IsolationForestParameters):
    extension_level: int = 0             # 0 == standard iForest


@functools.partial(jax.jit, static_argnames=("L",))
def _leaf_stats(x, leaf, active, L: int):
    """Per-leaf (min, max, count) of feature values over active rows."""
    big = jnp.float32(3.4e38)
    xa = jnp.where(active, x, big)
    xb = jnp.where(active, x, -big)
    mn = jax.ops.segment_min(xa, leaf, num_segments=L)
    mx = jax.ops.segment_max(xb, leaf, num_segments=L)
    cnt = jax.ops.segment_sum(active.astype(jnp.float32), leaf, num_segments=L)
    return mn, mx, cnt


def _termination_depths(valid_levels: List[np.ndarray],
                        max_depth: int) -> np.ndarray:
    """Per final leaf: number of valid splits along its ancestor path."""
    Lfin = 2 ** max_depth
    depths = np.zeros(Lfin, np.int64)
    for d, v in enumerate(valid_levels):
        anc = np.arange(Lfin) >> (max_depth - d)
        depths += v[anc].astype(np.int64)
    return depths


class IsolationForestModel(SharedTreeModel):
    algo = "isolationforest"

    def _path_lengths(self, X: jax.Array) -> jax.Array:
        levels, values = stack_trees(self.output["trees"])
        return traverse_jit(levels, values, X) / len(self.output["trees"])

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        mean_len = self._path_lengths(X)
        c = self.output["c_norm"]
        return jnp.exp2(-mean_len / max(c, 1e-9))

    def predict(self, frame: Frame) -> Frame:
        X = self._design(frame)
        mean_len = np.asarray(self._path_lengths(X), np.float64)[: frame.nrows]
        c = self.output["c_norm"]
        score = np.exp2(-mean_len / max(c, 1e-9))
        names = ["predict", "mean_length"]
        vecs = [Vec.from_numpy(score, T_NUM), Vec.from_numpy(mean_len, T_NUM)]
        return Frame(names, vecs)

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        score = self.predict(frame).vecs[0].to_numpy()
        return {"mean_score": float(np.mean(score)),
                "max_score": float(np.max(score))}


class IsolationForest(ModelBuilder):
    """Isolation Forest builder — H2OIsolationForestEstimator analog."""

    algo = "isolationforest"
    model_class = IsolationForestModel
    supervised = False

    def __init__(self, params: Optional[IsolationForestParameters] = None,
                 **kw):
        super().__init__(params or IsolationForestParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            standardize=False, add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    def _sample_mask(self, N: int, nrows: int, size: int,
                     rng: np.random.Generator):
        size = min(size, nrows)
        idx = rng.choice(nrows, size=size, replace=False)
        m = np.zeros(N, np.float32)
        m[idx] = 1.0
        return jnp.asarray(m), size

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> IsolationForestModel:
        p: IsolationForestParameters = self.params
        rng = np.random.default_rng(p.effective_seed())
        model = IsolationForestModel(
            job.dest_key or dkv.make_key(self.algo), p, di)
        X = model._design(frame)
        N, Fn = X.shape
        depth = p.max_depth
        trees: List[Tree] = []
        for t in range(p.ntrees):
            mask, size = self._sample_mask(N, frame.nrows, p.sample_size, rng)
            leaf = jnp.zeros(N, jnp.int32)
            feat_l, thr_l, nal_l, val_l = [], [], [], []
            for d in range(depth):
                L = 2 ** d
                f = rng.integers(0, Fn, size=L).astype(np.int32)
                fj = jnp.asarray(f)
                x = jnp.take_along_axis(X, fj[leaf][:, None], axis=1)[:, 0]
                active = (mask > 0) & ~jnp.isnan(x)
                mn, mx, cnt = _leaf_stats(x, leaf, active, L)
                mn_h = np.asarray(mn, np.float64)
                mx_h = np.asarray(mx, np.float64)
                cnt_h = np.asarray(cnt, np.float64)
                valid = (cnt_h > 1) & (mx_h > mn_h)
                u = rng.random(L)
                mn_h = np.where(valid, mn_h, 0.0)   # empty leaves hold ±big
                mx_h = np.where(valid, mx_h, 0.0)
                thr = (mn_h + u * (mx_h - mn_h)).astype(np.float32)
                vj = jnp.asarray(valid)
                tj = jnp.asarray(thr)
                right = jnp.where(jnp.isnan(x), False, x >= tj[leaf])
                leaf = (2 * leaf + (right & vj[leaf]).astype(jnp.int32))
                feat_l.append(f)
                thr_l.append(thr)
                nal_l.append(np.ones(L, bool))      # NaN goes left
                val_l.append(valid)
            # per-leaf path length = termination depth + c(final count)
            Lfin = 2 ** depth
            cnt = jax.ops.segment_sum(mask, leaf, num_segments=Lfin)
            cnt_h = np.asarray(cnt, np.float64)
            depths = _termination_depths(val_l, depth)
            pl = depths + np.array([_avg_path_length(int(c)) for c in cnt_h])
            trees.append(Tree(feat_l, thr_l, nal_l, val_l,
                              pl.astype(np.float32)))
            job.update((t + 1) / p.ntrees, f"itree {t + 1}/{p.ntrees}")

        model.output.update({
            "trees": trees, "ntrees_trained": len(trees),
            "c_norm": _avg_path_length(min(p.sample_size, frame.nrows)),
            "nclass_trees": 1, "init_score": 0.0,
        })
        score = model.predict(frame).vecs[0].to_numpy()
        model.training_metrics = {
            "mean_score": float(np.mean(score)),
            "max_score": float(np.max(score)),
        }
        if p.contamination > 0:
            model.output["threshold"] = float(
                np.quantile(score, 1.0 - p.contamination))
        return model


# ===================================================== extended isolation
@dataclasses.dataclass
class _EITree:
    normals: List[np.ndarray]     # per level [L, F]
    offsets: List[np.ndarray]     # per level [L]
    valid: List[np.ndarray]       # per level [L]
    values: np.ndarray            # [2^depth] path lengths


class ExtendedIsolationForestModel(SharedTreeModel):
    algo = "extendedisolationforest"

    def _path_lengths(self, X: jax.Array) -> jax.Array:
        total = jnp.zeros(X.shape[0], jnp.float32)
        Xz = jnp.nan_to_num(X)
        for t in self.output["trees"]:
            node = jnp.zeros(X.shape[0], jnp.int32)
            for nm, off, vd in zip(t.normals, t.offsets, t.valid):
                nmj = jnp.asarray(nm)[node]            # [N, F]
                proj = jnp.sum(Xz * nmj, axis=1)
                right = (proj >= jnp.asarray(off)[node]) & jnp.asarray(vd)[node]
                node = 2 * node + right.astype(jnp.int32)
            total = total + jnp.asarray(t.values)[node]
        return total / len(self.output["trees"])

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        c = self.output["c_norm"]
        return jnp.exp2(-self._path_lengths(X) / max(c, 1e-9))

    def predict(self, frame: Frame) -> Frame:
        X = self._design(frame)
        mean_len = np.asarray(self._path_lengths(X), np.float64)[: frame.nrows]
        c = self.output["c_norm"]
        score = np.exp2(-mean_len / max(c, 1e-9))
        return Frame(["anomaly_score", "mean_length"],
                     [Vec.from_numpy(score, T_NUM),
                      Vec.from_numpy(mean_len, T_NUM)])

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        score = self.predict(frame).vecs[0].to_numpy()
        return {"mean_score": float(np.mean(score))}


class ExtendedIsolationForest(IsolationForest):
    """Extended IF builder — H2OExtendedIsolationForestEstimator analog."""

    algo = "extendedisolationforest"
    model_class = ExtendedIsolationForestModel

    def __init__(self, params: Optional[ExtendedIsolationForestParameters]
                 = None, **kw):
        ModelBuilder.__init__(
            self, params or ExtendedIsolationForestParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> ExtendedIsolationForestModel:
        p: ExtendedIsolationForestParameters = self.params
        rng = np.random.default_rng(p.effective_seed())
        model = ExtendedIsolationForestModel(
            job.dest_key or dkv.make_key(self.algo), p, di)
        X = model._design(frame)
        N, Fn = X.shape
        ext = min(p.extension_level, Fn - 1)
        depth = p.max_depth
        trees: List[_EITree] = []
        for t in range(p.ntrees):
            mask, size = self._sample_mask(N, frame.nrows, p.sample_size, rng)
            leaf = jnp.zeros(N, jnp.int32)
            Xz = jnp.nan_to_num(X)
            norm_l, off_l, val_l = [], [], []
            for d in range(depth):
                L = 2 ** d
                # bounding box per (leaf, feature) for intercept sampling
                active = (mask > 0)
                big = jnp.float32(3.4e38)
                Xa = jnp.where(active[:, None], Xz, big)
                Xb = jnp.where(active[:, None], Xz, -big)
                mn = np.asarray(jax.ops.segment_min(Xa, leaf, num_segments=L),
                                np.float64)
                mx = np.asarray(jax.ops.segment_max(Xb, leaf, num_segments=L),
                                np.float64)
                cnt = np.asarray(jax.ops.segment_sum(
                    mask, leaf, num_segments=L), np.float64)
                valid = (cnt > 1) & (mx > mn).any(axis=1)
                occupied = cnt[:, None] > 0          # empty leaves hold ±big
                mn = np.where(occupied, mn, 0.0)
                mx = np.where(occupied, np.maximum(mx, mn), 0.0)
                # random hyperplane with ext+1 nonzero components
                nm = rng.normal(size=(L, Fn))
                if ext + 1 < Fn:
                    for i in range(L):
                        keep = rng.choice(Fn, size=ext + 1, replace=False)
                        z = np.ones(Fn, bool)
                        z[keep] = False
                        nm[i, z] = 0.0
                nm /= np.maximum(np.linalg.norm(nm, axis=1, keepdims=True),
                                 1e-12)
                pt = mn + rng.random((L, Fn)) * np.maximum(mx - mn, 0.0)
                off = np.sum(nm * pt, axis=1)
                nmj = jnp.asarray(nm, jnp.float32)
                offj = jnp.asarray(off, jnp.float32)
                vj = jnp.asarray(valid)
                proj = jnp.sum(Xz * nmj[leaf], axis=1)
                right = (proj >= offj[leaf]) & vj[leaf]
                leaf = 2 * leaf + right.astype(jnp.int32)
                norm_l.append(nm.astype(np.float32))
                off_l.append(off.astype(np.float32))
                val_l.append(valid)
            Lfin = 2 ** depth
            cnt = np.asarray(jax.ops.segment_sum(mask, leaf,
                                                 num_segments=Lfin), np.float64)
            depths = _termination_depths(val_l, depth)
            pl = depths + np.array([_avg_path_length(int(c)) for c in cnt])
            trees.append(_EITree(norm_l, off_l, val_l, pl.astype(np.float32)))
            job.update((t + 1) / p.ntrees, f"eitree {t + 1}/{p.ntrees}")

        model.output.update({
            "trees": trees, "ntrees_trained": len(trees),
            "c_norm": _avg_path_length(min(p.sample_size, frame.nrows)),
        })
        score = model.predict(frame).vecs[0].to_numpy()
        model.training_metrics = {"mean_score": float(np.mean(score))}
        return model
