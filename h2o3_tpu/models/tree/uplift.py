"""Uplift DRF: treatment-effect forests on the tpu_hist kernels.

Reference: ``hex/tree/uplift/UpliftDRF.java`` + the uplift histogram columns
in ``hex/tree/DHistogram.java:80-85`` (per-bin response sums split by the
treatment flag) and the ``Divergence`` criteria (KL, Euclidean,
ChiSquared).  Prediction = p(y=1|treated) - p(y=1|control) per leaf,
averaged over the forest; quality is AUUC (qini) over the uplift ranking.

TPU-native redesign: the treatment/control histograms are TWO passes of the
same tpu_hist kernel with masked stat planes ((y*t, t, w*t) and the control
complement) — no new kernel; the divergence split search is a fused jnp
pass with the same cumulative-prefix structure as best_splits.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...frame.vec import T_CAT
from ...runtime import dkv
from ...runtime.job import Job
from ..base import Model, ModelBuilder
from ..datainfo import DataInfo
from .binning import fit_bins, edges_matrix
from .hist import (make_batched_level_fn, make_batched_sparse_level_fn,
                   make_hist_fn, make_sparse_level_fn,
                   make_subtract_level_fn, partition, partition_right,
                   sparse_slot_budget, sparse_slot_maps, table_lookup)
from .shared import (SharedTreeModel, SharedTree, SharedTreeParameters,
                     StackedTrees, Tree, TreeList, dense_mem_cap,
                     traverse_jit)

_EPS = 1e-6


@dataclasses.dataclass
class UpliftDRFParameters(SharedTreeParameters):
    treatment_column: str = ""
    uplift_metric: str = "KL"            # KL | euclidean | chi_squared
    ntrees: int = 50
    max_depth: int = 10
    min_rows: float = 10.0
    sample_rate: float = 0.632
    mtries: int = -2                     # all features by default


def _divergence(pt, pc, metric: str):
    pt = jnp.clip(pt, _EPS, 1 - _EPS)
    pc = jnp.clip(pc, _EPS, 1 - _EPS)
    if metric == "KL":
        return pt * jnp.log(pt / pc) + (1 - pt) * jnp.log((1 - pt)
                                                          / (1 - pc))
    if metric == "euclidean":
        return (pt - pc) ** 2 + ((1 - pt) - (1 - pc)) ** 2
    if metric == "chi_squared":
        return (pt - pc) ** 2 / pc + ((1 - pt) - (1 - pc)) ** 2 / (1 - pc)
    raise ValueError(f"unknown uplift_metric {metric!r}")


def _uplift_best_splits(Ht, Hc, nbins: int, metric: str, min_rows: float,
                        feat_mask=None):
    """Best divergence-gain split per leaf.

    ``Ht``/``Hc``: [3, L, F, B] with planes (sum w*y, sum w, sum w) for the
    treatment / control subsets (B includes the NA bin; NA routes left).
    Gain = weighted child divergence - parent divergence
    (UpliftDRF's Divergence.value).
    """
    y1t, nt = Ht[0], Ht[1]
    y1c, ncn = Hc[0], Hc[1]
    # fold the NA bin into bin 0 (NA goes left always)
    def fold(a):
        return a[..., :-1].at[..., 0].add(a[..., -1])
    y1t, nt, y1c, ncn = fold(y1t), fold(nt), fold(y1c), fold(ncn)
    cy1t, cnt = jnp.cumsum(y1t, -1), jnp.cumsum(nt, -1)
    cy1c, cnc = jnp.cumsum(y1c, -1), jnp.cumsum(ncn, -1)
    tot_y1t, tot_nt = cy1t[..., -1], cnt[..., -1]          # [L, F]
    tot_y1c, tot_nc = cy1c[..., -1], cnc[..., -1]
    n_tot = tot_nt + tot_nc
    d_parent = _divergence(tot_y1t / jnp.maximum(tot_nt, _EPS),
                           tot_y1c / jnp.maximum(tot_nc, _EPS), metric)

    # split after bin b: left = bins <= b (b in [0, nbins-2])
    ly1t, lnt = cy1t[..., :-1], cnt[..., :-1]
    ly1c, lnc = cy1c[..., :-1], cnc[..., :-1]
    ry1t, rnt = tot_y1t[..., None] - ly1t, tot_nt[..., None] - lnt
    ry1c, rnc = tot_y1c[..., None] - ly1c, tot_nc[..., None] - lnc
    dl = _divergence(ly1t / jnp.maximum(lnt, _EPS),
                     ly1c / jnp.maximum(lnc, _EPS), metric)
    dr = _divergence(ry1t / jnp.maximum(rnt, _EPS),
                     ry1c / jnp.maximum(rnc, _EPS), metric)
    nl = lnt + lnc
    nr = rnt + rnc
    gain = (nl * dl + nr * dr) / jnp.maximum(n_tot[..., None], _EPS) \
        - d_parent[..., None]
    ok = (nl >= min_rows) & (nr >= min_rows) & (lnt > 0) & (lnc > 0) \
        & (rnt > 0) & (rnc > 0)
    gain = jnp.where(ok, gain, -jnp.inf)
    if feat_mask is not None:
        m = feat_mask if feat_mask.ndim == 2 else feat_mask[None, :]
        gain = jnp.where(m[..., None], gain, -jnp.inf)

    L, F = d_parent.shape
    flat = gain.reshape(L, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    feat = (best // (nbins - 1)).astype(jnp.int32)
    bin_ = (best % (nbins - 1)).astype(jnp.int32)
    valid = jnp.isfinite(best_gain) & (best_gain > 0)
    return feat, bin_, valid, best_gain


class UpliftDRFModel(SharedTreeModel):
    algo = "upliftdrf"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        T = self.output["ntrees_trained"]
        st_t: StackedTrees = self.output["stacked_pt"]
        st_c: StackedTrees = self.output["stacked_pc"]
        pt = traverse_jit(st_t.levels, st_t.values, X) / max(T, 1)
        pc = traverse_jit(st_c.levels, st_c.values, X) / max(T, 1)
        return jnp.stack([pt - pc, pt, pc], axis=1)

    def predict(self, frame: Frame) -> Frame:
        from ...frame.vec import Vec, T_NUM
        raw = np.asarray(self._predict_raw(self._score_matrix(frame)))
        raw = raw[: frame.nrows]
        return Frame(["uplift_predict", "p_y1_ct1", "p_y1_ct0"],
                     [Vec.from_numpy(raw[:, j], T_NUM) for j in range(3)])

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        from ...metrics.uplift import uplift_metrics
        p = self.params
        pred = np.asarray(self._predict_raw(
            self._score_matrix(frame)))[: frame.nrows, 0]
        y = np.asarray(self.datainfo.response(frame))[: frame.nrows]
        t = frame.vec(p.treatment_column)
        treat = np.asarray(t.to_numpy(), np.float64)
        return uplift_metrics(pred, y, treat)


class UpliftDRF(SharedTree):
    """Treatment-effect forest — hex/tree/uplift/UpliftDRF analog."""

    algo = "upliftdrf"
    model_class = UpliftDRFModel
    _force_classification = True

    def __init__(self, params: Optional[UpliftDRFParameters] = None, **kw):
        super().__init__(params or UpliftDRFParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        if not p.treatment_column:
            raise ValueError("upliftdrf requires treatment_column")
        return DataInfo.fit(
            frame, response_column=p.response_column,
            ignored_columns=tuple(p.ignored_columns)
            + (p.treatment_column,),
            weights_column=p.weights_column, standardize=False,
            missing_values_handling="mean_imputation",
            force_classification=True)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> UpliftDRFModel:
        p: UpliftDRFParameters = self.params
        y = jnp.nan_to_num(di.response(frame))
        w = di.weights(frame)
        tvec = frame.vec(p.treatment_column)
        if tvec.type == T_CAT:
            treat = (tvec.data == (len(tvec.domain) - 1)) \
                .astype(jnp.float32)
        else:
            treat = (jnp.nan_to_num(tvec.data) > 0).astype(jnp.float32)
        binned = fit_bins(frame, [s.name for s in di.specs], nbins=p.nbins,
                          histogram_type=p.histogram_type,
                          seed=p.effective_seed())
        codes = binned.codes
        edges_mat = jnp.asarray(edges_matrix(binned.edges, p.nbins),
                                jnp.float32)
        F, N = codes.shape
        B = p.nbins + 1
        rng = jax.random.PRNGKey(p.effective_seed())
        # Treatment/control histograms ride the shared subtraction level
        # driver: the two stat triples share one leaf assignment, so each
        # level compacts the smaller siblings twice (once per arm) and
        # reconstructs the larger arm histograms from the per-shard parent
        # carries — the same <= N/2 row stream as GBM/DRF.  hist_mode="full"
        # keeps the oracle (the old always-full build); "check" grows the
        # first tree both ways and asserts identical splits.  "auto"
        # knobs route through the cost-model autotuner (K=2: the two
        # arms ride the batched level program as the class axis)
        from ...runtime import autotune
        knobs = autotune.resolve_tree_knobs(p, kind=self.algo, F=F, N=N,
                                            K=2)
        autotune.activate(knobs)
        if knobs.sparse_depth_threshold != p.sparse_depth_threshold:
            p = dataclasses.replace(
                p, sparse_depth_threshold=knobs.sparse_depth_threshold)
        hist_mode = knobs.hist_mode
        level_fns = [make_subtract_level_fn(d, F, B, N)
                     for d in range(p.max_depth)] \
            if hist_mode in ("subtract", "check") else None
        full_fns = [make_hist_fn(2 ** d, F, B, N)
                    for d in range(p.max_depth)] \
            if hist_mode in ("full", "check") else None
        # split_mode="fused": the two arms ride the batched level program
        # as the K axis (K=2, shared leaf routing, per-arm stat planes) —
        # one hist launch per level instead of two; the divergence split
        # search itself stays _uplift_best_splits.  "check" grows the
        # first tree both ways and asserts, then trains batched.
        split_mode = knobs.split_mode
        bfns = [make_batched_level_fn(
                    d, 2, F, B, N, subtract=(hist_mode != "full"))
                for d in range(p.max_depth)] \
            if split_mode != "separate" else None
        # hist_layout="sparse": levels at/below the clamped threshold key
        # histograms by ALIVE-leaf slots [A, F, B] instead of the dense
        # [2^d, F, B] grid (both arms share one slot map — the leaf
        # assignment is shared).  "check" grows the first tree both ways.
        hist_layout = knobs.hist_layout
        # tree_program: uplift's bespoke two-arm grow_tree loop has no
        # scan-fused build (its divergence split search interleaves both
        # treatment arms between levels), so any scan request silently
        # rides the per-level program.  The tuner never tunes the knob
        # for kind="uplift"; this covers an explicit tree_program="scan".
        tree_program = "level"
        if hist_layout == "check" and (hist_mode == "check"
                                       or split_mode == "check"):
            raise ValueError(
                "hist_layout='check' needs a resolved hist_mode/split_mode "
                "(run one crosscheck at a time)")
        t0 = max(1, min(p.sparse_depth_threshold, dense_mem_cap(p.nbins, F)))
        sparse_from0 = t0 if (hist_layout in ("sparse", "check")
                              and p.max_depth > t0) else p.max_depth
        A_cap = sparse_slot_budget(F, B)
        A_lv = {d: min(2 ** d, A_cap)
                for d in range(sparse_from0, p.max_depth)}
        Ap_lv = {d: (2 ** (d - 1) if d == sparse_from0 else A_lv[d - 1])
                 for d in range(sparse_from0, p.max_depth)}
        sparse_fns = {d: make_sparse_level_fn(Ap_lv[d], A_lv[d], F, B, N)
                      for d in range(sparse_from0, p.max_depth)}
        sparse_bfns = {d: make_batched_sparse_level_fn(
                           Ap_lv[d], A_lv[d], 2, F, B, N)
                       for d in range(sparse_from0, p.max_depth)} \
            if split_mode != "separate" else None

        def _slot_maps(d, prev_valid, slot_of_leaf, leaf_of_slot):
            # slot assignment + dense<->slot index maps for sparse level d
            # (shared.make_build_tree_fn's helper, at uplift's geometry)
            A = A_lv[d]
            sidx = jnp.arange(A, dtype=jnp.int32)
            child_base, ps_of_slot, real = sparse_slot_maps(prev_valid, A)
            l2 = jnp.arange(2 ** d, dtype=jnp.int32)
            if d == sparse_from0:
                sol = jnp.minimum(child_base[l2 >> 1] + (l2 & 1), A)
                los = 2 * ps_of_slot + (sidx & 1)
            else:
                sol = jnp.minimum(child_base[slot_of_leaf[l2 >> 1]]
                                  + (l2 & 1), A)
                los = 2 * leaf_of_slot[ps_of_slot] + (sidx & 1)
            return child_base, ps_of_slot, real, sol, los

        def _sleaf_of_leaf(slot_of_leaf, leaf, L):
            # boundary only: dense leaf id -> slot id, one MXU lookup
            return table_lookup(slot_of_leaf[None].astype(jnp.float32),
                                leaf, L)[0].astype(jnp.int32)

        def _pad_slot_tables(feat_s, bin_s, na_s, valid_s):
            # sentinel row (slot A): valid=False -> dead rows flow left
            def z(a):
                return jnp.concatenate([a, jnp.zeros((1,), a.dtype)])
            return z(feat_s), z(bin_s), z(na_s), z(valid_s)

        col_rate = 1.0 if p.mtries == -2 else \
            max(min(p.mtries if p.mtries > 0 else int(np.sqrt(F)), F), 1) / F

        @jax.jit
        def leaf_stats(leaf, wv):
            nseg = 2 ** p.max_depth
            y1t = jax.ops.segment_sum(wv * y * treat, leaf,
                                      num_segments=nseg)
            nt = jax.ops.segment_sum(wv * treat, leaf, num_segments=nseg)
            y1c = jax.ops.segment_sum(wv * y * (1 - treat), leaf,
                                      num_segments=nseg)
            nc = jax.ops.segment_sum(wv * (1 - treat), leaf,
                                     num_segments=nseg)
            pt = jnp.where(nt > 0, y1t / jnp.maximum(nt, _EPS), 0.0)
            pc = jnp.where(nc > 0, y1c / jnp.maximum(nc, _EPS), 0.0)
            return pt.astype(jnp.float32), pc.astype(jnp.float32)

        def grow_tree(wv, keys, mode, batched=False, layout="dense"):
            """One uplift tree's level loop under the given hist_mode."""
            leaf = jnp.zeros(N, jnp.int32)
            levels = []
            # terminality invariant (see shared.make_build_tree_fn): a dead
            # node's descendants stay dead — required by the node-sparse
            # exporters AND by the sparse layout (dead chains get no slots)
            alive = jnp.ones((1,), bool)
            gt, nt = wv * y * treat, wv * treat
            gc, nc = wv * y * (1 - treat), wv * (1 - treat)
            if batched:
                gA, nA = jnp.stack([gt, gc]), jnp.stack([nt, nc])
            sparse_from = sparse_from0 if (layout == "sparse"
                                           and mode == "subtract") \
                else p.max_depth
            Ht_carry = Hc_carry = HA_carry = None
            valid = valid_s = slot_of_leaf = leaf_of_slot = None
            sleaf = right = None
            for d in range(p.max_depth):
                L = 2 ** d
                mask = jax.random.uniform(keys[d], (L, F)) < col_rate
                mask = mask.at[:, 0].set(mask[:, 0] | ~mask.any(axis=1))
                if d >= sparse_from:
                    A = A_lv[d]
                    if d == sparse_from:
                        # boundary: slots from the last DENSE level's valid
                        # flags; the dense subtract carry is consumed
                        # unchanged (its slot space = dense parent space)
                        (child_base, ps_of_slot, real, slot_of_leaf,
                         leaf_of_slot) = _slot_maps(d, valid, None, None)
                        sleaf = _sleaf_of_leaf(slot_of_leaf, leaf, L)
                    else:
                        (child_base, ps_of_slot, real, slot_of_leaf,
                         leaf_of_slot) = _slot_maps(d, valid_s,
                                                    slot_of_leaf,
                                                    leaf_of_slot)
                        sleaf = jnp.minimum(jnp.take(child_base, sleaf)
                                            + right, A)
                    if batched:
                        # both arms share the slot map (shared leaf
                        # assignment) — one launch covers both
                        sleafA = jnp.broadcast_to(sleaf, (2, N))
                        psA = jnp.broadcast_to(ps_of_slot, (2, A))
                        HA, HA_carry = sparse_bfns[d](codes, sleafA, gA,
                                                      nA, nA, HA_carry,
                                                      psA)
                        Ht, Hc = HA[0], HA[1]
                    else:
                        Ht, Ht_carry = sparse_fns[d](codes, sleaf, gt, nt,
                                                     nt, Ht_carry,
                                                     ps_of_slot)
                        Hc, Hc_carry = sparse_fns[d](codes, sleaf, gc, nc,
                                                     nc, Hc_carry,
                                                     ps_of_slot)
                    # col mask DRAWN dense (bit-identical RNG to the dense
                    # layout), gathered to slots
                    mask_s = mask[leaf_of_slot]
                    feat_s, bin_s, valid_s, gain = _uplift_best_splits(
                        Ht, Hc, p.nbins, p.uplift_metric, p.min_rows,
                        mask_s)
                    # phantom slots past the live range carry no rows
                    valid_s = valid_s & real
                    na_s = jnp.ones_like(valid_s)
                    # expand slot records to the dense [2^d] level contract
                    mapped = slot_of_leaf < A
                    slc = jnp.minimum(slot_of_leaf, A - 1)
                    feat = jnp.where(mapped, feat_s[slc], 0)
                    bin_ = jnp.where(mapped, bin_s[slc], 0)
                    valid = mapped & valid_s[slc]
                    na_left = jnp.ones_like(valid)
                    thr = edges_mat[feat, jnp.clip(bin_, 0, p.nbins - 1)]
                    fp, bp, nap, vp = _pad_slot_tables(feat_s, bin_s,
                                                       na_s, valid_s)
                    right = partition_right(codes, sleaf, fp, bp, nap, vp,
                                            jnp.int32(p.nbins))
                    leaf = 2 * leaf + right
                    levels.append((feat, thr, na_left, valid))
                    continue
                if batched:
                    # both arms in ONE launch per level: arm = batched-K
                    # axis; the shared leaf broadcasts, so both arms pick
                    # identical smaller-sibling compactions
                    leafA = jnp.broadcast_to(leaf, (2, N))
                    if mode == "subtract":
                        if d == 0:
                            HA, HA_carry = bfns[0](codes, leafA, gA, nA,
                                                   nA)
                        else:
                            HA, HA_carry = bfns[d](codes, leafA, gA, nA,
                                                   nA, HA_carry)
                    else:
                        HA = bfns[d](codes, leafA, gA, nA, nA)
                    Ht, Hc = HA[0], HA[1]
                elif mode == "subtract":
                    if d == 0:
                        Ht, Ht_carry = level_fns[0](codes, leaf, gt, nt, nt)
                        Hc, Hc_carry = level_fns[0](codes, leaf, gc, nc, nc)
                    else:
                        Ht, Ht_carry = level_fns[d](codes, leaf, gt, nt, nt,
                                                    Ht_carry)
                        Hc, Hc_carry = level_fns[d](codes, leaf, gc, nc, nc,
                                                    Hc_carry)
                else:
                    Ht = full_fns[d](codes, leaf, gt, nt, nt)
                    Hc = full_fns[d](codes, leaf, gc, nc, nc)
                feat, bin_, valid, gain = _uplift_best_splits(
                    Ht, Hc, p.nbins, p.uplift_metric, p.min_rows, mask)
                valid = valid & alive
                alive = jnp.stack([valid, valid], axis=1).reshape(-1)
                na_left = jnp.ones_like(valid)
                thr = edges_mat[feat, jnp.clip(bin_, 0, p.nbins - 1)]
                leaf = partition(codes, leaf, feat, bin_, na_left, valid,
                                 jnp.int32(p.nbins))
                levels.append((feat, thr, na_left, valid))
            return levels, leaf

        trees_t: List[Tree] = []
        trees_c: List[Tree] = []
        from ...runtime import failure
        for t_i in range(p.ntrees):
            rng, ks, km = jax.random.split(rng, 3)
            wv = w
            if p.sample_rate < 1.0:
                wv = w * jax.random.bernoulli(ks, p.sample_rate, w.shape)
            keys = jax.random.split(km, p.max_depth)
            hm = "full" if hist_mode == "full" else "subtract"
            if sparse_from0 < p.max_depth:
                # kill/resume while node-sparse deep levels are live
                failure.maybe_inject("deep_level")
            if hist_layout == "check" and t_i == 0:
                # driver assert: dense and node-sparse layouts must grow
                # the same first tree (valid + routing exact; feat/thr
                # compared where valid — dense keeps candidate records on
                # dead slots, sparse drops the rows)
                lv_sp, leaf_sp = grow_tree(
                    wv, keys, hm, batched=(split_mode == "fused"),
                    layout="sparse")
                lv_d, leaf_d = grow_tree(
                    wv, keys, hm, batched=(split_mode == "fused"))
                host = jax.device_get([lv_sp, leaf_sp, lv_d, leaf_d])
                for d, (a, b) in enumerate(zip(host[0], host[2])):
                    va, vb = np.asarray(a[3]), np.asarray(b[3])
                    if not np.array_equal(va, vb):
                        raise AssertionError(
                            f"hist_layout='check': uplift dense and sparse "
                            f"layouts disagree on valid at level {d}")
                    for i, nm in ((0, "feat"), (1, "thr")):
                        if not np.allclose(np.where(va, a[i], 0),
                                           np.where(vb, b[i], 0)):
                            raise AssertionError(
                                f"hist_layout='check': uplift dense and "
                                f"sparse layouts disagree on {nm} at "
                                f"level {d}")
                if not np.array_equal(host[1], host[3]):
                    raise AssertionError(
                        "hist_layout='check': uplift final leaf routing "
                        "differs between the dense and sparse layouts")
                hist_layout = "sparse"
                levels, leaf = lv_sp, leaf_sp
            elif hist_mode == "check" and t_i == 0:
                # driver assert: first tree grown both ways must agree
                lv_s, leaf_s = grow_tree(wv, keys, "subtract")
                lv_f, leaf_f = grow_tree(wv, keys, "full")
                host = jax.device_get([lv_s, leaf_s, lv_f, leaf_f])
                for d, (a, b) in enumerate(zip(host[0], host[2])):
                    for i, nm in ((0, "feat"), (1, "thr"), (3, "valid")):
                        if not np.allclose(a[i], b[i]):
                            raise AssertionError(
                                f"hist_mode='check': uplift subtraction "
                                f"and full builds disagree on {nm} at "
                                f"level {d}")
                if not np.array_equal(host[1], host[3]):
                    raise AssertionError(
                        "hist_mode='check': uplift final leaf routing "
                        "differs between histogram builds")
                levels, leaf = lv_s, leaf_s
            elif split_mode == "check" and t_i == 0:
                # driver assert: the batched two-arm level program must
                # grow the same first tree as the two-call-per-level path
                lv_b, leaf_b = grow_tree(wv, keys, hm, batched=True)
                lv_s, leaf_s = grow_tree(wv, keys, hm)
                host = jax.device_get([lv_b, leaf_b, lv_s, leaf_s])
                for d, (a, b) in enumerate(zip(host[0], host[2])):
                    for i, nm in ((0, "feat"), (1, "thr"), (3, "valid")):
                        if not np.allclose(a[i], b[i]):
                            raise AssertionError(
                                f"split_mode='check': uplift batched and "
                                f"separate level builds disagree on {nm} "
                                f"at level {d}")
                if not np.array_equal(host[1], host[3]):
                    raise AssertionError(
                        "split_mode='check': uplift final leaf routing "
                        "differs between the batched and separate builds")
                split_mode = "fused"
                levels, leaf = lv_b, leaf_b
            else:
                levels, leaf = grow_tree(
                    wv, keys, hm, batched=(split_mode == "fused"),
                    layout=("sparse" if hist_layout == "sparse"
                            else "dense"))
            pt_vals, pc_vals = leaf_stats(leaf, wv)
            lv = [tuple(x) if not isinstance(x, tuple) else x
                  for x in levels]
            trees_t.append(Tree([x[0] for x in lv], [x[1] for x in lv],
                                [x[2] for x in lv], [x[3] for x in lv],
                                pt_vals))
            trees_c.append(Tree([x[0] for x in lv], [x[1] for x in lv],
                                [x[2] for x in lv], [x[3] for x in lv],
                                pc_vals))
            job.update((t_i + 1) / p.ntrees, f"tree {t_i + 1}/{p.ntrees}")

        model = UpliftDRFModel(job.dest_key or dkv.make_key(self.algo),
                               p, di)
        model.output["stacked_pt"] = StackedTrees.from_trees(trees_t)
        model.output["stacked_pc"] = StackedTrees.from_trees(trees_c)
        model.output["trees"] = TreeList(model.output["stacked_pt"])
        model.output["ntrees_trained"] = p.ntrees
        model.output["edges"] = binned.edges
        model.output["init_score"] = 0.0
        model.output["nclass_trees"] = 1
        model.output["hist_layout"] = hist_layout
        model.output["tree_program"] = tree_program

        from ...metrics.uplift import uplift_metrics
        X = model._design(frame)
        pred = np.asarray(model._predict_raw(X))[: frame.nrows, 0]
        model.training_metrics = uplift_metrics(
            pred, np.asarray(y)[: frame.nrows],
            np.asarray(treat)[: frame.nrows])
        return model
