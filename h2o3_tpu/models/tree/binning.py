"""Quantile binning: the feature-discretization prepass for histogram trees.

Reference: ``hex/tree/DHistogram.java:48`` computes per-column min/max and
bins on the fly per node; XGBoost's ``hist``/``gpu_hist`` (the perf target,
h2o-extensions/xgboost) instead quantile-sketches each feature ONCE and
trains on small integer bin codes.  The TPU design follows the sketch
approach: static shapes, int codes, all histogram work becomes dense matmuls.

Layout: each feature gets ``nbins`` regular bins; bin ``nbins`` is reserved
for NA (the missing bucket).  Categorical codes are their own bins (capped at
``nbins``, the reference's nbins_cats analog).  Edges are float32 split
thresholds usable directly at prediction time.

Perf note (round 4, measured on chip): the original host-loop sketch cost
16.9 s on the 10M x 8 bench shape — five per-feature tunnel fetches plus
eight separately-compiled searchsorted dispatches, each charged the
remote backend's first-execution penalty.  It is now TWO cached compiled
programs: one masked-sort sketch over all numeric columns (device sort is
4.5 ms/column on chip), one encode pass over all features; the only
device->host traffic is the small [C, nbins-1] edge matrix.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...frame.vec import T_CAT


@dataclasses.dataclass
class BinnedFrame:
    """Device-resident binned design block + host-side bin metadata.

    Codes are FEATURE-MAJOR [F, padded_rows]: rows in the lane dimension.
    A row-major [N, F] block would tile-pad F up to 128 lanes (16x HBM blowup
    for narrow tabular data); feature-major keeps the hot array dense.
    """

    codes: jax.Array            # [F, padded_rows] int32 bin codes
    edges: List[np.ndarray]     # per-feature ascending split thresholds
    names: List[str]            # feature column names
    is_cat: List[bool]
    cat_domains: List[Optional[List[str]]]
    nbins: int                  # regular bins; code == nbins means NA

    @property
    def nfeatures(self) -> int:
        return len(self.names)

    @property
    def na_bin(self) -> int:
        return self.nbins

    @property
    def bin_counts(self) -> tuple:
        """Per-feature count of bins actually in use (codes < this;
        DHistogram's per-column bin sizing).  Cats: min(card, nbins);
        numerics: len(edges)+1 regions."""
        out = []
        for e, cat, dom in zip(self.edges, self.is_cat, self.cat_domains):
            if cat:
                out.append(max(min(len(dom or []) or 1, self.nbins), 1))
            else:
                out.append(min(len(e) + 1, self.nbins))
        return tuple(out)


@functools.lru_cache(maxsize=None)
def _make_sketch_fn(n: int, padded: int, ncols: int, nq: int):
    """One compiled program: exact masked quantiles + min/max for a stacked
    [C, padded] block of numeric columns.

    Rows beyond ``n``, non-finite values, and rows with weight <= 0 are
    masked to +inf before an ascending device sort; quantile k then linearly
    interpolates positions q_k * (m_c - 1) within each column's m_c valid
    rows (numpy's default interpolation, so edges match the old host
    np.quantile sketch on unweighted data).
    """

    def sketch(X, w):
        iota = jax.lax.broadcasted_iota(jnp.int32, (ncols, padded), 1)
        valid = (iota < n) & jnp.isfinite(X) & (w[None, :] > 0)
        m = jnp.sum(valid, axis=1)                       # [C] valid counts
        Xm = jnp.where(valid, X, jnp.inf)
        Xs = jnp.sort(Xm, axis=1)                        # invalid -> tail
        lo = jnp.min(jnp.where(valid, X, jnp.inf), axis=1)
        hi = jnp.max(jnp.where(valid, X, -jnp.inf), axis=1)
        qs = jnp.arange(1, nq + 1, dtype=jnp.float32) / (nq + 1)
        pos = qs[None, :] * jnp.maximum(m[:, None] - 1, 0)   # [C, nq]
        p0 = jnp.floor(pos).astype(jnp.int32)
        frac = pos - p0
        v0 = jnp.take_along_axis(Xs, p0, axis=1)
        v1 = jnp.take_along_axis(
            Xs, jnp.minimum(p0 + 1, jnp.maximum(m[:, None] - 1, 0)), axis=1)
        edges = v0 * (1 - frac) + v1 * frac
        return edges, lo, hi, m

    return jax.jit(sketch)


@functools.lru_cache(maxsize=None)
def _make_encode_fn(padded: int, ecounts: tuple, is_cat: tuple,
                    nbins: int):
    """One compiled program encoding all features to bin codes.

    Numerics: blocked compare-count (== searchsorted side="right") against
    +inf-padded edge rows, clipped to each feature's edge count; NaN -> the
    NA bin.  Cats: code as bin, clamped to ``nbins - 1``; negative (NA
    sentinel) or NaN -> NA bin.

    Features are processed in GROUPS (all cats at once; numerics bucketed
    by edge width), not per-feature: the per-feature unrolled program
    compiled in O(F) (23 s at 481 columns, minutes at springleaf's ~1,900)
    while the grouped one stays O(log emax) programs with one static
    row-permutation gather at the end.
    """
    F = len(is_cat)
    cat_idx = [f for f in range(F) if is_cat[f]]
    num_idx = [f for f in range(F) if not is_cat[f]]
    emax = max([1] + [ecounts[f] for f in num_idx])
    groups: dict = {}
    for f in num_idx:
        w = 1
        while w < max(ecounts[f], 1):
            w *= 4
        groups.setdefault(min(w, emax), []).append(f)
    order = list(cat_idx) + [f for w in sorted(groups) for f in groups[w]]
    iperm = np.argsort(np.asarray(order, np.int64)).astype(np.int32)
    counts_np = np.asarray(ecounts, np.int32)

    def encode(X, E):
        pieces = []
        if cat_idx:
            Xc = X[jnp.asarray(cat_idx)]
            xi = jnp.where(jnp.isnan(Xc), -1.0, Xc).astype(jnp.int32)
            pieces.append(jnp.where(xi < 0, nbins,
                                    jnp.minimum(xi, nbins - 1)))
        for w in sorted(groups):
            idx = groups[w]
            Cg = len(idx)
            Xg = X[jnp.asarray(idx)]
            Eg = E[jnp.asarray(idx), :w]                  # [Cg, w]
            blk = int(min(padded,
                          max(1024, 67_108_864 // max(Cg * w, 1))))
            nblk = -(-padded // blk)
            pad = nblk * blk - padded
            Xb = jnp.pad(Xg, [(0, 0), (0, pad)]) \
                .reshape(Cg, nblk, blk).transpose(1, 0, 2)

            def body(_, xr, _Eg=Eg):
                # fused broadcast-compare + reduce (never materializes
                # [Cg, w, blk]); side="right" == count of edges <= x
                cb = jnp.sum(xr[:, None, :] >= _Eg[:, :, None],
                             axis=1, dtype=jnp.int32)
                return _, cb

            _, cb = jax.lax.scan(body, None, Xb)          # [nblk, Cg, blk]
            c = cb.transpose(1, 0, 2).reshape(Cg, -1)[:, :padded]
            # +inf rows also count the +inf edge PADDING — clip to the
            # feature's own edge count
            c = jnp.minimum(c, jnp.asarray(counts_np[idx])[:, None])
            pieces.append(jnp.where(jnp.isnan(Xg), nbins, c))
        out = pieces[0] if len(pieces) == 1 \
            else jnp.concatenate(pieces, axis=0)
        return out[jnp.asarray(iperm)].astype(jnp.int32)

    return jax.jit(encode)


def fit_bins(frame: Frame, features: List[str], nbins: int = 64,
             sample: int = 1_000_000, seed: int = 0,
             weights=None,
             histogram_type: str = "quantiles_global") -> BinnedFrame:
    """Sketch each feature's bin edges and encode the frame as bin codes.

    ``histogram_type`` (SharedTree histogram_type analog, hex/tree
    DHistogram): "quantiles_global" (default; XGBoost's approx sketch),
    "uniform_adaptive" (equal-width over the observed range) or
    "random" (uniform-random split points; drawn ONCE per model — the
    frame is encoded a single time, so unlike the reference's per-tree
    redraw, ensembles share these edges; vary ``seed`` for diversity
    across models).  Quantiles are EXACT over all weight>0 rows while the
    numeric stack fits a ~2 GB device budget (a device sort costs less
    than the old 1M-row host sample did in transfer); beyond that a
    strided ``sample``-row device subsample bounds memory.  ``weights``
    (host or device [>=nrows]) restricts the sketch to rows with
    weight > 0 — keeps CV's zero-weight holdout rows out of the bin edges.
    """
    htype = histogram_type.lower().replace("_", "")
    if htype in ("auto", "quantilesglobal"):
        htype = "quantiles"
    elif htype == "uniformadaptive":
        htype = "uniform"
    elif htype != "random":
        raise ValueError(
            f"unknown histogram_type {histogram_type!r}: use "
            "QuantilesGlobal, UniformAdaptive or Random")
    rng = np.random.default_rng(seed)
    n = frame.nrows

    vecs = [frame.vec(name) for name in features]
    is_cat = [v.type == T_CAT for v in vecs]
    domains = [v.domain if c else None for v, c in zip(vecs, is_cat)]
    num_idx = [f for f, c in enumerate(is_cat) if not c]

    # --- sketch: one device program over the stacked numeric block.
    # Exact quantiles when the stack fits a device budget; above it, a
    # strided row subsample (the old host sketch's ``sample`` bound, kept
    # on device) caps sort memory — rows are unordered, so a stride is as
    # good a sample as a uniform draw.
    num_edges: dict = {}
    if num_idx:
        full_padded = int(vecs[num_idx[0]].data.shape[0])
        budget_rows = max(int(2e9) // (4 * len(num_idx)), sample)
        stride = 1 if full_padded <= budget_rows \
            else -(-full_padded // max(sample, 1))
        X = jnp.stack([vecs[f].data[::stride].astype(jnp.float32)
                       for f in num_idx], axis=0)
        padded = int(X.shape[1])
        n_eff = min(-(-n // stride), padded)
        if weights is not None:
            wv = jnp.asarray(weights, jnp.float32)[::stride]
            if wv.shape[0] < padded:
                wv = jnp.pad(wv, (0, padded - wv.shape[0]))
            wv = wv[:padded]
        else:
            wv = jnp.ones((padded,), jnp.float32)
        sk = _make_sketch_fn(n_eff, padded, len(num_idx), nbins - 1)
        edges_q, lo, hi, m = (np.asarray(a, np.float64) for a in
                              jax.device_get(sk(X, wv)))  # ONE batched fetch
        if weights is not None and stride > 1:
            # The strided subsample ran BEFORE the w>0 mask; when live rows
            # are rare or correlated with row order (stacked CV folds,
            # sorted frames) it can see few/zero live rows and a feature
            # silently gets degenerate edges.  Re-sketch from the live rows
            # when some column's valid count is far below what ITS OWN
            # finite population could supply — a mostly-NaN column with a
            # small count is expected and must not fire the re-sketch.
            iota_ok = jax.lax.broadcasted_iota(jnp.int32, X.shape, 1) < n_eff
            fin = np.asarray(jax.device_get(
                jnp.sum(jnp.isfinite(X) & iota_ok, axis=1)))
            wl = np.asarray(jax.device_get(jnp.asarray(weights)))[:n] > 0
            n_live = int(wl.sum())
            want = min(n_live, sample)
            starved = (m < np.maximum(want // 4, nbins)) & \
                (fin >= 2 * np.maximum(m, 1))
            if n_live and starved.any():
                idx = np.flatnonzero(wl)
                if len(idx) > sample:
                    idx = idx[:: -(-len(idx) // sample)]
                idx_d = jnp.asarray(idx, jnp.int32)
                X2 = jnp.stack([jnp.take(vecs[f].data, idx_d)
                                .astype(jnp.float32) for f in num_idx],
                               axis=0)
                sk2 = _make_sketch_fn(len(idx), len(idx), len(num_idx),
                                      nbins - 1)
                edges_q, lo, hi, m = (
                    np.asarray(a, np.float64) for a in jax.device_get(
                        sk2(X2, jnp.ones((len(idx),), jnp.float32))))
        for i, f in enumerate(num_idx):
            if m[i] == 0:
                e = np.zeros(0, dtype=np.float32)
            elif htype == "uniform":
                e = np.unique(np.linspace(lo[i], hi[i], nbins + 1)[1:-1]
                              .astype(np.float32))
            elif htype == "random":
                e = np.unique(np.sort(
                    rng.uniform(lo[i], hi[i], nbins - 1)).astype(np.float32))
            else:
                e = np.unique(edges_q[i].astype(np.float32))
                e = e[np.isfinite(e)]
            num_edges[f] = e

    edges_list = []
    for f, cat in enumerate(is_cat):
        if cat:
            card = vecs[f].cardinality
            edges_list.append(np.arange(
                0.5, min(card, nbins) - 0.5 + 1e-9, 1.0, dtype=np.float32))
        else:
            edges_list.append(num_edges[f])

    codes = encode_bins(frame, features, edges_list, is_cat, nbins)
    return BinnedFrame(codes=codes, edges=edges_list, names=list(features),
                       is_cat=is_cat, cat_domains=domains, nbins=nbins)


def edges_matrix(edges_list, nbins: int) -> np.ndarray:
    """Dense [F, nbins] threshold table for on-device split lookup.

    Row f holds feature f's edges, right-padded by repeating the last edge
    (short rows only matter for invalid splits, which traversal ignores).
    """
    F = len(edges_list)
    mat = np.zeros((F, nbins), np.float32)
    for f, e in enumerate(edges_list):
        if len(e):
            mat[f, : len(e)] = e
            mat[f, len(e):] = e[-1]
    return mat


def encode_bins(frame: Frame, features: List[str], edges_list, is_cat,
                nbins: int) -> jax.Array:
    """Encode columns as bin codes — ONE cached device program per
    geometry (padded length, feature count, edge width, cat pattern)."""
    vecs = [frame.vec(name) for name in features]
    X = jnp.stack([v.data.astype(jnp.float32) for v in vecs], axis=0)
    ecounts = tuple(len(e) for e in edges_list)
    # E width covers every NUMERIC group bucket (next pow-4 of the widest
    # numeric) AND every categorical edge row stored alongside
    emax = max([1] + [c for c, cat in zip(ecounts, is_cat) if not cat])
    w = 1
    while w < emax:
        w *= 4
    w = max(w, max(ecounts, default=1), 1)
    E = np.full((len(features), w), np.inf, np.float32)
    for f, e in enumerate(edges_list):
        E[f, : len(e)] = e
    enc = _make_encode_fn(int(X.shape[1]), ecounts,
                          tuple(bool(c) for c in is_cat), nbins)
    return enc(X, jnp.asarray(E))
