"""Quantile binning: the feature-discretization prepass for histogram trees.

Reference: ``hex/tree/DHistogram.java:48`` computes per-column min/max and
bins on the fly per node; XGBoost's ``hist``/``gpu_hist`` (the perf target,
h2o-extensions/xgboost) instead quantile-sketches each feature ONCE and
trains on small integer bin codes.  The TPU design follows the sketch
approach: static shapes, int codes, all histogram work becomes dense matmuls.

Layout: each feature gets ``nbins`` regular bins; bin ``nbins`` is reserved
for NA (the missing bucket).  Categorical codes are their own bins (capped at
``nbins``, the reference's nbins_cats analog).  Edges are float32 split
thresholds usable directly at prediction time.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...frame.vec import T_CAT


@dataclasses.dataclass
class BinnedFrame:
    """Device-resident binned design block + host-side bin metadata.

    Codes are FEATURE-MAJOR [F, padded_rows]: rows in the lane dimension.
    A row-major [N, F] block would tile-pad F up to 128 lanes (16x HBM blowup
    for narrow tabular data); feature-major keeps the hot array dense.
    """

    codes: jax.Array            # [F, padded_rows] int32 bin codes
    edges: List[np.ndarray]     # per-feature ascending split thresholds
    names: List[str]            # feature column names
    is_cat: List[bool]
    cat_domains: List[Optional[List[str]]]
    nbins: int                  # regular bins; code == nbins means NA

    @property
    def nfeatures(self) -> int:
        return len(self.names)

    @property
    def na_bin(self) -> int:
        return self.nbins

    @property
    def bin_counts(self) -> tuple:
        """Per-feature count of bins actually in use (codes < this;
        DHistogram's per-column bin sizing).  Cats: min(card, nbins);
        numerics: len(edges)+1 regions."""
        out = []
        for e, cat, dom in zip(self.edges, self.is_cat, self.cat_domains):
            if cat:
                out.append(max(min(len(dom or []) or 1, self.nbins), 1))
            else:
                out.append(min(len(e) + 1, self.nbins))
        return tuple(out)


def fit_bins(frame: Frame, features: List[str], nbins: int = 64,
             sample: int = 1_000_000, seed: int = 0,
             weights=None,
             histogram_type: str = "quantiles_global") -> BinnedFrame:
    """Sketch each feature's bin edges and encode the frame as bin codes.

    ``histogram_type`` (SharedTree histogram_type analog, hex/tree
    DHistogram): "quantiles_global" (default; XGBoost's approx sketch),
    "uniform_adaptive" (equal-width over the observed range) or
    "random" (uniform-random split points; drawn ONCE per model — the
    frame is encoded a single time, so unlike the reference's per-tree
    redraw, ensembles share these edges; vary ``seed`` for diversity
    across models).  The sketch runs on a host-side row sample; the encode
    step is one fused device pass per call.  ``weights`` (host or
    device [>=nrows]) restricts the sketch to rows with weight > 0 —
    keeps CV's zero-weight holdout rows out of the bin edges.
    """
    htype = histogram_type.lower().replace("_", "")
    if htype in ("auto", "quantilesglobal"):
        htype = "quantiles"
    elif htype == "uniformadaptive":
        htype = "uniform"
    elif htype != "random":
        raise ValueError(
            f"unknown histogram_type {histogram_type!r}: use "
            "QuantilesGlobal, UniformAdaptive or Random")
    from ...runtime.cluster import fetch
    rng = np.random.default_rng(seed)
    n = frame.nrows
    idx = None
    stride = 1
    if weights is not None:
        live = np.flatnonzero(fetch(weights)[:n] > 0)
        idx = live if len(live) <= sample \
            else rng.choice(live, size=sample, replace=False)
    elif n > sample:
        # strided device slice: rows are unordered, so a stride is as good a
        # sketch sample as rng.choice — and it fetches `sample` elements to
        # host instead of the whole 40MB+ column over the device link
        stride = -(-n // sample)
    edges_list, is_cat, domains = [], [], []
    for name in features:
        vec = frame.vec(name)
        if vec.type == T_CAT:
            card = vec.cardinality
            # categorical: one bin per code (codes >= nbins clamp into last)
            edges = np.arange(0.5, min(card, nbins) - 0.5 + 1e-9, 1.0,
                              dtype=np.float32)
            is_cat.append(True)
            domains.append(vec.domain)
        else:
            if stride > 1:
                col = fetch(vec.data[:n:stride])
            else:
                col = fetch(vec.data)[: n]
                if idx is not None:
                    col = col[idx]
            col = col[np.isfinite(col)]
            if len(col) == 0:
                edges = np.zeros(0, dtype=np.float32)
            elif htype == "uniform":
                lo, hi = float(col.min()), float(col.max())
                edges = np.unique(np.linspace(lo, hi, nbins + 1)[1:-1]
                                  .astype(np.float32))
            elif htype == "random":
                lo, hi = float(col.min()), float(col.max())
                edges = np.unique(np.sort(
                    rng.uniform(lo, hi, nbins - 1)).astype(np.float32))
            else:
                qs = np.linspace(0, 1, nbins + 1)[1:-1]
                edges = np.unique(np.quantile(col, qs).astype(np.float32))
            is_cat.append(False)
            domains.append(None)
        edges_list.append(edges)
    codes = encode_bins(frame, features, edges_list, is_cat, nbins)
    return BinnedFrame(codes=codes, edges=edges_list, names=list(features),
                       is_cat=is_cat, cat_domains=domains, nbins=nbins)


def edges_matrix(edges_list, nbins: int) -> np.ndarray:
    """Dense [F, nbins] threshold table for on-device split lookup.

    Row f holds feature f's edges, right-padded by repeating the last edge
    (short rows only matter for invalid splits, which traversal ignores).
    """
    F = len(edges_list)
    mat = np.zeros((F, nbins), np.float32)
    for f, e in enumerate(edges_list):
        if len(e):
            mat[f, : len(e)] = e
            mat[f, len(e):] = e[-1]
    return mat


def encode_bins(frame: Frame, features: List[str], edges_list, is_cat,
                nbins: int) -> jax.Array:
    """Encode columns as bin codes with one device pass per feature."""
    cols = []
    for name, edges, cat in zip(features, edges_list, is_cat):
        vec = frame.vec(name)
        if cat:
            codes = vec.data if vec.type == T_CAT else jnp.where(
                jnp.isnan(vec.data), -1, vec.data).astype(jnp.int32)
            c = jnp.where(codes < 0, nbins, jnp.minimum(codes, nbins - 1))
        else:
            x = vec.data
            e = jnp.asarray(edges, dtype=jnp.float32)
            c = jnp.searchsorted(e, x, side="right").astype(jnp.int32) \
                if len(edges) else jnp.zeros(x.shape, jnp.int32)
            c = jnp.where(jnp.isnan(x), nbins, c)
        cols.append(c.astype(jnp.int32))
    return jnp.stack(cols, axis=0)
