"""Shared tree infrastructure: level-wise growth driver + ensemble scoring.

Reference: ``hex/tree/SharedTree.java:29`` (Driver:231, scoreAndBuildTrees:483,
buildLayer:561), ``hex/tree/DTree.java`` (in-progress tree),
``hex/tree/CompressedTree`` (packed scoring form), ``hex/tree/Score.java``.

TPU-native redesign: a tree level is three fused device programs (histogram ->
split-search -> partition, see hist.py); a finished tree is a set of per-level
arrays (feature, threshold, NA-direction, valid) + leaf values — the
CompressedTree analog, directly gather-traversable on device.  Ensemble
prediction stacks trees per level and lax.scan's over them: depth gathers per
tree, all batched over rows on the VPU.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...frame.vec import T_CAT
from ...runtime import dkv
from ...runtime.job import Job
from ..base import Model, ModelBuilder, Parameters
from ..datainfo import DataInfo, ColumnSpec
from ..scorekeeper import stop_early, metric_direction
from ..distributions import make_distribution
from .binning import BinnedFrame, fit_bins, encode_bins
from .hist import (_ledger, make_hist_fn, make_fine_hist_fn,
                   make_varbin_hist_fn,
                   make_subtract_level_fn, make_batched_level_fn,
                   make_scan_level_fn, make_batched_scan_level_fn,
                   make_sparse_level_fn, make_batched_sparse_level_fn,
                   sparse_slot_budget, sparse_slot_maps,
                   offset_codes, best_splits, best_splits_hier,
                   fused_best_splits, fused_best_splits_batched,
                   select_superbins, partition, partition_right,
                   table_lookup)


@contextlib.contextmanager
def level_phase(phase: str, level: int):
    """Host-side span around one per-level phase (hist/split/partition).

    The level loop runs at TRACE time inside ``jax.jit``, so inside a
    jitted build this measures per-phase tracing/dispatch cost on the
    host (events fire once per compilation; the device-side timeline
    stays ``jax.profiler``'s job).  Around EAGER phase calls (crosscheck
    drivers, bench pieces) it times real execution.  Durations land in
    ``tree_phase_seconds{phase,level}`` and on the event ring."""
    from ...runtime import observability as obs
    t0 = time.perf_counter()
    with obs.span("tree_phase", phase=phase, level=level):
        yield
    obs.observe("tree_phase_seconds", time.perf_counter() - t0,
                phase=phase, level=str(level))


@dataclasses.dataclass
class SharedTreeParameters(Parameters):
    ntrees: int = 50
    max_depth: int = 5
    min_rows: float = 10.0
    nbins: int = 64                  # quantile-sketch bins (ref nbins=20)
    histogram_type: str = "QuantilesGlobal"   # UniformAdaptive | Random
    # {column: 1|-1} — numeric features, binomial/regression only
    # (hex/tree/gbm monotone_constraints; enforced via split rejection +
    # propagated value-bound clamping, the XGBoost mechanism)
    monotone_constraints: Optional[dict] = None
    learn_rate: float = 0.1
    sample_rate: float = 1.0
    col_sample_rate: float = 1.0         # per split (mtries analog)
    col_sample_rate_per_tree: float = 1.0
    min_split_improvement: float = 1e-5
    reg_lambda: float = 0.0
    reg_alpha: float = 0.0               # L1 on leaf values (XGBoost alpha)
    gamma: float = 0.0                   # min loss reduction (XGBoost gamma)
    min_child_weight: float = 0.0        # min child hessian sum (XGBoost)
    distribution: str = "auto"
    tweedie_power: float = 1.5
    quantile_alpha: float = 0.5
    huber_alpha: float = 0.9
    score_tree_interval: int = 5
    stopping_rounds: int = 0
    standardize: bool = False            # trees never standardize
    hist_precision: str = "bf16"         # f32 for exact reproducibility
    split_search: str = "auto"           # auto | exact | hier (see shared.py)
    # histogram build strategy per level (DHistogram/gpu_hist sibling trick):
    #   "subtract" (default) — compact each parent's SMALLER child into a
    #     dense row prefix, histogram only those <= N/2 rows, reconstruct
    #     the larger sibling as parent - small (hist.make_subtract_level_fn);
    #   "full"     — histogram every child from all N rows (the oracle);
    #   "check"    — driver assert mode: grow one tree both ways on the
    #     real data and raise on divergence, then train with "subtract";
    #   "auto"     (default) — the cost-model autotuner picks per
    #     (shape, depth, K, mesh) signature (runtime/autotune.py); with
    #     H2O3_TPU_AUTOTUNE=off this is exactly "subtract".
    hist_mode: str = "auto"
    # split-search strategy per level (mirrors hist_mode):
    #   "fused"    (default) — single-pass winner-record kernel between the
    #     histogram and the tiny feature-argmax epilogue (hist.py
    #     fused_best_splits; off-TPU the bit-identical XLA twin), and
    #     multinomial/DRF-multiclass/uplift rounds grow their K trees as
    #     ONE batched level program (one kernel launch per level);
    #   "separate" — the multi-pass best_splits oracle + sequential
    #     K-iteration class loops (the pre-batching pipeline, kept whole);
    #   "check"    — driver assert mode: grow the first round both ways on
    #     the real data and raise on divergence, then train with "fused".
    #   "auto"     (default) — autotuner-decided, as with hist_mode
    #     ("fused" with the tuner off).
    # Monotone constraints, EFB bundling and the hierarchical search stay
    # on the separate path (drivers downgrade automatically).
    split_mode: str = "auto"
    # per-level histogram LAYOUT (mirrors hist_mode/split_mode):
    #   "auto"   (default) — dense [2^d, F, B] slot grids above
    #     sparse_depth_threshold, node-sparse [A, F, B] slots keyed by the
    #     compacted row prefix below it (hist.make_sparse_level_fn):
    #     histogram bytes scale with ALIVE leaves instead of 2^d, so the
    #     64 MB histogram budget no longer caps tree depth;
    #   "dense"  — the dense grid at every level (the oracle);
    #   "sparse" — force the sparse layout below the threshold even when
    #     "auto" would (identically) pick it; fails fast when it cannot
    #     engage (hist_mode="full" has no carry to subtract from);
    #   "check"  — driver assert mode: grow one tree both ways on the real
    #     data, compare structure exactly and values to f32 tolerance
    #     (run_layout_crosscheck), then train with "auto".
    # Monotone constraints, EFB bundling and the hierarchical search stay
    # dense (drivers downgrade automatically, as with split_mode).
    hist_layout: str = "auto"
    # first sparse level under hist_layout auto/sparse (expert knob): level
    # d >= threshold histograms in slot space.  Clamped per frame to the
    # dense memory cap so the dense levels above it always fit the budget.
    sparse_depth_threshold: int = 8
    # whole-tree program STRUCTURE (mirrors hist_mode/split_mode):
    #   "level" — the level loop is unrolled at TRACE time inside one jit:
    #     the compiled program holds one hist + one split kernel per level
    #     (2*depth compiled launches per tree) — the pre-scan pipeline,
    #     kept whole as the oracle;
    #   "scan"  — the level loop becomes a lax.scan over levels inside the
    #     same jitted program: fixed-width padded levels with alive-slot
    #     masking, the early-exit fence a scan-carried on-device
    #     predicate, O(1) compiled kernel programs per tree regardless of
    #     depth (and a far smaller program to compile for deep trees);
    #   "check" — driver assert mode: grow the first tree/round both ways
    #     on the real data and raise on divergence (run_program_crosscheck),
    #     then train with "scan";
    #   "auto"  (default) — autotuner-decided, as with hist_mode ("level"
    #     with the tuner off — bit-identical to the pre-scan pipeline).
    # Monotone constraints, EFB bundling, the hierarchical search,
    # node-sparse deep levels, the variable-bin kernel and depth-1 trees
    # stay on the level path ("auto"/"check" downgrade automatically;
    # uplift always grows level-wise).
    tree_program: str = "auto"
    # probability calibration (hex/tree CalibrationHelper)
    calibrate_model: bool = False
    calibration_frame: Optional[object] = None
    calibration_method: str = "platt"    # platt | isotonic
    # bit-reproducible runs (the reference's `reproducible` flag): forces
    # f32 histogram accumulation so sums don't depend on bf16 rounding;
    # psum ordering is already deterministic for a FIXED mesh shape —
    # results vary across different device counts, as in the reference
    # when node counts change
    reproducible: bool = False
    # exclusive feature bundling for wide/sparse frames (efb.py):
    # "auto" engages only when the packed-kernel cost drops enough to win
    efb: str = "auto"                    # auto | off

    @property
    def effective_hist_precision(self) -> str:
        return "f32" if self.reproducible else self.hist_precision


@dataclasses.dataclass
class Tree:
    """One grown tree — the CompressedTree analog (host-side)."""
    feat: List[np.ndarray]       # per level [2^d] int32
    thr: List[np.ndarray]        # per level [2^d] float32
    na_left: List[np.ndarray]    # per level [2^d] bool
    valid: List[np.ndarray]      # per level [2^d] bool
    values: np.ndarray           # [2^depth] float32
    cover: Optional[np.ndarray] = None   # [2^depth] weighted leaf counts


def stack_trees(trees: List[Tree]):
    """[T, ...] per-level stacks for compiled whole-ensemble traversal."""
    depth = len(trees[0].feat)
    levels = []
    # jnp.stack keeps device-resident per-level arrays on device — no
    # host round-trip per tree (matters for per-tree valid scoring)
    for d in range(depth):
        levels.append((
            jnp.stack([jnp.asarray(t.feat[d]) for t in trees]),
            jnp.stack([jnp.asarray(t.thr[d]) for t in trees]),
            jnp.stack([jnp.asarray(t.na_left[d]) for t in trees]),
            jnp.stack([jnp.asarray(t.valid[d]) for t in trees])))
    values = jnp.stack([jnp.asarray(t.values) for t in trees])
    return levels, values


@dataclasses.dataclass
class StackedTrees:
    """Device-resident whole-ensemble form: per-level [T, 2^d] stacks.

    This is the canonical trained-tree storage — trees never round-trip
    through host during training (the driver loop appends whole chunks of
    scanned trees), and traversal consumes it directly.  ``to_tree_list``
    materializes per-tree host ``Tree`` objects only when something needs
    them (MOJO export, SHAP, tests).
    """

    levels: List[tuple]          # per depth: (feat, thr, na_left, valid)
    values: jax.Array            # [T, 2^depth]
    covers: Optional[jax.Array] = None   # [T, 2^depth] leaf covers

    @property
    def ntrees(self) -> int:
        return int(self.values.shape[0])

    @property
    def depth(self) -> int:
        return len(self.levels)

    @staticmethod
    def from_trees(trees: List[Tree]) -> "StackedTrees":
        levels, values = stack_trees(trees)
        covers = None
        if all(t.cover is not None for t in trees):
            covers = jnp.stack([jnp.asarray(t.cover) for t in trees])
        return StackedTrees(levels, values, covers)

    @staticmethod
    def concat(chunks: Sequence["StackedTrees"]) -> "StackedTrees":
        """Host-side concatenation.  Tree metadata is kilobytes; a device
        ``jnp.concatenate`` here compiled one program per (level, array,
        chunk-count) geometry — measured 9.3 s of XLA compiles inside the
        bench's timed 50-tree train (chunk counts the warmup never saw).
        The fetch is ONE ``jax.device_get`` over every chunk array: it
        prefetches all transfers async, so the whole pull costs ~one round
        trip instead of one per array (measured 0.13 s vs 7.9 s for the
        5-chunk x 26-array case on the tunnel)."""
        if len(chunks) == 1:
            return chunks[0]
        if any(c.depth != chunks[0].depth for c in chunks):
            raise ValueError(
                "StackedTrees.concat: chunks disagree on depth "
                f"({[c.depth for c in chunks]}); continuation stacks must "
                "share one effective depth (validate_checkpoint_depth)")
        host = jax.device_get([
            [[c.levels[d][i] for i in range(4)]
             for d in range(c.depth)] +
            [c.values, c.covers if c.covers is not None else np.zeros(0)]
            for c in chunks])
        depth = chunks[0].depth
        levels = []
        for d in range(depth):
            levels.append(tuple(
                np.concatenate([h[d][i] for h in host], axis=0)
                for i in range(4)))
        values = np.concatenate([h[depth] for h in host], axis=0)
        covers = None
        if all(c.covers is not None for c in chunks):
            covers = np.concatenate([h[depth + 1] for h in host], axis=0)
        return StackedTrees(levels, values, covers)

    def to_tree_list(self) -> List[Tree]:
        """Host materialization — one batched fetch, then slices."""
        host_levels, values, covers = jax.device_get(
            [[tuple(a for a in lv) for lv in self.levels], self.values,
             self.covers if self.covers is not None else np.zeros(0)])
        if self.covers is None:
            covers = None
        out = []
        for t in range(values.shape[0]):
            out.append(Tree(
                feat=[lv[0][t] for lv in host_levels],
                thr=[lv[1][t] for lv in host_levels],
                na_left=[lv[2][t] for lv in host_levels],
                valid=[lv[3][t] for lv in host_levels],
                values=values[t],
                cover=covers[t] if covers is not None else None))
        return out


class TreeListMulti:
    """Lazy per-round list of per-class ``Tree`` lists (multinomial form).

    ``output["trees"][t][k]`` — materialized from the K per-class
    ``StackedTrees`` only on first index, mirroring ``TreeList``.
    """

    def __init__(self, stacks: List[StackedTrees]):
        self._stacks = stacks
        self._cache: Optional[List[list]] = None

    def _mat(self) -> List[list]:
        if self._cache is None:
            per_class = [s.to_tree_list() for s in self._stacks]
            self._cache = [list(t) for t in zip(*per_class)]
        return self._cache

    def __len__(self):
        return self._stacks[0].ntrees

    def __getitem__(self, i):
        return self._mat()[i]

    def __iter__(self):
        return iter(self._mat())

    def __getstate__(self):
        return {"trees": self._mat()}

    def __setstate__(self, state):
        self._cache = state["trees"]
        self._stacks = [
            StackedTrees.from_trees([t[k] for t in self._cache])
            for k in range(len(self._cache[0]))]


class TreeList:
    """Lazy list-of-``Tree`` view over a ``StackedTrees``.

    Keeps ``model.output["trees"]`` available to export/inspection code
    without pulling the ensemble to host unless someone actually indexes it.
    """

    def __init__(self, stacked: StackedTrees):
        self._stacked = stacked
        self._cache: Optional[List[Tree]] = None

    def _mat(self) -> List[Tree]:
        if self._cache is None:
            self._cache = self._stacked.to_tree_list()
        return self._cache

    def __len__(self):
        return self._stacked.ntrees

    def __getitem__(self, i):
        return self._mat()[i]

    def __iter__(self):
        return iter(self._mat())

    def __getstate__(self):
        return {"trees": self._mat()}

    def __setstate__(self, state):
        self._cache = state["trees"]
        self._stacked = StackedTrees.from_trees(self._cache)


def traverse(levels, values, X):
    """Sum of leaf values over stacked trees for raw feature matrix X.

    scan over trees; per level: look up node params, compare, descend.
    NaN feature -> NA direction (sparsity-aware default, hist.py).  All
    per-row lookups go through one-hot matmuls (hist.table_lookup) — TPU
    per-row gathers are ~2 orders of magnitude slower.
    """
    from .hist import table_lookup
    N, Fdim = X.shape

    def one_tree(carry, tree_slices):
        acc = carry
        node = jnp.zeros(N, jnp.int32)
        for (feat, thr, na_left, valid) in tree_slices[0]:
            L = feat.shape[0]
            tbl = jnp.stack([feat.astype(jnp.float32), thr,
                             na_left.astype(jnp.float32),
                             valid.astype(jnp.float32)], axis=0)
            t = table_lookup(tbl, node, L)
            f = t[0].astype(jnp.int32)
            x = jnp.zeros(N, X.dtype)
            for fi in range(Fdim):
                x = jnp.where(f == fi, X[:, fi], x)
            right = jnp.where(jnp.isnan(x), t[2] <= 0.5, x >= t[1])
            right = right & (t[3] > 0.5)
            node = 2 * node + right.astype(jnp.int32)
        V = tree_slices[1].shape[0]
        acc = acc + table_lookup(tree_slices[1][None, :], node, V)[0]
        return acc, None

    # lax.scan needs uniform pytrees; reorganize levels per tree via index map
    T = values.shape[0]

    def body(acc, i):
        slices = tuple((lv[0][i], lv[1][i], lv[2][i], lv[3][i])
                       for lv in levels)
        return one_tree(acc, (slices, values[i]))

    acc = jnp.zeros(N, jnp.float32)
    acc, _ = jax.lax.scan(lambda c, i: body(c, i), acc, jnp.arange(T))
    return acc


traverse_jit = jax.jit(traverse)


def dense_mem_cap(nbins: int, F: int) -> int:
    """Deepest level whose dense [2^d, F, B] histogram fits the 64 MB
    device budget — the memory wall the node-sparse layout removes."""
    B = nbins + 1
    mem_cap = 1
    while (mem_cap < 24
           and F * B * 3 * 2 ** mem_cap * 4 <= 64 * 1024 * 1024):
        mem_cap += 1
    return mem_cap


def effective_max_depth(max_depth: int, nbins: int, F: int,
                        n_padded: int, hist_layout: str = "dense",
                        sparse_depth_threshold: int = 8) -> int:
    """Depth cap, shared by EVERY consumer of the build factories (the
    scan drivers and checkpoint validation must agree with the tree
    builder on the level count).

    Dense levels are FULL-WIDTH [2^d] arrays (that is what makes every
    per-level op a dense matmul), so histogram memory doubles per level;
    the reference's node-sparse trees have no such coupling and default to
    depth 20 (DRF).  Cap where (a) a balanced tree would run out of rows
    (2^d > n admits only chain-shaped deeper trees, which terminal-leaf
    masking reproduces as no-op levels), and (b) — dense layout only —
    the per-level histogram would exceed a 64 MB device budget.  With the
    node-sparse layout engaged (``hist_layout`` "sparse"/"auto", passed
    here ALREADY RESOLVED for downgrades — see sparse_layout_active) the
    memory bound applies only to the dense levels above the threshold:
    the builder clamps the threshold itself to dense_mem_cap and the
    sparse levels' slot axis is budget-sized (hist.sparse_slot_budget),
    so depth becomes row/compute-bound.  Growth virtually always stops
    earlier via min_rows/purity (valid masking); configs asking for more
    depth get the capped tree — a documented design bound (PROFILE.md
    round-4, revised round-8)."""
    row_cap = max(1, int(np.ceil(np.log2(max(n_padded, 2)))) + 1)
    if hist_layout in ("sparse", "auto", "check"):
        return max(1, min(max_depth, row_cap))
    return max(1, min(max_depth, row_cap, dense_mem_cap(nbins, F)))


def record_effective_depth(model, params, F: int, n_padded: int,
                           hist_layout: str = "dense") -> int:
    """Record requested vs effective depth in model.output and WARN when the
    dense-level bound caps the user's max_depth — the divergence from the
    reference's node-sparse trees (which honor depth 20+) must be visible,
    not silent (ADVICE round-4 medium finding).  ``hist_layout`` is the
    driver-RESOLVED layout (resolve_hist_layout), so a sparse-capable run
    records — and gets — the uncapped depth."""
    import warnings
    eff = effective_max_depth(
        params.max_depth, params.nbins, F, n_padded, hist_layout,
        getattr(params, "sparse_depth_threshold", 8))
    model.output["requested_max_depth"] = params.max_depth
    model.output["effective_max_depth"] = eff
    model.output["hist_layout"] = hist_layout
    if eff < params.max_depth:
        hint = ("rows bound the tree" if hist_layout != "dense" else
                "full-width [2^d] levels double histogram memory per "
                "level; hist_layout='auto' lifts the memory bound")
        warnings.warn(
            f"max_depth={params.max_depth} is capped to {eff} on this frame "
            f"({hint}; {F} features x {params.nbins} bins "
            f"x {n_padded} rows). Trees train at depth {eff}; lower "
            f"max_depth to silence this.", stacklevel=3)
    return eff


def validate_checkpoint_depth(prior, k, params, F: int, n_padded: int,
                              hist_layout: str = "dense"):
    """Continuation chunks must stack at ONE depth: the depth cap depends
    on the frame size AND the resolved histogram layout, so a continuation
    on a differently-sized frame (or with the other layout) could disagree
    with the checkpoint's level count — fail clearly instead of
    mis-stacking."""
    eff = effective_max_depth(
        params.max_depth, params.nbins, F, n_padded, hist_layout,
        getattr(params, "sparse_depth_threshold", 8))
    pd = prior_stacked(prior, k).depth
    if pd != eff:
        raise ValueError(
            f"checkpoint tree depth {pd} != effective depth {eff} on this "
            f"frame (depth cap under hist_layout={hist_layout!r}); continue "
            f"on a similarly sized frame with the same layout or lower "
            f"max_depth to {pd}")


def _per_k(x, extra_dims: int):
    """Broadcast a per-member ``[K]`` parameter against ``extra_dims``
    trailing axes; scalars pass through untouched so the scalar
    (non-grid) trace stays byte-identical."""
    return x.reshape(x.shape + (1,) * extra_dims) \
        if getattr(x, "ndim", 0) else x


@functools.lru_cache(maxsize=None)
def make_build_tree_fn(max_depth: int, nbins: int, F: int, n_padded: int,
                       hist_precision: str = "bf16", hier: bool = False,
                       fine_k: int = 2, bin_counts=None, mono=None,
                       plan=None, hist_mode: str = "subtract",
                       nk: int = 1, split_mode: str = "separate",
                       hist_layout: str = "dense",
                       sparse_depth_threshold: int = 8,
                       tree_program: str = "level"):
    """One compiled program that grows a whole tree on device.

    The level loop (SharedTree.buildLayer) is unrolled inside a single jit:
    histogram -> split-search -> threshold lookup -> partition per level,
    then final-leaf Newton values — zero host syncs per tree, which is what
    the driver-loop latency budget demands on a remote TPU.  Returns
    (per-level (feat, thr, na_left, valid) tuples, leaf values, final leaf
    assignment), all device-resident.

    ``hist_mode`` picks the per-level histogram strategy (non-hier path):
    ``"subtract"`` (default) compacts each parent's smaller child into a
    dense row prefix, histograms only those <= N/2 rows and reconstructs
    the larger sibling by f32 subtraction from a per-shard parent carry
    (hist.make_subtract_level_fn — the DHistogram/gpu_hist sibling trick
    with the row stream actually halved, not just masked); ``"full"``
    histograms every child from all N rows and is kept as the exactness
    oracle (run_hist_crosscheck / the hist_mode="check" driver assert).

    ``hier=True`` takes the hierarchical split-search path: a coarse
    super-bin histogram (S = 8/16) + fine refinement of the ``fine_k`` most
    promising super-bins per (leaf, feature) — ~4-5x fewer VPU element-ops
    than the full (nbins+1)-bin pass (PROFILE.md).  Refinement targets the
    super-bins adjacent to the best exact coarse-boundary gains; the
    refined search is exact WITHIN the refined bins plus all super-bin
    boundaries, so it can (rarely) choose a different split than the full
    pass when the best split hides far from every top coarse boundary.
    Drivers therefore enable it only at benchmark scale
    (split_search="auto" gate) or on request.  ``hier`` keeps its own
    coarse-level subtraction; ``hist_mode`` does not apply to it.

    ``split_mode="fused"`` swaps best_splits for the single-pass
    winner-record path (hist.fused_best_splits — on TPU a Pallas kernel
    that never materializes the [3, L, F, B] gain intermediates, off-TPU
    a bit-identical XLA twin).  ``nk > 1`` grows K trees at once: g/h,
    rng_key and tree_mask gain a leading [K] axis, every level issues ONE
    batched hist launch + ONE records launch for all K trees
    (hist.make_batched_level_fn), and levels/vals/cover/leaf come back
    with leading [K].  The batched build reproduces the sequential
    per-tree key chains exactly (vmapped threefry draws are bitwise the
    per-key calls), so a K-loop of single-tree builds is its oracle.

    ``hist_layout="sparse"`` switches levels at/below
    ``sparse_depth_threshold`` (clamped per frame to the dense memory cap)
    to the node-sparse slot layout: histograms, split search and routing
    run in an [A] slot space sized by ALIVE leaves (hist.sparse_slot_budget
    caps A so the 64 MB histogram budget holds at EVERY depth), rows carry
    a slot id updated through A+1-entry tables, and each level's records
    are expanded back to the dense [2^d] contract so traversal, exporters
    and checkpoints are layout-blind.  Requires hist_mode="subtract" (the
    slot carry IS the subtraction carry); dense candidate records on dead
    chains are not reproduced (sparse never histograms dead rows), so
    parity with "dense" is: valid/leaf routing exact, feat/thr/na_left
    exact WHERE VALID, leaf values to f32 tolerance
    (run_layout_crosscheck).

    ``tree_program="scan"`` replaces the trace-time level unroll with a
    ``lax.scan`` over levels inside the same jit (one fixed-width level
    program compiled ONCE instead of one program pair per level):
    level 0 runs outside the scan on the existing depth-0 machinery and
    seeds the carries, levels 1..max_depth-1 run at the padded width
    2^(max_depth-1) with alive-slot masking, and the early-exit fence is
    a scan-carried on-device ``dead`` predicate (hist.make_scan_level_fn
    skips the histogram kernel and the builder skips partition on dead
    levels — both skips are bitwise the live computation).  Composes
    with hist_mode subtract/full, split_mode separate/fused and the
    batched K-tree build; NOT with mono/EFB/hier/sparse layout (raises)
    or the variable-bin kernel (silently uses the uniform kernels —
    "auto" keeps per-level programs where varbin wins).
    """
    B = nbins + 1
    if hist_layout not in ("dense", "sparse"):
        raise ValueError(
            f"hist_layout={hist_layout!r}: use 'dense' or 'sparse' here "
            "('auto'/'check' are driver modes — see resolve_hist_layout)")
    if hist_layout == "sparse":
        if hist_mode != "subtract":
            raise ValueError(
                "hist_layout='sparse' requires hist_mode='subtract': the "
                "slot-space level carry is the subtraction carry "
                "(hist_mode='full' has no carry to subtract from)")
        if hier or mono is not None or plan is not None:
            raise ValueError(
                "hist_layout='sparse' does not compose with monotone "
                "constraints, EFB bundling or the hierarchical search; "
                "the drivers downgrade to 'dense' automatically under "
                "hist_layout='auto'")
    if split_mode not in ("separate", "fused"):
        raise ValueError(
            f"split_mode={split_mode!r}: use 'separate' or 'fused' here "
            "('check' is a driver mode — see run_split_crosscheck)")
    if split_mode == "fused" and (mono is not None or plan is not None
                                  or hier):
        raise ValueError(
            "split_mode='fused' does not compose with monotone "
            "constraints, EFB bundling or the hierarchical search; the "
            "drivers downgrade to 'separate' automatically")
    if nk > 1 and split_mode != "fused":
        raise ValueError("the batched K-tree build (nk > 1) requires "
                         "split_mode='fused'")
    if mono is not None and hier:
        raise ValueError("monotone constraints are not supported with "
                         "the hierarchical split search")
    if plan is not None and (mono is not None or hier):
        raise ValueError("feature bundling (EFB) does not compose with "
                         "monotone constraints or the hierarchical search; "
                         "the drivers disable it automatically")
    if hist_mode not in ("subtract", "full"):
        raise ValueError(
            f"hist_mode={hist_mode!r}: use 'subtract' or 'full' here "
            "('check' is a driver mode — see run_hist_crosscheck)")
    if tree_program not in ("level", "scan"):
        raise ValueError(
            f"tree_program={tree_program!r}: use 'level' or 'scan' here "
            "('auto'/'check' are driver modes — see resolve_tree_program)")
    if tree_program == "scan" and (hier or mono is not None
                                   or plan is not None):
        raise ValueError(
            "tree_program='scan' does not compose with monotone "
            "constraints, EFB bundling or the hierarchical split search; "
            "tree_program='auto' downgrades to 'level' automatically")
    max_depth = effective_max_depth(max_depth, nbins, F, n_padded,
                                    hist_layout, sparse_depth_threshold)
    # first node-sparse level: the threshold clamps to the dense memory
    # cap so every dense level above it fits the budget, and to >= 1 so
    # the root level (whose carry seeds the chain) is always dense
    t0 = max(1, min(sparse_depth_threshold, dense_mem_cap(nbins, F)))
    sparse_from = t0 if (hist_layout == "sparse" and max_depth > t0) \
        else max_depth
    if tree_program == "scan":
        if sparse_from < max_depth:
            raise ValueError(
                "tree_program='scan' requires the dense layout at every "
                "level (the scan body is ONE fixed-width program; node-"
                "sparse slot maps reshape per level); use "
                "hist_layout='dense' or tree_program='auto'")
        if max_depth < 2:
            raise ValueError(
                "tree_program='scan' needs effective max_depth >= 2 (a "
                "depth-1 tree is the root level only — nothing to scan); "
                "tree_program='auto' downgrades to 'level' automatically")
        return _make_scan_build(max_depth, nbins, F, n_padded,
                                hist_precision, hist_mode, nk, split_mode)
    A_cap = sparse_slot_budget(F, B)
    # slot capacity per sparse level, and the PREVIOUS level's slot space
    # (the carry/compaction geometry) — at the boundary that is the dense
    # parent id space, so the first sparse level consumes the dense
    # subtract carry unchanged
    A_lv = {d: min(2 ** d, A_cap) for d in range(sparse_from, max_depth)}
    Ap_lv = {d: (2 ** (d - 1) if d == sparse_from else A_lv[d - 1])
             for d in range(sparse_from, max_depth)}
    from ...runtime.cluster import cluster
    # per-feature packed bins (DHistogram-style): only the TPU Pallas path
    # has the ragged kernel; dense einsum covers CPU tests.  The packed
    # result has the exact same [3, L, F, B] contract, so split search is
    # byte-identical — this is a pure kernel-cost optimization.
    # H2O3_TPU_HIST_IMPL=varbin forces the varbin path off-TPU (interpret
    # Pallas) so the multichip dryrun exercises the bench kernel code path.
    on_tpu = cluster().mesh.devices.flat[0].platform == "tpu"
    use_varbin = varbin_kernel_engages(bin_counts, nbins, F)
    # Per-LEVEL kernel choice: the varbin Pallas kernel has no einsum
    # fallback, its minimum row block must keep [R, 3L] A-build
    # intermediates inside scoped VMEM (3L <= 1024), and its whole-
    # histogram output block must stage through VMEM (12 MB).  Deeper
    # levels take the uniform path, which falls back to einsum past its
    # own bound — the gate is per level so a deep tree keeps the fast
    # kernel on its shallow levels.  The subtract path histograms at the
    # PARENT slot count (2^(d-1)); the full oracle at the child count.
    kern_L = [Ap_lv[d] if d >= sparse_from
              else (2 ** d if hist_mode == "full" else 2 ** max(d - 1, 0))
              for d in range(max_depth)]
    varbin_level = [
        use_varbin and 3 * kern_L[d] <= 1024
        and F * B * 3 * kern_L[d] * 4 <= 12 * 1024 * 1024
        for d in range(max_depth)]
    force = "" if on_tpu else "pallas_interpret"

    # ---- node-sparse deep levels (hist_layout="sparse", d >= sparse_from)
    # Per-tree helpers shared by build()/buildK() (buildK vmaps them).
    # All slot bookkeeping is O(A) or O(2^d) index math — the only per-row
    # work is the A+1-entry table routing (partition_right) and the one
    # boundary slot lookup.
    sparse_fns = {}
    for d in range(sparse_from, max_depth):
        _kw = dict(bin_counts=(tuple(bin_counts) if varbin_level[d]
                               else None),
                   force_impl=force if varbin_level[d] else "",
                   precision=hist_precision)
        sparse_fns[d] = (
            make_batched_sparse_level_fn(Ap_lv[d], A_lv[d], nk, F, B,
                                         n_padded, **_kw)
            if nk > 1 else
            make_sparse_level_fn(Ap_lv[d], A_lv[d], F, B, n_padded, **_kw))

    def _slot_maps(d, prev_valid, slot_of_leaf, leaf_of_slot):
        """Slot assignment + dense<->slot index maps for sparse level d.
        ``prev_valid`` is the previous level's valid flags in its OWN
        space: dense [2^(d-1)] at the boundary, [Ap] slots after it."""
        A = A_lv[d]
        sidx = jnp.arange(A, dtype=jnp.int32)
        child_base, ps_of_slot, real = sparse_slot_maps(prev_valid, A)
        l2 = jnp.arange(2 ** d, dtype=jnp.int32)
        if d == sparse_from:
            sol = jnp.minimum(child_base[l2 >> 1] + (l2 & 1), A)
            los = 2 * ps_of_slot + (sidx & 1)
        else:
            sol = jnp.minimum(child_base[slot_of_leaf[l2 >> 1]]
                              + (l2 & 1), A)
            los = 2 * leaf_of_slot[ps_of_slot] + (sidx & 1)
        return child_base, ps_of_slot, real, sol, los

    def _sleaf_of_leaf(slot_of_leaf, leaf, L):
        # boundary only: dense leaf id -> slot id, one [1, 2^t] MXU lookup
        return table_lookup(slot_of_leaf[None].astype(jnp.float32),
                            leaf, L)[0].astype(jnp.int32)

    def _slot_collapse(valid_s, children_s):
        # the dense dead-slot stat collapse, in slot space: non-split
        # slots keep full totals on the left so their rows' leaf values
        # cover everything draining through them
        gl, hl, cl2 = children_s[:, 0], children_s[:, 1], children_s[:, 2]
        gr, hr, cr2 = children_s[:, 3], children_s[:, 4], children_s[:, 5]
        return jnp.stack(
            [jnp.where(valid_s, gl, gl + gr),
             jnp.where(valid_s, hl, hl + hr),
             jnp.where(valid_s, cl2, cl2 + cr2),
             jnp.where(valid_s, gr, 0.0),
             jnp.where(valid_s, hr, 0.0),
             jnp.where(valid_s, cr2, 0.0)], axis=1)

    def _expand_sparse(d, feat_s, bin_s, na_s, valid_s, children_s,
                       slot_of_leaf, prev_children):
        """Slot records -> the dense [2^d] level contract.  Unslotted
        nodes (dead chains / slot-budget overflow) are terminal: invalid
        records, child stats inherited from their side of the parent's
        record so every row draining through them keeps a leaf value
        (the dense collapse semantics, to f32 tolerance)."""
        A = A_lv[d]
        l2 = jnp.arange(2 ** d, dtype=jnp.int32)
        mapped = slot_of_leaf < A
        slc = jnp.minimum(slot_of_leaf, A - 1)
        feat_d = jnp.where(mapped, feat_s[slc], 0)
        bin_d = jnp.where(mapped, bin_s[slc], 0)
        na_d = jnp.where(mapped, na_s[slc], False)
        valid_d = mapped & valid_s[slc]
        pc = prev_children[l2 >> 1]
        tot = jnp.where((l2 & 1)[:, None] == 0, pc[:, 0:3], pc[:, 3:6])
        inherit = jnp.concatenate([tot, jnp.zeros_like(tot)], axis=1)
        children_d = jnp.where(mapped[:, None], children_s[slc], inherit)
        return feat_d, bin_d, na_d, valid_d, children_d

    def _pad_slot_tables(feat_s, bin_s, na_s, valid_s):
        # sentinel row (slot A): valid=False, so dead/overflowed rows
        # keep flowing left — dense terminality through slot tables
        def z(a):
            return jnp.concatenate([a, jnp.zeros((1,), a.dtype)])
        return z(feat_s), z(bin_s), z(na_s), z(valid_s)

    if nk > 1:
        lev_fns = [
            make_batched_level_fn(
                d, nk, F, B, n_padded,
                bin_counts=tuple(bin_counts) if varbin_level[d] else None,
                force_impl=force if varbin_level[d] else "",
                precision=hist_precision,
                subtract=(hist_mode == "subtract"))
            for d in range(sparse_from)]

        def buildK(codes, g, h, w, edges_mat, rng_keys, reg_lambda,
                   min_rows, min_split_improvement, learn_rate,
                   col_sample_rate, tree_mask, reg_alpha, gamma,
                   min_child_weight):
            # the K-tree analog of build() below: one level loop, every
            # array carrying a leading [K].  w may be [N] (row sample
            # shared across class trees — reference semantics) or [K, N]
            # (uplift arms); either broadcasts to g's shape.  The scalar
            # params also accept per-member [K] arrays (batched grid
            # sweeps) — anything that doesn't change trace shape batches.
            N = codes.shape[1]
            csr2 = _per_k(col_sample_rate, 2)
            wK = jnp.broadcast_to(w, g.shape)
            leaf = jnp.zeros((nk, N), jnp.int32)
            levels = []
            alive = jnp.ones((nk, 1), bool)
            # per-tree key chains: vmapped threefry emits bitwise the
            # per-key split/uniform results, so each tree's column draws
            # match the sequential oracle exactly
            keysK = jax.vmap(
                lambda kk: jax.random.split(kk, max_depth))(rng_keys)
            H_carry = None
            hcodes = offset_codes(codes, bin_counts, nbins) \
                if any(varbin_level) else codes
            for d in range(max_depth):
                L = 2 ** d
                per_split = jax.vmap(
                    lambda kd: jax.random.uniform(kd, (L, F)))(
                        keysK[:, d]) < csr2
                per_split = per_split.at[:, :, 0].set(
                    (per_split.any(axis=2) & per_split[:, :, 0])
                    | ~per_split.any(axis=2))
                mask = per_split & tree_mask[:, None, :]
                lcodes = hcodes if varbin_level[d] else codes
                if d >= sparse_from:
                    A = A_lv[d]
                    if d == sparse_from:
                        (child_base, ps_of_slot, real, slot_of_leaf,
                         leaf_of_slot) = jax.vmap(
                            lambda v: _slot_maps(d, v, None, None))(valid)
                        sleaf = jax.vmap(_sleaf_of_leaf,
                                         in_axes=(0, 0, None))(
                            slot_of_leaf, leaf, L)
                    else:
                        (child_base, ps_of_slot, real, slot_of_leaf,
                         leaf_of_slot) = jax.vmap(
                            functools.partial(_slot_maps, d))(
                            valid_s, slot_of_leaf, leaf_of_slot)
                        sleaf = jnp.minimum(
                            jnp.take_along_axis(child_base, sleaf, axis=1)
                            + right, A)
                    H, H_carry = sparse_fns[d](lcodes, sleaf, g, h, wK,
                                               H_carry, ps_of_slot)
                    # the col mask is DRAWN dense (same keys as the dense
                    # layout, bit-identical RNG), then gathered to slots
                    mask_s = jax.vmap(lambda m, i: m[i])(mask,
                                                         leaf_of_slot)
                    feat_s, bin_s, na_s, gain, valid_s, children_s = \
                        fused_best_splits_batched(
                            H, nbins, reg_lambda, min_rows,
                            min_split_improvement, mask_s, reg_alpha,
                            gamma, min_child_weight)
                    # phantom slots past the live range gathered parent
                    # slot 0's histogram — no rows, records discarded
                    valid_s = valid_s & real
                    children_s = jax.vmap(_slot_collapse)(valid_s,
                                                          children_s)
                    feat, bin_, na_left, valid, children = jax.vmap(
                        functools.partial(_expand_sparse, d))(
                        feat_s, bin_s, na_s, valid_s, children_s,
                        slot_of_leaf, children)
                    thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
                    fp, bp, nap, vp = jax.vmap(_pad_slot_tables)(
                        feat_s, bin_s, na_s, valid_s)
                    right = jax.vmap(
                        partition_right,
                        in_axes=(None, 0, 0, 0, 0, 0, None))(
                        codes, sleaf, fp, bp, nap, vp, jnp.int32(nbins))
                    leaf = 2 * leaf + right
                    levels.append((feat, thr, na_left, valid))
                    continue
                if hist_mode == "subtract":
                    if d == 0:
                        H, H_carry = lev_fns[0](lcodes, leaf, g, h, wK)
                    else:
                        H, H_carry = lev_fns[d](lcodes, leaf, g, h, wK,
                                                H_carry)
                else:
                    H = lev_fns[d](lcodes, leaf, g, h, wK)
                feat, bin_, na_left, gain, valid, children = \
                    fused_best_splits_batched(
                        H, nbins, reg_lambda, min_rows,
                        min_split_improvement, mask, reg_alpha, gamma,
                        min_child_weight)
                if d > 0:
                    valid = valid & alive
                    gl, hl, cl2 = (children[..., 0], children[..., 1],
                                   children[..., 2])
                    gr, hr, cr2 = (children[..., 3], children[..., 4],
                                   children[..., 5])
                    children = jnp.stack(
                        [jnp.where(valid, gl, gl + gr),
                         jnp.where(valid, hl, hl + hr),
                         jnp.where(valid, cl2, cl2 + cr2),
                         jnp.where(valid, gr, 0.0),
                         jnp.where(valid, hr, 0.0),
                         jnp.where(valid, cr2, 0.0)], axis=-1)
                alive = jnp.stack([valid, valid], axis=2).reshape(nk, -1)
                thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
                leaf = jax.vmap(partition,
                                in_axes=(None, 0, 0, 0, 0, 0, None))(
                    codes, leaf, feat, bin_, na_left, valid,
                    jnp.int32(nbins))
                levels.append((feat, thr, na_left, valid))
            gl, hl, cl = (children[..., 0], children[..., 1],
                          children[..., 2])
            gr, hr, cr = (children[..., 3], children[..., 4],
                          children[..., 5])

            from .hist import newton_value

            def newton(gc, hc, cc):
                return jnp.where(cc > 0,
                                 newton_value(gc, hc, _per_k(reg_lambda, 1),
                                              _per_k(reg_alpha, 1)),
                                 0.0)
            vals = jnp.stack([newton(gl, hl, cl), newton(gr, hr, cr)],
                             axis=2).reshape(nk, -1)
            vals = (vals * _per_k(learn_rate, 1)).astype(jnp.float32)
            cover = jnp.stack([cl, cr], axis=2).reshape(nk, -1) \
                .astype(jnp.float32)
            return levels, vals, cover, leaf

        return _ledger("tree_build_batched", jax.jit(buildK), orig=buildK)
    if not hier and hist_mode == "subtract":
        level_fns = [
            make_subtract_level_fn(
                d, F, B, n_padded,
                bin_counts=tuple(bin_counts) if varbin_level[d] else None,
                force_impl=force if varbin_level[d] else "",
                precision=hist_precision)
            for d in range(sparse_from)]
    else:
        hist_fns = [
            make_varbin_hist_fn(kern_L[d], F, tuple(bin_counts), B,
                                n_padded, precision=hist_precision,
                                force_impl=force)
            if varbin_level[d]
            else make_hist_fn(kern_L[d], F, B, n_padded,
                              precision=hist_precision)
            for d in range(max_depth)]
    if hier:
        S = 16 if nbins >= 128 else 8
        W = -(-nbins // S)
        coarse_fns = [make_hist_fn(2 ** max(d - 1, 0), F, S + 1, n_padded,
                                   precision=hist_precision)
                      for d in range(max_depth)]
        fine_fns = [make_fine_hist_fn(2 ** d, F, W, fine_k, nbins, n_padded,
                                      precision=hist_precision)
                    for d in range(max_depth)]

    def build(codes, g, h, w, edges_mat, rng_key, reg_lambda, min_rows,
              min_split_improvement, learn_rate, col_sample_rate, tree_mask,
              reg_alpha, gamma, min_child_weight):
        N = codes.shape[1]
        leaf = jnp.zeros(N, jnp.int32)
        levels = []
        # terminality invariant: once a node fails to split, every
        # descendant slot is dead too.  Without this mask a dead node's
        # rows (which keep flowing left through the dense [2^d] levels)
        # could be re-split at a deeper level when a fresh per-level
        # column draw (DRF mtries) samples a feature the failed level
        # missed — the node-sparse exporters (POJO/MOJO/SHAP/tree API)
        # all assume the first invalid node is a leaf, so such "revived"
        # splits made exported scorers diverge from device traversal.
        alive = jnp.ones((1,), bool)
        keys = jax.random.split(rng_key, max_depth)
        if mono is not None:
            mono_arr = jnp.asarray(mono, jnp.float32)        # [F] in {-1,0,1}
            lo = jnp.full((1,), -jnp.inf)                    # per-node value
            hi = jnp.full((1,), jnp.inf)                     # bounds
        H_prev = None
        H_carry = None            # subtract path: per-shard local hist stack
        if hier:
            ccodes = jnp.where(codes >= nbins, S, codes // W)
        hcodes = offset_codes(codes, bin_counts, nbins) \
            if any(varbin_level) else codes
        for d in range(max_depth):
            L = 2 ** d
            per_split = jax.random.uniform(keys[d], (L, F)) < col_sample_rate
            # always keep at least one feature per leaf
            per_split = per_split.at[:, 0].set(
                (per_split.any(axis=1) & per_split[:, 0])
                | ~per_split.any(axis=1))
            mask = per_split & tree_mask[None, :]
            if d >= sparse_from:
                A = A_lv[d]
                if d == sparse_from:
                    # boundary: slots assigned from the last DENSE level's
                    # valid flags; the dense subtract carry is consumed
                    # unchanged (its slot space is the dense parent space)
                    (child_base, ps_of_slot, real, slot_of_leaf,
                     leaf_of_slot) = _slot_maps(d, valid, None, None)
                    sleaf = _sleaf_of_leaf(slot_of_leaf, leaf, L)
                else:
                    (child_base, ps_of_slot, real, slot_of_leaf,
                     leaf_of_slot) = _slot_maps(d, valid_s, slot_of_leaf,
                                                leaf_of_slot)
                    sleaf = jnp.minimum(jnp.take(child_base, sleaf)
                                        + right, A)
                lcodes = hcodes if varbin_level[d] else codes
                with level_phase("hist", d):
                    H, H_carry = sparse_fns[d](lcodes, sleaf, g, h, w,
                                               H_carry, ps_of_slot)
                # col mask DRAWN dense (bit-identical RNG to the dense
                # layout), gathered to slots
                mask_s = mask[leaf_of_slot]
                with level_phase("split", d):
                    if split_mode == "fused":
                        feat_s, bin_s, na_s, gain, valid_s, children_s = \
                            fused_best_splits(
                                H, nbins, reg_lambda, min_rows,
                                min_split_improvement, mask_s, reg_alpha,
                                gamma, min_child_weight)
                    else:
                        feat_s, bin_s, na_s, gain, valid_s, children_s = \
                            best_splits(
                                H, nbins, reg_lambda, min_rows,
                                min_split_improvement, mask_s, reg_alpha,
                                gamma, min_child_weight)
                # phantom slots past the live range gathered parent slot
                # 0's histogram — no rows, records discarded here
                valid_s = valid_s & real
                children_s = _slot_collapse(valid_s, children_s)
                feat, bin_, na_left, valid, children = _expand_sparse(
                    d, feat_s, bin_s, na_s, valid_s, children_s,
                    slot_of_leaf, children)
                thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
                fp, bp, nap, vp = _pad_slot_tables(feat_s, bin_s, na_s,
                                                   valid_s)
                with level_phase("partition", d):
                    right = partition_right(codes, sleaf, fp, bp, nap, vp,
                                            jnp.int32(nbins))
                # same went-right bit updates BOTH ids: dense leaf (final
                # values/traversal) and slot (next level's routing)
                leaf = 2 * leaf + right
                levels.append((feat, thr, na_left, valid))
                continue
            if hier:
                with level_phase("hist", d):
                    if d == 0:
                        Hc = coarse_fns[0](ccodes, leaf, g, h, w)
                    else:
                        em = ((leaf & 1) == 0).astype(jnp.float32)
                        Hcl = coarse_fns[d](ccodes, leaf >> 1,
                                            g * em, h * em, w * em)
                        # clamp the h/w planes at 0: per-level kernel
                        # routing can pair differently-rounded kernels
                        # across the subtraction (bf16 vs f32), and
                        # negative hessian/weight sums would corrupt
                        # best_splits at the boundary level
                        Hcr = H_prev - Hcl
                        Hcr = Hcr.at[1:].max(0.0)
                        Hc = jnp.stack([Hcl, Hcr], axis=2) \
                            .reshape(3, L, F, S + 1)
                    H_prev = Hc
                    sel, ub = select_superbins(
                        Hc, nbins, W, fine_k, reg_lambda, reg_alpha, gamma,
                        min_rows, min_child_weight, mask)
                    Hf = fine_fns[d](codes, leaf, g, h, w, sel)
                with level_phase("split", d):
                    feat, bin_, na_left, gain, valid, children, _ = \
                        best_splits_hier(
                            Hc, Hf, sel, ub, nbins, W, reg_lambda, min_rows,
                            min_split_improvement, mask, reg_alpha, gamma,
                            min_child_weight)
            else:
                lcodes = hcodes if varbin_level[d] else codes
                with level_phase("hist", d):
                    if hist_mode == "subtract":
                        # smaller-sibling compaction + parent subtraction:
                        # the kernel streams only the <= N/2 rows of each
                        # parent's smaller child; the larger sibling is
                        # reconstructed from the per-shard parent carry
                        # (hist.py)
                        if d == 0:
                            H, H_carry = level_fns[0](lcodes, leaf, g, h, w)
                        else:
                            H, H_carry = level_fns[d](lcodes, leaf, g, h, w,
                                                      H_carry)
                    else:
                        # "full" oracle: every child histogrammed from
                        # all rows
                        H = hist_fns[d](lcodes, leaf, g, h, w)
                with level_phase("split", d):
                    if plan is not None:
                        from .efb import best_splits_mixed
                        (feat, bin_, na_left, gain, valid, children, wfeat,
                         lo_w, hi_w, inv_w) = best_splits_mixed(
                            H, nbins, plan, reg_lambda, min_rows,
                            min_split_improvement, mask, reg_alpha, gamma,
                            min_child_weight)
                    elif split_mode == "fused":
                        # single-pass winner records between hist and the
                        # tiny feature argmax — no [3, L, F, B] gain
                        # intermediates
                        feat, bin_, na_left, gain, valid, children = \
                            fused_best_splits(
                                H, nbins, reg_lambda, min_rows,
                                min_split_improvement, mask, reg_alpha,
                                gamma, min_child_weight)
                    else:
                        feat, bin_, na_left, gain, valid, children = \
                            best_splits(
                                H, nbins, reg_lambda, min_rows,
                                min_split_improvement, mask, reg_alpha,
                                gamma, min_child_weight,
                                mono=mono_arr if mono is not None else None)
            if d > 0:
                valid = valid & alive
                # collapse the child stats of dead slots back to "all rows
                # left" (full totals = left + right of whatever candidate
                # split best_splits picked), so final-level leaf values
                # cover every row that drains through a dead chain
                gl, hl, cl2 = children[:, 0], children[:, 1], children[:, 2]
                gr, hr, cr2 = children[:, 3], children[:, 4], children[:, 5]
                children = jnp.stack(
                    [jnp.where(valid, gl, gl + gr),
                     jnp.where(valid, hl, hl + hr),
                     jnp.where(valid, cl2, cl2 + cr2),
                     jnp.where(valid, gr, 0.0),
                     jnp.where(valid, hr, 0.0),
                     jnp.where(valid, cr2, 0.0)], axis=1)
            alive = jnp.stack([valid, valid], axis=1).reshape(-1)
            if mono is not None:
                # propagate value bounds to the children (the clamp at the
                # leaves is what guarantees global monotonicity, exactly
                # XGBoost's interaction of bounds + mid-point split)
                from .hist import newton_value
                vL = jnp.clip(newton_value(children[:, 0], children[:, 1],
                                           reg_lambda, reg_alpha), lo, hi)
                vR = jnp.clip(newton_value(children[:, 3], children[:, 4],
                                           reg_lambda, reg_alpha), lo, hi)
                mid = 0.5 * (vL + vR)
                c = mono_arr[feat] * valid.astype(jnp.float32)
                hi_l = jnp.where(c > 0, jnp.minimum(hi, mid), hi)
                lo_l = jnp.where(c < 0, jnp.maximum(lo, mid), lo)
                hi_r = jnp.where(c < 0, jnp.minimum(hi, mid), hi)
                lo_r = jnp.where(c > 0, jnp.maximum(lo, mid), lo)
                lo = jnp.stack([lo_l, lo_r], axis=1).reshape(-1)
                hi = jnp.stack([hi_l, hi_r], axis=1).reshape(-1)
            thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
            with level_phase("partition", d):
                if plan is not None:
                    from .hist import partition_ranged
                    leaf = partition_ranged(codes, leaf, wfeat, lo_w, hi_w,
                                            inv_w, na_left, valid,
                                            jnp.int32(nbins))
                else:
                    leaf = partition(codes, leaf, feat, bin_, na_left,
                                     valid, jnp.int32(nbins))
            levels.append((feat, thr, na_left, valid))
        # Newton leaf values from the last level's child sums — no extra
        # data pass (fitBestConstants from the histograms themselves)
        gl, hl, cl = children[:, 0], children[:, 1], children[:, 2]
        gr, hr, cr = children[:, 3], children[:, 4], children[:, 5]

        from .hist import newton_value

        def newton(gc, hc, cc):
            return jnp.where(cc > 0,
                             newton_value(gc, hc, reg_lambda, reg_alpha),
                             0.0)
        vals = jnp.stack([newton(gl, hl, cl), newton(gr, hr, cr)],
                         axis=1).reshape(-1)
        if mono is not None:
            # lo/hi were interleaved (left, right) per parent at the last
            # level — the same layout vals was just reshaped into
            vals = jnp.clip(vals, lo, hi)
        vals = (vals * learn_rate).astype(jnp.float32)
        # leaf covers (weighted row counts) from the same child sums — the
        # per-node weights TreeSHAP needs (PredictTreeSHAPTask reads them
        # from the compressed tree the same way)
        cover = jnp.stack([cl, cr], axis=1).reshape(-1).astype(jnp.float32)
        return levels, vals, cover, leaf

    return _ledger("tree_build", jax.jit(build), orig=build)


def _make_scan_build(max_depth: int, nbins: int, F: int, n_padded: int,
                     hist_precision: str, hist_mode: str, nk: int,
                     split_mode: str):
    """The ``tree_program="scan"`` build: one lax.scan over levels.

    Level 0 runs OUTSIDE the scan on the existing depth-0 machinery (the
    root histogram has no parent carry and no sibling to compact) and
    seeds the carries; levels 1..max_depth-1 are iterations of ONE
    fixed-width program at W = 2^(max_depth-1), the deepest level's
    child count.  Shallower levels leave slots >= 2^d empty; empty
    slots are bitwise inert end to end — they histogram exact zeros
    (no rows route there), the split search marks them invalid (then
    ``valid &= alive`` kills any padded-slot artifact), and the dead
    collapse writes the zero totals back — so each level's records and
    routing match the level-path build bit for bit on the live prefix.

    Per-level column-sample masks are drawn OUTSIDE the scan at their
    TRUE [2^d, F] shapes (threefry output depends on the draw shape, and
    bit-parity with the level path requires identical draws), padded to
    [W, F] with False and fed as scan xs.  The early-exit fence becomes
    the scan-carried ``dead = ~any(alive)`` predicate: a dead iteration
    skips the histogram kernel (hist.make_scan_level_fn's internal cond
    — the skip branch is provably the live branch's output when no rows
    moved) and the partition pass (all-invalid records route every row
    left, i.e. ``leaf -> 2*leaf`` exactly); the level path has no early
    exit, so the skips elide only provably-identical work and parity
    holds level by level.

    Bitwise caveat (documented in operations.md): the einsum histogram's
    row-block size depends on the slot width, so at padded width W vs
    the level path's true 2^d the row accumulation can associate
    differently once N is large enough to split blocks — structure stays
    exact, leaf values agree to f32 tolerance (run_program_crosscheck's
    contract).  The variable-bin kernel is never used here (uniform
    kernels only); resolve_tree_program keeps "auto" on the level path
    when varbin would engage.
    """
    B = nbins + 1
    W = 2 ** (max_depth - 1)
    Wp = W // 2
    if nk > 1:
        lev0 = make_batched_level_fn(0, nk, F, B, n_padded,
                                     precision=hist_precision,
                                     subtract=(hist_mode == "subtract"))
        if hist_mode == "subtract":
            scan_lev = make_batched_scan_level_fn(W, nk, F, B, n_padded,
                                                  precision=hist_precision)
        else:
            scan_lev = make_batched_level_fn(max_depth - 1, nk, F, B,
                                             n_padded,
                                             precision=hist_precision,
                                             subtract=False)
    else:
        if hist_mode == "subtract":
            lev0 = make_subtract_level_fn(0, F, B, n_padded,
                                          precision=hist_precision)
            scan_lev = make_scan_level_fn(W, F, B, n_padded,
                                          precision=hist_precision)
        else:
            lev0 = make_hist_fn(1, F, B, n_padded,
                                precision=hist_precision)
            scan_lev = make_hist_fn(W, F, B, n_padded,
                                    precision=hist_precision)

    def _collapse(valid, ch):
        # the level path's dead-slot stat collapse (axis=-1 indexing
        # covers both the [W, 6] and the batched [K, W, 6] shapes)
        gl, hl, cl2 = ch[..., 0], ch[..., 1], ch[..., 2]
        gr, hr, cr2 = ch[..., 3], ch[..., 4], ch[..., 5]
        return jnp.stack(
            [jnp.where(valid, gl, gl + gr),
             jnp.where(valid, hl, hl + hr),
             jnp.where(valid, cl2, cl2 + cr2),
             jnp.where(valid, gr, 0.0),
             jnp.where(valid, hr, 0.0),
             jnp.where(valid, cr2, 0.0)], axis=-1)

    def build(codes, g, h, w, edges_mat, rng_key, reg_lambda, min_rows,
              min_split_improvement, learn_rate, col_sample_rate, tree_mask,
              reg_alpha, gamma, min_child_weight):
        N = codes.shape[1]
        leaf = jnp.zeros(N, jnp.int32)
        keys = jax.random.split(rng_key, max_depth)

        def draw_mask(d):
            L = 2 ** d
            ps = jax.random.uniform(keys[d], (L, F)) < col_sample_rate
            ps = ps.at[:, 0].set((ps.any(axis=1) & ps[:, 0])
                                 | ~ps.any(axis=1))
            return ps & tree_mask[None, :]

        def _split(H, mask):
            if split_mode == "fused":
                return fused_best_splits(H, nbins, reg_lambda, min_rows,
                                         min_split_improvement, mask,
                                         reg_alpha, gamma, min_child_weight)
            return best_splits(H, nbins, reg_lambda, min_rows,
                               min_split_improvement, mask, reg_alpha,
                               gamma, min_child_weight)

        # ---- level 0 outside the scan (root: no carry, no sibling)
        if hist_mode == "subtract":
            H, Hc = lev0(codes, leaf, g, h, w)
            H_carry = jnp.pad(Hc, ((0, 0), (0, 0), (0, Wp - 1), (0, 0),
                                   (0, 0)))
        else:
            H = lev0(codes, leaf, g, h, w)
        feat, bin_, na_left, gain, valid, children = _split(H, draw_mask(0))
        thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
        leaf = partition(codes, leaf, feat, bin_, na_left, valid,
                         jnp.int32(nbins))
        lv0 = (feat, thr, na_left, valid)
        alive = jnp.pad(jnp.stack([valid, valid], axis=1).reshape(-1),
                        (0, W - 2))
        children = jnp.pad(children, ((0, W - 1), (0, 0)))
        masks = jnp.stack([
            jnp.pad(draw_mask(d), ((0, W - 2 ** d), (0, 0)))
            for d in range(1, max_depth)])

        def body(carry, mask):
            if hist_mode == "subtract":
                leaf, alive, children, H_carry = carry
            else:
                leaf, alive, children = carry
            dead = ~jnp.any(alive)
            if hist_mode == "subtract":
                H, H_carry = scan_lev(codes, leaf, g, h, w, H_carry, dead)
            else:
                H = scan_lev(codes, leaf, g, h, w)
            feat, bin_, na_left, gain, valid, ch = _split(H, mask)
            valid = valid & alive
            children = _collapse(valid, ch)
            # the next iteration reads only its first 2^(d+1) <= W slots:
            # the interleave of the first Wp parents covers them all
            alive = jnp.stack([valid[:Wp], valid[:Wp]], axis=1).reshape(-1)
            thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
            leaf = jax.lax.cond(
                dead,
                lambda c, l, f, b, na, v: 2 * l,
                lambda c, l, f, b, na, v: partition(c, l, f, b, na, v,
                                                    jnp.int32(nbins)),
                codes, leaf, feat, bin_, na_left, valid)
            out = (leaf, alive, children, H_carry) \
                if hist_mode == "subtract" else (leaf, alive, children)
            return out, (feat, thr, na_left, valid)

        carry0 = (leaf, alive, children, H_carry) \
            if hist_mode == "subtract" else (leaf, alive, children)
        carry, ys = jax.lax.scan(body, carry0, masks)
        leaf, children = carry[0], carry[2]
        # per-level records back to their true widths — static slicing
        # inside the jit, so the level contract is shape-identical to the
        # level path's
        levels = [lv0] + [
            tuple(y[i][: 2 ** (i + 1)] for y in ys)
            for i in range(max_depth - 1)]
        gl, hl, cl = children[:, 0], children[:, 1], children[:, 2]
        gr, hr, cr = children[:, 3], children[:, 4], children[:, 5]

        from .hist import newton_value

        def newton(gc, hc, cc):
            return jnp.where(cc > 0,
                             newton_value(gc, hc, reg_lambda, reg_alpha),
                             0.0)
        vals = jnp.stack([newton(gl, hl, cl), newton(gr, hr, cr)],
                         axis=1).reshape(-1)
        vals = (vals * learn_rate).astype(jnp.float32)
        cover = jnp.stack([cl, cr], axis=1).reshape(-1).astype(jnp.float32)
        return levels, vals, cover, leaf

    def buildK(codes, g, h, w, edges_mat, rng_keys, reg_lambda,
               min_rows, min_split_improvement, learn_rate,
               col_sample_rate, tree_mask, reg_alpha, gamma,
               min_child_weight):
        N = codes.shape[1]
        wK = jnp.broadcast_to(w, g.shape)
        leaf = jnp.zeros((nk, N), jnp.int32)
        keysK = jax.vmap(
            lambda kk: jax.random.split(kk, max_depth))(rng_keys)

        def draw_maskK(d):
            L = 2 ** d
            ps = jax.vmap(
                lambda kd: jax.random.uniform(kd, (L, F)))(
                    keysK[:, d]) < _per_k(col_sample_rate, 2)
            ps = ps.at[:, :, 0].set(
                (ps.any(axis=2) & ps[:, :, 0]) | ~ps.any(axis=2))
            return ps & tree_mask[:, None, :]

        if hist_mode == "subtract":
            H, Hc = lev0(codes, leaf, g, h, wK)
            H_carry = jnp.pad(Hc, ((0, 0), (0, 0), (0, 0), (0, Wp - 1),
                                   (0, 0), (0, 0)))
        else:
            H = lev0(codes, leaf, g, h, wK)
        feat, bin_, na_left, gain, valid, children = \
            fused_best_splits_batched(
                H, nbins, reg_lambda, min_rows, min_split_improvement,
                draw_maskK(0), reg_alpha, gamma, min_child_weight)
        thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
        leaf = jax.vmap(partition, in_axes=(None, 0, 0, 0, 0, 0, None))(
            codes, leaf, feat, bin_, na_left, valid, jnp.int32(nbins))
        lv0 = (feat, thr, na_left, valid)
        alive = jnp.pad(jnp.stack([valid, valid], axis=2).reshape(nk, -1),
                        ((0, 0), (0, W - 2)))
        children = jnp.pad(children, ((0, 0), (0, W - 1), (0, 0)))
        masks = jnp.stack([
            jnp.pad(draw_maskK(d), ((0, 0), (0, W - 2 ** d), (0, 0)))
            for d in range(1, max_depth)])

        def body(carry, mask):
            if hist_mode == "subtract":
                leaf, alive, children, H_carry = carry
            else:
                leaf, alive, children = carry
            # all K trees dead (an individually finished tree inside a
            # live iteration already produces the parent passthrough
            # bitwise on its own — every slot is invalid, so collapse
            # and routing are the identity for it)
            dead = ~jnp.any(alive)
            if hist_mode == "subtract":
                H, H_carry = scan_lev(codes, leaf, g, h, wK, H_carry,
                                      dead)
            else:
                H = scan_lev(codes, leaf, g, h, wK)
            feat, bin_, na_left, gain, valid, ch = \
                fused_best_splits_batched(
                    H, nbins, reg_lambda, min_rows,
                    min_split_improvement, mask, reg_alpha, gamma,
                    min_child_weight)
            valid = valid & alive
            children = _collapse(valid, ch)
            alive = jnp.stack([valid[:, :Wp], valid[:, :Wp]],
                              axis=2).reshape(nk, -1)
            thr = edges_mat[feat, jnp.clip(bin_, 0, nbins - 1)]
            leaf = jax.lax.cond(
                dead,
                lambda c, l, f, b, na, v: 2 * l,
                lambda c, l, f, b, na, v: jax.vmap(
                    partition, in_axes=(None, 0, 0, 0, 0, 0, None))(
                    c, l, f, b, na, v, jnp.int32(nbins)),
                codes, leaf, feat, bin_, na_left, valid)
            out = (leaf, alive, children, H_carry) \
                if hist_mode == "subtract" else (leaf, alive, children)
            return out, (feat, thr, na_left, valid)

        carry0 = (leaf, alive, children, H_carry) \
            if hist_mode == "subtract" else (leaf, alive, children)
        carry, ys = jax.lax.scan(body, carry0, masks)
        leaf, children = carry[0], carry[2]
        levels = [lv0] + [
            tuple(y[i][:, : 2 ** (i + 1)] for y in ys)
            for i in range(max_depth - 1)]
        gl, hl, cl = children[..., 0], children[..., 1], children[..., 2]
        gr, hr, cr = children[..., 3], children[..., 4], children[..., 5]

        from .hist import newton_value

        def newton(gc, hc, cc):
            return jnp.where(cc > 0,
                             newton_value(gc, hc, _per_k(reg_lambda, 1),
                                          _per_k(reg_alpha, 1)),
                             0.0)
        vals = jnp.stack([newton(gl, hl, cl), newton(gr, hr, cr)],
                         axis=2).reshape(nk, -1)
        vals = (vals * _per_k(learn_rate, 1)).astype(jnp.float32)
        cover = jnp.stack([cl, cr], axis=2).reshape(nk, -1) \
            .astype(jnp.float32)
        return levels, vals, cover, leaf

    if nk > 1:
        return _ledger("tree_build_scan_batched", jax.jit(buildK),
                       orig=buildK)
    return _ledger("tree_build_scan", jax.jit(build), orig=build)


def resolve_mono(params, di) -> Optional[tuple]:
    """monotone_constraints dict -> per-feature tuple in di.specs order."""
    mc = getattr(params, "monotone_constraints", None)
    if not mc:
        return None
    names = [s.name for s in di.specs]
    vec = [0.0] * len(names)
    for col, direction in mc.items():
        if col not in names:
            raise ValueError(f"monotone_constraints: unknown column "
                             f"{col!r}")
        spec = di.specs[names.index(col)]
        if getattr(spec, "type", None) == T_CAT:
            raise ValueError(f"monotone_constraints: {col!r} is "
                             "categorical; numeric features only")
        if direction not in (1, -1, 0):
            raise ValueError(f"monotone_constraints[{col!r}] must be "
                             f"1, -1 or 0, got {direction!r}")
        vec[names.index(col)] = float(direction)
    if not any(vec):
        return None                      # all zeros: unconstrained
    return tuple(vec)


def maybe_bundle(binned, params, mono, nrows: int):
    """Driver gate for EFB: plan bundles when the mode allows and the packed
    cost model says bundling wins; None keeps the un-bundled pipeline.
    Returns (plan, working_codes, F_w, working_bin_counts)."""
    from .efb import plan_bundles, apply_bundles
    mode = str(getattr(params, "efb", "auto")).lower()
    plan = None
    if mode not in ("off", "false", "0") and mono is None \
            and not use_hier_split_search(params, nrows):
        plan = plan_bundles(binned.codes, binned.bin_counts, binned.nbins,
                            nrows)
    if plan is None:
        return None, binned.codes, binned.nfeatures, binned.bin_counts
    return (plan, apply_bundles(binned.codes, plan), plan.n_working,
            plan.bin_counts)


def use_hier_split_search(params, n_padded: int) -> bool:
    """Policy gate for the hierarchical split-search path.

    ``split_search="hier"`` opts in; anything else (incl. the default
    "auto") takes the exact full-bin search — with the variable-bin kernel
    the exact path matches or beats the hierarchical one at benchmark
    scale (PROFILE.md round-2 numbers), so the approximation never
    engages implicitly.
    """
    mode = getattr(params, "split_search", "auto")
    if mode == "hier":
        return True
    # "auto" resolves to the exact search: with the variable-bin kernel the
    # exact path now matches or beats the hierarchical one at benchmark
    # scale (PROFILE.md round-2 numbers), so the approximation is opt-in.
    return False


def resolve_hist_mode(params) -> str:
    """Validate + normalize the ``hist_mode`` knob (drivers call this once;
    ``"check"`` is resolved to ``"subtract"`` AFTER run_hist_crosscheck).
    ``"auto"`` resolves to the fixed default here — drivers that route
    through ``autotune.resolve_tree_knobs`` get the tuned choice
    instead; this fallback is what the tuner's "off" mode serves."""
    mode = str(getattr(params, "hist_mode", "auto")).lower()
    if mode == "auto":
        return "subtract"
    if mode not in ("subtract", "full", "check"):
        raise ValueError(
            f"hist_mode={mode!r}: use auto | subtract | full | check")
    return mode


def resolve_split_mode(params, *, mono=None, plan=None,
                       hier: bool = False) -> str:
    """Validate + normalize the ``split_mode`` knob (mirrors
    resolve_hist_mode; drivers call this once and ``"check"`` is resolved
    to ``"fused"`` AFTER run_split_crosscheck).  Monotone constraints, EFB
    bundling and the hierarchical search have no fused implementation, so
    those builds downgrade to ``"separate"`` here — silently, matching
    the drivers' existing auto-gating of those features.  ``"auto"``
    resolves to the fixed default here (see resolve_hist_mode)."""
    mode = str(getattr(params, "split_mode", "auto")).lower()
    if mode == "auto":
        mode = "fused"
    if mode not in ("fused", "separate", "check"):
        raise ValueError(
            f"split_mode={mode!r}: use auto | fused | separate | check")
    if mode != "separate" and (mono is not None or plan is not None
                               or hier):
        return "separate"
    return mode


def sparse_layout_active(hist_layout: str, hist_mode: str = "subtract", *,
                         mono=None, plan=None, hier: bool = False) -> bool:
    """Whether the node-sparse deep-level layout ENGAGES for a build with
    these features — the single predicate every consumer (the build
    factories, the scan factories' own depth computation,
    record_effective_depth / validate_checkpoint_depth, and the drivers'
    deep_level fault hook) shares, so level counts agree everywhere.
    ``hist_mode="check"`` counts as subtract (that is what it trains with
    after the crosscheck); depth-threshold gating is the builder's job."""
    return (hist_layout in ("sparse", "auto", "check")
            and hist_mode in ("subtract", "check")
            and mono is None and plan is None and not hier)


def resolve_hist_layout(params, *, hist_mode=None, mono=None, plan=None,
                        hier: bool = False) -> str:
    """Validate + normalize the ``hist_layout`` knob (mirrors
    resolve_split_mode; drivers call this once, and ``"check"`` is
    resolved to ``"sparse"`` AFTER run_layout_crosscheck).  Returns the
    BUILDER value — "dense" or "sparse" ("sparse" means "below the
    clamped sparse_depth_threshold"; the builder applies the threshold,
    so "auto" and "sparse" build identically) — or "check" for the driver
    to act on first.  "auto" downgrades silently to "dense" for monotone
    constraints, EFB bundling, the hierarchical search, or
    hist_mode="full" (no carry to subtract from); an EXPLICIT "sparse"
    with any of those raises — failing fast beats silently training a
    different layout than asked."""
    layout = str(getattr(params, "hist_layout", "auto")).lower()
    if layout not in ("dense", "sparse", "auto", "check"):
        raise ValueError(
            f"hist_layout={layout!r}: use dense | sparse | auto | check")
    if int(getattr(params, "sparse_depth_threshold", 8)) < 1:
        raise ValueError("sparse_depth_threshold must be >= 1 (the root "
                         "level seeds the carry and is always dense)")
    if layout == "dense":
        return "dense"
    hm = hist_mode if hist_mode is not None else resolve_hist_mode(params)
    if not sparse_layout_active(layout, hm, mono=mono, plan=plan,
                                hier=hier):
        if layout == "sparse":
            raise ValueError(
                "hist_layout='sparse' does not compose with "
                "hist_mode='full', monotone constraints, EFB bundling or "
                "the hierarchical split search; use hist_layout='auto' "
                "to downgrade automatically")
        return "dense"
    return "check" if layout == "check" else "sparse"


def varbin_kernel_engages(bin_counts, nbins: int, F: int) -> bool:
    """Whether the variable-bin packed kernel would carry this frame's
    histogram levels — make_build_tree_fn's gate, factored out so
    resolve_tree_program shares it: the scan build composes with the
    uniform kernels only, so tree_program="auto" keeps per-level
    programs where varbin wins (the autotuner arbitrates the rest)."""
    if bin_counts is None:
        return False
    from ...runtime.cluster import cluster
    on_tpu = cluster().mesh.devices.flat[0].platform == "tpu"
    if not (on_tpu or os.environ.get("H2O3_TPU_HIST_IMPL", "") == "varbin"):
        return False
    return sum(min(b, nbins) + 9 for b in bin_counts) < F * (nbins + 1)


def resolve_tree_program(params, *, hist_layout: str = "dense", mono=None,
                         plan=None, hier: bool = False, bin_counts=None,
                         F: Optional[int] = None,
                         n_padded: Optional[int] = None) -> str:
    """Validate + normalize the ``tree_program`` knob (mirrors
    resolve_hist_layout; drivers call this once, and ``"check"`` is
    resolved to ``"scan"`` AFTER run_program_crosscheck).  Returns the
    BUILDER value — "level" or "scan" — or "check" for the driver to act
    on first.

    ``"auto"`` resolves to the fixed default ("level") here — drivers
    that route through ``autotune.resolve_tree_knobs`` get the tuned
    choice instead, so with ``H2O3_TPU_AUTOTUNE=off`` the pipeline stays
    bit-identical to the pre-scan per-level path.  The scan composes
    with the dense layout, uniform kernels and the plain (non-mono /
    non-EFB / non-hier) split search at effective depth >= 2;
    "auto"/"check" downgrade silently to "level" outside that envelope,
    while an EXPLICIT "scan" raises for missing features (mono / EFB /
    hier / engaged sparse levels / depth < 2) but is allowed to forfeit
    the variable-bin kernel (the one-launch program vs the packed
    per-feature kernel is a cost tradeoff, not a correctness one)."""
    prog = str(getattr(params, "tree_program", "auto")).lower()
    if prog not in ("level", "scan", "auto", "check"):
        raise ValueError(
            f"tree_program={prog!r}: use auto | level | scan | check")
    if prog == "level":
        return "level"
    blocked = mono is not None or plan is not None or hier
    md = int(getattr(params, "max_depth", 5))
    nb = int(getattr(params, "nbins", 64))
    thr = int(getattr(params, "sparse_depth_threshold", 8))
    if F is not None and n_padded is not None:
        md = effective_max_depth(md, nb, F, n_padded, hist_layout, thr)
    t0 = max(1, min(thr, dense_mem_cap(nb, F)) if F is not None else thr)
    sparse = hist_layout in ("sparse", "check") and md > t0
    if prog == "scan":
        if blocked:
            raise ValueError(
                "tree_program='scan' does not compose with monotone "
                "constraints, EFB bundling or the hierarchical split "
                "search; use tree_program='auto' to downgrade "
                "automatically")
        if sparse:
            raise ValueError(
                "tree_program='scan' requires the dense layout at every "
                "level (the scan body is ONE fixed-width program; node-"
                "sparse slot maps reshape per level); use "
                "hist_layout='dense' or tree_program='auto'")
        if md < 2:
            raise ValueError(
                "tree_program='scan' needs effective max_depth >= 2 (a "
                "depth-1 tree is the root level only — nothing to scan); "
                "use tree_program='auto' to downgrade automatically")
        return "scan"
    if prog == "auto":
        return "level"
    # "check": compare only where the scan can actually engage —
    # otherwise both builds would BE the level build (nothing to check)
    if blocked or sparse or md < 2 \
            or varbin_kernel_engages(bin_counts, nb, F or 0):
        return "level"
    return "check"


def run_hist_crosscheck(codes, g, h, w, edges_mat, rng_key, *, max_depth,
                        nbins, F, n_padded, hist_precision="f32",
                        bin_counts=None, mono=None, plan=None,
                        reg_lambda=0.0, min_rows=1.0,
                        min_split_improvement=1e-5, learn_rate=0.1,
                        reg_alpha=0.0, gamma=0.0, min_child_weight=0.0,
                        nk: int = 1, atol=1e-4):
    """The hist_mode="check" driver assert: grow ONE tree with the
    subtraction path and one with the full oracle on identical inputs and
    raise AssertionError on any divergence in split structure, row routing
    or leaf values.

    Runs on the caller's real (codes, gradients, weights) at the real
    padded shape, so it validates the exact kernel geometry + compaction
    the training run will use; cost is one extra tree build.  Exactly-tied
    gains are the one legitimate divergence source (f32 subtraction
    rounding can reorder equal gains) — that trips the assert by design:
    "byte-exact or provably within tolerance" is the contract checked.

    ``nk > 1`` covers the batched K-tree path: g/h are [K, N], rng_key is
    [K, 2], and both hist modes run through the batched level programs
    (which require the fused split search) — so a multinomial/DRF round's
    exact batched kernel geometry is what gets checked.
    """
    outs = {}
    tm = jnp.ones((nk, F), bool) if nk > 1 else jnp.ones((F,), bool)
    for mode in ("subtract", "full"):
        fn = make_build_tree_fn(max_depth, nbins, F, n_padded,
                                hist_precision, bin_counts=bin_counts,
                                mono=mono, plan=plan, hist_mode=mode,
                                nk=nk,
                                split_mode="fused" if nk > 1
                                else "separate")
        levels, vals, cover, leaf = fn(
            codes, g, h, w, edges_mat, rng_key, reg_lambda, min_rows,
            min_split_improvement, learn_rate, 1.0, tm, reg_alpha, gamma,
            min_child_weight)
        outs[mode] = jax.device_get([[list(lv) for lv in levels], vals,
                                     leaf])
    lv_s, v_s, leaf_s = outs["subtract"]
    lv_f, v_f, leaf_f = outs["full"]
    for d, (ls, lf) in enumerate(zip(lv_s, lv_f)):
        for name, i in (("feat", 0), ("na_left", 2), ("valid", 3)):
            if not np.array_equal(ls[i], lf[i]):
                raise AssertionError(
                    f"hist_mode='check': subtraction and full builds "
                    f"disagree on {name} at level {d}: "
                    f"{np.asarray(ls[i])} vs {np.asarray(lf[i])}")
        if not np.allclose(ls[1], lf[1], atol=atol, rtol=1e-5):
            raise AssertionError(
                f"hist_mode='check': split thresholds diverge at level {d}")
    if not np.array_equal(leaf_s, leaf_f):
        raise AssertionError(
            "hist_mode='check': final leaf routing differs between the "
            "subtraction and full histogram builds")
    if not np.allclose(v_s, v_f, atol=atol, rtol=1e-4):
        raise AssertionError(
            "hist_mode='check': leaf values diverge beyond tolerance "
            f"(max abs diff "
            f"{np.max(np.abs(np.asarray(v_s) - np.asarray(v_f)))})")


def run_split_crosscheck(codes, g, h, w, edges_mat, rng_keys, *, max_depth,
                         nbins, F, n_padded, hist_precision="f32",
                         bin_counts=None, hist_mode="subtract",
                         tree_masks=None, reg_lambda=0.0, min_rows=1.0,
                         min_split_improvement=1e-5, learn_rate=0.1,
                         col_sample_rate=1.0, reg_alpha=0.0, gamma=0.0,
                         min_child_weight=0.0, atol=1e-4):
    """The split_mode="check" driver assert: grow ONE round of K trees
    with the fused path (batched-K when K > 1) and with a K-loop of
    sequential separate-oracle builds on identical inputs; raise
    AssertionError on any divergence in split structure, row routing or
    leaf values.

    ``g``/``h``/``rng_keys``/``tree_masks`` carry a leading [K] (K=1
    collapses to the single-tree fused-vs-best_splits check); ``w`` is
    [N] shared or [K, N].  Runs at the caller's real padded shape so the
    exact batched kernel geometry of the training run is validated.
    Comparisons at invalid slots are masked: a dead node's stored
    (feat, thr) is arbitrary — the paths may legitimately disagree there
    when a leaf's feature draw is empty — and nothing reads it
    (partition routes by valid).  On chip, exactly tied gains can reorder
    under the records kernel's different cumsum association — same
    legitimate-divergence caveat as hist_mode="check".
    """
    g, h = jnp.asarray(g), jnp.asarray(h)
    if g.ndim == 1:
        g, h = g[None], h[None]
    K = g.shape[0]
    rng_keys = jnp.asarray(rng_keys)
    if rng_keys.ndim == 1:
        rng_keys = rng_keys[None]
    tm = jnp.asarray(tree_masks, bool) if tree_masks is not None \
        else jnp.ones((K, F), bool)
    if tm.ndim == 1:
        tm = tm[None]
    wK = jnp.broadcast_to(jnp.asarray(w), g.shape)
    hm = hist_mode if hist_mode in ("subtract", "full") else "subtract"
    sep = make_build_tree_fn(max_depth, nbins, F, n_padded, hist_precision,
                             bin_counts=bin_counts, hist_mode=hm)
    sep_out = []
    for k in range(K):
        levels, vals, cover, leaf = sep(
            codes, g[k], h[k], wK[k], edges_mat, rng_keys[k], reg_lambda,
            min_rows, min_split_improvement, learn_rate, col_sample_rate,
            tm[k], reg_alpha, gamma, min_child_weight)
        sep_out.append(jax.device_get([[list(lv) for lv in levels], vals,
                                       leaf]))
    if K > 1:
        fus = make_build_tree_fn(max_depth, nbins, F, n_padded,
                                 hist_precision, bin_counts=bin_counts,
                                 hist_mode=hm, nk=K, split_mode="fused")
        levels, vals, cover, leaf = fus(
            codes, g, h, wK, edges_mat, rng_keys, reg_lambda, min_rows,
            min_split_improvement, learn_rate, col_sample_rate, tm,
            reg_alpha, gamma, min_child_weight)
    else:
        fus = make_build_tree_fn(max_depth, nbins, F, n_padded,
                                 hist_precision, bin_counts=bin_counts,
                                 hist_mode=hm, split_mode="fused")
        levels, vals, cover, leaf = fus(
            codes, g[0], h[0], wK[0], edges_mat, rng_keys[0], reg_lambda,
            min_rows, min_split_improvement, learn_rate, col_sample_rate,
            tm[0], reg_alpha, gamma, min_child_weight)
        levels = [tuple(x[None] for x in lv) for lv in levels]
        vals, leaf = vals[None], leaf[None]
    lv_fus, v_fus, leaf_fus = jax.device_get(
        [[list(lv) for lv in levels], vals, leaf])
    for k in range(K):
        lv_s, v_s, leaf_s = sep_out[k]
        for d in range(len(lv_s)):
            valid_s = np.asarray(lv_s[d][3], bool)
            if not np.array_equal(valid_s,
                                  np.asarray(lv_fus[d][3][k], bool)):
                raise AssertionError(
                    f"split_mode='check': fused and separate builds "
                    f"disagree on valid at tree {k} level {d}")
            for name, i in (("feat", 0), ("na_left", 2)):
                a = np.asarray(lv_s[d][i])
                b = np.asarray(lv_fus[d][i][k])
                if not np.array_equal(a[valid_s], b[valid_s]):
                    raise AssertionError(
                        f"split_mode='check': {name} diverges at tree "
                        f"{k} level {d}: {a} vs {b}")
            a = np.asarray(lv_s[d][1])
            b = np.asarray(lv_fus[d][1][k])
            if not np.allclose(a[valid_s], b[valid_s], atol=atol,
                               rtol=1e-5):
                raise AssertionError(
                    f"split_mode='check': split thresholds diverge at "
                    f"tree {k} level {d}")
        if not np.array_equal(leaf_s, leaf_fus[k]):
            raise AssertionError(
                "split_mode='check': final leaf routing differs between "
                f"the fused and separate builds for tree {k}")
        if not np.allclose(v_s, v_fus[k], atol=atol, rtol=1e-4):
            raise AssertionError(
                f"split_mode='check': leaf values diverge for tree {k} "
                f"(max abs diff "
                f"{np.max(np.abs(np.asarray(v_s) - np.asarray(v_fus[k])))}"
                ")")


def run_layout_crosscheck(codes, g, h, w, edges_mat, rng_keys, *,
                          max_depth, nbins, F, n_padded,
                          hist_precision="f32", bin_counts=None,
                          sparse_depth_threshold=8, tree_masks=None,
                          reg_lambda=0.0, min_rows=1.0,
                          min_split_improvement=1e-5, learn_rate=0.1,
                          col_sample_rate=1.0, reg_alpha=0.0, gamma=0.0,
                          min_child_weight=0.0, atol=1e-4):
    """The hist_layout="check" driver assert: grow ONE tree (or one
    batched-K round — g/h/rng_keys with leading [K]) with the dense
    layout and one with the node-sparse layout on identical real inputs,
    and raise AssertionError on divergence.

    Depth is clamped to the DENSE effective depth for the comparison (the
    whole point of "sparse" is to grow past the dense memory cap, where
    no oracle exists).  The sparse path never histograms rows on dead
    chains, so dense candidate records on invalid slots are not
    reproduced: valid flags and row routing are compared EXACTLY,
    feat/na_left exactly and thresholds to tolerance WHERE VALID, and
    leaf values to f32 tolerance everywhere (dead-chain values come from
    the parent-side inheritance rather than a re-histogram).  A slot
    budget overflow (alive leaves past hist.sparse_slot_budget) forces
    children terminal on the sparse side and trips the valid compare —
    surfacing the num_leaves-style degradation is this mode's job."""
    md = effective_max_depth(max_depth, nbins, F, n_padded)
    g, h = jnp.asarray(g), jnp.asarray(h)
    squeeze = g.ndim == 1
    if squeeze:
        g, h = g[None], h[None]
    K = g.shape[0]
    rng_keys = jnp.asarray(rng_keys)
    if rng_keys.ndim == 1:
        rng_keys = rng_keys[None]
    tm = jnp.asarray(tree_masks, bool) if tree_masks is not None \
        else jnp.ones((K, F), bool)
    if tm.ndim == 1:
        tm = tm[None]
    wK = jnp.broadcast_to(jnp.asarray(w), g.shape)
    outs = {}
    for layout in ("dense", "sparse"):
        fn = make_build_tree_fn(
            md, nbins, F, n_padded, hist_precision,
            bin_counts=bin_counts, hist_mode="subtract",
            nk=K if K > 1 else 1,
            split_mode="fused" if K > 1 else "separate",
            hist_layout=layout,
            sparse_depth_threshold=sparse_depth_threshold)
        if K > 1:
            levels, vals, cover, leaf = fn(
                codes, g, h, wK, edges_mat, rng_keys, reg_lambda,
                min_rows, min_split_improvement, learn_rate,
                col_sample_rate, tm, reg_alpha, gamma, min_child_weight)
        else:
            levels, vals, cover, leaf = fn(
                codes, g[0], h[0], wK[0], edges_mat, rng_keys[0],
                reg_lambda, min_rows, min_split_improvement, learn_rate,
                col_sample_rate, tm[0], reg_alpha, gamma,
                min_child_weight)
            levels = [tuple(x[None] for x in lv) for lv in levels]
            vals, leaf = vals[None], leaf[None]
        outs[layout] = jax.device_get(
            [[list(lv) for lv in levels], vals, leaf])
    lv_d, v_d, leaf_d = outs["dense"]
    lv_s, v_s, leaf_s = outs["sparse"]
    for k in range(K):
        for d in range(len(lv_d)):
            valid_d = np.asarray(lv_d[d][3][k], bool)
            if not np.array_equal(valid_d,
                                  np.asarray(lv_s[d][3][k], bool)):
                raise AssertionError(
                    f"hist_layout='check': dense and sparse builds "
                    f"disagree on valid at tree {k} level {d} (an alive-"
                    f"leaf count past the slot budget forces terminal "
                    f"leaves on the sparse side — see sparse_slot_budget)")
            for name, i in (("feat", 0), ("na_left", 2)):
                a = np.asarray(lv_d[d][i][k])
                b = np.asarray(lv_s[d][i][k])
                if not np.array_equal(a[valid_d], b[valid_d]):
                    raise AssertionError(
                        f"hist_layout='check': {name} diverges at tree "
                        f"{k} level {d}")
            a = np.asarray(lv_d[d][1][k])
            b = np.asarray(lv_s[d][1][k])
            if not np.allclose(a[valid_d], b[valid_d], atol=atol,
                               rtol=1e-5):
                raise AssertionError(
                    f"hist_layout='check': split thresholds diverge at "
                    f"tree {k} level {d}")
        if not np.array_equal(leaf_d[k], leaf_s[k]):
            raise AssertionError(
                "hist_layout='check': final leaf routing differs "
                f"between the dense and sparse builds for tree {k}")
        if not np.allclose(v_d[k], v_s[k], atol=atol, rtol=1e-4):
            raise AssertionError(
                f"hist_layout='check': leaf values diverge for tree {k} "
                f"(max abs diff "
                f"{np.max(np.abs(np.asarray(v_d[k]) - np.asarray(v_s[k])))}"
                ")")


def run_program_crosscheck(codes, g, h, w, edges_mat, rng_keys, *,
                           max_depth, nbins, F, n_padded,
                           hist_precision="f32", hist_mode="subtract",
                           split_mode="fused", tree_masks=None,
                           reg_lambda=0.0, min_rows=1.0,
                           min_split_improvement=1e-5, learn_rate=0.1,
                           col_sample_rate=1.0, reg_alpha=0.0, gamma=0.0,
                           min_child_weight=0.0, atol=1e-4):
    """The tree_program="check" driver assert: grow ONE tree (or one
    batched-K round — g/h/rng_keys with leading [K]) with the scan-fused
    program and one with the per-level program on identical real inputs,
    and raise AssertionError on divergence.

    The scan runs every level at the padded width 2^(max_depth-1), so
    the einsum histogram's row blocking can associate f32 row sums
    differently than the level path's true-width programs once N splits
    blocks: structure (valid flags, feat/na_left where valid, row
    routing) is compared EXACTLY, thresholds and leaf values to f32
    tolerance — the same contract run_layout_crosscheck enforces for the
    node-sparse layout.  Dead-slot candidate records are masked out of
    the compare (nothing reads them; partition routes by valid)."""
    g, h = jnp.asarray(g), jnp.asarray(h)
    if g.ndim == 1:
        g, h = g[None], h[None]
    K = g.shape[0]
    rng_keys = jnp.asarray(rng_keys)
    if rng_keys.ndim == 1:
        rng_keys = rng_keys[None]
    tm = jnp.asarray(tree_masks, bool) if tree_masks is not None \
        else jnp.ones((K, F), bool)
    if tm.ndim == 1:
        tm = tm[None]
    wK = jnp.broadcast_to(jnp.asarray(w), g.shape)
    hm = hist_mode if hist_mode in ("subtract", "full") else "subtract"
    sm = split_mode if split_mode in ("fused", "separate") else "fused"
    outs = {}
    for prog in ("level", "scan"):
        fn = make_build_tree_fn(
            max_depth, nbins, F, n_padded, hist_precision,
            hist_mode=hm, nk=K if K > 1 else 1,
            split_mode="fused" if K > 1 else sm,
            tree_program=prog)
        if K > 1:
            levels, vals, cover, leaf = fn(
                codes, g, h, wK, edges_mat, rng_keys, reg_lambda,
                min_rows, min_split_improvement, learn_rate,
                col_sample_rate, tm, reg_alpha, gamma, min_child_weight)
        else:
            levels, vals, cover, leaf = fn(
                codes, g[0], h[0], wK[0], edges_mat, rng_keys[0],
                reg_lambda, min_rows, min_split_improvement, learn_rate,
                col_sample_rate, tm[0], reg_alpha, gamma,
                min_child_weight)
            levels = [tuple(x[None] for x in lv) for lv in levels]
            vals, leaf = vals[None], leaf[None]
        outs[prog] = jax.device_get(
            [[list(lv) for lv in levels], vals, leaf])
    lv_l, v_l, leaf_l = outs["level"]
    lv_s, v_s, leaf_s = outs["scan"]
    for k in range(K):
        for d in range(len(lv_l)):
            valid_d = np.asarray(lv_l[d][3][k], bool)
            if not np.array_equal(valid_d,
                                  np.asarray(lv_s[d][3][k], bool)):
                raise AssertionError(
                    f"tree_program='check': scan and level builds "
                    f"disagree on valid at tree {k} level {d}")
            for name, i in (("feat", 0), ("na_left", 2)):
                a = np.asarray(lv_l[d][i][k])
                b = np.asarray(lv_s[d][i][k])
                if not np.array_equal(a[valid_d], b[valid_d]):
                    raise AssertionError(
                        f"tree_program='check': {name} diverges at tree "
                        f"{k} level {d}")
            a = np.asarray(lv_l[d][1][k])
            b = np.asarray(lv_s[d][1][k])
            if not np.allclose(a[valid_d], b[valid_d], atol=atol,
                               rtol=1e-5):
                raise AssertionError(
                    f"tree_program='check': split thresholds diverge at "
                    f"tree {k} level {d}")
        if not np.array_equal(leaf_l[k], leaf_s[k]):
            raise AssertionError(
                "tree_program='check': final leaf routing differs "
                f"between the scan and level builds for tree {k}")
        if not np.allclose(v_l[k], v_s[k], atol=atol, rtol=1e-4):
            raise AssertionError(
                f"tree_program='check': leaf values diverge for tree {k} "
                f"(max abs diff "
                f"{np.max(np.abs(np.asarray(v_l[k]) - np.asarray(v_s[k])))}"
                ")")


@functools.lru_cache(maxsize=None)
def make_tree_scan_fn(mode: str, tweedie_power: float, quantile_alpha: float,
                      huber_alpha: float, max_depth: int, nbins: int, F: int,
                      n_padded: int, hist_precision: str, sample_rate: float,
                      col_sample_rate_per_tree: float, hier: bool = False,
                      bin_counts=None, mono=None, custom_fn=None, plan=None,
                      hist_mode: str = "subtract",
                      split_mode: str = "fused",
                      hist_layout: str = "dense",
                      sparse_depth_threshold: int = 8,
                      tree_program: str = "level"):
    """Scan a CHUNK of boosting/bagging rounds in ONE device dispatch.

    The per-tree driver loop (gradients -> row/column sample -> grow ->
    F update) becomes the body of a ``lax.scan`` over per-tree PRNG keys, so
    a whole scoring interval of trees costs one dispatch instead of
    one-plus per tree — on a remote TPU the per-dispatch round trip is the
    dominant driver-side cost.  ``mode`` is a distribution name for boosting
    or ``"drf"`` for the forest mean-fit (grad=-y, hess=1).  Returns
    (F_final, levels, values) with levels/values carrying a leading [T] dim —
    exactly the ``StackedTrees`` layout.
    """
    from ..distributions import make_distribution
    dist = None
    if mode != "drf":
        dist = make_distribution(
            mode, nclasses=2 if mode == "bernoulli" else 1,
            tweedie_power=tweedie_power, quantile_alpha=quantile_alpha,
            huber_alpha=huber_alpha, custom_distribution_func=custom_fn)
    if mono is not None or plan is not None or hier:
        split_mode = "separate"          # no fused path for these builds
        hist_layout = "dense"            # nor a sparse one (resolve_*)
        tree_program = "level"           # nor a scan-fused one
    bt_fn = make_build_tree_fn(max_depth, nbins, F, n_padded, hist_precision,
                               hier=hier, bin_counts=bin_counts, mono=mono,
                               plan=plan, hist_mode=hist_mode,
                               split_mode=split_mode,
                               hist_layout=hist_layout,
                               sparse_depth_threshold=sparse_depth_threshold,
                               tree_program=tree_program)

    def scan_fn(codes, y, w, F0, edges_mat, rng0, chunk_no, nchunk,
                reg_lambda, min_rows, min_split_improvement, learn_rate,
                col_sample_rate, reg_alpha, gamma, min_child_weight, salt=0):
        # Per-chunk keys derive IN-JIT from (rng0, chunk_no): each eager
        # jax.random op costs a ~50 ms round trip on a tunnelled backend
        # (measured round 4), so the driver loop must stay dispatch-only.
        # ``nchunk`` (trees per chunk) is static — it sets the scan length.
        # ``salt`` decorrelates column/build randomness between callers that
        # share the chunk stream (DRF class trees share the bootstrap via ks
        # but must draw independent per-split feature subsets).
        keys = jax.random.split(jax.random.fold_in(rng0, chunk_no), nchunk)
        def body(Fc, key_t):
            ks, km, kb = jax.random.split(key_t, 3)
            km = jax.random.fold_in(km, salt)
            kb = jax.random.fold_in(kb, salt)
            if mode == "drf":
                g0, h0 = -y, jnp.ones_like(y)
            else:
                g0, h0 = dist.grad_hess(y, Fc)
            wv = w
            if sample_rate < 1.0:
                wv = w * jax.random.bernoulli(ks, sample_rate, w.shape)
            tm = jnp.ones((F,), bool)
            if col_sample_rate_per_tree < 1.0:
                m = jax.random.uniform(km, (F,)) < col_sample_rate_per_tree
                tm = m.at[0].set(m[0] | ~m.any())
            levels, vals, cover, leaf = bt_fn(
                codes, g0 * wv, h0 * wv, wv, edges_mat, kb, reg_lambda,
                min_rows, min_split_improvement, learn_rate, col_sample_rate,
                tm, reg_alpha, gamma, min_child_weight)
            from .hist import table_lookup
            dF = table_lookup(vals[None, :], leaf, vals.shape[0])[0]
            return Fc + dF, (tuple(levels), vals, cover)

        Ff, (lv, vals, covers) = jax.lax.scan(body, F0, keys)
        return Ff, list(lv), vals, covers

    return _ledger("tree_scan",
                   jax.jit(scan_fn, donate_argnums=(3,), static_argnums=(7,)),
                   static_argnums=(7,), orig=scan_fn)


@functools.lru_cache(maxsize=None)
def make_multinomial_scan_fn(K: int, max_depth: int, nbins: int, F: int,
                             n_padded: int, hist_precision: str,
                             sample_rate: float,
                             col_sample_rate_per_tree: float,
                             hier: bool = False, bin_counts=None, plan=None,
                             hist_mode: str = "subtract",
                             split_mode: str = "fused",
                             mode: str = "multinomial",
                             hist_layout: str = "dense",
                             sparse_depth_threshold: int = 8,
                             tree_program: str = "level"):
    """Scan a chunk of K-tree rounds in ONE dispatch.

    Each round grows K one-vs-rest trees — on softmax gradients for
    ``mode="multinomial"`` (GBM.java buildNextKTrees' K-tree loop) or on
    the constant forest fit (grad=-y, hess=1) for ``mode="drf"`` — all
    inside the scan body.  Rows are sampled once per round and shared
    across the K class trees (reference semantics).

    ``split_mode="fused"`` (default) grows the K trees as ONE batched
    build (make_build_tree_fn nk=K): one hist launch + one split-records
    launch per level regardless of K, and the traced scan body holds one
    level program instead of K copies.  ``"separate"`` keeps the
    K-iteration Python loop of single-tree builds — the oracle the
    batched path reproduces key-for-key (same fold_in structure), which
    run_split_crosscheck asserts on real data.

    Returns (F_final [N, K], levels with leading [T, K, ...] dims, values
    [T, K, 2^depth], covers [T, K, 2^depth]) — identical layout on both
    paths.
    """
    if mode not in ("multinomial", "drf"):
        raise ValueError(f"mode={mode!r}: use 'multinomial' or 'drf'")
    if hier or plan is not None:
        split_mode = "separate"          # no fused path for these builds
        hist_layout = "dense"            # nor a sparse one (resolve_*)
        tree_program = "level"           # nor a scan-fused one
    # the builder clamps internally; the level-stacking loop below must
    # iterate the SAME effective count — layout-aware, like the builder
    max_depth = effective_max_depth(max_depth, nbins, F, n_padded,
                                    hist_layout, sparse_depth_threshold)
    batched = split_mode == "fused" and K > 1
    bt_fn = make_build_tree_fn(max_depth, nbins, F, n_padded,
                               hist_precision, hier=hier,
                               bin_counts=bin_counts, plan=plan,
                               hist_mode=hist_mode,
                               nk=K if batched else 1,
                               split_mode=split_mode,
                               hist_layout=hist_layout,
                               sparse_depth_threshold=sparse_depth_threshold,
                               tree_program=tree_program)

    def scan_fn(codes, Y1, w, F0, edges_mat, rng0, chunk_no, nchunk,
                reg_lambda, min_rows, min_split_improvement, learn_rate,
                col_sample_rate, reg_alpha, gamma, min_child_weight):
        from .hist import table_lookup
        # in-jit key derivation — see make_tree_scan_fn
        keys = jax.random.split(jax.random.fold_in(rng0, chunk_no), nchunk)

        def body(Fc, key_t):
            ks, km, kb = jax.random.split(key_t, 3)
            if mode == "drf":
                # forest mean-fit: constant pseudo-gradients, no feedback
                g = -Y1
                h = jnp.ones_like(Y1)
            else:
                Pr = jax.nn.softmax(Fc, axis=1)
                g = Pr - Y1
                h = jnp.maximum(Pr * (1 - Pr), 1e-10)
            wv = w
            if sample_rate < 1.0:
                wv = w * jax.random.bernoulli(ks, sample_rate, w.shape)
            # per-class key/mask derivation is IDENTICAL on both paths
            # (fold_in(kb, k) / fold_in(km, k)) so batched and separate
            # rounds draw the same columns and per-split subsets
            tms, kks = [], []
            for k in range(K):
                kks.append(jax.random.fold_in(kb, k))
                tm = jnp.ones((F,), bool)
                if col_sample_rate_per_tree < 1.0:
                    m = jax.random.uniform(
                        jax.random.fold_in(km, k),
                        (F,)) < col_sample_rate_per_tree
                    tm = m.at[0].set(m[0] | ~m.any())
                tms.append(tm)
            if batched:
                levels, vals, covers, leafK = bt_fn(
                    codes, (g * wv[:, None]).T, (h * wv[:, None]).T, wv,
                    edges_mat, jnp.stack(kks), reg_lambda, min_rows,
                    min_split_improvement, learn_rate, col_sample_rate,
                    jnp.stack(tms), reg_alpha, gamma, min_child_weight)
                dF = jax.vmap(
                    lambda v, l: table_lookup(v[None, :], l,
                                              v.shape[0])[0])(vals, leafK)
                return Fc + dF.T, (tuple(tuple(lvl) for lvl in levels),
                                   vals, covers)
            per_levels, per_vals, per_covers, dFs = [], [], [], []
            for k in range(K):
                levels, vals, cover, leaf = bt_fn(
                    codes, g[:, k] * wv, h[:, k] * wv, wv, edges_mat,
                    kks[k], reg_lambda, min_rows, min_split_improvement,
                    learn_rate, col_sample_rate, tms[k], reg_alpha, gamma,
                    min_child_weight)
                per_levels.append(levels)
                per_vals.append(vals)
                per_covers.append(cover)
                dFs.append(table_lookup(vals[None, :], leaf,
                                        vals.shape[0])[0])
            Fc = Fc + jnp.stack(dFs, axis=1)
            # stack class-k trees: per depth, each field gains a [K] dim
            lv = tuple(
                tuple(jnp.stack([per_levels[k][d][i] for k in range(K)])
                      for i in range(4))
                for d in range(max_depth))
            vals = jnp.stack(per_vals)
            covers = jnp.stack(per_covers)
            return Fc, (lv, vals, covers)

        Ff, (lv, vals, covers) = jax.lax.scan(body, F0, keys)
        return Ff, list(lv), vals, covers

    return _ledger("tree_scan_multinomial",
                   jax.jit(scan_fn, donate_argnums=(3,), static_argnums=(7,)),
                   static_argnums=(7,), orig=scan_fn)


@functools.lru_cache(maxsize=None)
def make_grid_scan_fn(G: int, mode: str, tweedie_power: float,
                      quantile_alpha: float, huber_alpha: float,
                      max_depth: int, nbins: int, F: int, n_padded: int,
                      hist_precision: str, custom_fn=None,
                      hist_mode: str = "subtract",
                      tree_program: str = "level"):
    """Scan a chunk of G-member GRID rounds in ONE dispatch.

    The hyperparameter analog of ``make_multinomial_scan_fn``: the K
    class-tree axis generalizes to G grid members of the SAME shape
    (max_depth/nbins/ntrees/layout), each carrying its OWN scalar
    hyperparameters as ``[G]`` operands — eta, row/column sample rates,
    lambda/alpha/gamma, ``min_rows``/``min_child_weight``/
    ``min_split_improvement``.  Anything that doesn't change trace shape
    batches; the shared ``[F, N]`` codes stay unbatched.

    Per-member RNG reproduces ``make_tree_scan_fn``'s sequential chains
    bitwise: each member supplies its own root key (``rng0G [G, 2]``),
    the chunk/tree/draw derivation (``fold_in(chunk_no)`` -> split ->
    ks/km/kb with the salt-0 fold) is vmapped per member, and vmapped
    threefry emits the per-key bits exactly — so a G-loop of sequential
    ``make_tree_scan_fn`` builds is this program's bitwise oracle.
    Row/column sampling draws ALWAYS happen here (the sequential path
    skips them statically at rate 1.0); a rate-1.0 member's mask is
    all-True and ``x * 1.0`` is an IEEE identity, so parity holds.

    ``alive [G]`` is the successive-halving retirement mask, a TRACED
    operand: retiring a member zeroes its row weights (all histograms
    empty -> every split invalid -> zero leaf values -> its F column
    freezes) without recompilation.

    Unlike the single/multinomial factories the per-member params are
    call operands, not factory constants — one compiled program serves
    the whole cohort across rungs.  Fused splits + dense layout only
    (grid cohorts gate hier/mono/EFB/sparse to the wave path).
    """
    from ..distributions import make_distribution
    if G < 2:
        raise ValueError("make_grid_scan_fn needs G >= 2 (a single "
                         "member is the sequential path)")
    dist = None
    if mode != "drf":
        dist = make_distribution(
            mode, nclasses=2 if mode == "bernoulli" else 1,
            tweedie_power=tweedie_power, quantile_alpha=quantile_alpha,
            huber_alpha=huber_alpha, custom_distribution_func=custom_fn)
    bt_fn = make_build_tree_fn(max_depth, nbins, F, n_padded,
                               hist_precision, hist_mode=hist_mode,
                               nk=G, split_mode="fused",
                               hist_layout="dense",
                               tree_program=tree_program)

    def scan_fn(codes, y, w, F0, edges_mat, rng0G, chunk_no, nchunk,
                reg_lambda, min_rows, min_split_improvement, learn_rate,
                col_sample_rate, sample_rate, col_sample_rate_per_tree,
                alive, reg_alpha, gamma, min_child_weight):
        from .hist import table_lookup
        N = codes.shape[1]
        # per-member chunk keys, vmapped: [G, T, 2] -> scan xs [T, G, 2]
        keysG = jax.vmap(
            lambda r: jax.random.split(jax.random.fold_in(r, chunk_no),
                                       nchunk))(rng0G)
        keys = jnp.swapaxes(keysG, 0, 1)
        srG = jnp.broadcast_to(jnp.asarray(sample_rate, jnp.float32), (G,))
        csptG = jnp.broadcast_to(
            jnp.asarray(col_sample_rate_per_tree, jnp.float32), (G,))

        def body(Fc, keys_g):
            kk = jax.vmap(lambda k: jax.random.split(k, 3))(keys_g)
            ks, km, kb = kk[:, 0], kk[:, 1], kk[:, 2]
            # the sequential scan applies the salt fold unconditionally
            # (GBM salt=0, and fold_in(k, 0) != k) — replicate it
            km = jax.vmap(lambda k: jax.random.fold_in(k, 0))(km)
            kb = jax.vmap(lambda k: jax.random.fold_in(k, 0))(kb)
            if mode == "drf":
                g0 = jnp.broadcast_to(-y, Fc.shape)
                h0 = jnp.ones_like(Fc)
            else:
                g0, h0 = jax.vmap(dist.grad_hess, in_axes=(None, 0))(y, Fc)
            rs = jax.vmap(
                lambda k2, r: jax.random.bernoulli(k2, r, (N,)))(ks, srG)
            wv = (w[None, :] * rs) * alive[:, None]
            m = jax.vmap(
                lambda k2: jax.random.uniform(k2, (F,)))(km) \
                < csptG[:, None]
            tm = m.at[:, 0].set(m[:, 0] | ~m.any(axis=1))
            levels, vals, cover, leafG = bt_fn(
                codes, g0 * wv, h0 * wv, wv, edges_mat, kb, reg_lambda,
                min_rows, min_split_improvement, learn_rate,
                col_sample_rate, tm, reg_alpha, gamma, min_child_weight)
            dF = jax.vmap(
                lambda v, l: table_lookup(v[None, :], l,
                                          v.shape[0])[0])(vals, leafG)
            return Fc + dF, (tuple(tuple(lvl) for lvl in levels),
                             vals, cover)

        Ff, (lv, vals, covers) = jax.lax.scan(body, F0, keys)
        return Ff, list(lv), vals, covers

    return _ledger("tree_scan_grid",
                   jax.jit(scan_fn, donate_argnums=(3,), static_argnums=(7,)),
                   static_argnums=(7,), orig=scan_fn)


# jitted-program caches keyed on distribution parameters (pure functions of
# their key — custom UDF distributions bypass these)
_PREDS_JIT_CACHE: dict = {}
_PREP_JIT_CACHE: dict = {}


def tree_snapshot_state(chunks, init_host, edges) -> dict:
    """Model-so-far output override for a progress snapshot of a fused
    single-class tree build (runtime/snapshot.py): concatenates the
    trained chunks host-side (tree metadata — kilobytes) into exactly the
    fields ``resolve_checkpoint`` needs to continue the run."""
    st = StackedTrees.concat(list(chunks))
    return {"trees": TreeList(st), "ntrees_trained": st.ntrees,
            "init_score": init_host, "edges": edges}


def tree_snapshot_state_multi(chunks_k, init_host, edges) -> dict:
    """Multinomial variant of ``tree_snapshot_state`` (K per-class
    chunk lists -> TreeListMulti)."""
    stacks = [StackedTrees.concat(list(ch)) for ch in chunks_k]
    return {"trees": TreeListMulti(stacks),
            "ntrees_trained": stacks[0].ntrees,
            "init_score": init_host, "edges": edges}


def chunk_schedule(ntrees: int, score_tree_interval: int,
                   chunk_cap: int = 10, fence=None):
    """Yield (chunk_len, trees_done, score_now) for the scan driver loop.

    Chunks have a fixed length (``chunk_cap``) so every chunk reuses one
    compiled scan program; chunk boundaries land exactly on scoring
    intervals so early-stopping semantics match the per-tree loop.

    ``fence(trees_done) -> bool`` is the streaming-ingest rendezvous: it
    runs after the consumer has processed each yielded chunk, and a True
    return ends the schedule early so the driver can finalize on the
    trees built so far (the stream driver then re-bins the grown frame
    and continues via a checkpoint segment).
    """
    from ...runtime import failure, scheduler
    from .. import parallel
    interval = max(1, min(score_tree_interval, ntrees))
    cap = min(chunk_cap, interval)
    t = 0
    while t < ntrees:
        failure.maybe_inject("tree_chunk")
        # cooperative max_runtime_secs cancel: a deadline set by
        # map_builds (grid waves) or the cohort trainer fires HERE, at
        # the chunk fence, so an in-flight member stops between chunks
        # instead of overshooting the budget by a whole build
        parallel.check_deadline()
        # chunk boundaries are the fence for elastic mesh rebuilds: a
        # host join armed by the membership observer applies here, and
        # the next compile re-traces against the rebuilt mesh
        scheduler.chunk_fence()
        c = min(cap, ntrees - t, interval - (t % interval))
        t += c
        yield c, t, (t % interval == 0 or t >= ntrees)
        if fence is not None and t < ntrees and fence(t):
            return


def build_tree(codes, g, h, w, edges, nbins: int, max_depth: int,
               reg_lambda: float, min_rows: float, min_split_improvement: float,
               learn_rate: float, rng_key, col_sample_rate: float = 1.0,
               tree_col_mask: Optional[np.ndarray] = None,
               reg_alpha: float = 0.0, gamma: float = 0.0,
               min_child_weight: float = 0.0, hist_precision: str = "bf16",
               hier: bool = False, mono=None, hist_mode: str = "subtract",
               split_mode: str = "fused", hist_layout: str = "dense",
               sparse_depth_threshold: int = 8,
               tree_program: str = "level"):
    """Grow one tree — convenience wrapper around make_build_tree_fn.

    ``edges`` may be the per-feature edge list (converted to the dense
    lookup table here) or an already-built [F, nbins] matrix.
    Returns (Tree, final_leaf_assignment[N]); Tree fields stay on device
    until something materializes them.
    """
    from .binning import edges_matrix
    F, N = codes.shape
    if isinstance(edges, (list, tuple)):
        edges = edges_matrix(edges, nbins)
    edges_mat = jnp.asarray(edges, jnp.float32)
    tm = jnp.asarray(tree_col_mask, bool) if tree_col_mask is not None \
        else jnp.ones(F, bool)
    if mono is not None or hier:
        split_mode = "separate"          # no fused path for these builds
        hist_layout = "dense"            # nor a sparse one (resolve_*)
        tree_program = "level"           # nor a scan-fused one
    fn = make_build_tree_fn(max_depth, nbins, F, N, hist_precision,
                            hier=hier, mono=mono, hist_mode=hist_mode,
                            split_mode=split_mode, hist_layout=hist_layout,
                            sparse_depth_threshold=sparse_depth_threshold,
                            tree_program=tree_program)
    from ...runtime import observability as obs
    with obs.span("tree_build", depth=max_depth, rows=int(N)):
        levels, vals, cover, leaf = fn(codes, g, h, w, edges_mat, rng_key,
                                       reg_lambda, min_rows,
                                       min_split_improvement, learn_rate,
                                       col_sample_rate, tm, reg_alpha,
                                       gamma, min_child_weight)
    tree = Tree([lv[0] for lv in levels], [lv[1] for lv in levels],
                [lv[2] for lv in levels], [lv[3] for lv in levels], vals,
                cover=cover)
    return tree, leaf


class SharedTreeModel(Model):
    """Tree-ensemble model: scores via compiled stacked-tree traversal."""

    def _calibration_curve(self, p1: np.ndarray) -> np.ndarray:
        cal = self.output.get("calibration")
        if cal is None:
            raise ValueError("model was not calibrated "
                             "(calibrate_model=True + calibration_frame)")
        if cal["method"] == "platt":
            return 1.0 / (1.0 + np.exp(-(cal["a"] * p1 + cal["b"])))
        return np.interp(p1, cal["x"], cal["y"])

    def calibrated_probabilities(self, frame: Frame) -> np.ndarray:
        """P(class 1) after calibration — CalibrationHelper.predict."""
        raw = np.asarray(self._predict_raw(
            self._score_matrix(frame)))[: frame.nrows]
        return self._calibration_curve(raw[:, 1] if raw.ndim == 2 else raw)

    def predict(self, frame: Frame) -> Frame:
        out = super().predict(frame)
        if self.output.get("calibration") is not None:
            from ...frame.vec import Vec
            # reuse the class-1 probability column already computed —
            # no second traversal of the ensemble
            dom = self.datainfo.response_domain
            p1 = self._calibration_curve(out.vec(str(dom[1])).to_numpy())
            out = out.with_vec("cal_p0", Vec.from_numpy(1.0 - p1))
            out = out.with_vec("cal_p1", Vec.from_numpy(p1))
        return out

    def varimp(self, frame: Optional[Frame] = None,
               method: str = "cover") -> dict:
        """Variable importances — hex/tree VarImp analog.

        ``method="cover"``: per-feature sum of training covers at the
        nodes that split on it (cover-weighted split frequency; computed
        from the recorded leaf covers, no data pass).  ``method="shap"``:
        mean |TreeSHAP contribution| over ``frame`` (needs a frame;
        binomial/regression only).  Returns {feature: relative importance}
        scaled so the max is 1.
        """
        names = [s.name for s in self.datainfo.specs]
        if method == "shap":
            if frame is None:
                raise ValueError("varimp(method='shap') needs a frame")
            contrib = self.predict_contributions(frame).to_numpy()[:, :-1]
            imp = np.abs(contrib).mean(axis=0)
        else:
            from ...export.treeshap import shap_trees_from_model
            imp = np.zeros(len(names))
            trees = list(self.output["trees"])
            if trees and isinstance(trees[0], list):
                trees = [tc for kt in trees for tc in kt]  # multinomial
            for t in shap_trees_from_model(trees):
                for d in range(t.depth):
                    valid = t.valid[d]
                    cover = t.cover[d]
                    feats = t.feat[d]
                    for i in np.flatnonzero(valid):
                        imp[int(feats[i])] += cover[i]
        mx = imp.max()
        rel = imp / mx if mx > 0 else imp
        order = np.argsort(-rel)
        return {names[i]: float(rel[i]) for i in order}

    def predict_contributions(self, frame: Frame) -> Frame:
        """Per-feature TreeSHAP contributions + BiasTerm (margin space).

        Reference: EasyPredictModelWrapper.predictContributions /
        PredictTreeSHAPTask — binomial and regression models only, exact
        Shapley values per Lundberg's TreeSHAP using the per-node covers
        recorded at training.  ``sum(contributions) + BiasTerm`` equals
        the raw margin (GBM/XGBoost) or the averaged leaf sum (DRF).
        """
        from ...export import treeshap
        K = self.output.get("nclass_trees", 1)
        if K > 1:
            raise ValueError("predict_contributions supports binomial and "
                             "regression models only (reference parity)")
        trees = list(self.output["trees"])
        st = treeshap.shap_trees_from_model(trees)
        X = np.asarray(self._design(frame))[: frame.nrows].astype(np.float64)
        if self.algo == "drf":
            scale, init = 1.0 / max(len(trees), 1), 0.0
        else:
            scale, init = 1.0, float(np.asarray(self.output["init_score"]))
        contribs = treeshap.ensemble_contributions(st, X, init, scale)
        names = [s.name for s in self.datainfo.specs] + ["BiasTerm"]
        from ...frame.vec import Vec
        vecs = [Vec.from_numpy(contribs[:, j]) for j in range(len(names))]
        return Frame(names, vecs)

    def _score_matrix(self, frame: Frame) -> jax.Array:
        return self._design(frame)

    def _design(self, frame: Frame) -> jax.Array:
        """Raw-value matrix [padded, F]: numerics as-is, cats as codes."""
        di = self.datainfo
        cols = []
        for s in di.specs:
            vec = frame.vec(s.name)
            if s.type == T_CAT:
                codes = di._aligned_codes(vec, s)
                cols.append(jnp.where(codes < 0, jnp.nan,
                                      codes.astype(jnp.float32)))
            else:
                cols.append(vec.data)
        return jnp.stack(cols, axis=1)

    def _raw_scores(self, X: jax.Array):
        init = self.output["init_score"]
        K = self.output.get("nclass_trees", 1)
        stacked = self.output.get("stacked")
        if K == 1:
            if stacked is None:
                stacked = StackedTrees.from_trees(self.output["trees"])
                self.output["stacked"] = stacked
            return init + traverse_jit(stacked.levels, stacked.values, X)
        if stacked is None:
            trees = self.output["trees"]
            stacked = [StackedTrees.from_trees([t[k] for t in trees])
                       for k in range(K)]
            self.output["stacked"] = stacked
        outs = []
        for k in range(K):
            outs.append(init[k]
                        + traverse_jit(stacked[k].levels, stacked[k].values, X))
        return jnp.stack(outs, axis=1)


def resolve_checkpoint(params, di, algo: str):
    """Load + validate a checkpoint model for continued training.

    Reference: ``hex/Model.java:521`` (checkpoint support for DL/DRF/GBM/
    XGBoost) and GBM.java's non-modifiable-parameter check: the continued
    run must keep the tree geometry (max_depth, nbins, distribution) and
    ask for MORE trees; the prior model's bin edges are reused so codes
    stay consistent across the two runs.
    """
    ckpt = params.checkpoint
    if ckpt is None:
        return None
    prior = ckpt if not isinstance(ckpt, str) else dkv.get(ckpt)
    if prior is None:
        raise ValueError(f"checkpoint {ckpt!r} not found in DKV")
    if prior.algo != algo:
        raise ValueError(f"checkpoint algo {prior.algo!r} != {algo!r}")
    for attr in ("max_depth", "nbins", "distribution", "response_column",
                 "histogram_type"):
        a, b = getattr(prior.params, attr, None), getattr(params, attr, None)
        if a != b:
            raise ValueError(
                f"checkpoint parameter mismatch: {attr} was {a!r}, now {b!r}"
                " (non-modifiable for checkpoint continuation)")
    prior_nt = prior.output["ntrees_trained"]
    if params.ntrees <= prior_nt:
        raise ValueError(
            f"ntrees={params.ntrees} must exceed the checkpoint's "
            f"{prior_nt} trees")
    prior_cols = [s.name for s in prior.datainfo.specs]
    cols = [s.name for s in di.specs]
    if prior_cols != cols:
        raise ValueError("checkpoint feature columns differ from frame")
    return prior


def checkpoint_binned(frame: Frame, di: DataInfo, prior, nbins: int):
    """Re-encode a frame with the checkpoint model's stored bin edges."""
    from .binning import BinnedFrame, encode_bins
    names = [s.name for s in di.specs]
    is_cat = [s.type == T_CAT for s in di.specs]
    edges = prior.output["edges"]
    codes = encode_bins(frame, names, edges, is_cat, nbins)
    domains = [frame.vec(n).domain if c else None
               for n, c in zip(names, is_cat)]
    return BinnedFrame(codes=codes, edges=edges, names=names,
                       is_cat=is_cat, cat_domains=domains, nbins=nbins)


def prior_stacked(prior, k: Optional[int] = None) -> "StackedTrees":
    """The checkpoint's ensemble as StackedTrees (class k for multinomial)."""
    st = prior.output.get("stacked")
    if st is not None:
        if k is not None and isinstance(st, list):
            return st[k]
        if k is None and not isinstance(st, list):
            return st
    trees = prior.output["trees"]
    if k is not None:
        return StackedTrees.from_trees([t[k] for t in trees])
    return StackedTrees.from_trees(list(trees))


class SharedTree(ModelBuilder):
    """Common driver: binning, main loop, scoring, early stopping."""

    # the tree family honors params.checkpoint, which also unlocks
    # train(warm_start=...) and StreamingFrame stream training
    _supports_checkpoint = True

    #: builders whose fused driver can grow G same-shape grid members as
    #: one batched program (models/tree/grid_batch.py); opted in per
    #: subclass — the batched trainer mirrors GBM's fused chunk loop
    _grid_batchable = False

    def _validate(self, frame) -> None:
        super()._validate(frame)
        if getattr(self.params, "monotone_constraints", None) and \
                self.algo not in ("gbm", "xgboost"):
            raise ValueError(
                "monotone_constraints is only enforced for GBM/XGBoost; "
                f"{self.algo} would silently ignore it")
        p = self.params
        if getattr(p, "calibrate_model", False):
            # fail BEFORE training, not after (CalibrationHelper checks)
            if getattr(p, "calibration_frame", None) is None:
                raise ValueError(
                    "calibrate_model=True needs calibration_frame")
            if getattr(p, "calibration_method", "platt") not in (
                    "platt", "isotonic"):
                raise ValueError("calibration_method: platt | isotonic")
            rc = p.response_column
            dom = frame.vec(rc).domain if rc in frame.names else None
            if dom is not None and len(dom) != 2:
                raise ValueError("calibration supports binomial models only")

    def _post_fit(self, model, frame, valid) -> None:
        """Probability calibration on a held-out frame —
        hex/tree/CalibrationHelper (Platt scaling / isotonic)."""
        p = self.params
        if not getattr(p, "calibrate_model", False):
            return
        cal_fr = p.calibration_frame
        di = model.datainfo
        if not di.is_classifier or di.nclasses != 2:
            raise ValueError("calibration supports binomial models only")
        raw = np.asarray(model._predict_raw(
            model._score_matrix(cal_fr)))[: cal_fr.nrows]
        p1 = np.clip(raw[:, 1] if raw.ndim == 2 else raw, 1e-12, 1 - 1e-12)
        y = np.asarray(di.response(cal_fr))[: cal_fr.nrows]
        ok = np.isfinite(y)
        p1, y = p1[ok], y[ok]
        if p.calibration_method == "isotonic":
            from ..isotonic import _pav
            order = np.argsort(p1)
            ys = _pav(y[order].astype(np.float64),
                      np.ones(len(y), np.float64))
            model.output["calibration"] = {
                "method": "isotonic", "x": p1[order], "y": ys}
        else:
            # Platt: logistic regression of y on the raw score (1-D IRLS)
            a, b = 1.0, 0.0
            for _ in range(25):
                eta = a * p1 + b
                mu = 1.0 / (1.0 + np.exp(-eta))
                wq = np.maximum(mu * (1 - mu), 1e-9)
                z = eta + (y - mu) / wq
                X2 = np.stack([p1, np.ones_like(p1)], axis=1)
                A = (X2 * wq[:, None]).T @ X2
                rhs = (X2 * wq[:, None]).T @ z
                sol = np.linalg.solve(A + 1e-9 * np.eye(2), rhs)
                if abs(sol[0] - a) + abs(sol[1] - b) < 1e-9:
                    a, b = float(sol[0]), float(sol[1])
                    break
                a, b = float(sol[0]), float(sol[1])
            model.output["calibration"] = {"method": "platt",
                                           "a": a, "b": b}

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=p.response_column if self.supervised else None,
            ignored_columns=p.ignored_columns, weights_column=p.weights_column,
            offset_column=p.offset_column, standardize=False,
            missing_values_handling="mean_imputation",
            force_classification=getattr(self, "_force_classification", False))

    def _score_and_log(self, model, it, F_train, y, w, di, dist, history,
                       valid_state):
        from ...metrics.core import make_metrics
        raw = self._scores_to_preds(F_train, dist, di)
        m = make_metrics(di, raw, y, w)
        entry = {"iteration": it, **m.describe()}
        mv = None
        if valid_state is not None:
            F_v, y_v, w_v = valid_state
            mv = make_metrics(di, self._scores_to_preds(F_v, dist, di),
                              y_v, w_v)
            entry.update({f"valid_{k}": v for k, v in mv.describe().items()})
        history.append(entry)
        # stash for _finalize_fused: when the last interval lands on the
        # final tree count, finalize reuses these instead of recomputing a
        # full-frame metrics pass (and a whole-ensemble valid traverse)
        model._interval_metrics = (it, m, mv)
        return m

    def _prep_targets(self, y, w, dist):
        """(y NaN-cleaned, init score) in ONE jitted program — the eager
        chain (isnan/where + the distribution's init reductions) costs a
        dispatch round trip per op on a tunnelled backend (~3.7 s measured
        before the chunk loop on the 10M-row bench)."""
        if dist.name == "custom":
            y0 = jnp.where(jnp.isnan(y), 0.0, y)
            return y0, dist.init_score(y0, w)
        key = (dist.name, getattr(dist, "p", None),
               getattr(dist, "alpha", None), getattr(dist, "delta", None))
        fn = _PREP_JIT_CACHE.get(key)
        if fn is None:
            def _prep(yv, wv, _d=dist):
                y0 = jnp.where(jnp.isnan(yv), 0.0, yv)
                return y0, _d.init_score(y0, wv)
            fn = jax.jit(_prep)
            _PREP_JIT_CACHE[key] = fn
        return fn(y, w)

    def _interval_score(self, model, t_done, F, y, w, di, dist, history,
                        vstate, metric_name, maximize) -> bool:
        """Score at an interval boundary; True = early-stop now (the
        shared tail of every fused chunk loop)."""
        p = self.params
        self._score_and_log(model, t_done, F, y, w, di, dist, history,
                            vstate)
        if not p.stopping_rounds:
            return False
        key = (f"valid_{metric_name}" if vstate is not None
               else metric_name)
        series = [hh.get(key) for hh in history if hh.get(key) is not None]
        return bool(series and stop_early(series, p.stopping_rounds,
                                          p.stopping_tolerance, maximize))

    def _scores_to_preds(self, F, dist, di):
        # jitted + cached: eagerly, the clip/stack chain over 10M rows cost
        # ~3.8 s of per-op dispatch round trips on a tunnelled backend
        kind = ("multi" if di.is_classifier and di.nclasses > 2
                else "binomial" if di.is_classifier else "regression")
        if dist.name == "custom":
            # user UDF linkinv: not keyable — keep the eager path
            if kind == "multi":
                return jax.nn.softmax(F, axis=1)
            if kind == "binomial":
                p1 = jnp.clip(dist.linkinv(F), 0.0, 1.0)
                return jnp.stack([1 - p1, p1], axis=1)
            return dist.linkinv(F)
        key = (kind, dist.name, getattr(dist, "p", None),
               getattr(dist, "alpha", None), getattr(dist, "delta", None))
        fn = _PREDS_JIT_CACHE.get(key)
        if fn is None:
            if kind == "multi":
                fn = jax.jit(lambda Fv: jax.nn.softmax(Fv, axis=1))
            elif kind == "binomial":
                def _binp(Fv, _d=dist):
                    p1 = jnp.clip(_d.linkinv(Fv), 0.0, 1.0)
                    return jnp.stack([1 - p1, p1], axis=1)
                fn = jax.jit(_binp)
            else:
                fn = jax.jit(lambda Fv, _d=dist: _d.linkinv(Fv))
            _PREDS_JIT_CACHE[key] = fn
        return fn(F)
