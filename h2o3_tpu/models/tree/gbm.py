"""GBM: gradient boosting machine on the tpu_hist kernels.

Reference: ``hex/tree/gbm/GBM.java:220`` (GBMDriver; buildNextKTrees:464,
growTrees:608, fitBestConstants:534) — per iteration: compute
pseudo-residuals (an MRTask), grow K trees layer-by-layer via
ScoreBuildHistogram2, fit leaf constants, score every score_tree_interval.

TPU-native redesign: the residual pass is one fused elementwise program
(distributions.py grad_hess), tree growth is the hist->split->partition
pipeline (hist.py), and leaf fitting is the Newton step from the final-level
leaf aggregation — numerically equivalent to fitBestConstants' per-
distribution formulas.  Multinomial grows K trees per iteration on softmax
gradients (buildNextKTrees's K-tree loop).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...runtime import dkv
from ...runtime.job import Job
from ..datainfo import DataInfo
from ..distributions import make_distribution, Multinomial
from ..scorekeeper import stop_early, metric_direction
from .binning import fit_bins, edges_matrix
from .shared import (SharedTree, SharedTreeModel, SharedTreeParameters,
                     StackedTrees, Tree, TreeList, build_tree,
                     chunk_schedule, dense_mem_cap, make_build_tree_fn,
                     make_tree_scan_fn,
                     run_hist_crosscheck, run_layout_crosscheck,
                     run_program_crosscheck,
                     run_split_crosscheck, stack_trees,
                     traverse_jit, use_hier_split_search)
from ...metrics.core import make_metrics


@dataclasses.dataclass
class GBMParameters(SharedTreeParameters):
    # custom loss UDF (water/udf/CDistributionFunc analog); see
    # distributions.CustomDistribution for the protocol
    custom_distribution_func: Optional[object] = None


class GBMModel(SharedTreeModel):
    algo = "gbm"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        F = self._raw_scores(X)
        dist = make_distribution(
            self.output["distribution"],
            nclasses=self.datainfo.nclasses,
            tweedie_power=self.params.tweedie_power,
            quantile_alpha=self.params.quantile_alpha,
            huber_alpha=self.params.huber_alpha,
            custom_distribution_func=getattr(
                self.params, "custom_distribution_func", None))
        if self.datainfo.is_classifier and self.datainfo.nclasses > 2:
            return jax.nn.softmax(F, axis=1)
        if self.datainfo.is_classifier:
            p1 = jnp.clip(dist.linkinv(F), 0.0, 1.0)
            return jnp.stack([1 - p1, p1], axis=1)
        return dist.linkinv(F)


class GBM(SharedTree):
    algo = "gbm"
    model_class = GBMModel
    # grid cohorts batch through the fused single-class path below
    # (grid_batch.py reuses _prep_targets/_interval_score/_finalize_fused)
    _grid_batchable = True

    def __init__(self, params: Optional[GBMParameters] = None, **kw):
        super().__init__(params or GBMParameters(**kw))

    def _finalize_fused(self, model, di, dist, F, y, w, valid, history,
                        binned, init_host, ntrees, stacked, trees):
        """Shared fused-path epilogue (single-class and multinomial)."""
        model.output["stacked"] = stacked
        model.output["trees"] = trees
        model.output["init_score"] = init_host
        model.output["ntrees_trained"] = ntrees
        model.output["edges"] = binned.edges
        model.scoring_history = history
        im = getattr(model, "_interval_metrics", None)
        if im is not None and im[0] == ntrees:
            # the final interval already scored this exact ensemble state
            model.training_metrics = im[1]
            if valid is not None and im[2] is not None:
                model.validation_metrics = im[2]
            elif valid is not None:
                model.validation_metrics = model.model_performance(valid)
            return model
        model.training_metrics = make_metrics(
            di, self._scores_to_preds(F, dist, di), y, w)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GBMModel:
        p: GBMParameters = self.params
        K = di.nclasses if (di.is_classifier and di.nclasses > 2) else 1
        dist = make_distribution(p.distribution, nclasses=di.nclasses,
                                 tweedie_power=p.tweedie_power,
                                 quantile_alpha=p.quantile_alpha,
                                 huber_alpha=p.huber_alpha,
                                 custom_distribution_func=getattr(
                                     p, "custom_distribution_func", None))
        multinomial = isinstance(dist, Multinomial) or K > 1
        if multinomial and getattr(p, "custom_distribution_func",
                                   None) is not None:
            raise ValueError(
                "custom_distribution_func is not supported for multinomial "
                "responses (the K-tree softmax path has its own gradients)")
        y = di.response(frame)
        w = di.weights(frame)
        from .shared import (resolve_checkpoint, checkpoint_binned,
                             prior_stacked, resolve_mono)
        y, f0_dev = self._prep_targets(y, w, dist)
        mono = resolve_mono(p, di)
        if mono is not None and multinomial:
            raise ValueError(
                "monotone_constraints: multinomial is not supported")
        prior = resolve_checkpoint(p, di, self.algo)
        if prior is not None:
            binned = checkpoint_binned(frame, di, prior, p.nbins)
        else:
            binned = fit_bins(frame, [s.name for s in di.specs],
                              nbins=p.nbins, seed=p.effective_seed(),
                              weights=w if p.weights_column else None,
                              histogram_type=p.histogram_type)
        codes = binned.codes
        edges_mat = jnp.asarray(
            edges_matrix(binned.edges, p.nbins), jnp.float32)
        N = codes.shape[1]
        # EFB: wide/sparse frames train on bundled working codes (efb.py);
        # the recorded trees stay in original feature space
        from .shared import maybe_bundle
        plan, wcodes, Fw, wbin_counts = maybe_bundle(binned, p, mono,
                                                     frame.nrows)
        # resolve the kernel-strategy knobs ONCE, up front: the layout
        # changes the effective-depth cap (node-sparse levels drop the
        # dense 64 MB histogram bound), so checkpoint validation and the
        # recorded depth must see the resolved layout, not the raw knob.
        # "auto" knobs route through the cost-model autotuner (a no-op
        # resolving to the fixed defaults with H2O3_TPU_AUTOTUNE=off);
        # activate() scopes sampled device timings to this decision.
        from ...runtime import autotune
        knobs = autotune.resolve_tree_knobs(
            p, kind=self.algo, F=Fw, N=N, K=K if multinomial else 1,
            mono=mono, plan=plan, hier=use_hier_split_search(p, N),
            checkpoint=prior is not None)
        autotune.activate(knobs)
        hist_mode, split_mode, hist_layout = (
            knobs.hist_mode, knobs.split_mode, knobs.hist_layout)
        tree_program = knobs.tree_program
        if knobs.sparse_depth_threshold != p.sparse_depth_threshold:
            # the tuned threshold must flow to EVERY consumer (effective
            # depth, scan factories, checkpoint validation, the params
            # echo records the effective value)
            p = dataclasses.replace(
                p, sparse_depth_threshold=knobs.sparse_depth_threshold)
        if prior is not None:
            from .shared import validate_checkpoint_depth
            validate_checkpoint_depth(prior, 0 if multinomial else None,
                                      p, Fw, N, hist_layout=hist_layout)
        seed = p.effective_seed()
        rng = jax.random.PRNGKey(seed)
        nprng = np.random.default_rng(seed)

        model = self.model_class(job.dest_key or dkv.make_key(self.algo),
                                 p, di)
        model.output["distribution"] = dist.name if not multinomial \
            else "multinomial"
        model.output["binning"] = {"nbins": p.nbins}
        model.output["nclass_trees"] = K
        from .shared import record_effective_depth
        eff_depth = record_effective_depth(model, p, Fw, N,
                                           hist_layout=hist_layout)
        # deep_level chaos hook fires only when sparse levels actually run
        sparse_deep = (hist_layout in ("sparse", "check") and eff_depth
                       > max(1, min(p.sparse_depth_threshold,
                                    dense_mem_cap(p.nbins, Fw))))
        if plan is not None:
            model.output["efb_bundles"] = sum(
                1 for w in plan.working if w[0] == "bundle")

        if valid is not None:
            Xv = model._design(valid)
            y_v, w_v = di.response(valid), di.weights(valid)

        if multinomial:
            yi = jnp.clip(y.astype(jnp.int32), 0, K - 1)
            Y1 = jax.nn.one_hot(yi, K, dtype=jnp.float32)
            base = jnp.sum(w[:, None] * Y1, axis=0) / jnp.maximum(jnp.sum(w), 1e-12)
            init = jnp.log(jnp.clip(base, 1e-10, 1.0))
            if prior is not None:
                init = jnp.asarray(prior.output["init_score"], jnp.float32)
            F = jnp.broadcast_to(init[None, :], (N, K)).astype(jnp.float32)
            F_v = jnp.broadcast_to(init[None, :], (Xv.shape[0], K)) \
                if valid is not None else None
            init_host = np.asarray(init)
        else:
            f0 = f0_dev if prior is None else prior.output["init_score"]
            F = jnp.broadcast_to(jnp.asarray(f0, jnp.float32), (N,))
            F_v = jnp.broadcast_to(jnp.asarray(f0, jnp.float32),
                                   (Xv.shape[0],)) \
                if valid is not None else None
            init_host = float(f0)
        # Commit F to the replicated sharding the scan chunk outputs use:
        # an uncommitted F0 and a committed chunk-output F key DIFFERENT
        # jit executables for the same scan program — the warmup paid a
        # silent ~16 s recompile between chunk 1 and chunk 2 (the round-2
        # "first-execution anomaly" decoded).
        from jax.sharding import NamedSharding, PartitionSpec
        from ...runtime.cluster import cluster
        F = jax.device_put(F, NamedSharding(cluster().mesh, PartitionSpec()))
        prior_nt = 0
        if prior is not None:
            # continue from the checkpoint: F starts at its predictions
            prior_nt = prior.output["ntrees_trained"]
            # decorrelate the PRNG stream from the prior run: without this,
            # a fixed seed regenerates the SAME per-tree keys and the
            # continuation's row/column samples duplicate the prior trees'
            rng = jax.random.fold_in(rng, prior_nt)
            X_ck = model._design(frame)
            if multinomial:
                for k in range(K):
                    st = prior_stacked(prior, k)
                    F = F.at[:, k].add(traverse_jit(st.levels, st.values,
                                                    X_ck))
                    if valid is not None:
                        F_v = F_v.at[:, k].add(
                            traverse_jit(st.levels, st.values, Xv))
            else:
                st = prior_stacked(prior)
                F = F + traverse_jit(st.levels, st.values, X_ck)
                if valid is not None:
                    F_v = F_v + traverse_jit(st.levels, st.values, Xv)

        @jax.jit
        def grads_single(y, F):
            return dist.grad_hess(y, F)

        @jax.jit
        def grads_multi(Y1, F):
            Pr = jax.nn.softmax(F, axis=1)
            return Pr - Y1, jnp.maximum(Pr * (1 - Pr), 1e-10)

        # DART booster (XGBoost estimator): drop a random subset of prior
        # trees when computing gradients, then renormalize (libxgboost dart)
        dart = getattr(p, "booster", "gbtree") == "dart"
        X_tr = model._design(frame) if dart else None
        lr_build = 1.0 if dart else p.learn_rate

        def drop_sum(idx):
            if multinomial:
                outs = []
                for k in range(K):
                    levels, vals = stack_trees([trees[i][k] for i in idx])
                    outs.append(traverse_jit(levels, vals, X_tr))
                return jnp.stack(outs, axis=1)
            levels, vals = stack_trees([trees[i] for i in idx])
            return traverse_jit(levels, vals, X_tr)

        trees = []
        history = []
        metric_name, maximize = metric_direction(
            p.stopping_metric, di.is_classifier)
        fused = not multinomial and not dart
        fused_multi = multinomial and not dart

        # hist_mode="check" — the driver assert: one tree grown with both
        # the subtraction path and the full oracle on the REAL first-tree
        # gradients must agree (shared.run_hist_crosscheck), then training
        # proceeds on the subtraction path.
        if hist_mode == "check":
            if multinomial:
                g0, h0 = grads_multi(Y1, F)
                g0, h0 = g0[:, 0], h0[:, 0]
            else:
                g0, h0 = grads_single(y, F)
            run_hist_crosscheck(
                wcodes, g0 * w, h0 * w, w, edges_mat, rng,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                bin_counts=wbin_counts, mono=mono, plan=plan,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=p.learn_rate, reg_alpha=p.reg_alpha,
                gamma=p.gamma, min_child_weight=p.min_child_weight)
            hist_mode = "subtract"

        # split_mode="check" — fused (batched-K for multinomial) vs the
        # sequential best_splits oracle on the REAL first-round gradients
        # (shared.run_split_crosscheck), then training rides the fused path.
        if split_mode == "check":
            if multinomial:
                g0, h0 = grads_multi(Y1, F)
                gc_, hc_ = (g0 * w[:, None]).T, (h0 * w[:, None]).T
                kchk = jnp.stack([jax.random.fold_in(rng, k)
                                  for k in range(K)])
            else:
                g0, h0 = grads_single(y, F)
                gc_, hc_ = g0 * w, h0 * w
                kchk = rng
            run_split_crosscheck(
                wcodes, gc_, hc_, w, edges_mat, kchk,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                bin_counts=wbin_counts, hist_mode=hist_mode,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=p.learn_rate, col_sample_rate=p.col_sample_rate,
                reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            split_mode = "fused"

        # hist_layout="check" — dense vs node-sparse deep levels on the
        # REAL first-round gradients (shared.run_layout_crosscheck: depth
        # clamped to the DENSE cap so both layouts can grow it), then
        # training rides the sparse path at the full layout-aware depth.
        if hist_layout == "check":
            if multinomial:
                g0, h0 = grads_multi(Y1, F)
                gc_, hc_ = (g0 * w[:, None]).T, (h0 * w[:, None]).T
                kchk = jnp.stack([jax.random.fold_in(rng, k)
                                  for k in range(K)])
            else:
                g0, h0 = grads_single(y, F)
                gc_, hc_ = g0 * w, h0 * w
                kchk = rng
            run_layout_crosscheck(
                wcodes, gc_, hc_, w, edges_mat, kchk,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                bin_counts=wbin_counts,
                sparse_depth_threshold=p.sparse_depth_threshold,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=p.learn_rate, col_sample_rate=p.col_sample_rate,
                reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            hist_layout = "sparse"
            model.output["hist_layout"] = hist_layout

        # tree_program="check" — the whole-tree scan program vs the
        # per-level dispatch loop on the REAL first-round gradients
        # (shared.run_program_crosscheck), then training rides the
        # scan-fused path.  resolve_tree_program already downgraded
        # "check" to "level" for shapes the scan cannot grow (mono/plan/
        # hier, engaged sparse layout, effective depth < 2, varbin).
        if tree_program == "check":
            if multinomial:
                g0, h0 = grads_multi(Y1, F)
                gc_, hc_ = (g0 * w[:, None]).T, (h0 * w[:, None]).T
                kchk = jnp.stack([jax.random.fold_in(rng, k)
                                  for k in range(K)])
            else:
                g0, h0 = grads_single(y, F)
                gc_, hc_ = g0 * w, h0 * w
                kchk = rng
            run_program_crosscheck(
                wcodes, gc_, hc_, w, edges_mat, kchk,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                hist_precision=p.effective_hist_precision,
                hist_mode=hist_mode, split_mode=split_mode,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=p.learn_rate, col_sample_rate=p.col_sample_rate,
                reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            tree_program = "scan"
        model.output["tree_program"] = tree_program

        if fused_multi:
            # multinomial fast path: K class trees per round, a whole
            # scoring interval of rounds per dispatch
            from .shared import make_multinomial_scan_fn
            scan_fn = make_multinomial_scan_fn(
                K, p.max_depth, p.nbins, Fw, N,
                p.effective_hist_precision, p.sample_rate, p.col_sample_rate_per_tree,
                hier=use_hier_split_search(p, N),
                bin_counts=wbin_counts, plan=plan, hist_mode=hist_mode,
                split_mode=split_mode, hist_layout=hist_layout,
                sparse_depth_threshold=p.sparse_depth_threshold,
                tree_program=tree_program)
            scalars = (p.reg_lambda, p.min_rows, p.min_split_improvement,
                       p.learn_rate, p.col_sample_rate, p.reg_alpha, p.gamma,
                       p.min_child_weight)
            chunks_k = [[prior_stacked(prior, k)] if prior is not None
                        else [] for k in range(K)]
            from ...runtime import failure
            for chunk_no, (c, t_new, score_now) in enumerate(chunk_schedule(
                    p.ntrees - prior_nt, p.score_tree_interval,
                    fence=getattr(self, "_stream_fence", None))):
                t_done = prior_nt + t_new
                # chaos matrix: kill/resume mid-multinomial-round — each
                # chunk is a batch of K-tree rounds on the fused path
                failure.maybe_inject("ktree_round")
                if sparse_deep:
                    # kill/resume while node-sparse deep levels are live
                    failure.maybe_inject("deep_level")
                from ...runtime import observability as obs
                from ...runtime import xprof
                t0 = time.perf_counter()
                with obs.span("tree_chunk", job=job.key, chunk=chunk_no,
                              trees=c, classes=K):
                    F, lv, vals, cov = scan_fn(wcodes, Y1, w, F, edges_mat,
                                               rng, chunk_no, c, *scalars)
                # true device time for the whole K-tree chunk (sampled
                # block-until-ready; no-op with H2O3_TPU_DEVICE_TIMING=off)
                xprof.maybe_device_sync("tree_chunk", chunk_no, t0, F)
                for k in range(K):
                    lv_k = [tuple(lvd[i][:, k] for i in range(4))
                            for lvd in lv]
                    chunk = StackedTrees(lv_k, vals[:, k], cov[:, k])
                    chunks_k[k].append(chunk)
                    if valid is not None:
                        F_v = F_v.at[:, k].add(
                            traverse_jit(chunk.levels, chunk.values, Xv))
                job.update(t_done / p.ntrees, f"tree {t_done}/{p.ntrees}")
                from ...runtime import snapshot
                from .shared import tree_snapshot_state_multi
                snapshot.maybe_snapshot(
                    job, model,
                    {"trees_done": t_done, "granularity": "tree_chunk"},
                    lambda c=[list(ch) for ch in chunks_k]:
                        tree_snapshot_state_multi(c, init_host,
                                                  binned.edges))
                if not score_now:
                    continue
                vstate = (F_v, y_v, w_v) if valid is not None else None
                if self._interval_score(model, t_done, F, y, w, di, dist,
                                        history, vstate, metric_name,
                                        maximize):
                    break
            from .shared import TreeListMulti
            stacks = [StackedTrees.concat(ch) for ch in chunks_k]
            return self._finalize_fused(
                model, di, dist, F, y, w, valid, history, binned, init_host,
                stacks[0].ntrees, stacked=stacks,
                trees=TreeListMulti(stacks))

        if fused:
            # fast path: scan a whole scoring interval of trees per dispatch
            scan_fn = make_tree_scan_fn(
                dist.name, p.tweedie_power, p.quantile_alpha, p.huber_alpha,
                p.max_depth, p.nbins, Fw, N, p.effective_hist_precision,
                p.sample_rate, p.col_sample_rate_per_tree,
                hier=use_hier_split_search(p, N) and mono is None,
                bin_counts=wbin_counts, mono=mono, plan=plan,
                custom_fn=getattr(p, "custom_distribution_func", None),
                hist_mode=hist_mode, split_mode=split_mode,
                hist_layout=hist_layout,
                sparse_depth_threshold=p.sparse_depth_threshold,
                tree_program=tree_program)
            scalars = (p.reg_lambda, p.min_rows, p.min_split_improvement,
                       p.learn_rate, p.col_sample_rate, p.reg_alpha, p.gamma,
                       p.min_child_weight)
            chunks = [prior_stacked(prior)] if prior is not None else []
            from ...runtime import failure
            for chunk_no, (c, t_new, score_now) in enumerate(chunk_schedule(
                    p.ntrees - prior_nt, p.score_tree_interval,
                    fence=getattr(self, "_stream_fence", None))):
                t_done = prior_nt + t_new
                if sparse_deep:
                    # kill/resume while node-sparse deep levels are live
                    failure.maybe_inject("deep_level")
                from ...runtime import observability as obs
                from ...runtime import xprof
                t0 = time.perf_counter()
                with obs.span("tree_chunk", job=job.key, chunk=chunk_no,
                              trees=c):
                    F, lv, vals, cov = scan_fn(wcodes, y, w, F, edges_mat,
                                               rng, chunk_no, c, *scalars, 0)
                # true device time for the whole tree chunk (sampled
                # block-until-ready; no-op with H2O3_TPU_DEVICE_TIMING=off)
                xprof.maybe_device_sync("tree_chunk", chunk_no, t0, F)
                chunk = StackedTrees(lv, vals, cov)
                chunks.append(chunk)
                job.update(t_done / p.ntrees, f"tree {t_done}/{p.ntrees}")
                from ...runtime import snapshot
                from .shared import tree_snapshot_state
                snapshot.maybe_snapshot(
                    job, model,
                    {"trees_done": t_done, "granularity": "tree_chunk"},
                    lambda c=list(chunks): tree_snapshot_state(
                        c, init_host, binned.edges))
                if valid is not None:
                    F_v = F_v + traverse_jit(chunk.levels, chunk.values, Xv)
                if not score_now:
                    continue
                vstate = (F_v, y_v, w_v) if valid is not None else None
                if self._interval_score(model, t_done, F, y, w, di, dist,
                                        history, vstate, metric_name,
                                        maximize):
                    break
            stacked = StackedTrees.concat(chunks)
            return self._finalize_fused(
                model, di, dist, F, y, w, valid, history, binned, init_host,
                stacked.ntrees, stacked=stacked, trees=TreeList(stacked))

        if prior is not None:
            # materialized per-tree list continuation (DART / multinomial).
            # Copy the Tree objects: DART rescales trees[i].values in place,
            # which must not corrupt the checkpoint model still in the DKV.
            for t_prior in list(prior.output["trees"]):
                if isinstance(t_prior, list):
                    trees.append([dataclasses.replace(tc) for tc in t_prior])
                else:
                    trees.append(dataclasses.replace(t_prior))
        for t in range(prior_nt, p.ntrees):
            rng, ks, kc = jax.random.split(rng, 3)
            w_eff = w
            if p.sample_rate < 1.0:
                w_eff = w * jax.random.bernoulli(ks, p.sample_rate, (N,))
            tree_mask = None
            if p.col_sample_rate_per_tree < 1.0:
                m = nprng.random(binned.nfeatures) < p.col_sample_rate_per_tree
                if not m.any():
                    m[nprng.integers(binned.nfeatures)] = True
                tree_mask = m

            drop_idx = []
            S_D = None
            if dart and trees and nprng.random() >= getattr(p, "skip_drop", 0.0):
                md = nprng.random(len(trees)) < getattr(p, "rate_drop", 0.0)
                if getattr(p, "one_drop", False) and not md.any():
                    md[nprng.integers(len(trees))] = True
                drop_idx = list(np.flatnonzero(md))
                if drop_idx:
                    S_D = drop_sum(drop_idx)
            F_eff = F - S_D if S_D is not None else F

            if dart:
                kdrop, nu = len(drop_idx), p.learn_rate
                if kdrop:
                    if getattr(p, "normalize_type", "tree") == "forest":
                        a_scale = b_scale = 1.0 / (1.0 + nu)
                    else:
                        a_scale = kdrop / (kdrop + nu)
                        b_scale = 1.0 / (kdrop + nu)
                else:
                    a_scale, b_scale = 1.0, nu

            if multinomial:
                g, h = grads_multi(Y1, F_eff)
                # preserve the sequential loop's key sequence: one split
                # per class tree, whether or not the round is batched
                kks = []
                for k in range(K):
                    rng, kk = jax.random.split(rng)
                    kks.append(kk)
                from .hist import table_lookup
                if split_mode == "fused" and not use_hier_split_search(p, N):
                    # DART candidate round on the batched path: ONE build
                    # grows all K class trees (one launch per level)
                    fnK = make_build_tree_fn(
                        p.max_depth, p.nbins, binned.nfeatures, N,
                        p.effective_hist_precision, hist_mode=hist_mode,
                        nk=K, split_mode="fused", hist_layout=hist_layout,
                        sparse_depth_threshold=p.sparse_depth_threshold,
                        tree_program=tree_program)
                    tmK = jnp.broadcast_to(
                        jnp.asarray(tree_mask, bool) if tree_mask
                        is not None else jnp.ones(binned.nfeatures, bool),
                        (K, binned.nfeatures))
                    levels, valsK, coverK, leafK = fnK(
                        codes, (g * w_eff[:, None]).T,
                        (h * w_eff[:, None]).T, w_eff, edges_mat,
                        jnp.stack(kks), p.reg_lambda, p.min_rows,
                        p.min_split_improvement, lr_build,
                        p.col_sample_rate, tmK, p.reg_alpha, p.gamma,
                        p.min_child_weight)
                    if dart:
                        valsK = valsK * b_scale
                    ktrees = [Tree([lv[0][k] for lv in levels],
                                   [lv[1][k] for lv in levels],
                                   [lv[2][k] for lv in levels],
                                   [lv[3][k] for lv in levels], valsK[k],
                                   cover=coverK[k]) for k in range(K)]
                    dF = jax.vmap(
                        lambda v, l: table_lookup(v[None, :], l,
                                                  v.shape[0])[0])(
                        valsK, leafK)
                    F = F + dF.T
                else:
                    ktrees = []
                    for k in range(K):
                        tree, leaf = build_tree(
                            codes, g[:, k] * w_eff, h[:, k] * w_eff, w_eff,
                            edges_mat, p.nbins,
                            p.max_depth, p.reg_lambda, p.min_rows,
                            p.min_split_improvement, lr_build, kks[k],
                            p.col_sample_rate, tree_mask,
                            p.reg_alpha, p.gamma, p.min_child_weight,
                            hist_precision=p.effective_hist_precision,
                            hier=use_hier_split_search(p, N),
                            hist_mode=hist_mode, split_mode=split_mode,
                            hist_layout=hist_layout,
                            sparse_depth_threshold=p.sparse_depth_threshold,
                            tree_program=tree_program)
                        if dart:
                            tree.values = tree.values * b_scale
                        ktrees.append(tree)
                        dF = table_lookup(jnp.asarray(tree.values)[None, :],
                                          leaf, len(tree.values))[0]
                        F = F.at[:, k].add(dF)
                trees.append(ktrees)
                if dart and drop_idx:
                    for i in drop_idx:
                        for k in range(K):
                            trees[i][k].values = trees[i][k].values * a_scale
                    F = F - (1.0 - a_scale) * S_D
                if valid is not None and not dart:
                    for k in range(K):
                        levels, vals = stack_trees([ktrees[k]])
                        F_v = F_v.at[:, k].add(traverse_jit(levels, vals, Xv))
            else:
                g, h = grads_single(y, F_eff)
                tree, leaf = build_tree(
                    codes, g * w_eff, h * w_eff, w_eff, edges_mat, p.nbins,
                    p.max_depth, p.reg_lambda, p.min_rows,
                    p.min_split_improvement, lr_build, kc,
                    p.col_sample_rate, tree_mask,
                    p.reg_alpha, p.gamma, p.min_child_weight, mono=mono,
                    hist_precision=p.effective_hist_precision,
                    hier=use_hier_split_search(p, N) and mono is None,
                    hist_mode=hist_mode, split_mode=split_mode,
                    hist_layout=hist_layout,
                    sparse_depth_threshold=p.sparse_depth_threshold,
                    tree_program=tree_program)
                tree.values = tree.values * b_scale
                trees.append(tree)
                from .hist import table_lookup
                F = F + table_lookup(jnp.asarray(tree.values)[None, :],
                                     leaf, len(tree.values))[0]
                if drop_idx:
                    for i in drop_idx:
                        trees[i].values = trees[i].values * a_scale
                    F = F - (1.0 - a_scale) * S_D
            job.update((t + 1) / p.ntrees, f"tree {t + 1}/{p.ntrees}")

            if ((t + 1) % p.score_tree_interval == 0) or t == p.ntrees - 1:
                if dart and valid is not None:
                    # DART rescales prior trees, so F_v can't be incremental
                    if multinomial:
                        for k in range(K):
                            levels, vals = stack_trees(
                                [tr[k] for tr in trees])
                            F_v = F_v.at[:, k].set(
                                init_host[k] + traverse_jit(levels, vals, Xv))
                    else:
                        levels, vals = stack_trees(trees)
                        F_v = init_host + traverse_jit(levels, vals, Xv)
                vstate = (F_v, y_v, w_v) if valid is not None else None
                self._score_and_log(model, t + 1, F, y, w, di, dist, history,
                                    vstate)
                if p.stopping_rounds:
                    key = (f"valid_{metric_name}" if valid is not None
                           else metric_name)
                    series = [hh.get(key) for hh in history
                              if hh.get(key) is not None]
                    if series and stop_early(series, p.stopping_rounds,
                                             p.stopping_tolerance, maximize):
                        break

        model.output["trees"] = trees
        model.output["init_score"] = init_host
        model.output["ntrees_trained"] = len(trees)
        model.output["edges"] = binned.edges
        model.scoring_history = history
        # F already holds the final training scores — no tree re-traversal
        model.training_metrics = make_metrics(
            di, self._scores_to_preds(F, dist, di), y, w)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
