"""EFB: exclusive feature bundling — the wide/sparse tree path.

Reference handling of wide sparse frames: sparse chunk codecs
(``water/fvec/NewChunk.java:1133`` — CX chunks) and XGBoost's CSR bridge
(``hex/tree/xgboost/matrix/SparseMatrixFactory.java``).  Both keep the
per-feature loop; on a TPU the histogram kernel's cost is the PACKED bin-row
count ``sum(pad8(B_f + 2))`` (PROFILE.md: linear in slots, flat in depth), so
the winning move is LightGBM-style Exclusive Feature Bundling: mutually
exclusive sparse features (never non-default on the same row) share ONE
working feature whose bin axis concatenates the members' non-default bins.
A 1,900-column one-hot/sparse frame collapses to a handful of ~nbins-wide
bundles — the kernel, the partition select-chain, and the per-level split
scan all shrink by the bundling factor.

Bundles exist ONLY in the working space (histogram + partition).  Split
search "unbundles": member f's default-bin mass (its per-feature MODE bin
d_f — under quantile edges even a 0/1 column's zero usually lands in bin 1,
not 0) is reconstructed as ``leaf_total - sum(f's packed slots)``: every row
non-default in another member is default in f, by exclusivity.  Candidate
gains are therefore EXACT per original feature and the recorded tree stores
original (feature, threshold) pairs — prediction, TreeSHAP, MOJO export and
varimp are untouched.  In working space the chosen split becomes a bin
RANGE with an optional complement (default mass can sit on either side of
the cut), handled by ``partition_ranged``.

Mechanics are deliberately layered on the existing kernels: a bundle is just
a working feature with a large ``bin_count``, so the varbin Pallas kernel
(hist.py) and the parent-sibling subtraction drive it unchanged.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class BundlePlan(NamedTuple):
    """Static bundling decision (hashable — it keys the jit caches).

    ``working``: per working feature, either ``("raw", orig_idx, B_f)`` or
    ``("bundle", members)`` with members a tuple of
    ``(orig_idx, start_slot, B_f, default_bin)``; a bundle's slot 0 is the
    shared all-default bin, member f owns slots
    ``[start_slot, start_slot + B_f - 2]`` holding its non-default original
    bins in ascending order (the default bin d_f is skipped).
    """

    working: tuple
    bin_counts: tuple            # per working feature: bins in use

    @property
    def n_working(self) -> int:
        return len(self.working)


@functools.lru_cache(maxsize=None)
def _plan_stats_fn(F: int, nrows: int, S: int, stride: int, nbins: int):
    """Device prepass for the bundle planner: per-feature NA count, sample
    mode bin, non-default count, and the BIT-PACKED non-default sample
    mask.  Fetching the raw [F, S] code sample cost ~10 s per train() on
    a tunnelled backend (hundreds of MB); the packed mask is ~S/8 bytes
    per feature — one small fetch."""

    def stats(codes):
        sub = jax.lax.slice(codes, (0, 0), (F, nrows), (1, stride))
        na_cnt = jnp.sum(codes[:, :nrows] == nbins, axis=1)
        # mode bin via per-bin compare-count (B small static loop on
        # device; avoids materializing [F, S, B])
        counts = jax.lax.map(
            lambda b: jnp.sum((sub == b).astype(jnp.int32), axis=1),
            jnp.arange(nbins + 1))                      # [B, F]
        d_bin = jnp.argmax(counts, axis=0).astype(jnp.int32)
        Z = sub != d_bin[:, None]
        nz = jnp.sum(Z, axis=1)
        S8 = (S + 7) // 8 * 8
        Zp8 = jnp.pad(Z, [(0, 0), (0, S8 - S)]).reshape(F, S8 // 8, 8)
        weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.int32)
        Zp = jnp.sum(Zp8.astype(jnp.int32) * weights, axis=2) \
            .astype(jnp.uint8)
        return na_cnt, d_bin, nz, Zp

    return jax.jit(stats)


def plan_bundles(codes, bin_counts, nbins: int, nrows: int,
                 sample: int = 16384, min_features: int = 32,
                 min_reduction: float = 0.85) -> Optional[BundlePlan]:
    """Greedy conflict-free packing of sparse features into bundles.

    ``codes``: [F, padded] device bin codes (NA == nbins).  Per-feature
    default bin = the sample MODE bin; exclusivity (never two members
    non-default on one row) is checked on a strided ~``sample``-row
    subsample (LightGBM's greedy bundling, conflict budget 0 on the
    sample).  Features with any NA code among the first ``nrows`` rows, or
    with non-default rate > 50%, stay unbundled.  Returns None unless the
    packed kernel cost drops below ``min_reduction`` of the unbundled
    cost — bundling only engages where it wins.
    """
    F = len(bin_counts)
    if F < min_features:
        return None
    stride = max(1, -(-nrows // sample))
    S = len(range(0, nrows, stride))
    na_cnt, d_bin, nz, Zp = jax.device_get(
        _plan_stats_fn(F, nrows, S, stride, nbins)(codes))
    d_bin = np.asarray(d_bin, np.int64)
    nz = np.asarray(nz)
    Zp = np.asarray(Zp)
    cand = [f for f in range(F)
            if na_cnt[f] == 0 and bin_counts[f] >= 2
            and d_bin[f] < nbins
            and bin_counts[f] - 1 <= nbins - 1
            and nz[f] <= 0.5 * S]
    if len(cand) < 4:
        return None
    # greedy: heaviest features first, into the first conflict-free bundle
    # with slot room (width cap = nbins so bundles fit the B = nbins+1
    # axis).  Conflict masks are bit-packed so a probe is a ~S/8-byte
    # AND — cheap enough to probe EVERY bundle: a capped probe count (the
    # first version's max_probe=64) made a few hundred non-exclusive
    # features fill the head of the bundle list and starve every later
    # exclusive feature of its match (observed on the springleaf shape:
    # 1200 one-hot columns, zero bundles formed).
    order = sorted(cand, key=lambda f: -int(nz[f]))
    bundles = []           # [members: [(f, B_f, d_f)], packed mask, width]
    for f in order:
        need = bin_counts[f] - 1
        placed = False
        for b in bundles:
            if b[2] + need > nbins:          # cheap width check
                continue
            if not np.bitwise_and(b[1], Zp[f]).any():
                b[0].append((f, bin_counts[f], int(d_bin[f])))
                b[1] |= Zp[f]
                b[2] += need
                placed = True
                break
        if not placed:
            bundles.append([[(f, bin_counts[f], int(d_bin[f]))],
                            Zp[f].copy(), 1 + need])
    bundled = {f for b in bundles if len(b[0]) > 1 for f, _, _ in b[0]}
    if not bundled:
        return None
    working, wbins = [], []
    for f in range(F):
        if f not in bundled:
            working.append(("raw", f, int(bin_counts[f])))
            wbins.append(int(bin_counts[f]))
    for b in bundles:
        if len(b[0]) > 1:
            # re-pack member starts in orig-feature order (determinism)
            members, start = [], 1
            for f, bf, df in sorted(b[0]):
                members.append((f, start, int(bf), df))
                start += bf - 1
            working.append(("bundle", tuple(members)))
            wbins.append(start)

    def packed_cost(bcs):
        return sum(((min(b, nbins) + 2) + 7) // 8 * 8 for b in bcs)

    # engage whenever the packed kernel cost meaningfully drops: besides
    # the VPU slot count, the working-feature count drives varbin kernel
    # COMPILE time (statically unrolled per-feature compares) and the
    # per-level split-search width, so even a ~15% slot reduction wins
    if packed_cost(wbins) > min_reduction * packed_cost(bin_counts):
        return None
    return BundlePlan(tuple(working), tuple(wbins))


@functools.lru_cache(maxsize=None)
def _apply_fn(plan: BundlePlan):
    def apply(codes):
        pieces = []
        for w in plan.working:
            if w[0] == "raw":
                pieces.append(codes[w[1]])
            else:
                idx = jnp.asarray([m[0] for m in w[1]], jnp.int32)
                starts = jnp.asarray([m[1] for m in w[1]],
                                     jnp.int32)[:, None]
                dfs = jnp.asarray([m[3] for m in w[1]], jnp.int32)[:, None]
                mc = jnp.take(codes, idx, axis=0)          # [m, N]
                # slot = start + (#non-default orig bins < c): bins above
                # the skipped default shift down by one
                mapped = jnp.where(mc == dfs, 0,
                                   starts + mc - (mc > dfs))
                pieces.append(jnp.max(mapped, axis=0))
        return jnp.stack(pieces, axis=0).astype(jnp.int32)
    return jax.jit(apply)


def apply_bundles(codes, plan: BundlePlan):
    """[F, N] original codes -> [F_w, N] working codes (one compiled
    program per plan).  Conflict rows (possible off-sample) resolve to the
    highest-mapped member — the LightGBM conflict tolerance."""
    return _apply_fn(plan)(codes)


@functools.lru_cache(maxsize=None)
def efb_maps(plan: BundlePlan, B: int):
    """Static working-space maps for the mixed split search.

    Dense group: working/orig index vectors.  Bundle group, per slot s of
    the [Fb, B-1] regular-bin axis: the owning member's slot range
    [seg_a, seg_b), its original feature, the slot's ORIGINAL bin, whether
    the default bin sits at-or-below it (addD -> default mass joins the
    left child), and the member's first-above-default slot (candidate-B
    anchor, where the cut lands exactly on the default bin).
    """
    dense_w = [i for i, w in enumerate(plan.working) if w[0] == "raw"]
    dense_orig = [plan.working[i][1] for i in dense_w]
    bundle_w = [i for i, w in enumerate(plan.working) if w[0] == "bundle"]
    Fb = len(bundle_w)
    shape = (Fb, B - 1)
    seg_a = np.zeros(shape, np.int32)
    seg_b = np.zeros(shape, np.int32)
    ofeat = np.zeros(shape, np.int32)
    obin = np.zeros(shape, np.int32)
    dflt = np.zeros(shape, np.int32)
    addD = np.zeros(shape, bool)
    is_slot = np.zeros(shape, bool)
    is_candB = np.zeros(shape, bool)
    first_above = np.zeros(shape, np.int32)
    for j, wi in enumerate(bundle_w):
        for f, start, bf, df in plan.working[wi][1]:
            end = start + bf - 1
            nd_bins = [b for b in range(bf) if b != df]
            fa = start + sum(1 for b in nd_bins if b < df)   # first slot > df
            for k, b in enumerate(nd_bins):
                s = start + k
                seg_a[j, s] = start
                seg_b[j, s] = end
                ofeat[j, s] = f
                obin[j, s] = b
                dflt[j, s] = df
                addD[j, s] = b > df
                is_slot[j, s] = True
                first_above[j, s] = fa
            if fa < end:
                is_candB[j, fa] = True
    return {
        "dense_w": np.asarray(dense_w, np.int32),
        "dense_orig": np.asarray(dense_orig, np.int32),
        "bundle_w": np.asarray(bundle_w, np.int32),
        "seg_a": seg_a, "seg_b": seg_b, "ofeat": ofeat, "obin": obin,
        "dflt": dflt, "addD": addD, "is_slot": is_slot,
        "is_candB": is_candB, "first_above": first_above,
    }


def best_splits_mixed(H, nbins: int, plan: BundlePlan, reg_lambda,
                      min_rows, min_split_improvement, feat_mask,
                      reg_alpha: float = 0.0, gamma: float = 0.0,
                      min_child_weight: float = 0.0):
    """Best split per leaf over a mixed working space.

    ``H``: [3, L, F_w, B] working histogram.  Dense (raw) features run the
    exact ``best_splits`` scan on their sub-block; bundled members are
    scanned per-slot with the reconstructed default mass.  Returns
    (ofeat, obin, na_left, gain, valid, children, wfeat, lo, hi, inv) —
    the first six in ORIGINAL feature space for the recorded tree, the
    last four in WORKING space for ``partition_ranged`` (right child =
    ``inv XOR (lo < code <= hi)``).
    """
    from .hist import best_splits, _score
    maps = efb_maps(plan, nbins + 1)
    L = H.shape[1]

    outs = []        # (gain, ofeat, obin, na_left, children, wfeat, lo,
    #                   hi, inv)
    if len(maps["dense_w"]):
        dw = jnp.asarray(maps["dense_w"])
        Hd = H[:, :, maps["dense_w"], :]
        fm = feat_mask[:, maps["dense_w"]] if feat_mask is not None else None
        feat_d, bin_d, nal_d, gain_d, _, ch_d = best_splits(
            Hd, nbins, reg_lambda, min_rows, min_split_improvement, fm,
            reg_alpha, gamma, min_child_weight)
        wfeat_d = dw[feat_d]
        ofeat_d = jnp.asarray(maps["dense_orig"])[feat_d]
        outs.append((gain_d, ofeat_d, bin_d, nal_d, ch_d, wfeat_d,
                     bin_d, jnp.full((L,), nbins, jnp.int32),
                     jnp.zeros((L,), bool)))

    if len(maps["bundle_w"]):
        Hb = H[:, :, maps["bundle_w"], :]          # [3, L, Fb, B]
        G, Hs, C = Hb[0], Hb[1], Hb[2]
        Fb, B = G.shape[-2], G.shape[-1]
        cums = (jnp.cumsum(G, -1), jnp.cumsum(Hs, -1), jnp.cumsum(C, -1))
        tots = tuple(c[..., -1] for c in cums)     # [L, Fb] leaf totals
        parent = _score(tots[0], tots[1], reg_lambda, reg_alpha)

        seg_a = jnp.asarray(maps["seg_a"])         # [Fb, B-1]
        seg_b = jnp.asarray(maps["seg_b"])
        first_above = jnp.asarray(maps["first_above"])
        is_slot = jnp.asarray(maps["is_slot"])
        is_candB = jnp.asarray(maps["is_candB"])
        addD = jnp.asarray(maps["addD"])

        def seg_stats(cum):
            # per slot s: member prefix P(s) (incl. s), prefix EXCL. s, and
            # member total S_f, via gathers at static boundaries
            a = jnp.broadcast_to(seg_a[None] - 1, (L, Fb, B - 1))
            b = jnp.broadcast_to(seg_b[None] - 1, (L, Fb, B - 1))
            cumA = jnp.take_along_axis(cum, jnp.maximum(a, 0), axis=-1)
            cumB = jnp.take_along_axis(cum, jnp.maximum(b, 0), axis=-1)
            P = cum[..., :-1] - cumA
            S = cumB - cumA
            return P, S

        PG, SG = seg_stats(cums[0])
        PH, SH = seg_stats(cums[1])
        PC, SC = seg_stats(cums[2])
        totG, totH, totC = (t[..., None] for t in tots)
        DG, DH, DC = totG - SG, totH - SH, totC - SC   # default-in-f mass

        def gains(GL, HL, CL):
            GR, HR, CR = totG - GL, totH - HL, totC - CL
            g = 0.5 * (_score(GL, HL, reg_lambda, reg_alpha)
                       + _score(GR, HR, reg_lambda, reg_alpha)
                       - parent[..., None]) - gamma
            ok = (CL >= min_rows) & (CR >= min_rows) & \
                (HL >= min_child_weight) & (HR >= min_child_weight)
            return jnp.where(ok, g, -jnp.inf), (GL, HL, CL, GR, HR, CR)

        if feat_mask is not None:
            bm = feat_mask[:, maps["bundle_w"]][..., None]
        else:
            bm = jnp.ones((L, Fb, 1), bool)
        aD = addD[None].astype(jnp.float32)
        # candidate A (cut after slot s's ORIGINAL bin): left = member
        # slots <= s, plus the default mass when d_f is below the cut
        gA, chA = gains(PG + aD * DG, PH + aD * DH, PC + aD * DC)
        gA = jnp.where(is_slot[None] & bm, gA, -jnp.inf)
        # candidate B (cut exactly after the default bin), evaluated at the
        # member's first-above-default slot: left = slots below d_f + D
        a = jnp.broadcast_to(seg_a[None] - 1, (L, Fb, B - 1))
        sm1 = jnp.maximum(jnp.arange(B - 1, dtype=jnp.int32) - 1, 0)

        def pexcl(cum):
            cumA = jnp.take_along_axis(cum, jnp.maximum(a, 0), axis=-1)
            cumS = jnp.take_along_axis(
                cum, jnp.broadcast_to(sm1, (L, Fb, B - 1)), axis=-1)
            first = jnp.arange(B - 1, dtype=jnp.int32) == seg_a[None]
            return jnp.where(first, 0.0, cumS - cumA)

        gB, chB = gains(pexcl(cums[0]) + DG, pexcl(cums[1]) + DH,
                        pexcl(cums[2]) + DC)
        gB = jnp.where(is_candB[None] & bm, gB, -jnp.inf)

        def pick_best(gain3, ch3):
            flat = gain3.reshape(L, -1)
            best = jnp.argmax(flat, axis=1)
            gsel = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]

            def sel(x):
                return jnp.take_along_axis(x.reshape(L, -1),
                                           best[:, None], 1)[:, 0]
            j = (best // (B - 1)).astype(jnp.int32)
            s = (best % (B - 1)).astype(jnp.int32)
            ch = jnp.stack([sel(c) for c in ch3], axis=1)
            return gsel, j, s, ch

        obins = jnp.asarray(maps["obin"])
        dflts = jnp.asarray(maps["dflt"])
        wl = jnp.asarray(maps["bundle_w"])
        gA_s, jA, sA, chA_s = pick_best(gA, chA)
        gB_s, jB, sB, chB_s = pick_best(gB, chB)
        # candidate-A partition rule: default-above cut -> right child is
        # the contiguous tail range; default-below -> LEFT child is the
        # head range, expressed as the complement (inv)
        aD_A = addD[jA, sA]
        loA = jnp.where(aD_A, sA, seg_a[jA, sA] - 1)
        hiA = jnp.where(aD_A, seg_b[jA, sA] - 1, sA)
        invA = ~aD_A
        ofA = jnp.asarray(maps["ofeat"])[jA, sA]
        obA = obins[jA, sA]
        wfA = wl[jA]
        nalA = aD_A                       # NaN at predict follows default
        # candidate B: right = slots strictly above the default
        ofB = jnp.asarray(maps["ofeat"])[jB, sB]
        obB = dflts[jB, sB]
        wfB = wl[jB]
        loB = first_above[jB, sB] - 1
        hiB = seg_b[jB, sB] - 1
        invB = jnp.zeros_like(loB, bool)
        nalB = jnp.ones_like(invB)

        useB = gB_s > gA_s
        gain_b = jnp.maximum(gA_s, gB_s)

        def w2(bv, av):
            cond = useB[:, None] if av.ndim == 2 else useB
            return jnp.where(cond, bv, av)
        of_b = w2(ofB, ofA)
        ob_b = w2(obB, obA)
        nal_b = w2(nalB, nalA)
        ch_b = w2(chB_s, chA_s)
        wf_b = w2(wfB, wfA)
        lo_b = w2(loB, loA)
        hi_b = w2(hiB, hiA)
        inv_b = w2(invB, invA)
        outs.append((gain_b, of_b, ob_b, nal_b, ch_b, wf_b, lo_b, hi_b,
                     inv_b))

    if len(outs) == 1:
        gain, ofeat, obin, na_left, children, wfeat, lo, hi, inv = outs[0]
    else:
        gd, gb = outs[0][0], outs[1][0]
        use_b = gb > gd

        def mix(i):
            av, bv = outs[0][i], outs[1][i]
            cond = use_b[:, None] if av.ndim == 2 else use_b
            return jnp.where(cond, bv, av)
        gain, ofeat, obin, na_left, children, wfeat, lo, hi, inv = \
            (mix(i) for i in range(9))

    # leaf totals are identical across working features; reuse working 0
    totG_all = jnp.sum(H[0, :, 0, :], axis=-1)
    totH_all = jnp.sum(H[1, :, 0, :], axis=-1)
    totC_all = jnp.sum(H[2, :, 0, :], axis=-1)
    valid = jnp.isfinite(gain) & (gain > min_split_improvement) & \
        (totC_all >= 2 * min_rows)
    gl = jnp.where(valid, children[:, 0], totG_all)
    hl = jnp.where(valid, children[:, 1], totH_all)
    cl = jnp.where(valid, children[:, 2], totC_all)
    gr = jnp.where(valid, children[:, 3], 0.0)
    hr = jnp.where(valid, children[:, 4], 0.0)
    cr = jnp.where(valid, children[:, 5], 0.0)
    children = jnp.stack([gl, hl, cl, gr, hr, cr], axis=1)
    return (ofeat.astype(jnp.int32), obin.astype(jnp.int32), na_left, gain,
            valid, children, wfeat.astype(jnp.int32),
            lo.astype(jnp.int32), hi.astype(jnp.int32), inv)
