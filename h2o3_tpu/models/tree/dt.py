"""Single decision tree (DT) — ``hex/tree/dt/DT.java`` analog.

The reference's DT is a single depth-limited CART classifier (binary
response, entropy splits).  Here it is the degenerate forest: one
unsampled tree over all features through the same tpu_hist growth engine,
predicting per-leaf class frequencies — the same estimator family, one
compiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .drf import DRF, DRFModel, DRFParameters
from .shared import SharedTree


@dataclasses.dataclass
class DTParameters(DRFParameters):
    ntrees: int = 1
    max_depth: int = 20
    sample_rate: float = 1.0
    mtries: int = -2                     # all features at every split
    min_rows: float = 10.0


class DTModel(DRFModel):
    algo = "dt"


class DecisionTree(DRF):
    algo = "dt"
    model_class = DTModel

    def __init__(self, params: Optional[DTParameters] = None, **kw):
        SharedTree.__init__(self, params or DTParameters(**kw))
