"""Batched grid cohorts: G same-shape grid members in ONE compiled program.

Reference: ``hex/grid/GridSearch.java`` runs every hyperparameter combo as
an independent training job.  On a TPU that is G dispatch streams for G
programs whose traced shape is IDENTICAL whenever the combo only varies
scalar hyperparameters (eta, sample rates, lambda/alpha/gamma, min_rows,
min_child_weight, min_split_improvement, seed) — everything that enters
the kernels as an operand, not a shape.

TPU-native redesign: partition the combo list into shape-compatible
COHORTS (same max_depth/nbins/ntrees/layout/..., see ``BATCHABLE``) and
grow each cohort with ``make_grid_scan_fn`` — the grid analog of the
multinomial K-tree batch: one histogram launch and one split launch per
level for ALL G members, per-member PRNG via vmapped key chains, scalar
hyperparameters as ``[G]`` operands.  A G-loop of sequential builds is
the bitwise oracle (run_split_crosscheck's nk contract + the vmapped
threefry contract).

Successive halving (``search_criteria={"successive_halving": True}``)
retires losing members mid-train through the traced ``alive [G]`` mask:
a retired member's row weights zero out, every split goes invalid, its
leaf values are zero and its margin column freezes — zero recompiles,
since ``alive`` is an operand of the one compiled program.

Anything shape-changing or path-changing (multinomial, EFB bundling,
hier split search, sparse layout, DART, monotone constraints, CV folds,
checkpoints) falls back to the scheduler-parallel wave path in
``grid.py`` — raised here as ``CohortFallback`` with the reason.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: per-member knobs that batch as ``[G]`` operands (or per-member host
#: state, for ``seed``) — anything else changes the traced program and
#: therefore partitions cohorts
BATCHABLE = frozenset({
    "learn_rate", "sample_rate", "col_sample_rate",
    "col_sample_rate_per_tree", "reg_lambda", "reg_alpha", "gamma",
    "min_child_weight", "min_rows", "min_split_improvement", "seed",
})


class CohortFallback(Exception):
    """This cohort cannot ride the batched path — reroute its members
    through the scheduler-parallel wave path (the reason is the arg)."""


def _eligibility(builder_cls, p) -> Optional[str]:
    """Param-level disqualifiers, checked before any device work.
    Returns the fallback reason, or None when the member may batch."""
    if not getattr(builder_cls, "_grid_batchable", False):
        return f"{getattr(builder_cls, 'algo', builder_cls.__name__)} " \
               "has no batched-cohort trainer"
    if getattr(p, "nfolds", 0) and p.nfolds > 1:
        return "nfolds (CV folds already multiply the build)"
    if getattr(p, "checkpoint", None) is not None \
            or getattr(p, "warm_start", None) is not None:
        return "checkpoint/warm_start continuation"
    if getattr(p, "balance_classes", False):
        return "balance_classes"
    if getattr(p, "monotone_constraints", None):
        return "monotone_constraints"
    if getattr(p, "custom_distribution_func", None) is not None:
        return "custom_distribution_func"
    if getattr(p, "booster", "gbtree") == "dart":
        return "dart booster (per-tree drop state is sequential)"
    if str(getattr(p, "histogram_type", "auto")).lower() == "random":
        return "random histogram_type (per-seed bin edges cannot share " \
               "one binning)"
    if str(getattr(p, "split_search", "auto")).lower() == "hier":
        return "hierarchical split search"
    if str(getattr(p, "split_mode", "auto")).lower() not in ("auto",
                                                             "fused"):
        return "split_mode (batched builds are fused-only)"
    if str(getattr(p, "hist_layout", "auto")).lower() not in ("auto",
                                                              "dense"):
        return "hist_layout (batched builds are dense-only)"
    for knob in ("hist_mode", "tree_program"):
        if str(getattr(p, knob, "auto")).lower() == "check":
            return f"{knob}=check (per-member crosscheck diagnostics)"
    if str(getattr(p, "efb", "auto")).lower() == "on":
        return "efb=on (bundled working codes are per-plan)"
    if getattr(p, "calibrate_model", False):
        return "calibrate_model"
    if getattr(p, "export_checkpoints_dir", None):
        return "export_checkpoints_dir"
    if getattr(p, "stream", False):
        return "stream mode"
    return None


def plan_cohorts(builder_cls, base_params: dict,
                 combos: Sequence[dict]) -> Tuple[List[List[int]],
                                                  List[Tuple[int, str]]]:
    """Partition combo indices into batchable cohorts.

    Returns ``(cohorts, rest)``: cohorts are index lists (len >= 2) whose
    members agree on every non-``BATCHABLE`` parameter; ``rest`` carries
    ``(index, reason)`` for members that must take the wave path
    (ineligible params, bad combos, or no shape-compatible partner).
    """
    groups: Dict[tuple, List[int]] = {}
    rest: List[Tuple[int, str]] = []
    for i, combo in enumerate(combos):
        try:
            b = builder_cls(**{**base_params, **combo})
        except Exception as e:                          # noqa: BLE001
            rest.append((i, f"builder rejected params: {e!r}"))
            continue
        reason = _eligibility(builder_cls, b.params)
        if reason is not None:
            rest.append((i, reason))
            continue
        key = tuple(sorted((k, repr(v)) for k, v in combo.items()
                           if k not in BATCHABLE))
        groups.setdefault(key, []).append(i)
    cohorts = []
    for key, members in groups.items():
        if len(members) >= 2:
            cohorts.append(members)
        else:
            rest.append((members[0],
                         "singleton cohort (no shape-compatible partner)"))
    return cohorts, rest


def _halving_rungs(G: int, ntrees: int, eta: float) -> List[Tuple[int,
                                                                  int]]:
    """Successive-halving schedule: ``[(tree_count, keep), ...]`` with
    geometric tree budgets and survivor counts (classic SHA: G members
    at ntrees/eta^R, keep G/eta each rung, final survivors train to
    completion).  Rung boundaries snap UP to the next scoring fence at
    run time (retirement decisions need fresh interval metrics)."""
    if eta <= 1.0 or G < 2:
        return []
    R = int(math.floor(math.log(G) / math.log(eta) + 1e-9))
    rungs = []
    for i in range(R):
        trees = int(math.ceil(ntrees / eta ** (R - i)))
        keep = int(math.ceil(G / eta ** (i + 1)))
        if trees >= ntrees or keep >= G:
            continue
        rungs.append((trees, keep))
    return rungs


def train_cohort(builder_cls, base_params: dict, combos: Sequence[dict],
                 frame, valid=None, search_criteria: Optional[dict] = None,
                 deadline: Optional[float] = None
                 ) -> List[Tuple[Optional[object], Optional[str]]]:
    """Train G shape-compatible grid members as ONE batched program.

    Mirrors GBM's fused single-class driver with the member axis G where
    the multinomial driver has the class axis K: shared binning/DataInfo/
    init (identical across members by cohort construction), per-member
    Jobs + recovery journals (resolved seeds journaled, so a killed
    cohort resumes each member through the normal sequential path), ONE
    device lease around the chunk loop, per-member unbatch into
    ``StackedTrees`` chunks, snapshots, interval scoring, early stopping
    and successive halving via the host-side alive mask.

    Returns ``[(model, None) | (None, error_str)]`` aligned with
    ``combos``.  Raises ``CohortFallback`` (before any journal exists)
    when a train-time property disqualifies the whole cohort.
    """
    from ...runtime import autotune, dkv, recovery, snapshot, xprof
    from ...runtime import observability as obs
    from ...runtime import scheduler as _sched
    from ...runtime.job import DONE, RUNNING, Job
    from .. import parallel
    from ..distributions import make_distribution
    from ..scorekeeper import METRIC_MAXIMIZE, metric_direction
    from .binning import edges_matrix, fit_bins
    from .shared import (StackedTrees, TreeList, chunk_schedule,
                         effective_max_depth, make_grid_scan_fn,
                         maybe_bundle, record_effective_depth,
                         traverse_jit, tree_snapshot_state,
                         use_hier_split_search)

    G = len(combos)
    if G < 2:
        raise CohortFallback("singleton cohort")
    builders = []
    for combo in combos:
        b = builder_cls(**{**base_params, **combo})
        # resolve seed=-1 ONCE and pin it: the journaled params must
        # regrow the same trees on per-member resume
        b.params = dataclasses.replace(b.params,
                                       seed=b.params.effective_seed())
        builders.append(b)
    rep = builders[0]
    p0 = rep.params
    rep._validate(frame)
    di = rep._make_datainfo(frame)
    if di.is_classifier and di.nclasses > 2:
        raise CohortFallback(
            "multinomial response (class trees already occupy the batch "
            "axis)")
    dist = make_distribution(p0.distribution, nclasses=di.nclasses,
                             tweedie_power=p0.tweedie_power,
                             quantile_alpha=p0.quantile_alpha,
                             huber_alpha=p0.huber_alpha)
    y = di.response(frame)
    w = di.weights(frame)
    y, f0_dev = rep._prep_targets(y, w, dist)
    # shared binning: quantile/uniform edges are seed-independent, so one
    # binning serves every member bitwise (random histograms fell back)
    binned = fit_bins(frame, [s.name for s in di.specs], nbins=p0.nbins,
                      seed=p0.seed,
                      weights=w if p0.weights_column else None,
                      histogram_type=p0.histogram_type)
    edges_mat = jnp.asarray(edges_matrix(binned.edges, p0.nbins),
                            jnp.float32)
    N = binned.codes.shape[1]
    plan, wcodes, Fw, _wbc = maybe_bundle(binned, p0, None, frame.nrows)
    if plan is not None:
        raise CohortFallback("EFB bundling engaged")
    if use_hier_split_search(p0, N):
        raise CohortFallback("hierarchical split search engaged")
    knobs = autotune.resolve_tree_knobs(p0, kind=rep.algo, F=Fw, N=N, K=1,
                                        mono=None, plan=None, hier=False,
                                        checkpoint=False)
    autotune.activate(knobs)
    hist_layout = knobs.hist_layout
    if hist_layout != "dense":
        # _eligibility already rerouted an explicit "sparse", so this is
        # auto-resolution picking the node-sparse layout as a perf
        # choice.  Layouts are bitwise-equal at equal effective depth
        # (run_layout_crosscheck contract), and they only diverge through
        # the dense memory cap — so pin the cohort to dense whenever
        # dense can grow the same depth, and fall back only when it
        # genuinely caps the tree shallower.
        d_dense = effective_max_depth(p0.max_depth, p0.nbins, Fw, N,
                                      "dense")
        d_sparse = effective_max_depth(p0.max_depth, p0.nbins, Fw, N,
                                       "sparse",
                                       knobs.sparse_depth_threshold)
        if d_dense != d_sparse:
            raise CohortFallback(
                f"hist_layout={hist_layout} grows depth {d_sparse} but "
                f"dense caps at {d_dense} (batched cohorts are "
                "dense-only)")
        hist_layout = "dense"
    if knobs.split_mode != "fused":
        raise CohortFallback(f"split_mode={knobs.split_mode}")
    tree_program = knobs.tree_program \
        if knobs.tree_program in ("level", "scan") else "level"
    if knobs.sparse_depth_threshold != p0.sparse_depth_threshold:
        for i, b in enumerate(builders):
            b.params = dataclasses.replace(
                b.params,
                sparse_depth_threshold=knobs.sparse_depth_threshold)
        p0 = builders[0].params
    try:
        scan_fn = make_grid_scan_fn(
            G, dist.name, p0.tweedie_power, p0.quantile_alpha,
            p0.huber_alpha, p0.max_depth, p0.nbins, Fw, N,
            p0.effective_hist_precision, hist_mode=knobs.hist_mode,
            tree_program=tree_program)
    except ValueError as e:
        raise CohortFallback(str(e))

    algo = rep.algo
    obs.set_gauge("grid_cohort_size", float(G), algo=algo)
    obs.record("grid_cohort_start", algo=algo, size=G,
               tree_program=tree_program)

    models, jobs, journals = [], [], []
    for g, b in enumerate(builders):
        dest = dkv.make_key(algo)
        m = b.model_class(dest, b.params, di)
        m.output["distribution"] = dist.name
        m.output["binning"] = {"nbins": p0.nbins}
        m.output["nclass_trees"] = 1
        m.output["tree_program"] = tree_program
        m.output["grid_cohort"] = {"size": G, "member": g}
        record_effective_depth(m, b.params, Fw, N, hist_layout="dense")
        job = Job(f"{algo} train", dest_key=dest)
        models.append(m)
        jobs.append(job)
    # per-member journals AFTER every fallback check: a rerouted cohort
    # must not leave 'running' entries for the wave path to double-train
    for b, job in zip(builders, jobs):
        j = recovery.journal_start(b, frame, job)
        job.journal_uri = j
        journals.append(j)
        job.status = RUNNING
        job.start_time = time.time()
        job._mirror()

    if valid is not None:
        Xv = models[0]._design(valid)
        y_v, w_v = di.response(valid), di.weights(valid)
    f0 = float(f0_dev)
    from jax.sharding import NamedSharding, PartitionSpec
    from ...runtime.cluster import cluster
    # commit F to the replicated sharding the chunk outputs use — the
    # same silent-recompile trap the single-member driver decoded
    F = jax.device_put(
        jnp.broadcast_to(jnp.asarray(f0, jnp.float32), (G, N)),
        NamedSharding(cluster().mesh, PartitionSpec()))
    rng0G = jnp.stack([jax.random.PRNGKey(b.params.seed)
                       for b in builders])

    def arr(name):
        return jnp.asarray([float(getattr(b.params, name))
                            for b in builders], jnp.float32)

    head = (arr("reg_lambda"), arr("min_rows"),
            arr("min_split_improvement"), arr("learn_rate"),
            arr("col_sample_rate"), arr("sample_rate"),
            arr("col_sample_rate_per_tree"))
    tail = (arr("reg_alpha"), arr("gamma"), arr("min_child_weight"))
    metric_name, maximize = metric_direction(p0.stopping_metric,
                                             di.is_classifier)

    sc = dict(search_criteria or {})
    h_metric = sc.get("halving_metric") or metric_name
    h_maximize = METRIC_MAXIMIZE.get(h_metric, False) \
        if h_metric != metric_name else maximize
    rungs = _halving_rungs(G, p0.ntrees,
                           float(sc.get("halving_eta", 3.0))) \
        if sc.get("successive_halving") else []

    chunks: List[list] = [[] for _ in range(G)]
    histories: List[list] = [[] for _ in range(G)]
    alive = np.ones(G, bool)           # still growing trees
    failed: List[Optional[str]] = [None] * G
    nt = np.zeros(G, np.int64)         # trees trained per member
    Fvs = [jnp.broadcast_to(jnp.asarray(f0, jnp.float32),
                            (Xv.shape[0],))] * G if valid is not None \
        else None
    t_start = time.time()

    def member_failed(g: int, e: BaseException) -> None:
        failed[g] = repr(e)
        alive[g] = False
        obs.record("grid_member_failed", algo=algo, member=g,
                   error=repr(e))

    # the grid_member chaos/fault point fires per member here, exactly
    # like the wave path's per-build injection — a failing member becomes
    # a failed_entries row while its cohort siblings keep training
    from ...runtime import failure
    for g in range(G):
        try:
            failure.maybe_inject("grid_member")
        except Exception as e:                          # noqa: BLE001
            member_failed(g, e)

    prev_deadline = parallel.get_deadline()
    if deadline is not None:
        parallel.set_deadline(deadline)
    try:
        with _sched.device_slot():
            for chunk_no, (c, t_new, score_now) in enumerate(
                    chunk_schedule(p0.ntrees, p0.score_tree_interval)):
                if not alive.any():
                    break
                t_done = t_new
                aliveJ = jnp.asarray(alive)
                t0c = time.perf_counter()
                with obs.span("tree_chunk", job=jobs[0].key,
                              chunk=chunk_no, trees=c, cohort=G):
                    F, lv, vals, cov = scan_fn(wcodes, y, w, F, edges_mat,
                                               rng0G, chunk_no, c, *head,
                                               aliveJ, *tail)
                xprof.maybe_device_sync("tree_chunk", chunk_no, t0c, F)
                live = [g for g in range(G) if alive[g]]
                for g in live:
                    try:
                        lv_g = [tuple(lvd[i][:, g] for i in range(4))
                                for lvd in lv]
                        chunk = StackedTrees(lv_g, vals[:, g], cov[:, g])
                        chunks[g].append(chunk)
                        nt[g] = t_done
                        jobs[g].update(t_done / p0.ntrees,
                                       f"tree {t_done}/{p0.ntrees}")
                        snapshot.maybe_snapshot(
                            jobs[g], models[g],
                            {"trees_done": int(t_done),
                             "granularity": "tree_chunk"},
                            lambda cs=list(chunks[g]): tree_snapshot_state(
                                cs, f0, binned.edges))
                        if valid is not None:
                            Fvs[g] = Fvs[g] + traverse_jit(
                                chunk.levels, chunk.values, Xv)
                    except Exception as e:              # noqa: BLE001
                        member_failed(g, e)
                if not score_now:
                    continue
                for g in live:
                    if not alive[g]:
                        continue
                    try:
                        vstate = (Fvs[g], y_v, w_v) \
                            if valid is not None else None
                        if builders[g]._interval_score(
                                models[g], int(t_done), F[g], y, w, di,
                                dist, histories[g], vstate, metric_name,
                                maximize):
                            alive[g] = False    # member's own early stop
                    except Exception as e:              # noqa: BLE001
                        member_failed(g, e)
                # successive halving: at each rung fence, keep the best
                # `keep` members by metric; the rest retire through the
                # alive mask (same compiled program — zero recompiles)
                while rungs and t_done >= rungs[0][0]:
                    _, keep = rungs.pop(0)
                    live_now = [g for g in range(G)
                                if alive[g] and failed[g] is None]
                    if len(live_now) <= keep:
                        continue
                    key = f"valid_{h_metric}" if valid is not None \
                        else h_metric
                    worst = math.inf if h_maximize else -math.inf

                    def rank(g):
                        hh = histories[g][-1] if histories[g] else {}
                        v = hh.get(key)
                        return worst if v is None else v

                    ranked = sorted(live_now, key=rank,
                                    reverse=h_maximize)
                    for g in ranked[keep:]:
                        alive[g] = False
                        models[g].output["halving"] = {
                            "retired_at": int(t_done), "rung_keep": keep}
                        obs.inc("grid_members_retired_total", algo=algo)
                        obs.record("grid_member_retired", algo=algo,
                                   member=g, trees=int(t_done))
    except parallel.DeadlineExceeded:
        # cooperative max_runtime_secs: every member freezes at this
        # chunk fence and finalizes with the trees grown so far
        obs.record("grid_cohort_deadline", algo=algo,
                   trees=int(nt.max(initial=0)))
    finally:
        parallel.set_deadline(prev_deadline)

    results: List[Tuple[Optional[object], Optional[str]]] = []
    for g in range(G):
        if failed[g] is None and not chunks[g]:
            failed[g] = "DeadlineExceeded('max_runtime_secs deadline " \
                        "before the first tree chunk')"
        if failed[g] is not None:
            recovery.journal_fail(journals[g], failed[g])
            jobs[g].fail(RuntimeError(failed[g]))
            results.append((None, failed[g]))
            continue
        try:
            stacked = StackedTrees.concat(chunks[g])
            m = builders[g]._finalize_fused(
                models[g], di, dist, F[g], y, w, valid, histories[g],
                binned, f0, stacked.ntrees, stacked=stacked,
                trees=TreeList(stacked))
            m.output.setdefault("run_time_s", time.time() - t_start)
            m.output.setdefault("training_frame_rows", frame.nrows)
            builders[g]._post_fit(m, frame, valid)
            jobs[g].status = DONE
            jobs[g].progress = 1.0
            jobs[g].end_time = time.time()
            jobs[g]._done.set()
            jobs[g]._mirror()
            recovery.journal_done(journals[g])
            results.append((m, None))
        except Exception as e:                          # noqa: BLE001
            recovery.journal_fail(journals[g], repr(e))
            jobs[g].fail(e)
            results.append((None, repr(e)))
    return results
