"""XGBoost-parameter-compatible booster on the tpu_hist kernel family.

Reference: ``h2o-extensions/xgboost`` — ``hex/tree/xgboost/XGBoost.java``
(driver loop :371-398,486-524) delegates to native libxgboost
(``gpu_hist``/``hist`` tree builders + Rabit ring allreduce,
XGBoostModel.java:260-298 maps h2o params to xgboost params).

TPU-native redesign: same estimator surface and exact split math
(L1-soft-thresholded gain, gamma pruning, min_child_weight hessian
constraint, sparsity-aware NA direction — hist.py:best_splits) on the
tpu_hist MXU histogram kernels; ICI psum replaces Rabit.  ``booster='dart'``
runs libxgboost's DART dropout/renormalization inside the shared GBM driver.
The h2o alias surface (eta/subsample/colsample_bytree/...) is accepted
verbatim so estimator code ports 1:1.  Like gpu_hist, levels below the root
histogram only each parent's smaller child and derive the sibling by
subtraction (``hist_mode="subtract"``, the default; "full" is the exactness
oracle and "check" asserts their agreement on the first tree — shared.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ...frame.frame import Frame
from ..base import ModelBuilder
from .gbm import GBM, GBMModel, GBMParameters
from .shared import SharedTreeParameters

# h2o-py H2OXGBoostEstimator alias -> canonical field
_ALIASES = {
    "eta": "learn_rate",
    "subsample": "sample_rate",
    "colsample_bytree": "col_sample_rate_per_tree",
    "colsample_bylevel": "col_sample_rate",
    "max_bins": "nbins",
    "min_split_loss": "gamma",
    "n_estimators": "ntrees",
    "max_leaves": None,                 # accepted, depthwise growth only
    "tree_method": None,
    "grow_policy": None,
    "backend": None,
    "gpu_id": None,
}

# xgboost objective -> our distribution
_OBJECTIVES = {
    "reg:squarederror": "gaussian",
    "reg:linear": "gaussian",
    "binary:logistic": "bernoulli",
    "multi:softprob": "multinomial",
    "multi:softmax": "multinomial",
    "count:poisson": "poisson",
    "reg:gamma": "gamma",
    "reg:tweedie": "tweedie",
}


@dataclasses.dataclass
class XGBoostParameters(SharedTreeParameters):
    # xgboost defaults (XGBoostModel.java createParams defaults)
    ntrees: int = 50
    max_depth: int = 6
    learn_rate: float = 0.3
    min_rows: float = 1.0
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    gamma: float = 0.0
    min_child_weight: float = 1.0
    nbins: int = 256
    sample_rate: float = 1.0
    col_sample_rate: float = 1.0
    col_sample_rate_per_tree: float = 1.0
    booster: str = "gbtree"              # gbtree | dart
    scale_pos_weight: float = 1.0
    # DART params (libxgboost dart booster)
    rate_drop: float = 0.0
    skip_drop: float = 0.0
    one_drop: bool = False
    normalize_type: str = "tree"         # tree | forest
    sample_type: str = "uniform"


class XGBoostModel(GBMModel):
    algo = "xgboost"


class XGBoost(GBM):
    """XGBoost-compatible builder — H2OXGBoostEstimator analog on tpu_hist."""

    algo = "xgboost"
    model_class = XGBoostModel

    def __init__(self, params: Optional[XGBoostParameters] = None, **kw):
        if params is None:
            canon = {}
            for k, v in kw.items():
                if k == "objective":
                    canon["distribution"] = _OBJECTIVES.get(v, v)
                    continue
                if k in _ALIASES:
                    tgt = _ALIASES[k]
                    if tgt is not None:
                        canon[tgt] = v
                    continue
                canon[k] = v
            params = XGBoostParameters(**canon)
        if params.booster not in ("gbtree", "dart"):
            raise ValueError(
                f"booster={params.booster!r} not supported (gbtree, dart); "
                "gblinear maps to GLM in this framework")
        from .shared import (resolve_hist_layout, resolve_hist_mode,
                             resolve_split_mode, resolve_tree_program)
        resolve_hist_mode(params)        # fail fast on a bad hist_mode
        resolve_split_mode(params)       # ... and on a bad split_mode
        resolve_hist_layout(params)      # ... and on a bad hist_layout
        resolve_tree_program(params)     # ... and on a bad tree_program
        ModelBuilder.__init__(self, params)

    def train(self, frame, valid=None, warm_start=None):
        p: XGBoostParameters = self.params
        # scale_pos_weight needs materialized response codes — a
        # StreamingFrame defers to the per-segment trains on its
        # visible prefixes (each a real Frame re-entering here)
        scaled = self._apply_scale_pos_weight(frame) \
            if p.scale_pos_weight != 1.0 and isinstance(frame, Frame) \
            else None
        if scaled is None:
            return super().train(frame, valid, warm_start=warm_start)
        frame2, params2 = scaled
        self.params = params2
        try:
            return super().train(frame2, valid, warm_start=warm_start)
        finally:
            self.params = p          # builder stays reusable

    def _apply_scale_pos_weight(self, frame):
        """Fold scale_pos_weight into a row-weight column (binary only)."""
        import numpy as np
        from ...frame.frame import Frame
        from ...frame.vec import Vec, T_NUM, T_CAT
        p: XGBoostParameters = self.params
        rv = frame.vec(p.response_column)
        if rv.type != T_CAT or len(rv.domain or []) != 2:
            return None
        codes = rv.to_numpy()
        w = np.where(codes == 1, p.scale_pos_weight, 1.0)
        if p.weights_column:
            w = w * frame.vec(p.weights_column).to_numpy()
        names = list(frame.names) + ["_xgb_w_"]
        vecs = list(frame.vecs) + [Vec.from_numpy(w, T_NUM)]
        return (Frame(names, vecs),
                dataclasses.replace(p, weights_column="_xgb_w_"))
