"""tpu_hist: the histogram / split-search / partition kernels for tree algos.

Reference hot loop: ``hex/tree/DHistogram.java:48,67-95`` (per-(leaf, column,
bin) accumulate of w/wY/wYY into one double[]), driven by
``ScoreBuildHistogram2.java:62,119-235`` (two node-local passes: score rows ->
leaf assignment, then histogram build parallel over columns x row-ranges),
reduced across the cluster by elementwise array add (MRTask tree-reduce).
The XGBoost extension's CUDA ``gpu_hist`` is the performance target
(BASELINE.json: "gpu_hist via xgboost4j-gpu -> Pallas/XLA tpu_hist").

TPU-native redesign: scatter-adds are serialized on a vector machine, so the
histogram becomes DENSE MATMULS on the MXU: one-hot(leaf) x (g,h,w) planes
contracted with one-hot(bin codes) via einsum, blocked over rows to bound
memory, shard_mapped over the mesh's ("hosts", "chips") row axes with the
cross-device reduce staged ICI-then-DCN by runtime/mapreduce.psum_shards
(replacing both the LocalMR pass and the MRTask tree; ``reduce_mode``
picks flat/hier/check — see runtime/mapreduce.py).
Split search and row partition are fused elementwise/gather passes.  All
shapes static per tree level; one compile per (depth, F, B) geometry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...runtime.cluster import cluster, ROW_AXES, ROW_AXIS
from ...runtime.compat import shard_map
from ...runtime.mapreduce import checked_pair, psum_shards, \
    resolve_reduce_mode


def _row_sds(shape, dtype):
    """ShapeDtypeStruct carrying the rows-varying VMA mark; jax<0.5 has
    no VMA typing, where the plain struct is equivalent."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype,
                                    vma=frozenset(ROW_AXES))
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _ledger(name, jitted, orig=None, **kw):
    """Register a compiled seam with the compile ledger (runtime/xprof).

    Deferred import: hist is importable without the runtime observability
    stack loaded.  The wrapper is call-compatible with the jitted product
    (transparent under a trace; AOT + timed compile when eager)."""
    from ...runtime import xprof
    return xprof.register_program(name, jitted, orig=orig, **kw)


def _reduce_mode_dispatch(builder):
    """Resolve ``reduce_mode`` in front of a cached builder.

    ``""`` resolves to the configured mode so the LRU only ever caches
    concretely-scheduled programs; ``"check"`` returns a flat/hier
    checked pair (mapreduce.checked_pair) built from two cache entries.
    ``cache_clear`` is preserved — conftest's compiled-program release
    hook and cluster re-init both call it through the public name.
    """
    @functools.wraps(builder)
    def wrapper(*args, reduce_mode: str = "", **kw):
        mode = resolve_reduce_mode(reduce_mode or None)
        if mode == "check":
            return checked_pair(
                builder(*args, reduce_mode="flat", **kw),
                builder(*args, reduce_mode="hier", **kw),
                what=builder.__name__)
        return builder(*args, reduce_mode=mode, **kw)
    wrapper.cache_clear = builder.cache_clear
    return wrapper

def _make_pallas_hist(L: int, F: int, B: int, n_local: int,
                      interpret: bool = False, precision: str = "bf16",
                      planes: int = 3):
    """tpu_hist kernel: histogram as an in-VMEM one-hot matmul.

    The XLA einsum path materializes the [rows, F*B] one-hot in HBM every
    level (~N*F*B*4 bytes of traffic — bandwidth-bound); here the one-hot
    tile lives only in VMEM and feeds the MXU directly, so HBM traffic per
    level is just codes + (leaf,g,h,w).  Grid: (bin tiles, row blocks) —
    row blocks innermost so each [F*TB, 3L] output tile stays resident
    while rows stream through (replacing DHistogram's per-node scatter-adds
    and gpu_hist's shared-memory atomics).
    """
    R = int(min(4096, max(256, ((n_local + 255) // 256) * 256)))
    L3 = planes * L
    # the A build materializes [R, L3] intermediates (int32 iota + f32
    # selects + bf16 A ~ 12 B/elem) on the 16M scoped-VMEM stack; deep
    # trees (large L) must shrink the row block (found on chip: L=256,
    # R=4096 -> 18.6M scoped alloc, Mosaic OOM)
    R = int(min(R, max(256, (6_291_456 // (12 * L3)) // 256 * 256)))
    nblk = (n_local + R - 1) // R
    pad_to = nblk * R
    # bins per tile -> [F*TB, R] one-hot tile.  The [TB, F, R] compare
    # intermediate is laid out with F in the sublane dim, which pads to a
    # multiple of 8 — size TB against the PADDED F or small-F geometries
    # blow the 16M scoped-VMEM stack (observed: F=3 -> 22M alloc).  Also cap
    # the padded intermediate itself at 8M so wide-F geometries stay inside
    # the scoped-VMEM budget.
    F8 = (F + 7) // 8 * 8
    TB = max(1, min(512 // F8, 2_097_152 // (F8 * R)))
    # never build one-hot tiles wider than the bin range (small-B coarse
    # pass: TB=64 for B=17 wasted 3.7x of the kernel's dominant VPU work)
    TB = min(TB, (B + 7) // 8 * 8)
    FBT = F * TB
    n_fb = (B + TB - 1) // TB

    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32

    def _build_A(LS):
        # A[r, planes*l+s] = S[r, s] where leaf[r] == l, else 0.  Plane 3
        # (hierarchical bounds) is |g|, derived in-kernel from plane 0.
        # (A 3-D match*stat form would halve the op count but Mosaic cannot
        # shape-cast [R, L, p] minor dims back to [R, L*p].)
        leaf = LS[0].astype(jnp.int32)
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, L3), 1)
        l_of, s_of = cols // planes, cols % planes
        match = leaf[:, None] == l_of
        sv = jnp.where(s_of == 0, LS[1][:, None],
                       jnp.where(s_of == 1, LS[2][:, None],
                                 LS[3][:, None]))
        if planes == 4:
            sv = jnp.where(s_of == 3, jnp.abs(LS[1])[:, None], sv)
        return jnp.where(match, sv, 0.0).astype(dt)

    def kernel(codes_ref, ls_ref, out_ref, a_scratch):
        i = pl.program_id(0)                       # row block (outer)
        j = pl.program_id(1)                       # bin tile (inner)

        @pl.when(j == 0)
        def _():
            # built once per row block, reused across all bin tiles
            a_scratch[:] = _build_A(ls_ref[:])

        @pl.when((i == 0) & (j == 0))
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        # OHT[b*F+f, r] = (codes[f, r] == j*TB + b) via broadcast compare —
        # no materialized int32 repeat, one VPU pass straight to bf16
        # (bf16/int16 compares are not supported by the target's VPU)
        b_of = jax.lax.broadcasted_iota(jnp.int32, (TB, 1, 1), 0) + j * TB
        OHT = (codes_ref[:][None] == b_of).astype(dt).reshape(FBT, R)
        # the WHOLE histogram is one output block (index map is constant),
        # so every grid step revisits it consecutively — the accumulation
        # is safe under Pallas TPU's revisiting rule, and the block never
        # round-trips through HBM
        out_ref[pl.ds(j * FBT, FBT), :] += jnp.dot(
            OHT, a_scratch[:], preferred_element_type=jnp.float32)

    def kernel_deep(codes_ref, ls_ref, out_ref):
        # fallback for deep trees where the whole histogram exceeds VMEM:
        # out tile [FBT, L3] is stationary across the inner row loop
        # (consecutive revisits — safe), A rebuilt per step
        j = pl.program_id(0)                       # bin tile (outer)
        i = pl.program_id(1)                       # row block (inner)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        A = _build_A(ls_ref[:])
        b_of = jax.lax.broadcasted_iota(jnp.int32, (TB, 1, 1), 0) + j * TB
        OHT = (codes_ref[:][None] == b_of).astype(dt).reshape(FBT, R)
        out_ref[:] += jnp.dot(OHT, A, preferred_element_type=jnp.float32)

    out_bytes = n_fb * FBT * L3 * 4
    a_bytes = R * L3 * (2 if precision == "bf16" else 4)
    if out_bytes + a_bytes <= 8 * 1024 * 1024:
        call = pl.pallas_call(
            kernel,
            grid=(nblk, n_fb),
            in_specs=[
                pl.BlockSpec((F, R), lambda i, j: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((4, R), lambda i, j: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((n_fb * FBT, L3), lambda i, j: (0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=_row_sds((n_fb * FBT, L3), jnp.float32),
            scratch_shapes=[pltpu.VMEM((R, L3), dt)],
            interpret=interpret,
        )
    else:
        call = pl.pallas_call(
            kernel_deep,
            grid=(n_fb, nblk),
            in_specs=[
                pl.BlockSpec((F, R), lambda j, i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((4, R), lambda j, i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((FBT, L3), lambda j, i: (j, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=_row_sds((n_fb * FBT, L3), jnp.float32),
            interpret=interpret,
        )

    def local(codes, leaf, g, h, w):
        pad = pad_to - n_local

        def padr(x):
            if pad == 0:
                return x
            return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
        LS = jnp.stack([leaf.astype(jnp.float32), g, h, w], axis=0)
        out = call(padr(codes), padr(LS))[: B * F]
        # [B*F, pL] rows ordered (b*F + f), cols (l*p + s) -> [p, L, F, B]
        return out.reshape(B, F, L, planes).transpose(3, 2, 1, 0)

    return local


def varbin_layout(bin_counts, B: int):
    """Packed ragged bin-axis layout: per-feature [offset, B_f regular bins,
    NA slot], each segment 8-padded (sublane alignment).

    Returns (offsets[F], segment row counts [F], total rows Q8, and the
    dense gather map [F, B+1] -> packed row, with empty bins pointing at
    padding slots that provably stay zero).
    """
    offsets, rows = [], []
    q = 0
    for bf in bin_counts:
        bf = min(bf, B - 1)              # regular bins; NA gets slot bf
        # pad to sublane multiple with at least ONE spare slot: empty dense
        # bins map to the spare, which no code ever matches (stays zero)
        seg = ((bf + 2) + 7) // 8 * 8
        offsets.append(q)
        rows.append(seg)
        q += seg
    qmap = np.zeros((len(bin_counts), B + 1), np.int32)
    for f, bf in enumerate(bin_counts):
        bf = min(bf, B - 1)
        for b in range(B + 1):
            if b < bf:                   # regular bin
                qmap[f, b] = offsets[f] + b
            elif b == B:                 # NA bin (dense index B-1... see below)
                qmap[f, b] = offsets[f] + bf
            else:                        # empty bin -> padded zero slot
                qmap[f, b] = offsets[f] + rows[f] - 1
    return (np.asarray(offsets, np.int32), np.asarray(rows, np.int32),
            q, qmap)


def _make_pallas_varbin_hist(L: int, F: int, bin_counts, B: int,
                             n_local: int, interpret: bool = False,
                             precision: str = "bf16", planes: int = 3):
    """tpu_hist with a PACKED per-feature bin axis.

    The uniform kernel compares every feature row against every global bin
    id — O(F * B) VPU work per row even when most features use a fraction
    of the bins (a 22-carrier categorical against 257 slots).  Reference
    DHistogram sizes bins per column (DHistogram.java:48 min/max driven);
    here each feature gets exactly pad8(B_f+1) one-hot rows, built by a
    statically unrolled per-feature compare against its own code row, so
    VPU cost drops from F*B to sum(B_f).  Codes must arrive PRE-OFFSET
    (code + offset_f, NA -> offset_f + B_f): the build driver does that
    once per tree.
    """
    offsets, seg_rows, Q8, _ = varbin_layout(bin_counts, B)
    R = int(min(4096, max(512, (4_194_304 // max(Q8 * 2, 1))
                          // 128 * 128)))
    R = min(R, max(512, ((n_local + 511) // 512) * 512))
    L3 = planes * L
    # deep-tree guard: A-build intermediates are [R, L3] (~12 B/elem) on
    # the scoped-VMEM stack — see _make_pallas_hist
    R = int(min(R, max(512, (6_291_456 // (12 * L3)) // 128 * 128)))
    nblk = (n_local + R - 1) // R
    pad_to = nblk * R
    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32
    # PROFILE.md roadmap: stream codes+leaf as int16 and stats as bf16 —
    # halves the kernel's HBM input bytes.  The VPU cannot compare
    # sub-32-bit ints (Mosaic), so values upcast in-VMEM after the DMA;
    # int16 only when every id fits (packed bin ids < Q8, leaf < L).
    code_dt = jnp.int16 if max(Q8, L) < 32_000 else jnp.int32
    stat_dt = dt

    def kernel(codes_ref, leaf_ref, st_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        leaf = leaf_ref[0].astype(jnp.int32)
        # [3, R] stat_dt -> f32: Mosaic's apply-vector-layout pass only
        # supports non-no-op minor-dim insertion ([R] -> [R, 1]) for 32-bit
        # types, and the sv select below does exactly that broadcast.  The
        # upcast is VMEM-local; A still feeds the MXU as bf16.  (Found on
        # chip: the AOT gate's MLIR verifier passes this, the backend
        # layout pass rejects it.)
        ST = st_ref[:].astype(jnp.float32)
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, L3), 1)
        l_of, s_of = cols // planes, cols % planes
        match = leaf[:, None] == l_of
        sv = jnp.where(s_of == 0, ST[0][:, None],
                       jnp.where(s_of == 1, ST[1][:, None],
                                 ST[2][:, None]))
        if planes == 4:
            sv = jnp.where(s_of == 3, jnp.abs(ST[0])[:, None], sv)
        A = jnp.where(match, sv, 0.0).astype(dt)
        codes = codes_ref[:].astype(jnp.int32)         # [F, R]
        pieces = []
        for f in range(F):
            q_of = jax.lax.broadcasted_iota(
                jnp.int32, (int(seg_rows[f]), 1), 0) + int(offsets[f])
            pieces.append((codes[f, :][None, :] == q_of).astype(dt))
        OHT = jnp.concatenate(pieces, axis=0)          # [Q8, R]
        out_ref[:] += jnp.dot(OHT, A, preferred_element_type=jnp.float32)

    call = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((F, R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((3, R), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((Q8, L3), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_row_sds((Q8, L3), jnp.float32),
        interpret=interpret,
    )

    def local(gcodes, leaf, g, h, w):
        pad = pad_to - n_local

        def padr(x, fill):
            if pad == 0:
                return x
            return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)],
                           constant_values=fill)
        # casts fuse into the per-level leaf/grad producers; gcodes are
        # already code_dt from offset_codes (no per-level copy)
        ST = jnp.stack([g, h, w], axis=0).astype(stat_dt)
        return call(padr(gcodes.astype(code_dt), -1),
                    padr(leaf[None].astype(code_dt), -1),
                    padr(ST, 0))                       # [Q8, pL]

    return local


def offset_codes(codes, bin_counts, nbins: int):
    """codes [F, N] (NA == nbins) -> packed global bin ids for the varbin
    kernel.  Done once per tree by the build driver.  Emitted as int16
    when every packed id fits — the ids persist in HBM across all levels
    of the tree, so the narrow dtype halves the histogram kernel's
    dominant streaming input for the whole build."""
    offsets, _, Q8, _ = varbin_layout(bin_counts, nbins + 1)
    off = jnp.asarray(offsets)[:, None]
    bf = jnp.asarray([min(b, nbins) for b in bin_counts],
                     jnp.int32)[:, None]
    out = jnp.where(codes >= nbins, off + bf, codes + off)
    if Q8 < 32_000:
        out = out.astype(jnp.int16)
    return out


@functools.lru_cache(maxsize=None)
def _make_varbin_hist_fn(L: int, F: int, bin_counts: tuple, B: int,
                         n_padded: int, force_impl: str = "",
                         precision: str = "bf16", reduce_mode: str = "hier"):
    """Variable-bin histogram with the DENSE output contract of
    make_hist_fn: (gcodes, leaf, g, h, w) -> H[3, L, F, B].

    ``gcodes`` must be pre-offset (offset_codes).  The packed [Q8, 3L]
    kernel result is re-expanded through the static qmap gather (tiny).
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    _, _, Q8, qmap = varbin_layout(bin_counts, B)
    if force_impl == "pallas_interpret":
        inner = _make_pallas_varbin_hist(L, F, bin_counts, B, n_local,
                                         interpret=True, precision=precision)
    else:
        inner = _make_pallas_varbin_hist(L, F, bin_counts, B, n_local,
                                         precision=precision)
    qmap_dense = jnp.asarray(qmap[:, list(range(B - 1)) + [B]])  # [F, B]
    # dense layout [.., F, B]: regular bins 0..B-2 then NA at B-1

    def local_hist(gcodes, leaf, g, h, w):
        out = inner(gcodes, leaf, g, h, w)             # [Q8, 3L]
        H = out[qmap_dense.reshape(-1)]                # [F*B, 3L]
        H = H.reshape(F, B, L, 3).transpose(3, 2, 0, 1)
        return psum_shards(H, reduce_mode)

    specs_in = (P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                P(ROW_AXIS))
    f = shard_map(local_hist, mesh=cl.mesh, in_specs=specs_in, out_specs=P(),
                  check_vma=False)
    return _ledger("hist_varbin", jax.jit(f), orig=f)


make_varbin_hist_fn = _reduce_mode_dispatch(_make_varbin_hist_fn)


def _make_einsum_hist(L: int, F: int, B: int, n_local: int, planes: int = 3):
    """Portable XLA path (CPU mesh tests, non-TPU backends, and the
    deep-level fallback where [R, planes*L] exceeds scoped VMEM)."""
    blk = max((4 * 1024 * 1024) // max(F * B, 1), 256)
    # deep levels: the [blk, L] leaf one-hot / [blk, planes, L] stats
    # intermediates must stay bounded too
    blk = max(min(blk, 8_388_608 // max(L, 1)), 64)
    blk = min(n_local, blk)
    nblk = (n_local + blk - 1) // blk
    pad_to = nblk * blk

    def local(codes, leaf, g, h, w):
        def padr(x, fill=0):
            return jnp.pad(x, [(0, 0)] * (x.ndim - 1)
                           + [(0, pad_to - n_local)], constant_values=fill)
        codes = padr(codes).reshape(F, nblk, blk).transpose(1, 0, 2)
        leaf = padr(leaf).reshape(nblk, blk)
        stats = [g, h, w] + ([jnp.abs(g)] if planes == 4 else [])
        S = jnp.stack(stats, axis=1)              # [n, planes]
        S = jnp.pad(S, [(0, pad_to - n_local), (0, 0)]) \
            .reshape(nblk, blk, planes)

        def body(acc, args):
            c, lf, s = args
            Pl = jax.nn.one_hot(lf, L, dtype=jnp.float32)       # [blk, L]
            OH = jax.nn.one_hot(c, B, dtype=jnp.float32)        # [F, blk, B]
            PS = jnp.einsum("rl,rs->rsl", Pl, s)                # [blk,p,L]
            acc = acc + jnp.einsum("rsl,frb->slfb", PS, OH)
            return acc, None
        H0 = jnp.zeros((planes, L, F, B), jnp.float32)
        if hasattr(jax.lax, "pcast"):     # jax<0.5 has no VMA typing
            H0 = jax.lax.pcast(H0, ROW_AXES, to='varying')
        H, _ = jax.lax.scan(body, H0, (codes, leaf, S))
        return H

    return local


@functools.lru_cache(maxsize=None)
def _make_hist_fn(L: int, F: int, B: int, n_padded: int,
                  force_impl: str = "", precision: str = "bf16",
                  planes: int = 3, reduce_mode: str = "hier"):
    """Compiled histogram: (codes[N,F], leaf[N], g[N], h[N], w[N]) ->
    H[planes, L, F, B] with planes (sum g, sum h, sum w[, sum |g|]),
    psum'd over the mesh.

    ``B`` here includes the NA bin (= nbins + 1).  On TPU the local pass is
    the Pallas tpu_hist kernel; elsewhere (CPU test mesh) an equivalent
    einsum program.  ``force_impl`` ("pallas_interpret" | "einsum") pins the
    implementation for cross-checking.  ``planes=4`` adds the |g| plane the
    hierarchical split-search bounds need.
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    platform = cl.mesh.devices.flat[0].platform
    # very deep levels: the [F*B, 3L] result exceeds what XLA will stage
    # through VMEM for the custom call — take the portable path there
    hist_bytes = F * B * planes * L * 4
    if force_impl == "pallas_interpret":
        inner = _make_pallas_hist(L, F, B, n_local, interpret=True,
                                  precision=precision, planes=planes)
    elif force_impl == "einsum" or platform != "tpu" \
            or hist_bytes > 12 * 1024 * 1024 or planes * L > 2048:
        # planes*L > 2048: even the minimum row block's [R, planes*L]
        # A-build intermediates overflow the 16M scoped-VMEM stack
        inner = _make_einsum_hist(L, F, B, n_local, planes=planes)
    else:
        inner = _make_pallas_hist(L, F, B, n_local, precision=precision,
                                  planes=planes)

    def local_hist(codes, leaf, g, h, w):
        return psum_shards(inner(codes, leaf, g, h, w), reduce_mode)

    specs_in = (P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                P(ROW_AXIS))
    # check_vma=False: the kernel mixes varying refs with grid-constant
    # iotas, which the vma checker can't see through pallas_call
    f = shard_map(local_hist, mesh=cl.mesh, in_specs=specs_in, out_specs=P(),
                  check_vma=False)
    return _ledger("hist_uniform", jax.jit(f), orig=f)


make_hist_fn = _reduce_mode_dispatch(_make_hist_fn)


def _local_hist_impl(L: int, F: int, B: int, n_local: int, bin_counts=None,
                     force_impl: str = "", precision: str = "bf16"):
    """Per-shard local histogram (PRE-psum) at an (L, n_local) geometry.

    The kernel-selection rules of make_hist_fn / make_varbin_hist_fn
    factored out so the subtraction level driver can run the same kernels
    over a compacted (smaller-sibling) row prefix.  With ``bin_counts`` the
    varbin kernel is used (codes must be pre-offset packed ids) and the
    packed [Q8, 3L] result is re-expanded to the dense [3, L, F, B]
    contract; otherwise the uniform Pallas kernel with the einsum fallback
    (CPU mesh, deep levels — same bounds as make_hist_fn).
    ``force_impl="pallas"`` pins the REAL (non-interpret) kernel off-TPU —
    the AOT Mosaic export gate needs it to lower the true code path from a
    CPU host (tests/test_mosaic_lowering.py).
    """
    platform = cluster().mesh.devices.flat[0].platform
    if bin_counts is not None:
        _, _, _, qmap = varbin_layout(bin_counts, B)
        interpret = force_impl == "pallas_interpret" or \
            (platform != "tpu" and force_impl != "pallas")
        raw = _make_pallas_varbin_hist(L, F, bin_counts, B, n_local,
                                       interpret=interpret,
                                       precision=precision)
        qmap_dense = jnp.asarray(
            np.asarray(qmap)[:, list(range(B - 1)) + [B]].reshape(-1))

        def inner(codes, leaf, g, h, w):
            out = raw(codes, leaf, g, h, w)                # [Q8, 3L]
            H = out[qmap_dense]                            # [F*B, 3L]
            return H.reshape(F, B, L, 3).transpose(3, 2, 0, 1)

        return inner
    hist_bytes = F * B * 3 * L * 4
    if force_impl == "pallas_interpret":
        return _make_pallas_hist(L, F, B, n_local, interpret=True,
                                 precision=precision)
    if force_impl != "pallas" and (
            force_impl == "einsum" or platform != "tpu"
            or hist_bytes > 12 * 1024 * 1024 or 3 * L > 2048):
        return _make_einsum_hist(L, F, B, n_local)
    return _make_pallas_hist(L, F, B, n_local, precision=precision)


@functools.lru_cache(maxsize=None)
def _make_subtract_level_fn(d: int, F: int, B: int, n_padded: int,
                            bin_counts=None, force_impl: str = "",
                            precision: str = "bf16",
                            reduce_mode: str = "hier"):
    """Level-``d`` histogram via smaller-sibling row COMPACTION + parent
    subtraction — DHistogram / LightGBM / gpu_hist's classic halving,
    TPU-shaped (arXiv:1706.08359 §3.2).

    The masked-left subtraction this replaces still streamed ALL N rows
    through the one-hot kernel every level (the stats were zeroed, the VPU
    compare work was not).  Here each shard (a) picks, per parent, the
    child with fewer LOCAL physical rows, (b) compacts those rows into a
    dense prefix of length ``n_local // 2`` (sum over parents of
    min(left, right) can never exceed half the shard — the bound is exact
    because orientation is per-shard), (c) histograms only the prefix at
    the parent-slot geometry, and (d) reconstructs the larger siblings as
    ``H_parent_local - H_small_local`` in f32 before the cross-shard psum.
    The compaction itself is a cumsum-positioned monotonic scatter over the
    packed code/leaf/stat planes — one bandwidth-bound pass, NOT a per-row
    gather (PROFILE.md fix #1).

    The per-shard parent histogram needed for the subtraction rides along
    as a carry: each call returns ``(H_global, H_carry)`` where ``H_carry``
    is the [n_shards, 3, L, F, B] stack of pre-psum shard-local histograms
    that the NEXT level consumes.  ``d == 0`` takes
    ``(codes, leaf, g, h, w)`` (full build, all rows in leaf 0); ``d >= 1``
    additionally takes the previous level's carry.  Accumulation stays f32
    end to end (kernel outputs f32; h/w planes of the reconstructed side
    are clamped at 0 — see the driver's rounding note), so the dense
    [3, 2^d, F, B] contract matches the full build to f32 tolerance and
    split search is unchanged.
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    Lp = 2 ** max(d - 1, 0)            # parent slots the kernel histograms
    Lc = 2 ** d                        # children at this level
    cap = n_local // 2 if d > 0 else n_local
    inner = _local_hist_impl(Lp, F, B, cap, bin_counts=bin_counts,
                             force_impl=force_impl, precision=precision)
    specs_row = (P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                 P(ROW_AXIS))

    if d == 0:
        def local0(codes, leaf, g, h, w):
            Hl = inner(codes, leaf, g, h, w)
            return psum_shards(Hl, reduce_mode), Hl[None]

        f = shard_map(local0, mesh=cl.mesh, in_specs=specs_row,
                      out_specs=(P(), P(ROW_AXIS)), check_vma=False)
        return _ledger("hist_subtract", jax.jit(f), orig=f)

    def locald(codes, leaf, g, h, w, carry):
        Hp = carry[0]                              # this shard's [3,Lp,F,B]
        # local physical row count per child — orientation only (weighted
        # counts can't bound the compaction buffer: w=0 sampled-out rows
        # still occupy kernel lanes).  The compare fuses into the reduce.
        cidx = jax.lax.broadcasted_iota(jnp.int32, (Lc, 1), 0)
        cnt = jnp.sum(cidx == leaf[None, :], axis=1, dtype=jnp.int32)
        small_is_left = cnt[0::2] <= cnt[1::2]                 # [Lp]
        chosen_child = jnp.stack(
            [small_is_left, ~small_is_left], axis=1).reshape(-1)   # [Lc]
        # per-row smaller-sibling flag via the MXU one-hot product —
        # per-row gathers are poison (PROFILE.md fix #1)
        chosen = table_lookup(
            chosen_child.astype(jnp.float32)[None], leaf, Lc)[0] > 0.5
        # dense-prefix positions; unchosen rows target the out-of-bounds
        # slot ``cap`` and are dropped by the scatter
        target = jnp.where(chosen,
                           jnp.cumsum(chosen.astype(jnp.int32)) - 1, cap)
        ccodes = jnp.zeros((F, cap), codes.dtype) \
            .at[:, target].set(codes, mode="drop", unique_indices=True)
        pleaf = jnp.zeros((cap,), jnp.int32) \
            .at[target].set((leaf >> 1).astype(jnp.int32), mode="drop",
                            unique_indices=True)
        st = jnp.zeros((3, cap), jnp.float32) \
            .at[:, target].set(
                jnp.stack([g, h, w]).astype(jnp.float32), mode="drop",
                unique_indices=True)
        Hs = inner(ccodes, pleaf, st[0], st[1], st[2])     # [3, Lp, F, B]
        Ho = Hp - Hs
        # clamp the h/w planes at 0: per-level kernel routing can pair
        # differently-rounded kernels across the subtraction (bf16 vs f32),
        # and negative hessian/weight sums would corrupt best_splits
        Ho = Ho.at[1:].max(0.0)
        sl = small_is_left[None, :, None, None]
        Hl_ = jnp.where(sl, Hs, Ho)
        Hr_ = jnp.where(sl, Ho, Hs)
        Hloc = jnp.stack([Hl_, Hr_], axis=2).reshape(3, Lc, F, B)
        return psum_shards(Hloc, reduce_mode), Hloc[None]

    f = shard_map(locald, mesh=cl.mesh,
                  in_specs=specs_row + (P(ROW_AXIS),),
                  out_specs=(P(), P(ROW_AXIS)), check_vma=False)
    return _ledger("hist_subtract", jax.jit(f), orig=f)


make_subtract_level_fn = _reduce_mode_dispatch(_make_subtract_level_fn)


@functools.lru_cache(maxsize=None)
def _make_batched_level_fn(d: int, K: int, F: int, B: int, n_padded: int,
                           bin_counts=None, force_impl: str = "",
                           precision: str = "bf16", subtract: bool = True,
                           reduce_mode: str = "hier"):
    """Level-``d`` histograms for K trees in ONE kernel launch.

    The K-class multinomial round used to issue K separate level programs
    (K dispatches + K traced copies); here the per-tree local pass is
    ``jax.vmap``-ed over a leading K axis, which Pallas lowers to a single
    ``pallas_call`` with K prepended to the grid — one launch per level
    regardless of K (the batching rule leaves the shared ``codes`` operand
    unbatched, so the dominant streaming input is NOT duplicated K times).
    Per-tree row compaction (``subtract=True``, mirroring
    make_subtract_level_fn) stays plain vmapped XLA: each tree picks its
    own smaller siblings, so codes/leaf/stat planes diverge per tree after
    the scatter and batch cleanly into the kernel.

    ``subtract=False`` is the full-rebuild contract (hist_mode="full") at
    a K axis — the crosscheck oracle for the batched path.

    Shapes: codes [F, N] shared; leaf/g/h/w [K, N]; ``d >= 1`` subtract
    additionally takes carry [n_shards, K, 3, Lp, F, B].  Returns
    H [K, 3, 2^d, F, B] (psum'd) and, for subtract, the next carry.
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    Lc = 2 ** d
    Lp = 2 ** max(d - 1, 0)
    specs_k = (P(None, ROW_AXIS),) * 5

    if not subtract:
        inner = _local_hist_impl(Lc, F, B, n_local, bin_counts=bin_counts,
                                 force_impl=force_impl, precision=precision)

        def localf(codes, leafK, gK, hK, wK):
            Hl = jax.vmap(inner, in_axes=(None, 0, 0, 0, 0))(
                codes, leafK, gK, hK, wK)
            return psum_shards(Hl, reduce_mode)

        f = shard_map(localf, mesh=cl.mesh, in_specs=specs_k, out_specs=P(),
                      check_vma=False)
        return _ledger("hist_batched", jax.jit(f), orig=f)

    cap = n_local // 2 if d > 0 else n_local
    inner = _local_hist_impl(Lp, F, B, cap, bin_counts=bin_counts,
                             force_impl=force_impl, precision=precision)

    if d == 0:
        def local0(codes, leafK, gK, hK, wK):
            Hl = jax.vmap(inner, in_axes=(None, 0, 0, 0, 0))(
                codes, leafK, gK, hK, wK)
            return psum_shards(Hl, reduce_mode), Hl[None]

        f = shard_map(local0, mesh=cl.mesh, in_specs=specs_k,
                      out_specs=(P(), P(ROW_AXIS)), check_vma=False)
        return _ledger("hist_batched", jax.jit(f), orig=f)

    def locald(codes, leafK, gK, hK, wK, carry):
        HpK = carry[0]                             # [K, 3, Lp, F, B]

        def one(leaf, g, h, w, Hp):
            # per-tree smaller-sibling compaction — the exact
            # make_subtract_level_fn body, codes closed over (shared)
            cidx = jax.lax.broadcasted_iota(jnp.int32, (Lc, 1), 0)
            cnt = jnp.sum(cidx == leaf[None, :], axis=1, dtype=jnp.int32)
            small_is_left = cnt[0::2] <= cnt[1::2]
            chosen_child = jnp.stack(
                [small_is_left, ~small_is_left], axis=1).reshape(-1)
            chosen = table_lookup(
                chosen_child.astype(jnp.float32)[None], leaf, Lc)[0] > 0.5
            target = jnp.where(
                chosen, jnp.cumsum(chosen.astype(jnp.int32)) - 1, cap)
            ccodes = jnp.zeros((F, cap), codes.dtype) \
                .at[:, target].set(codes, mode="drop", unique_indices=True)
            pleaf = jnp.zeros((cap,), jnp.int32) \
                .at[target].set((leaf >> 1).astype(jnp.int32), mode="drop",
                                unique_indices=True)
            st = jnp.zeros((3, cap), jnp.float32) \
                .at[:, target].set(
                    jnp.stack([g, h, w]).astype(jnp.float32), mode="drop",
                    unique_indices=True)
            Hs = inner(ccodes, pleaf, st[0], st[1], st[2])
            Ho = Hp - Hs
            Ho = Ho.at[1:].max(0.0)
            sl = small_is_left[None, :, None, None]
            Hl_ = jnp.where(sl, Hs, Ho)
            Hr_ = jnp.where(sl, Ho, Hs)
            return jnp.stack([Hl_, Hr_], axis=2).reshape(3, Lc, F, B)

        HlocK = jax.vmap(one)(leafK, gK, hK, wK, HpK)
        return psum_shards(HlocK, reduce_mode), HlocK[None]

    f = shard_map(locald, mesh=cl.mesh, in_specs=specs_k + (P(ROW_AXIS),),
                  out_specs=(P(), P(ROW_AXIS)), check_vma=False)
    return _ledger("hist_batched", jax.jit(f), orig=f)


make_batched_level_fn = _reduce_mode_dispatch(_make_batched_level_fn)


@functools.lru_cache(maxsize=None)
def _make_scan_level_fn(W: int, F: int, B: int, n_padded: int,
                        force_impl: str = "", precision: str = "bf16",
                        reduce_mode: str = "hier"):
    """Depth-generic subtract-level histogram for the scan-fused build.

    The per-level factory (make_subtract_level_fn) closes over the level
    index ``d`` — one compiled program per depth, one dispatch per level.
    The whole-tree ``lax.scan`` needs ONE program whose shapes do not
    change across iterations, so this variant runs the identical
    smaller-sibling compaction at a FIXED child width ``W`` (the deepest
    scanned level's 2^d) with parent width ``W // 2``.  Shallower levels
    simply leave their padding slots empty: a slot with zero local rows
    has ``cnt == 0`` on both children, contributes an all-False chosen
    mask (exact +0.0 histogram), and reconstructs to exact +0.0 on the
    large side (``0 - 0`` clamped) — so padded slots are bitwise inert
    and the live prefix matches the per-level program (see the blocking
    caveat in shared.resolve_tree_program).

    ``dead`` is the scan-carried early-exit predicate (no alive leaf
    anywhere): the compaction + kernel launch is skipped under a
    ``lax.cond`` and the level degenerates to the pure parent
    passthrough — which IS what the live branch computes when every row
    sits on an even child (sibling side empty -> Hs = +0.0, large side
    = clamp(Hp)), so taking the branch never changes a bit.

    Returns ``(H_global [3, W, F, B], carry [n_shards, 3, W//2, F, B])``
    — the carry keeps only the first W//2 child slots, which covers
    every live slot of any non-final level (2^d <= W/2 below the last
    iteration; the final carry is discarded).
    """
    if W < 2 or W & (W - 1):
        raise ValueError(f"scan level width must be a power of two >= 2, "
                         f"got {W}")
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    Wp = W // 2
    cap = n_local // 2
    inner = _local_hist_impl(Wp, F, B, cap, force_impl=force_impl,
                             precision=precision)
    specs_row = (P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                 P(ROW_AXIS))

    def _live(codes, leaf, g, h, w, Hp):
        # make_subtract_level_fn's locald body at the (W, Wp) geometry
        cidx = jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0)
        cnt = jnp.sum(cidx == leaf[None, :], axis=1, dtype=jnp.int32)
        small_is_left = cnt[0::2] <= cnt[1::2]                 # [Wp]
        chosen_child = jnp.stack(
            [small_is_left, ~small_is_left], axis=1).reshape(-1)   # [W]
        chosen = table_lookup(
            chosen_child.astype(jnp.float32)[None], leaf, W)[0] > 0.5
        target = jnp.where(chosen,
                           jnp.cumsum(chosen.astype(jnp.int32)) - 1, cap)
        ccodes = jnp.zeros((F, cap), codes.dtype) \
            .at[:, target].set(codes, mode="drop", unique_indices=True)
        pleaf = jnp.zeros((cap,), jnp.int32) \
            .at[target].set((leaf >> 1).astype(jnp.int32), mode="drop",
                            unique_indices=True)
        st = jnp.zeros((3, cap), jnp.float32) \
            .at[:, target].set(
                jnp.stack([g, h, w]).astype(jnp.float32), mode="drop",
                unique_indices=True)
        Hs = inner(ccodes, pleaf, st[0], st[1], st[2])     # [3, Wp, F, B]
        Ho = Hp - Hs
        Ho = Ho.at[1:].max(0.0)
        sl = small_is_left[None, :, None, None]
        Hl_ = jnp.where(sl, Hs, Ho)
        Hr_ = jnp.where(sl, Ho, Hs)
        return jnp.stack([Hl_, Hr_], axis=2).reshape(3, W, F, B)

    def _skip(codes, leaf, g, h, w, Hp):
        # all rows on even children: the live branch reduces to exactly
        # this (Hs = +0.0, clamped parent on the left, zeros right)
        Hoc = Hp.at[1:].max(0.0)
        return jnp.stack([Hoc, jnp.zeros_like(Hp)],
                         axis=2).reshape(3, W, F, B)

    def locald(codes, leaf, g, h, w, carry, dead):
        Hp = carry[0]                              # this shard's [3,Wp,F,B]
        Hloc = jax.lax.cond(dead, _skip, _live, codes, leaf, g, h, w, Hp)
        return psum_shards(Hloc, reduce_mode), Hloc[:, :Wp][None]

    f = shard_map(locald, mesh=cl.mesh,
                  in_specs=specs_row + (P(ROW_AXIS), P()),
                  out_specs=(P(), P(ROW_AXIS)), check_vma=False)
    return _ledger("hist_scan", jax.jit(f), orig=f)


make_scan_level_fn = _reduce_mode_dispatch(_make_scan_level_fn)


@functools.lru_cache(maxsize=None)
def _make_batched_scan_level_fn(W: int, K: int, F: int, B: int,
                                n_padded: int, force_impl: str = "",
                                precision: str = "bf16",
                                reduce_mode: str = "hier"):
    """K-tree batched variant of ``make_scan_level_fn`` — one launch per
    scan iteration regardless of K (the vmap batching rule keeps the
    shared ``codes`` operand unbatched, mirroring make_batched_level_fn).
    ``dead`` is all-trees-dead; an individually finished tree inside a
    live level already produces the bitwise parent passthrough on its
    own (its rows all sit on even children), so no per-tree predicate is
    needed.  Shapes: leaf/g/h/w [K, N]; carry [n_shards, K, 3, W//2, F,
    B]; returns H [K, 3, W, F, B] plus the next carry."""
    if W < 2 or W & (W - 1):
        raise ValueError(f"scan level width must be a power of two >= 2, "
                         f"got {W}")
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    Wp = W // 2
    cap = n_local // 2
    inner = _local_hist_impl(Wp, F, B, cap, force_impl=force_impl,
                             precision=precision)
    specs_k = (P(None, ROW_AXIS),) * 5

    def locald(codes, leafK, gK, hK, wK, carry, dead):
        HpK = carry[0]                             # [K, 3, Wp, F, B]

        def one(leaf, g, h, w, Hp):
            cidx = jax.lax.broadcasted_iota(jnp.int32, (W, 1), 0)
            cnt = jnp.sum(cidx == leaf[None, :], axis=1, dtype=jnp.int32)
            small_is_left = cnt[0::2] <= cnt[1::2]
            chosen_child = jnp.stack(
                [small_is_left, ~small_is_left], axis=1).reshape(-1)
            chosen = table_lookup(
                chosen_child.astype(jnp.float32)[None], leaf, W)[0] > 0.5
            target = jnp.where(
                chosen, jnp.cumsum(chosen.astype(jnp.int32)) - 1, cap)
            ccodes = jnp.zeros((F, cap), codes.dtype) \
                .at[:, target].set(codes, mode="drop", unique_indices=True)
            pleaf = jnp.zeros((cap,), jnp.int32) \
                .at[target].set((leaf >> 1).astype(jnp.int32), mode="drop",
                                unique_indices=True)
            st = jnp.zeros((3, cap), jnp.float32) \
                .at[:, target].set(
                    jnp.stack([g, h, w]).astype(jnp.float32), mode="drop",
                    unique_indices=True)
            Hs = inner(ccodes, pleaf, st[0], st[1], st[2])
            Ho = Hp - Hs
            Ho = Ho.at[1:].max(0.0)
            sl = small_is_left[None, :, None, None]
            Hl_ = jnp.where(sl, Hs, Ho)
            Hr_ = jnp.where(sl, Ho, Hs)
            return jnp.stack([Hl_, Hr_], axis=2).reshape(3, W, F, B)

        def _live(codes, leafK, gK, hK, wK, HpK):
            return jax.vmap(one)(leafK, gK, hK, wK, HpK)

        def _skip(codes, leafK, gK, hK, wK, HpK):
            def pas(Hp):
                Hoc = Hp.at[1:].max(0.0)
                return jnp.stack([Hoc, jnp.zeros_like(Hp)],
                                 axis=2).reshape(3, W, F, B)
            return jax.vmap(pas)(HpK)

        HlocK = jax.lax.cond(dead, _skip, _live,
                             codes, leafK, gK, hK, wK, HpK)
        return psum_shards(HlocK, reduce_mode), HlocK[:, :, :Wp][None]

    f = shard_map(locald, mesh=cl.mesh,
                  in_specs=specs_k + (P(ROW_AXIS), P()),
                  out_specs=(P(), P(ROW_AXIS)), check_vma=False)
    return _ledger("hist_scan_batched", jax.jit(f), orig=f)


make_batched_scan_level_fn = _reduce_mode_dispatch(_make_batched_scan_level_fn)


def sparse_slot_budget(F: int, B: int,
                       cap_bytes: int = 64 * 1024 * 1024) -> int:
    """Static slot capacity for node-sparse deep levels.

    The dense grid hits its memory wall where ``F*B*3*2^d*4`` exceeds the
    64 MB histogram budget (shared.effective_max_depth).  The sparse layout
    sizes its slot axis so the SAME budget holds at every depth: the
    largest multiple of 8 (the f32 sublane tile) slots whose [A, F, B]
    triple-plane grid fits ``cap_bytes``, clamped to [16, 4096].  Levels
    whose full child width 2^d is smaller than this use 2^d directly."""
    a = cap_bytes // (F * B * 3 * 4)
    return int(max(16, min(4096, (a // 8) * 8)))


def hist_level_bytes(n_rows: int, F: int, B: int, width: int, K: int = 1,
                     *, layout: str = "dense",
                     hist_mode: str = "subtract",
                     cap_bytes: int = 64 * 1024 * 1024):
    """Roofline byte traffic for ONE level's histogram build — the cost
    atom ``runtime/autotune.py`` seeds its model from, kept next to the
    kernels it prices so a kernel change updates the model in one place.

    Reads: int32 codes + f32 g/h/w per contributing row per feature
    (subtract levels stream only the compacted smaller siblings,
    <= n/2 rows; the full oracle streams every row).  Writes: the
    [width|A, F, B] triple-plane grid, f32.  Returns ``None`` when the
    dense grid for ``width`` leaves exceeds the histogram budget — that
    config cannot run and the model must price it out."""
    rows = n_rows if (hist_mode == "full" or width <= 1) else n_rows // 2
    read = rows * F * (4 + 3 * 4) * max(K, 1)
    slots = width if layout == "dense" else min(width, sparse_slot_budget(
        F, B, cap_bytes))
    grid = slots * F * B * 3 * 4 * max(K, 1)
    if layout == "dense" and grid > cap_bytes * max(K, 1):
        return None
    if layout == "sparse":
        # slot-map gathers + compaction traffic: a small constant factor
        # over the dense write path, paid for unbounded depth
        grid = int(grid * 1.15) + rows * 4
    return float(read + grid)


def split_search_passes(split_mode: str) -> float:
    """Histogram re-read factor of the split search: the fused
    winner-record kernel reads the grid once; the separate multi-pass
    oracle scans it ~3x (gains, argmax, record)."""
    return 1.0 if split_mode == "fused" else 3.0


def sparse_slot_maps(valid_prev, A_next: int):
    """Child-slot assignment for the next node-sparse level.

    ``valid_prev`` [Ap] holds the previous level's split decisions in that
    level's own slot (or dense-leaf) space.  Both children of every valid
    slot get a contiguous slot pair (even = left), in slot order.  Returns

    - ``child_base`` [Ap+1]: first child slot of each previous slot
      (``A_next`` when the slot gets no pair — invalid, past the slot
      budget, or the appended sentinel row),
    - ``ps_of_slot`` [A_next]: each slot's parent slot (pairs share it;
      phantom slots past the live range point at 0 and are masked off),
    - ``real`` [A_next]: live-slot mask (phantom slots are never written
      by any row and their split records are discarded).

    When a level has more alive children than ``A_next`` slots, later
    pairs are dropped ATOMICALLY in slot order and those children become
    terminal leaves — the deterministic num_leaves-style degradation the
    operations guide documents; ``hist_layout="check"`` surfaces it."""
    Ap = valid_prev.shape[0]
    idx = jnp.cumsum(valid_prev.astype(jnp.int32)) - 1          # [Ap]
    kept = valid_prev & (2 * idx + 1 < A_next)
    base = jnp.where(kept, 2 * idx, A_next).astype(jnp.int32)
    child_base = jnp.concatenate(
        [base, jnp.full((1,), A_next, jnp.int32)])              # [Ap+1]
    half = jnp.zeros((A_next // 2,), jnp.int32) \
        .at[jnp.where(kept, idx, A_next // 2)] \
        .set(jnp.arange(Ap, dtype=jnp.int32), mode="drop")
    ps_of_slot = jnp.repeat(half, 2)
    real = jnp.arange(A_next) < 2 * jnp.sum(kept.astype(jnp.int32))
    return child_base, ps_of_slot, real


def _sparse_local_body(A_prev: int, A: int, F: int, cap: int, inner):
    """Per-shard node-sparse level body shared by the single-tree and
    batched-K wrappers: smaller-sibling compaction labeled by PARENT SLOT
    (not dense parent id), subtraction against the slot-space carry, then
    a slot-axis gather into this level's [A] slot space."""

    def body(codes, sleaf, g, h, w, Hp, ps_of_slot):
        side = jnp.arange(A, dtype=jnp.int32) & 1               # [A]
        # local physical row count per slot — orientation only, exactly as
        # the dense subtract kernel counts per dense child
        sidx = jax.lax.broadcasted_iota(jnp.int32, (A, 1), 0)
        cnt = jnp.sum(sidx == sleaf[None, :], axis=1, dtype=jnp.int32)
        # fold to per-parent-slot left/right counts (tiny [A] scatter-add;
        # phantom slots contribute 0 rows so pointing them at parent 0 is
        # harmless)
        cl_ = jnp.zeros((A_prev,), jnp.int32).at[ps_of_slot].add(
            jnp.where(side == 0, cnt, 0), mode="drop")
        cr_ = jnp.zeros((A_prev,), jnp.int32).at[ps_of_slot].add(
            jnp.where(side == 1, cnt, 0), mode="drop")
        small_is_left = cl_ <= cr_                              # [A_prev]
        chosen_slot = jnp.where(side == 0, small_is_left[ps_of_slot],
                                ~small_is_left[ps_of_slot])     # [A]
        # per-row (smaller-sibling?, parent slot) in ONE one-hot product
        # over the A+1-wide slot table; the sentinel row (slot A — nodes
        # whose chain died or overflowed) is never chosen, so dead rows
        # stay out of the histogram entirely
        tbl = jnp.stack([
            jnp.concatenate([chosen_slot.astype(jnp.float32),
                             jnp.zeros((1,), jnp.float32)]),
            jnp.concatenate([ps_of_slot.astype(jnp.float32),
                             jnp.zeros((1,), jnp.float32)])])
        t = table_lookup(tbl, sleaf, A + 1)                     # [2, N]
        chosen = t[0] > 0.5
        prow = t[1].astype(jnp.int32)
        target = jnp.where(chosen,
                           jnp.cumsum(chosen.astype(jnp.int32)) - 1, cap)
        ccodes = jnp.zeros((F, cap), codes.dtype) \
            .at[:, target].set(codes, mode="drop", unique_indices=True)
        pleaf = jnp.zeros((cap,), jnp.int32) \
            .at[target].set(prow, mode="drop", unique_indices=True)
        st = jnp.zeros((3, cap), jnp.float32) \
            .at[:, target].set(
                jnp.stack([g, h, w]).astype(jnp.float32), mode="drop",
                unique_indices=True)
        Hs = inner(ccodes, pleaf, st[0], st[1], st[2])     # [3, A_prev,F,B]
        Ho = Hp - Hs
        Ho = Ho.at[1:].max(0.0)
        # gather each slot's histogram from its parent row: the smaller
        # child reads Hs, the larger its reconstruction — a slot-axis
        # gather over A blocks, NOT a per-row op
        Hs_g = jnp.take(Hs, ps_of_slot, axis=1)
        Ho_g = jnp.take(Ho, ps_of_slot, axis=1)
        return jnp.where(chosen_slot[None, :, None, None], Hs_g, Ho_g)

    return body


@functools.lru_cache(maxsize=None)
def _make_sparse_level_fn(A_prev: int, A: int, F: int, B: int,
                          n_padded: int, bin_counts=None,
                          force_impl: str = "", precision: str = "bf16",
                          reduce_mode: str = "hier"):
    """Node-sparse deep-level histogram: [A, F, B] slots for ALIVE leaves
    instead of the dense [2^d, F, B] grid (ROADMAP item 1 — the CSR move
    the GPU tree-boosting literature sizes deep levels by).

    Below the depth threshold the smaller-sibling compaction already
    streams <= N/2 rows, but the dense slot grid kept histogram bytes at
    F*B*3*2^d*4 — the 64 MB wall that capped depth.  Here the level is
    keyed by slot ids: rows carry ``sleaf`` [N] in [0, A] (A = "no slot":
    terminal chains and budget overflow), the carry is the PREVIOUS
    level's per-shard slot-space histograms [n_shards, 3, A_prev, F, B],
    and ``ps_of_slot`` [A] (replicated) maps each slot to its parent's
    slot — at the dense->sparse boundary the "previous slot space" is just
    the dense parent id space, so the first sparse level consumes the
    dense subtract carry unchanged.  When every parent is valid and
    A = 2^d the slot map is the identity and the output is bit-identical
    to make_subtract_level_fn; with dead chains the compaction prefix
    differs (dead rows are dropped rather than histogrammed), so parity
    is structural + f32-tolerance, which hist_layout="check" asserts.

    Returns ``(H_global [3, A, F, B], carry [n_shards, 3, A, F, B])``.
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    cap = n_local // 2
    inner = _local_hist_impl(A_prev, F, B, cap, bin_counts=bin_counts,
                             force_impl=force_impl, precision=precision)
    body = _sparse_local_body(A_prev, A, F, cap, inner)

    def locald(codes, sleaf, g, h, w, carry, ps_of_slot):
        Hloc = body(codes, sleaf, g, h, w, carry[0], ps_of_slot)
        return psum_shards(Hloc, reduce_mode), Hloc[None]

    specs_in = (P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                P(ROW_AXIS), P(ROW_AXIS), P())
    f = shard_map(locald, mesh=cl.mesh, in_specs=specs_in,
                  out_specs=(P(), P(ROW_AXIS)), check_vma=False)
    return _ledger("hist_sparse", jax.jit(f), orig=f)


make_sparse_level_fn = _reduce_mode_dispatch(_make_sparse_level_fn)


@functools.lru_cache(maxsize=None)
def _make_batched_sparse_level_fn(A_prev: int, A: int, K: int, F: int,
                                  B: int, n_padded: int, bin_counts=None,
                                  force_impl: str = "",
                                  precision: str = "bf16",
                                  reduce_mode: str = "hier"):
    """K-tree node-sparse level in ONE kernel launch — the
    make_batched_level_fn contract at the sparse slot geometry.

    Each tree has its own slot assignment (per-tree valid flags), so
    ``sleaf``/``ps_of_slot`` carry a leading [K]; the per-tree body is
    vmapped and Pallas prepends K to the grid exactly as the dense
    batched path does, keeping the launch count at one hist + one records
    kernel per level regardless of K.  Shapes: codes [F, N] shared;
    sleaf/g/h/w [K, N]; carry [n_shards, K, 3, A_prev, F, B];
    ps_of_slot [K, A] replicated.  Returns (H [K, 3, A, F, B], carry)."""
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    cap = n_local // 2
    inner = _local_hist_impl(A_prev, F, B, cap, bin_counts=bin_counts,
                             force_impl=force_impl, precision=precision)
    body = _sparse_local_body(A_prev, A, F, cap, inner)

    def locald(codes, sleafK, gK, hK, wK, carry, psK):
        HlocK = jax.vmap(body, in_axes=(None, 0, 0, 0, 0, 0, 0))(
            codes, sleafK, gK, hK, wK, carry[0], psK)
        return psum_shards(HlocK, reduce_mode), HlocK[None]

    specs_in = (P(None, ROW_AXIS),) * 5 + (P(ROW_AXIS), P())
    f = shard_map(locald, mesh=cl.mesh, in_specs=specs_in,
                  out_specs=(P(), P(ROW_AXIS)), check_vma=False)
    return _ledger("hist_batched_sparse", jax.jit(f), orig=f)


make_batched_sparse_level_fn = \
    _reduce_mode_dispatch(_make_batched_sparse_level_fn)


def _make_pallas_fine_hist(L: int, F: int, W: int, K: int, nbins: int,
                           n_local: int, interpret: bool = False,
                           precision: str = "bf16"):
    """Fine-refinement kernel: histogram only the K selected super-bins.

    For each (leaf, feature) the coarse pass selected K candidate super-bins
    (``sel``); this kernel builds the [F*K*W, R] one-hot of "row's code falls
    on fine slot t of its leaf's k-th selected super-bin" and contracts with
    the A stats matrix on the MXU.  The per-row selected-super-bin table is
    itself an MXU product (one-hot(leaf) x sel) — no gathers anywhere.  VPU
    cost per row is F*K*(W+2) + 2L instead of the full pass's F*(nbins+1).
    """
    R = int(min(4096, max(256, ((n_local + 255) // 256) * 256)))
    L3 = 3 * L
    # deep-tree guard — see _make_pallas_hist
    R = int(min(R, max(256, (6_291_456 // (12 * L3)) // 256 * 256)))
    nblk = (n_local + R - 1) // R
    pad_to = nblk * R
    FK = F * K
    # feature tile: the [TF, K, W, R] one-hot intermediate must fit VMEM
    TF = max(1, min(F, 4_194_304 // (K * W * R * 2)))
    n_ft = (F + TF - 1) // TF
    dt = jnp.bfloat16 if precision == "bf16" else jnp.float32

    def kernel(codes_ref, ls_ref, sel_ref, out_ref):
        # grid (feature tiles j, row blocks i): out tile stationary over i
        i = pl.program_id(1)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        LS = ls_ref[:]                             # [4, R] (leaf,g,h,w)
        leaf = LS[0].astype(jnp.int32)
        # one-hot(leaf) [L, R] -> selected super-bin per (f-in-tile, k, row)
        # (iota is full [L, R]: Mosaic rejects 1x1-shaped iota vectors)
        liota = jax.lax.broadcasted_iota(jnp.int32, (L, R), 0)
        onehL = (liota == leaf[None, :]).astype(dt)            # [L, R]
        S = jnp.dot(sel_ref[:], onehL,
                    preferred_element_type=jnp.float32)        # [TF*K, R]
        codes_f = codes_ref[:].astype(jnp.float32)
        # mask the NA code (== nbins): when nbins < S*W it would otherwise
        # alias into a fine slot of the last super-bin
        codes_f = jnp.where(codes_f >= nbins, jnp.float32(-1e9), codes_f)
        rel = (codes_f[:, None, :]
               - jnp.float32(W) * S.reshape(TF, K, R)) \
            .reshape(TF * K, R)                                # [TF*K, R]
        rel_i = jnp.clip(rel, -2.0, jnp.float32(W)).astype(jnp.int32)
        # t-major one-hot rows ((t, f, k) order) via the same rank-3 int32
        # (T, 1, 1)-iota the coarse kernel uses — Mosaic rejects f32 iotas
        t_of = jax.lax.broadcasted_iota(jnp.int32, (W, 1, 1), 0)
        OHT = (rel_i[None] == t_of).astype(dt).reshape(W * TF * K, R)
        # A[r, 3l+s]
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, L3), 1)
        l_of, s_of = cols // 3, cols % 3
        match = leaf[:, None] == l_of
        sv = jnp.where(s_of == 0, LS[1][:, None],
                       jnp.where(s_of == 1, LS[2][:, None],
                                 LS[3][:, None]))
        A = jnp.where(match, sv, 0.0).astype(dt)
        out_ref[:] += jnp.dot(OHT, A, preferred_element_type=jnp.float32)

    call = pl.pallas_call(
        kernel,
        grid=(n_ft, nblk),
        in_specs=[
            pl.BlockSpec((TF, R), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4, R), lambda j, i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TF * K, L), lambda j, i: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((TF * K * W, L3), lambda j, i: (j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_row_sds((n_ft * TF * K * W, L3), jnp.float32),
        interpret=interpret,
    )

    def local(codes, leaf, g, h, w, sel):
        # sel: [L, F, K] int32 -> operand [F*K, L] f32 (feature-major rows)
        sel_t = sel.reshape(L, FK).T.astype(jnp.float32)
        if n_ft * TF > F:
            sel_t = jnp.pad(sel_t, [(0, n_ft * TF * K - FK), (0, 0)],
                            constant_values=-1.0)
        pad = pad_to - n_local

        def padr(x):
            if pad == 0:
                return x
            return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
        LS = jnp.stack([leaf.astype(jnp.float32), g, h, w], axis=0)
        codes_p = padr(codes)
        if n_ft * TF > F:
            codes_p = jnp.pad(codes_p, [(0, n_ft * TF - F), (0, 0)],
                              constant_values=-1)
        out = call(codes_p, padr(LS), sel_t)
        # tile-j rows ordered (t, f_local, k), cols l*3+s -> [3, L, F, K, W]
        out = out.reshape(n_ft, W, TF, K, L, 3) \
            .transpose(5, 4, 0, 2, 3, 1) \
            .reshape(3, L, n_ft * TF, K, W)
        return out[:, :, :F]

    return local


def _make_einsum_fine_hist(L: int, F: int, W: int, K: int, nbins: int,
                           n_local: int):
    """Portable fine-refinement path (CPU mesh tests)."""
    blk = max((2 * 1024 * 1024) // max(F * K * W, 1), 256)
    blk = min(n_local, blk)
    nblk = (n_local + blk - 1) // blk
    pad_to = nblk * blk

    def local(codes, leaf, g, h, w, sel):
        def padr(x, fill=0):
            return jnp.pad(x, [(0, 0)] * (x.ndim - 1)
                           + [(0, pad_to - n_local)], constant_values=fill)
        codes = padr(codes).reshape(F, nblk, blk).transpose(1, 0, 2)
        leaf = padr(leaf).reshape(nblk, blk)
        S = jnp.stack([g, h, w], axis=1)
        S = jnp.pad(S, [(0, pad_to - n_local), (0, 0)]).reshape(nblk, blk, 3)
        self_f = sel.astype(jnp.float32)                       # [L, F, K]

        def body(acc, args):
            c, lf, s = args
            Pl = jax.nn.one_hot(lf, L, dtype=jnp.float32)       # [blk, L]
            Sr = jnp.einsum("rl,lfk->rfk", Pl, self_f)          # [blk,F,K]
            cf = jnp.where(c >= nbins, jnp.float32(-1e9),
                           c.astype(jnp.float32))
            rel = cf.T[:, :, None] - W * Sr                     # [blk,F,K]
            OH = (rel[..., None]
                  == jnp.arange(W, dtype=jnp.float32)).astype(jnp.float32)
            PS = jnp.einsum("rl,rs->rsl", Pl, s)                # [blk,3,L]
            acc = acc + jnp.einsum("rsl,rfkt->slfkt", PS, OH)
            return acc, None
        H0 = jnp.zeros((3, L, F, K, W), jnp.float32)
        if hasattr(jax.lax, "pcast"):     # jax<0.5 has no VMA typing
            H0 = jax.lax.pcast(H0, ROW_AXES, to='varying')
        H, _ = jax.lax.scan(body, H0, (codes, leaf, S))
        return H

    return local


@functools.lru_cache(maxsize=None)
def _make_fine_hist_fn(L: int, F: int, W: int, K: int, nbins: int,
                       n_padded: int, force_impl: str = "",
                       precision: str = "bf16", reduce_mode: str = "hier"):
    """Compiled fine-refinement histogram:
    (codes[F,N], leaf, g, h, w, sel[L,F,K]) -> H[3, L, F, K, W] where slot
    (l,f,k,t) sums rows with leaf l whose code == sel[l,f,k]*W + t
    (NA rows, code == nbins, never land in a fine slot).
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    platform = cl.mesh.devices.flat[0].platform
    out_bytes = F * K * W * 3 * L * 4
    if force_impl == "pallas_interpret":
        inner = _make_pallas_fine_hist(L, F, W, K, nbins, n_local,
                                       interpret=True, precision=precision)
    elif force_impl == "einsum" or platform != "tpu" \
            or out_bytes > 12 * 1024 * 1024 or 3 * L > 1024:
        # 3L > 1024: the minimum row block's [R, 3L] A-build intermediates
        # would overflow scoped VMEM (see make_hist_fn)
        inner = _make_einsum_fine_hist(L, F, W, K, nbins, n_local)
    else:
        inner = _make_pallas_fine_hist(L, F, W, K, nbins, n_local,
                                       precision=precision)

    def local_hist(codes, leaf, g, h, w, sel):
        return psum_shards(inner(codes, leaf, g, h, w, sel), reduce_mode)

    specs_in = (P(None, ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                P(ROW_AXIS), P())
    f = shard_map(local_hist, mesh=cl.mesh, in_specs=specs_in, out_specs=P(),
                  check_vma=False)
    return _ledger("hist_fine", jax.jit(f), orig=f)


make_fine_hist_fn = _reduce_mode_dispatch(_make_fine_hist_fn)


def _soft_threshold(G, alpha):
    return jnp.sign(G) * jnp.maximum(jnp.abs(G) - alpha, 0.0)


def _score(G, H, lam, alpha=0.0):
    Gt = _soft_threshold(G, alpha)
    return Gt * Gt / (H + lam)


def newton_value(g, h, reg_lambda: float, reg_alpha: float):
    """Soft-thresholded Newton node value — the ONE formula shared by
    split rejection, bound propagation and leaf fitting (they must stay
    numerically identical for monotone enforcement to be consistent)."""
    num = jnp.sign(g) * jnp.maximum(jnp.abs(g) - reg_alpha, 0.0)
    return -num / (h + reg_lambda + 1e-12)


@functools.partial(jax.jit, static_argnames=("nbins",))
def best_splits(Hist, nbins: int, reg_lambda: float, min_rows: float,
                min_split_improvement: float, feat_mask=None,
                reg_alpha: float = 0.0, gamma: float = 0.0,
                min_child_weight: float = 0.0, mono=None):
    """Best split per leaf from H[3, L, F, B] (B = nbins regular + 1 NA bin).

    Tries NA-left and NA-right (XGBoost's sparsity-aware default direction;
    the reference tracks NA in DHistogram the same way).  Returns per-leaf
    (feat, bin, na_left, gain, valid).  ``feat_mask`` [L, F] (or [F]) disables
    features per leaf (DRF mtries / column sampling).

    ``reg_alpha`` / ``gamma`` / ``min_child_weight`` give the exact XGBoost
    objective: gain = 1/2(scoreL + scoreR - parent) - gamma with L1
    soft-thresholded numerators and a hessian-sum child constraint
    (libxgboost split_evaluator; h2o drives it via
    hex/tree/xgboost/XGBoostModel.java:260-298 tree_method=hist params).
    """
    G, Hs, C = Hist[0], Hist[1], Hist[2]           # [L, F, B]
    g_na, h_na, c_na = G[..., -1], Hs[..., -1], C[..., -1]
    Gr, Hr, Cr = G[..., :-1], Hs[..., :-1], C[..., :-1]
    cumG = jnp.cumsum(Gr, -1)
    cumH = jnp.cumsum(Hr, -1)
    cumC = jnp.cumsum(Cr, -1)
    totG = cumG[..., -1] + g_na                    # [L, F]
    totH = cumH[..., -1] + h_na
    totC = cumC[..., -1] + c_na
    parent = _score(totG, totH, reg_lambda, reg_alpha)   # [L, F]

    # candidate split after bin b (left = bins <= b), b in [0, nbins-2]
    GL, HL, CL = cumG[..., :-1], cumH[..., :-1], cumC[..., :-1]
    GR = totG[..., None] - GL - g_na[..., None]
    HR = totH[..., None] - HL - h_na[..., None]
    CR = totC[..., None] - CL - c_na[..., None]

    def gain_with_na(gl, hl, cl, gr, hr, cr):
        g = 0.5 * (_score(gl, hl, reg_lambda, reg_alpha)
                   + _score(gr, hr, reg_lambda, reg_alpha)
                   - parent[..., None]) - gamma
        ok = (cl >= min_rows) & (cr >= min_rows) & \
            (hl >= min_child_weight) & (hr >= min_child_weight)
        if mono is not None:
            # monotone constraints (XGBoost split_evaluator order test):
            # reject candidates whose child values break the direction
            vl = newton_value(gl, hl, reg_lambda, reg_alpha)
            vr = newton_value(gr, hr, reg_lambda, reg_alpha)
            c = mono[None, :, None]
            ok = ok & ~(((c > 0) & (vl > vr)) | ((c < 0) & (vl < vr)))
        return jnp.where(ok, g, -jnp.inf)

    gain_naL = gain_with_na(GL + g_na[..., None], HL + h_na[..., None],
                            CL + c_na[..., None], GR, HR, CR)
    gain_naR = gain_with_na(GL, HL, CL, GR + g_na[..., None],
                            HR + h_na[..., None], CR + c_na[..., None])
    na_left_better = gain_naL >= gain_naR
    gain = jnp.maximum(gain_naL, gain_naR)         # [L, F, nbins-1]
    if feat_mask is not None:
        m = feat_mask if feat_mask.ndim == 2 else feat_mask[None, :]
        gain = jnp.where(m[..., None], gain, -jnp.inf)

    L, F = parent.shape
    flat = gain.reshape(L, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    feat = (best // (nbins - 1)).astype(jnp.int32)
    bin_ = (best % (nbins - 1)).astype(jnp.int32)
    na_left = jnp.take_along_axis(
        na_left_better.reshape(L, -1), best[:, None], 1)[:, 0]
    valid = jnp.isfinite(best_gain) & \
        (best_gain > min_split_improvement) & (totC >= 2 * min_rows).any(-1)

    # child sufficient statistics at the chosen split (G, H, C per side) —
    # lets the final level derive Newton leaf values with no extra data pass
    def pick(a):
        return jnp.take_along_axis(a.reshape(L, -1), best[:, None], 1)[:, 0]
    gl, hl, cl = pick(GL), pick(HL), pick(CL)
    gr, hr, cr = pick(GR), pick(HR), pick(CR)
    gna, hna, cna = pick(jnp.broadcast_to(g_na[..., None], GL.shape)), \
        pick(jnp.broadcast_to(h_na[..., None], HL.shape)), \
        pick(jnp.broadcast_to(c_na[..., None], CL.shape))
    gl = jnp.where(na_left, gl + gna, gl)
    hl = jnp.where(na_left, hl + hna, hl)
    cl = jnp.where(na_left, cl + cna, cl)
    gr = jnp.where(na_left, gr, gr + gna)
    hr = jnp.where(na_left, hr, hr + hna)
    cr = jnp.where(na_left, cr, cr + cna)
    # terminal (invalid) nodes: everything routes to the left child
    ftot = jnp.take_along_axis(totG, feat[:, None], 1)[:, 0]
    htot = jnp.take_along_axis(totH, feat[:, None], 1)[:, 0]
    ctot = jnp.take_along_axis(totC, feat[:, None], 1)[:, 0]
    gl = jnp.where(valid, gl, ftot)
    hl = jnp.where(valid, hl, htot)
    cl = jnp.where(valid, cl, ctot)
    gr = jnp.where(valid, gr, 0.0)
    hr = jnp.where(valid, hr, 0.0)
    cr = jnp.where(valid, cr, 0.0)
    children = jnp.stack([gl, hl, cl, gr, hr, cr], axis=1)   # [L, 6]
    return feat, bin_, na_left, best_gain, valid, children


# --------------------------------------------------------- fused split search
#
# best_splits above materializes ~15 [L, F, B] intermediates (cumsums, both
# NA-direction gain planes, child stats) through HBM every level — at bench
# shape that read-back is ~5 ms/level (PROFILE.md round 6), comparable to
# the histogram kernel itself below the root.  The fused path replaces it
# with a single-pass Pallas kernel that reads the [3, L, F, B] block ONCE
# into VMEM, computes cumulative G/H/C via an upper-triangular one-hot
# matmul on the MXU, evaluates both NA-direction boundary gains, takes the
# per-(leaf, feature) argmax on-chip, and writes only a compact
# [L*F, 16]-float winner-record block back out.  A tiny XLA epilogue
# (finish_splits) then reduces records over features and reproduces
# best_splits' exact output tuple.  The split search itself cannot live
# inside the histogram kernel's epilogue: gains need the GLOBALLY psum'd
# histogram and the hist kernel is per-shard — the fusion here removes the
# multi-pass XLA materialization, not the (unavoidable) single H block.
#
# Record planes (lane k of the [L*F, 16] block):
#   0 gain   best boundary gain for this (leaf, feature), NA-resolved
#   1 bin    argmax bin (first index on ties — matches best_splits' argmax)
#   2 na_left
#   3-5  GL/HL/CL at the best bin, EXCLUDING the NA bucket
#   6-8  g/h/c of the NA bucket
#   9-11 totG/totH/totC (NA included)
# Lanes 12-15 pad the record row to the lane-tile multiple.
#
# The XLA twin (_split_records_xla) evaluates gains with the same formula
# and jnp.cumsum, making it BIT-identical to best_splits — it is the
# default off-TPU so CPU crosschecks compare exactly.  On chip the kernel's
# matmul cumsum accumulates in a different order than jnp.cumsum (both
# f32-exact per element, ±1 ulp on the sums), so exactly-tied gains are the
# one legitimate divergence source — same caveat as hist_mode="check".

_REC_PLANES = 12


def _per_leaf(x, extra_dims: int):
    """Broadcast a per-leaf ``[L]`` parameter against ``extra_dims``
    trailing axes; scalars pass through untouched, so the scalar path
    stays trace-identical to the pre-batched code."""
    return x.reshape(x.shape + (1,) * extra_dims) \
        if getattr(x, "ndim", 0) else x


def _split_records_xla(Hist, reg_lambda, min_rows, reg_alpha, gamma,
                       min_child_weight):
    """Per-(leaf, feature) winner records [L, F, 12] — XLA path, bit-
    identical gains to best_splits (same op sequence, jnp.cumsum).

    Regularization/constraint params accept scalars or per-leaf ``[L]``
    arrays (the batched grid plane flattens G members into the leaf axis
    with per-member lambda/alpha/gamma/min_rows/min_child_weight)."""
    G, Hs, C = Hist[0], Hist[1], Hist[2]
    g_na, h_na, c_na = G[..., -1], Hs[..., -1], C[..., -1]
    cumG = jnp.cumsum(G[..., :-1], -1)
    cumH = jnp.cumsum(Hs[..., :-1], -1)
    cumC = jnp.cumsum(C[..., :-1], -1)
    totG = cumG[..., -1] + g_na
    totH = cumH[..., -1] + h_na
    totC = cumC[..., -1] + c_na
    lam1, alpha1 = _per_leaf(reg_lambda, 1), _per_leaf(reg_alpha, 1)
    lam2, alpha2 = _per_leaf(reg_lambda, 2), _per_leaf(reg_alpha, 2)
    gamma2 = _per_leaf(gamma, 2)
    rows2, mcw2 = _per_leaf(min_rows, 2), _per_leaf(min_child_weight, 2)
    parent = _score(totG, totH, lam1, alpha1)
    GL, HL, CL = cumG[..., :-1], cumH[..., :-1], cumC[..., :-1]
    GR = totG[..., None] - GL - g_na[..., None]
    HR = totH[..., None] - HL - h_na[..., None]
    CR = totC[..., None] - CL - c_na[..., None]

    def gain_with_na(gl, hl, cl, gr, hr, cr):
        g = 0.5 * (_score(gl, hl, lam2, alpha2)
                   + _score(gr, hr, lam2, alpha2)
                   - parent[..., None]) - gamma2
        ok = (cl >= rows2) & (cr >= rows2) & \
            (hl >= mcw2) & (hr >= mcw2)
        return jnp.where(ok, g, -jnp.inf)

    gain_naL = gain_with_na(GL + g_na[..., None], HL + h_na[..., None],
                            CL + c_na[..., None], GR, HR, CR)
    gain_naR = gain_with_na(GL, HL, CL, GR + g_na[..., None],
                            HR + h_na[..., None], CR + c_na[..., None])
    na_left_better = gain_naL >= gain_naR
    gain = jnp.maximum(gain_naL, gain_naR)         # [L, F, nbins-1]
    bin_ = jnp.argmax(gain, axis=-1)

    def pick(a):
        return jnp.take_along_axis(a, bin_[..., None], -1)[..., 0]

    return jnp.stack(
        [pick(gain), bin_.astype(jnp.float32),
         pick(na_left_better).astype(jnp.float32),
         pick(GL), pick(HL), pick(CL), g_na, h_na, c_na,
         totG, totH, totC], axis=-1)               # [L, F, 12]


def _make_pallas_split_records(LF: int, B: int, interpret: bool = False,
                               per_row: bool = False):
    """Split-records kernel: (G2, H2, C2 [LF, B], scal [1, 8] SMEM) ->
    rec [LF, 16].  One (leaf, feature) pair per sublane row; bins in
    lanes; grid over row blocks.  Rows must arrive padded to the block
    multiple (padding rows emit garbage records the caller slices off).

    ``per_row=True`` swaps the broadcast SMEM scalar block for a
    row-aligned ``[LF, 8]`` VMEM block (lanes 0-4 = lam/alpha/gamma/
    min_rows/mcw per record row) — per-leaf regularization for the
    batched grid plane.  The kernel math broadcasts [RS, 1] columns
    against [RS, B] planes, so the compute body is shared."""
    nbins = B - 1
    Bpad = (B + 127) // 128 * 128
    # ~24 live [RS, Bpad] f32 intermediates on the scoped-VMEM stack
    RS = int(max(8, min(1024, (6_291_456 // (96 * Bpad)) // 8 * 8)))
    nblk = (LF + RS - 1) // RS

    def kernel(g_ref, h_ref, c_ref, sc_ref, out_ref):
        if per_row:
            lam = sc_ref[:, 0:1]                   # [RS, 1] columns
            alpha = sc_ref[:, 1:2]
            gamma = sc_ref[:, 2:3]
            min_rows = sc_ref[:, 3:4]
            mcw = sc_ref[:, 4:5]
        else:
            lam = sc_ref[0, 0]
            alpha = sc_ref[0, 1]
            gamma = sc_ref[0, 2]
            min_rows = sc_ref[0, 3]
            mcw = sc_ref[0, 4]
        Gb, Hb, Cb = g_ref[:], h_ref[:], c_ref[:]
        biota = jax.lax.broadcasted_iota(jnp.int32, (RS, B), 1)

        def lane(x, k):                            # extract lane k -> [RS, 1]
            return jnp.sum(jnp.where(biota == k, x, 0.0), axis=1,
                           keepdims=True)

        gna, hna, cna = lane(Gb, nbins), lane(Hb, nbins), lane(Cb, nbins)
        reg = biota < nbins
        # lane cumsum as an upper-triangular 0/1 matmul; HIGHEST because
        # the default TPU matmul rounds f32 operands to bf16 (the 0/1 side
        # is exact, so full passes recover exact f32 partial sums)
        U = (jax.lax.broadcasted_iota(jnp.int32, (B, B), 0)
             <= jax.lax.broadcasted_iota(jnp.int32, (B, B), 1)) \
            .astype(jnp.float32)

        def cum(x):
            return jax.lax.dot_general(
                jnp.where(reg, x, 0.0), U, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)

        cumG, cumH, cumC = cum(Gb), cum(Hb), cum(Cb)
        totG = lane(cumG, nbins - 1) + gna         # [RS, 1]
        totH = lane(cumH, nbins - 1) + hna
        totC = lane(cumC, nbins - 1) + cna

        def score(Gv, Hv):
            Gt = jnp.sign(Gv) * jnp.maximum(jnp.abs(Gv) - alpha, 0.0)
            return Gt * Gt / (Hv + lam)

        parent = score(totG, totH)
        cand = biota <= nbins - 2                  # split after bin b
        GL, HL, CL = cumG, cumH, cumC
        GR = totG - GL - gna
        HR = totH - HL - hna
        CR = totC - CL - cna

        def gain_dir(gl, hl, cl, gr, hr, cr):
            gn = 0.5 * (score(gl, hl) + score(gr, hr) - parent) - gamma
            ok = (cl >= min_rows) & (cr >= min_rows) & \
                (hl >= mcw) & (hr >= mcw)
            return jnp.where(ok & cand, gn, -jnp.inf)

        gL = gain_dir(GL + gna, HL + hna, CL + cna, GR, HR, CR)
        gR = gain_dir(GL, HL, CL, GR + gna, HR + hna, CR + cna)
        nab = (gL >= gR).astype(jnp.float32)
        gain = jnp.maximum(gL, gR)
        # first-index lane argmax (ties -> lowest bin, like jnp.argmax)
        m = jnp.max(gain, axis=1, keepdims=True)
        idx = jnp.min(jnp.where(gain == m, biota, B), axis=1, keepdims=True)
        sel = biota == idx

        def pick(x):
            return jnp.sum(jnp.where(sel, x, 0.0), axis=1, keepdims=True)

        recs = (pick(gain), idx.astype(jnp.float32), pick(nab),
                pick(GL), pick(HL), pick(CL), gna, hna, cna,
                totG, totH, totC)
        oiota = jax.lax.broadcasted_iota(jnp.int32, (RS, 16), 1)
        out = jnp.zeros((RS, 16), jnp.float32)
        for k, v in enumerate(recs):
            out = jnp.where(oiota == k, v, out)
        out_ref[:] = out

    sc_spec = pl.BlockSpec((RS, 8), lambda i: (i, 0),
                           memory_space=pltpu.VMEM) if per_row else \
        pl.BlockSpec((1, 8), lambda i: (0, 0), memory_space=pltpu.SMEM)
    return pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((RS, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((RS, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((RS, B), lambda i: (i, 0), memory_space=pltpu.VMEM),
            sc_spec,
        ],
        out_specs=pl.BlockSpec((RS, 16), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((nblk * RS, 16), jnp.float32),
        interpret=interpret,
    ), RS


def split_records(Hist, nbins: int, reg_lambda, min_rows, reg_alpha=0.0,
                  gamma=0.0, min_child_weight=0.0, force_impl: str = ""):
    """Per-(leaf, feature) winner records [L, F, 12] from H[3, L, F, B].

    On TPU the Pallas kernel; elsewhere the bit-identical XLA twin.
    ``force_impl``: "xla" | "pallas" | "pallas_interpret" pin the path.
    Regularization params accept scalars or per-leaf ``[L]`` arrays
    (batched grid members flattened into the leaf axis)."""
    cl = cluster()
    platform = cl.mesh.devices.flat[0].platform
    use_kernel = force_impl in ("pallas", "pallas_interpret") or \
        (force_impl == "" and platform == "tpu")
    if not use_kernel:
        return _split_records_xla(Hist, reg_lambda, min_rows, reg_alpha,
                                  gamma, min_child_weight)
    interpret = force_impl == "pallas_interpret" or platform != "tpu"
    _, L, F, B = Hist.shape
    per_leaf = any(getattr(x, "ndim", 0) for x in
                   (reg_lambda, min_rows, reg_alpha, gamma,
                    min_child_weight))
    call, RS = _make_pallas_split_records(L * F, B, interpret=interpret,
                                          per_row=per_leaf)
    pad = (L * F + RS - 1) // RS * RS - L * F
    planes = Hist.reshape(3, L * F, B)
    if pad:
        planes = jnp.pad(planes, [(0, 0), (0, pad), (0, 0)])
    if per_leaf:
        def as_l(x):
            return jnp.broadcast_to(jnp.asarray(x, jnp.float32), (L,))
        cols = jnp.stack([as_l(reg_lambda), as_l(reg_alpha), as_l(gamma),
                          as_l(min_rows), as_l(min_child_weight)],
                         axis=1)                       # [L, 5]
        rows = jnp.repeat(cols, F, axis=0)             # row l*F+f -> leaf l
        if pad:
            rows = jnp.pad(rows, [(0, pad), (0, 0)])
        sc = jnp.zeros((L * F + pad, 8), jnp.float32).at[:, :5].set(rows)
    else:
        sc = jnp.zeros((1, 8), jnp.float32).at[0, :5].set(
            jnp.stack([reg_lambda, reg_alpha, gamma, min_rows,
                       min_child_weight]).astype(jnp.float32))
    # the H block is replicated post-psum; run the kernel replicated too
    # (pallas_call must not meet the GSPMD partitioner un-shard_mapped)
    rec = shard_map(call, mesh=cl.mesh, in_specs=(P(), P(), P(), P()),
                    out_specs=P(), check_vma=False)(
        planes[0], planes[1], planes[2], sc)
    return rec[:L * F, :_REC_PLANES].reshape(L, F, _REC_PLANES)


def finish_splits(rec, min_rows, min_split_improvement, feat_mask=None):
    """Reduce winner records over features into best_splits' exact output
    tuple (feat, bin, na_left, gain, valid, children[L, 6]).  The child
    statistics reproduce best_splits' arithmetic ORDER (GR formed before
    the NA resolution), keeping the XLA fused path bitwise-identical."""
    L, F, _ = rec.shape
    gain = rec[..., 0]
    if feat_mask is not None:
        m = feat_mask if feat_mask.ndim == 2 else feat_mask[None, :]
        gain = jnp.where(m, gain, -jnp.inf)
    feat = jnp.argmax(gain, axis=1).astype(jnp.int32)

    def pick(i):
        return jnp.take_along_axis(rec[..., i], feat[:, None], 1)[:, 0]

    best_gain = jnp.take_along_axis(gain, feat[:, None], 1)[:, 0]
    bin_ = pick(1).astype(jnp.int32)
    na_left = pick(2) > 0.5
    glx, hlx, clx = pick(3), pick(4), pick(5)
    gna, hna, cna = pick(6), pick(7), pick(8)
    ftot, htot, ctot = pick(9), pick(10), pick(11)
    valid = jnp.isfinite(best_gain) & \
        (best_gain > min_split_improvement) & \
        (rec[..., 11] >= _per_leaf(2 * min_rows, 1)).any(-1)
    gr0 = ftot - glx - gna
    hr0 = htot - hlx - hna
    cr0 = ctot - clx - cna
    gl = jnp.where(na_left, glx + gna, glx)
    hl = jnp.where(na_left, hlx + hna, hlx)
    cl = jnp.where(na_left, clx + cna, clx)
    gr = jnp.where(na_left, gr0, gr0 + gna)
    hr = jnp.where(na_left, hr0, hr0 + hna)
    cr = jnp.where(na_left, cr0, cr0 + cna)
    gl = jnp.where(valid, gl, ftot)
    hl = jnp.where(valid, hl, htot)
    cl = jnp.where(valid, cl, ctot)
    gr = jnp.where(valid, gr, 0.0)
    hr = jnp.where(valid, hr, 0.0)
    cr = jnp.where(valid, cr, 0.0)
    children = jnp.stack([gl, hl, cl, gr, hr, cr], axis=1)
    return feat, bin_, na_left, best_gain, valid, children


def _fused_best_splits_impl(Hist, nbins: int, reg_lambda, min_rows,
                            min_split_improvement, feat_mask=None,
                            reg_alpha=0.0, gamma=0.0, min_child_weight=0.0,
                            force_impl: str = ""):
    rec = split_records(Hist, nbins, reg_lambda, min_rows, reg_alpha,
                        gamma, min_child_weight, force_impl=force_impl)
    return finish_splits(rec, min_rows, min_split_improvement, feat_mask)


_FUSED_SPLIT_PROGRAM = None


def _fused_split_program():
    """Lazy compile-ledger registration of the fused split program:
    traced callers (the build loop) inline the plain impl exactly as
    before; eager callers (crosschecks, benches) get the AOT path with
    timed compiles and cost gauges."""
    global _FUSED_SPLIT_PROGRAM
    if _FUSED_SPLIT_PROGRAM is None:
        _FUSED_SPLIT_PROGRAM = _ledger(
            "fused_split",
            jax.jit(_fused_best_splits_impl,
                    static_argnames=("nbins", "force_impl")),
            static_argnums=(1,), static_argnames=("nbins", "force_impl"),
            orig=_fused_best_splits_impl)
    return _FUSED_SPLIT_PROGRAM


def fused_best_splits(Hist, nbins: int, reg_lambda, min_rows,
                      min_split_improvement, feat_mask=None,
                      reg_alpha=0.0, gamma=0.0, min_child_weight=0.0,
                      force_impl: str = ""):
    """Drop-in best_splits replacement via the single-pass records path.

    Same output tuple; no ``mono`` support (callers gate monotone builds
    to the separate path).  Selection equivalence with best_splits' flat
    f-major argmax: per-(l, f) first-max over bins then first-max over
    features picks the same (f, b) — both resolve ties toward the lowest
    flat index.  Call inside jit (traces inline; the records kernel is the
    only launch)."""
    return _fused_split_program()(
        Hist, nbins, reg_lambda, min_rows, min_split_improvement,
        feat_mask, reg_alpha, gamma, min_child_weight,
        force_impl=force_impl)


def fused_best_splits_batched(HistK, nbins: int, reg_lambda, min_rows,
                              min_split_improvement, feat_mask=None,
                              reg_alpha=0.0, gamma=0.0,
                              min_child_weight=0.0, force_impl: str = ""):
    """Batched-K fused split search: H [K, 3, L, F, B] -> per-tree tuples
    with leading K axes.  The K*L leaves flatten into one records-kernel
    launch (one dispatch for all K trees); ``feat_mask`` is [K, L, F] or
    [K, F].  Per-leaf reductions (argmax, valid's any(-1)) are row-local,
    so flattening K into L is exact.  Regularization params accept
    scalars or per-member ``[K]`` arrays (batched grid sweeps); the flat
    row order is K-major (row k*L+l), so ``repeat(x, L)`` aligns member
    k's parameter with its leaves."""
    K, _, L, F, B = HistK.shape
    Hflat = jnp.moveaxis(HistK, 1, 0).reshape(3, K * L, F, B)
    fm = None
    if feat_mask is not None:
        fm = feat_mask if feat_mask.ndim == 3 else \
            jnp.broadcast_to(feat_mask[:, None, :], (K, L, F))
        fm = fm.reshape(K * L, F)

    def perk(x):                                   # [K] -> [K*L] (K-major)
        return jnp.repeat(x, L) if getattr(x, "ndim", 0) else x

    feat, bin_, na_left, gain, valid, children = fused_best_splits(
        Hflat, nbins, perk(reg_lambda), perk(min_rows),
        perk(min_split_improvement), feat_mask=fm,
        reg_alpha=perk(reg_alpha), gamma=perk(gamma),
        min_child_weight=perk(min_child_weight), force_impl=force_impl)
    return (feat.reshape(K, L), bin_.reshape(K, L),
            na_left.reshape(K, L), gain.reshape(K, L),
            valid.reshape(K, L), children.reshape(K, L, 6))


def _coarse_totals(Hc, reg_lambda, reg_alpha):
    """Shared preamble for the hierarchical search: per-(leaf, feature)
    totals (NA included) and the parent score from a coarse histogram."""
    cums = tuple(jnp.cumsum(Hc[i][..., :-1], -1) for i in range(3))
    nas = tuple(Hc[i][..., -1] for i in range(3))
    totG, totH, totC = (c[..., -1] + na for c, na in zip(cums, nas))
    parent = _score(totG, totH, reg_lambda, reg_alpha)
    return cums, nas, (totG, totH, totC), parent


def _gain_with_na(glx, hlx, clx, nas, tots, parent, reg_lambda, reg_alpha,
                  gamma, min_rows, min_child_weight):
    """Split gain at candidate left sums (EXCLUDING the NA bucket), maxed
    over the two NA directions — the one split-evaluation formula shared
    by super-bin selection and the refined search.  Returns (gain, na_left,
    na-resolved left stats)."""
    totG, totH, totC = tots
    gna, hna, cna = (x[..., None] for x in nas)

    def gain_dir(gl, hl, cl):
        gr = totG[..., None] - gl
        hr = totH[..., None] - hl
        cr = totC[..., None] - cl
        gn = 0.5 * (_score(gl, hl, reg_lambda, reg_alpha)
                    + _score(gr, hr, reg_lambda, reg_alpha)
                    - parent[..., None]) - gamma
        ok = (cl >= min_rows) & (cr >= min_rows) & \
            (hl >= min_child_weight) & (hr >= min_child_weight)
        return jnp.where(ok, gn, -jnp.inf)

    gL = gain_dir(glx + gna, hlx + hna, clx + cna)
    gR = gain_dir(glx, hlx, clx)
    na_left = gL >= gR
    gain = jnp.maximum(gL, gR)
    gl = jnp.where(na_left, glx + gna, glx)
    hl = jnp.where(na_left, hlx + hna, hlx)
    cl = jnp.where(na_left, clx + cna, clx)
    return gain, na_left, gl, hl, cl


def select_superbins(Hc, nbins: int, W: int, K: int, reg_lambda, reg_alpha,
                     gamma, min_rows, min_child_weight, feat_mask=None):
    """Pick the K super-bins per (leaf, feature) most likely to hold the
    best split — the first stage of the two-level quantile search.

    ``Hc``: [3, L, F, S+1] coarse histogram (G, H, count; NA last).
    The coarse boundaries give EXACT split gains at W-bin spacing; the best
    split is overwhelmingly adjacent to the best sampled boundary, so
    refinement targets the two super-bins touching each of the top
    ceil(K/2) boundaries.  (Sup-style upper bounds were tried and are
    useless for ranking: with the g/h coupling relaxed, edge super-bins
    with near-empty prefixes dominate every ranking regardless of signal.)
    """
    cums, nas, tots, parent = _coarse_totals(Hc, reg_lambda, reg_alpha)
    S = cums[0].shape[-1]
    # exact gains at the S-1 coarse boundaries (split after super-bin s)
    bgain, _, _, _, _ = _gain_with_na(
        cums[0][..., :-1], cums[1][..., :-1], cums[2][..., :-1],
        nas, tots, parent, reg_lambda, reg_alpha, gamma, min_rows,
        min_child_weight)                                   # [L, F, S-1]
    if feat_mask is not None:
        m = feat_mask if feat_mask.ndim == 2 else feat_mask[None, :]
        bgain = jnp.where(m[..., None], bgain, -jnp.inf)
    nb = max(1, (K + 1) // 2)
    _, top_b = jax.lax.top_k(bgain, nb)                     # [L, F, nb]
    # boundary s touches super-bins s and s+1
    pairs = jnp.stack([top_b, jnp.minimum(top_b + 1, S - 1)], axis=-1)
    sel = pairs.reshape(*top_b.shape[:-1], 2 * nb)[..., :K]
    return sel.astype(jnp.int32), bgain


def best_splits_hier(Hc, Hf, sel, ub, nbins: int, W: int, reg_lambda,
                     min_rows, min_split_improvement, feat_mask=None,
                     reg_alpha: float = 0.0, gamma: float = 0.0,
                     min_child_weight: float = 0.0):
    """Best split per leaf from coarse + refined histograms.

    Candidate splits = every coarse (super-bin) boundary + every fine
    boundary inside the K refined super-bins; gains and child statistics
    at every candidate are exact.  Returns the same tuple as
    ``best_splits`` plus a placeholder (kept for signature stability).
    Differs from the full pass only when the true best split hides in an
    unrefined super-bin away from every top coarse boundary.
    """
    cums, nas, tots, parent = _coarse_totals(Hc, reg_lambda, reg_alpha)
    cumG, cumH, cumC = cums
    totG, totH, totC = tots
    G, Hs, C = (Hc[i][..., :-1] for i in range(3))
    L, F, S = G.shape
    K = sel.shape[-1]
    if feat_mask is not None:
        fmask = feat_mask if feat_mask.ndim == 2 else feat_mask[None, :]
    else:
        fmask = jnp.ones((L, F), bool)

    def eval_cands(glx, hlx, clx, allowed):
        gain, na_left, gl, hl, cl = _gain_with_na(
            glx, hlx, clx, nas, tots, parent, reg_lambda, reg_alpha,
            gamma, min_rows, min_child_weight)
        gain = jnp.where(allowed & fmask[..., None], gain, -jnp.inf)
        return gain, na_left, gl, hl, cl

    # (a) coarse boundaries: split after super-bin s, s in 0..S-2
    bins_a = (jnp.arange(S - 1, dtype=jnp.int32) + 1) * W - 1
    allowed_a = (bins_a <= nbins - 2)[None, None, :]
    res_a = eval_cands(cumG[..., :-1], cumH[..., :-1], cumC[..., :-1],
                       allowed_a)
    bins_a_full = jnp.broadcast_to(bins_a, (L, F, S - 1))
    feat_a = jnp.broadcast_to(
        jnp.arange(F, dtype=jnp.int32)[None, :, None], (L, F, S - 1))

    # (b) fine boundaries inside refined super-bins
    Gpre_s = jnp.take_along_axis(cumG - G, sel, axis=-1)      # [L, F, K]
    Hpre_s = jnp.take_along_axis(cumH - Hs, sel, axis=-1)
    Cpre_s = jnp.take_along_axis(cumC - C, sel, axis=-1)
    cumGf = jnp.cumsum(Hf[0], -1)                             # [L, F, K, W]
    cumHf = jnp.cumsum(Hf[1], -1)
    cumCf = jnp.cumsum(Hf[2], -1)
    bins_f = sel[..., None] * W + jnp.arange(W, dtype=jnp.int32)
    allowed_f = bins_f <= nbins - 2
    res_f = eval_cands(
        (Gpre_s[..., None] + cumGf).reshape(L, F, K * W),
        (Hpre_s[..., None] + cumHf).reshape(L, F, K * W),
        (Cpre_s[..., None] + cumCf).reshape(L, F, K * W),
        allowed_f.reshape(L, F, K * W))
    bins_f_full = bins_f.reshape(L, F, K * W)
    feat_f = jnp.broadcast_to(
        jnp.arange(F, dtype=jnp.int32)[None, :, None], (L, F, K * W))

    def flat(a_part, f_part):
        return jnp.concatenate(
            [a_part.reshape(L, -1), f_part.reshape(L, -1)], axis=1)

    gain_all = flat(res_a[0], res_f[0])
    best = jnp.argmax(gain_all, axis=1)

    def pick(a_part, f_part):
        return jnp.take_along_axis(flat(a_part, f_part),
                                   best[:, None], 1)[:, 0]

    best_gain = jnp.take_along_axis(gain_all, best[:, None], 1)[:, 0]
    feat = pick(feat_a, feat_f)
    bin_ = pick(bins_a_full, bins_f_full)
    na_left = pick(res_a[1], res_f[1])
    gl = pick(res_a[2], res_f[2])
    hl = pick(res_a[3], res_f[3])
    cl = pick(res_a[4], res_f[4])

    ftot = jnp.take_along_axis(totG, feat[:, None], 1)[:, 0]
    htot = jnp.take_along_axis(totH, feat[:, None], 1)[:, 0]
    ctot = jnp.take_along_axis(totC, feat[:, None], 1)[:, 0]
    valid = jnp.isfinite(best_gain) & \
        (best_gain > min_split_improvement) & (totC >= 2 * min_rows).any(-1)
    gr, hr, cr = ftot - gl, htot - hl, ctot - cl
    gl = jnp.where(valid, gl, ftot)
    hl = jnp.where(valid, hl, htot)
    cl = jnp.where(valid, cl, ctot)
    gr = jnp.where(valid, gr, 0.0)
    hr = jnp.where(valid, hr, 0.0)
    cr = jnp.where(valid, cr, 0.0)
    children = jnp.stack([gl, hl, cl, gr, hr, cr], axis=1)

    return (feat.astype(jnp.int32), bin_.astype(jnp.int32), na_left,
            best_gain, valid, children, jnp.array(False))


def table_lookup(tables, idx, L: int):
    """Row-wise lookup t[:, idx] for a small table t [K, L] via one-hot
    matmul.

    XLA lowers ``t[idx]`` on TPU to a per-row dynamic gather that runs at
    ~40M rows/sec (measured: 240 ms for 4 lookups over 10M rows) — the MXU
    does the same lookup as a [K, L] x [L, N] product at memory speed.  The
    one-hot is built [L, N] (minor dim = rows) so nothing lane-pads; f32
    keeps the lookup exact for arbitrary float tables.
    """
    oh = (jax.lax.broadcasted_iota(jnp.int32, (L, 1), 0)
          == idx[None, :]).astype(jnp.float32)
    # HIGHEST: the default TPU matmul rounds f32 operands to bf16, which
    # would corrupt thresholds/leaf values; the one-hot side is exact 0/1,
    # so full-precision passes recover the exact f32 table entries
    return jnp.dot(tables.astype(jnp.float32), oh,
                   preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)


@jax.jit
def partition_ranged(codes, leaf, feat, lo, hi, inv, na_left, valid,
                     na_bin: jnp.int32):
    """``partition`` with a bin RANGE right-child condition:
    right = inv XOR (lo < code <= hi).  EFB bundle splits are member
    sub-ranges of the bundled bin axis (efb.py); ``inv`` flips the rule
    when the member's default mass sits on the right of the cut (then the
    LEFT child is the contiguous range).  A plain prefix split is lo=bin,
    hi=+inf, inv=False."""
    L = feat.shape[0]
    tables = jnp.stack([feat.astype(jnp.float32), lo.astype(jnp.float32),
                        hi.astype(jnp.float32), inv.astype(jnp.float32),
                        na_left.astype(jnp.float32),
                        valid.astype(jnp.float32)], axis=0)      # [6, L]
    t = table_lookup(tables, leaf, L)                            # [6, N]
    f = t[0].astype(jnp.int32)
    blo = t[1].astype(jnp.int32)
    bhi = t[2].astype(jnp.int32)
    iv = t[3] > 0.5
    nl = t[4] > 0.5
    v = t[5] > 0.5
    Fdim = codes.shape[0]
    fiota = jax.lax.broadcasted_iota(jnp.int32, (Fdim, 1), 0)
    c = jnp.sum(jnp.where(f[None, :] == fiota, codes, 0), axis=0)
    is_na = c == na_bin
    right = jnp.where(is_na, ~nl, iv ^ ((c > blo) & (c <= bhi)))
    right = right & v
    return (2 * leaf + right.astype(jnp.int32)).astype(jnp.int32)


@jax.jit
def partition(codes, leaf, feat, bin_, na_left, valid, na_bin: jnp.int32):
    """Send rows to child leaves: new_leaf = 2*leaf + went_right.

    ``codes`` is feature-major [F, N]; the per-row chosen-feature value is a
    select-chain over the (small) feature dim — a cross-sublane dynamic
    gather here would make XLA materialize a row-major transpose, whose
    lane padding costs 16x the array's HBM footprint.  The per-leaf split
    parameters are fetched via one MXU one-hot product (table_lookup), not
    gathers.  Terminal (invalid-split) leaves route everything left so
    descendants stay consistent; the leaf-value gather resolves them.
    """
    L = feat.shape[0]
    tables = jnp.stack([feat.astype(jnp.float32), bin_.astype(jnp.float32),
                        na_left.astype(jnp.float32),
                        valid.astype(jnp.float32)], axis=0)      # [4, L]
    t = table_lookup(tables, leaf, L)                            # [4, N]
    f = t[0].astype(jnp.int32)
    b = t[1].astype(jnp.int32)
    nl = t[2] > 0.5
    v = t[3] > 0.5
    Fdim = codes.shape[0]
    fiota = jax.lax.broadcasted_iota(jnp.int32, (Fdim, 1), 0)
    c = jnp.sum(jnp.where(f[None, :] == fiota, codes, 0), axis=0)
    is_na = c == na_bin
    right = jnp.where(is_na, ~nl, c > b)
    right = right & v
    return (2 * leaf + right.astype(jnp.int32)).astype(jnp.int32)


@jax.jit
def partition_right(codes, leaf, feat, bin_, na_left, valid,
                    na_bin: jnp.int32):
    """The ``partition`` routing decision alone — the went-right bit per
    row, without the dense ``2*leaf + right`` relabeling.  The node-sparse
    deep levels route rows through A+1-entry SLOT tables (instead of the
    2^d dense tables, whose one-hot product would reintroduce the dense
    per-row cost), then apply the bit to both the dense leaf id and the
    slot id; the sentinel slot's table row is valid=False so dead rows
    keep flowing left, matching dense terminality."""
    L = feat.shape[0]
    tables = jnp.stack([feat.astype(jnp.float32), bin_.astype(jnp.float32),
                        na_left.astype(jnp.float32),
                        valid.astype(jnp.float32)], axis=0)      # [4, L]
    t = table_lookup(tables, leaf, L)                            # [4, N]
    f = t[0].astype(jnp.int32)
    b = t[1].astype(jnp.int32)
    nl = t[2] > 0.5
    v = t[3] > 0.5
    Fdim = codes.shape[0]
    fiota = jax.lax.broadcasted_iota(jnp.int32, (Fdim, 1), 0)
    c = jnp.sum(jnp.where(f[None, :] == fiota, codes, 0), axis=0)
    is_na = c == na_bin
    right = jnp.where(is_na, ~nl, c > b)
    return (right & v).astype(jnp.int32)


