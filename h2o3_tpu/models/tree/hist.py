"""tpu_hist: the histogram / split-search / partition kernels for tree algos.

Reference hot loop: ``hex/tree/DHistogram.java:48,67-95`` (per-(leaf, column,
bin) accumulate of w/wY/wYY into one double[]), driven by
``ScoreBuildHistogram2.java:62,119-235`` (two node-local passes: score rows ->
leaf assignment, then histogram build parallel over columns x row-ranges),
reduced across the cluster by elementwise array add (MRTask tree-reduce).
The XGBoost extension's CUDA ``gpu_hist`` is the performance target
(BASELINE.json: "gpu_hist via xgboost4j-gpu -> Pallas/XLA tpu_hist").

TPU-native redesign: scatter-adds are serialized on a vector machine, so the
histogram becomes DENSE MATMULS on the MXU: one-hot(leaf) x (g,h,w) planes
contracted with one-hot(bin codes) via einsum, blocked over rows to bound
memory, shard_mapped over the mesh "rows" axis with a single ``psum`` as the
cross-device reduce (replacing both the LocalMR pass and the MRTask tree).
Split search and row partition are fused elementwise/gather passes.  All
shapes static per tree level; one compile per (depth, F, B) geometry.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from jax import shard_map

from ...runtime.cluster import cluster, ROW_AXIS

# target float32 elements for the one-hot block buffer (memory knob)
_BLOCK_BUDGET = 32 * 1024 * 1024


def _block_rows(n_local: int, F: int, B: int) -> int:
    blk = max(_BLOCK_BUDGET // max(F * B, 1), 256)
    return int(min(n_local, blk))


@functools.lru_cache(maxsize=None)
def make_hist_fn(L: int, F: int, B: int, n_padded: int):
    """Compiled histogram: (codes[N,F], leaf[N], g[N], h[N], w[N]) ->
    H[3, L, F, B] with planes (sum g, sum h, sum w), psum'd over the mesh.

    ``B`` here includes the NA bin (= nbins + 1).
    """
    cl = cluster()
    n_local = n_padded // cl.n_row_shards
    blk = _block_rows(n_local, F, B)
    nblk = (n_local + blk - 1) // blk
    pad_to = nblk * blk

    def local_hist(codes, leaf, g, h, w):
        # pad local shard to a whole number of blocks (w=0 rows contribute 0)
        def padr(x, fill=0):
            return jnp.pad(x, [(0, pad_to - n_local)] + [(0, 0)] * (x.ndim - 1),
                           constant_values=fill)
        codes = padr(codes).reshape(nblk, blk, F)
        leaf = padr(leaf).reshape(nblk, blk)
        S = jnp.stack([g, h, w], axis=1)          # [n, 3]
        S = padr(S).reshape(nblk, blk, 3)

        def body(acc, args):
            c, lf, s = args
            Pl = jax.nn.one_hot(lf, L, dtype=jnp.float32)       # [blk, L]
            OH = jax.nn.one_hot(c, B, dtype=jnp.float32)        # [blk, F, B]
            # [blk,L]x[blk,3] -> contract rows with [blk,F,B]
            PS = jnp.einsum("rl,rs->rsl", Pl, s)                # [blk,3,L]
            acc = acc + jnp.einsum("rsl,rfb->slfb", PS, OH)
            return acc, None
        H0 = jnp.zeros((3, L, F, B), jnp.float32)
        # carry becomes device-varying inside shard_map; mark it so upfront
        H0 = jax.lax.pcast(H0, (ROW_AXIS,), to='varying')
        H, _ = jax.lax.scan(body, H0, (codes, leaf, S))
        return jax.lax.psum(H, ROW_AXIS)

    specs_in = (P(ROW_AXIS, None), P(ROW_AXIS), P(ROW_AXIS), P(ROW_AXIS),
                P(ROW_AXIS))
    f = shard_map(local_hist, mesh=cl.mesh, in_specs=specs_in, out_specs=P())
    return jax.jit(f)


def _score(G, H, lam):
    return G * G / (H + lam)


@functools.partial(jax.jit, static_argnames=("nbins",))
def best_splits(Hist, nbins: int, reg_lambda: float, min_rows: float,
                min_split_improvement: float, feat_mask=None):
    """Best split per leaf from H[3, L, F, B] (B = nbins regular + 1 NA bin).

    Tries NA-left and NA-right (XGBoost's sparsity-aware default direction;
    the reference tracks NA in DHistogram the same way).  Returns per-leaf
    (feat, bin, na_left, gain, valid).  ``feat_mask`` [L, F] (or [F]) disables
    features per leaf (DRF mtries / column sampling).
    """
    G, Hs, C = Hist[0], Hist[1], Hist[2]           # [L, F, B]
    g_na, h_na, c_na = G[..., -1], Hs[..., -1], C[..., -1]
    Gr, Hr, Cr = G[..., :-1], Hs[..., :-1], C[..., :-1]
    cumG = jnp.cumsum(Gr, -1)
    cumH = jnp.cumsum(Hr, -1)
    cumC = jnp.cumsum(Cr, -1)
    totG = cumG[..., -1] + g_na                    # [L, F]
    totH = cumH[..., -1] + h_na
    totC = cumC[..., -1] + c_na
    parent = _score(totG, totH, reg_lambda)        # [L, F]

    # candidate split after bin b (left = bins <= b), b in [0, nbins-2]
    GL, HL, CL = cumG[..., :-1], cumH[..., :-1], cumC[..., :-1]
    GR = totG[..., None] - GL - g_na[..., None]
    HR = totH[..., None] - HL - h_na[..., None]
    CR = totC[..., None] - CL - c_na[..., None]

    def gain_with_na(gl, hl, cl, gr, hr, cr):
        g = 0.5 * (_score(gl, hl, reg_lambda) + _score(gr, hr, reg_lambda)
                   - parent[..., None])
        ok = (cl >= min_rows) & (cr >= min_rows)
        return jnp.where(ok, g, -jnp.inf)

    gain_naL = gain_with_na(GL + g_na[..., None], HL + h_na[..., None],
                            CL + c_na[..., None], GR, HR, CR)
    gain_naR = gain_with_na(GL, HL, CL, GR + g_na[..., None],
                            HR + h_na[..., None], CR + c_na[..., None])
    na_left_better = gain_naL >= gain_naR
    gain = jnp.maximum(gain_naL, gain_naR)         # [L, F, nbins-1]
    if feat_mask is not None:
        m = feat_mask if feat_mask.ndim == 2 else feat_mask[None, :]
        gain = jnp.where(m[..., None], gain, -jnp.inf)

    L, F = parent.shape
    flat = gain.reshape(L, -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    feat = (best // (nbins - 1)).astype(jnp.int32)
    bin_ = (best % (nbins - 1)).astype(jnp.int32)
    na_left = jnp.take_along_axis(
        na_left_better.reshape(L, -1), best[:, None], 1)[:, 0]
    valid = jnp.isfinite(best_gain) & \
        (best_gain > min_split_improvement) & (totC >= 2 * min_rows).any(-1)
    return feat, bin_, na_left, best_gain, valid


@jax.jit
def partition(codes, leaf, feat, bin_, na_left, valid, na_bin: jnp.int32):
    """Send rows to child leaves: new_leaf = 2*leaf + went_right.

    Terminal (invalid-split) leaves route everything left so descendants stay
    consistent; the final leaf-value gather resolves them.
    """
    f = feat[leaf]                                     # [N] gather
    c = jnp.take_along_axis(codes, f[:, None], axis=1)[:, 0]
    is_na = c == na_bin
    right = jnp.where(is_na, ~na_left[leaf], c > bin_[leaf])
    right = right & valid[leaf]
    return (2 * leaf + right.astype(jnp.int32)).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("L",))
def leaf_values_from_hist(Hist, L: int, reg_lambda: float, learn_rate: float,
                          max_abs: float = 1e10):
    """Newton leaf values -G/(H+lambda) x learn_rate (fitBestConstants)."""
    G = Hist[0].sum(axis=(1, 2)) if Hist[0].ndim == 3 else Hist[0]
    H = Hist[1].sum(axis=(1, 2)) if Hist[1].ndim == 3 else Hist[1]
    v = -G / (H + reg_lambda + 1e-12) * learn_rate
    return jnp.clip(v, -max_abs, max_abs)


@functools.lru_cache(maxsize=None)
def make_leaf_agg_fn(L: int, n_padded: int):
    """Compiled (leaf, g, h, w) -> [3, L] sums over the mesh (final-level
    aggregation for leaf values, no per-feature breakdown needed)."""
    cl = cluster()

    def local(leaf, g, h, w):
        Pl = jax.nn.one_hot(leaf, L, dtype=jnp.float32)
        out = jnp.stack([g @ Pl, h @ Pl, w @ Pl])
        return jax.lax.psum(out, ROW_AXIS)

    f = shard_map(local, mesh=cl.mesh,
                  in_specs=(P(ROW_AXIS),) * 4, out_specs=P())
    return jax.jit(f)
