"""DRF: distributed random forest on the tpu_hist kernels.

Reference: ``hex/tree/drf/DRF.java:30`` — the bootstrap+mtries variant of
SharedTree: each tree trains on a row sample (rate 1-1/e by default) with
per-split random feature subsets (mtries); predictions are the average of
per-tree leaf estimates (class probability / mean response).

TPU-native redesign: the "mean response per leaf" fit is expressed through
the same Newton machinery as GBM by setting grad=-y, hess=1 (leaf value
= sum(w*y)/sum(w)); mtries is a per-(leaf, feature) random mask pushed into
the split-search kernel; trees average instead of sum (init 0, divide by T).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...frame.frame import Frame
from ...runtime import dkv
from ...runtime.job import Job
from ..datainfo import DataInfo
from ..scorekeeper import stop_early, metric_direction
from .binning import fit_bins, edges_matrix
from .shared import (SharedTree, SharedTreeModel, SharedTreeParameters,
                     StackedTrees, TreeList, chunk_schedule, dense_mem_cap,
                     make_multinomial_scan_fn, make_tree_scan_fn,
                     run_hist_crosscheck,
                     run_layout_crosscheck, run_program_crosscheck,
                     run_split_crosscheck,
                     traverse_jit, use_hier_split_search)
from ...metrics.core import make_metrics


@dataclasses.dataclass
class DRFParameters(SharedTreeParameters):
    ntrees: int = 50
    max_depth: int = 20
    min_rows: float = 1.0
    sample_rate: float = 0.632           # DRF.java default (1 - 1/e)
    mtries: int = -1                     # -1: sqrt(F) cls / F/3 reg
    learn_rate: float = 1.0              # no shrinkage in a forest


class DRFModel(SharedTreeModel):
    algo = "drf"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        K = self.output.get("nclass_trees", 1)
        T = self.output["ntrees_trained"]
        F = self._raw_scores(X) / max(T, 1)
        if self.datainfo.is_classifier and K > 1:
            probs = jnp.clip(F, 0.0, 1.0)
            s = jnp.sum(probs, axis=1, keepdims=True)
            return probs / jnp.maximum(s, 1e-12)
        if self.datainfo.is_classifier:
            p1 = jnp.clip(F, 0.0, 1.0)
            return jnp.stack([1 - p1, p1], axis=1)
        return F


class DRF(SharedTree):
    algo = "drf"
    model_class = DRFModel
    # stays on the wave path: the forest driver's mtries/OOB bookkeeping
    # and per-class bootstrap sharing diverge from the fused GBM chunk
    # loop the batched cohort trainer mirrors
    _grid_batchable = False

    def __init__(self, params: Optional[DRFParameters] = None, **kw):
        super().__init__(params or DRFParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> DRFModel:
        p: DRFParameters = self.params
        K = di.nclasses if (di.is_classifier and di.nclasses > 2) else 1
        y = di.response(frame)
        w = di.weights(frame)
        from .shared import (resolve_checkpoint, checkpoint_binned,
                             prior_stacked)
        prior = resolve_checkpoint(p, di, self.algo)
        if prior is not None:
            binned = checkpoint_binned(frame, di, prior, p.nbins)
        else:
            binned = fit_bins(frame, [s.name for s in di.specs],
                              nbins=p.nbins, seed=p.effective_seed(),
                              weights=w if p.weights_column else None,
                              histogram_type=p.histogram_type)
        codes = binned.codes
        edges_mat = jnp.asarray(
            edges_matrix(binned.edges, p.nbins), jnp.float32)
        Fnum = binned.nfeatures
        y = jnp.where(jnp.isnan(y), 0.0, y)
        N = codes.shape[1]
        from .shared import maybe_bundle
        plan, wcodes, Fw, wbin_counts = maybe_bundle(binned, p, None,
                                                     frame.nrows)
        # resolve the kernel-strategy knobs ONCE, up front — the layout
        # changes the effective-depth cap, so checkpoint validation and
        # the recorded depth must see the resolved layout (see gbm.py);
        # "auto" knobs route through the cost-model autotuner
        from ...runtime import autotune
        knobs = autotune.resolve_tree_knobs(
            p, kind=self.algo, F=Fw, N=N, K=K,
            plan=plan, hier=use_hier_split_search(p, N),
            checkpoint=prior is not None)
        autotune.activate(knobs)
        hist_mode, split_mode, hist_layout = (
            knobs.hist_mode, knobs.split_mode, knobs.hist_layout)
        tree_program = knobs.tree_program
        if knobs.sparse_depth_threshold != p.sparse_depth_threshold:
            p = dataclasses.replace(
                p, sparse_depth_threshold=knobs.sparse_depth_threshold)
        if prior is not None:
            from .shared import validate_checkpoint_depth
            validate_checkpoint_depth(prior, 0 if K > 1 else None,
                                      p, Fw, N, hist_layout=hist_layout)
        rng = jax.random.PRNGKey(p.effective_seed())

        # mtries resolves against the WORKING feature count: the per-split
        # mask is drawn over working features, so a rate computed from the
        # original count would collapse to ~1 feature/split under bundling
        if p.mtries == -1:
            m = math.isqrt(Fw) if di.is_classifier else max(Fw // 3, 1)
            col_rate = max(min(m, Fw), 1) / Fw
        elif p.mtries == -2:
            col_rate = 1.0
        else:
            col_rate = max(min(p.mtries, Fw), 1) / Fw

        model = DRFModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["nclass_trees"] = K
        from .shared import record_effective_depth
        eff_depth = record_effective_depth(model, p, Fw, N,
                                           hist_layout=hist_layout)
        # deep_level chaos hook fires only when sparse levels actually run
        sparse_deep = (hist_layout in ("sparse", "check") and eff_depth
                       > max(1, min(p.sparse_depth_threshold,
                                    dense_mem_cap(p.nbins, Fw))))

        if K > 1:
            yi = jnp.clip(y.astype(jnp.int32), 0, K - 1)
            Y1 = jax.nn.one_hot(yi, K, dtype=jnp.float32)
            targets = [Y1[:, k] for k in range(K)]
        elif di.is_classifier:
            targets = [y]
        else:
            targets = [y]

        F_sum = jnp.zeros((N, K), jnp.float32) if K > 1 \
            else jnp.zeros((N,), jnp.float32)
        # commit to the chunk-output sharding — see gbm.py (avoids a second
        # jit executable keyed on uncommitted-vs-committed F)
        from jax.sharding import NamedSharding, PartitionSpec
        from ...runtime.cluster import cluster
        F_sum = jax.device_put(F_sum,
                               NamedSharding(cluster().mesh, PartitionSpec()))
        if valid is not None:
            Xv = model._design(valid)
            y_v, w_v = di.response(valid), di.weights(valid)
            F_v = jnp.zeros((Xv.shape[0], K), jnp.float32) if K > 1 \
                else jnp.zeros((Xv.shape[0],), jnp.float32)
        prior_nt = 0
        if prior is not None:
            prior_nt = prior.output["ntrees_trained"]
            # decorrelate the continuation's bootstrap keys from the prior
            # run (same-seed continuation must not regrow identical trees)
            rng = jax.random.fold_in(rng, prior_nt)
            X_ck = model._design(frame)
            for k in range(K):
                st = prior_stacked(prior, k if K > 1 else None)
                dF = traverse_jit(st.levels, st.values, X_ck)
                F_sum = F_sum.at[:, k].add(dF) if K > 1 else F_sum + dF
                if valid is not None:
                    dFv = traverse_jit(st.levels, st.values, Xv)
                    F_v = F_v.at[:, k].add(dFv) if K > 1 else F_v + dFv

        history = []
        metric_name, maximize = metric_direction(p.stopping_metric,
                                                 di.is_classifier)
        # mean-fit via the scan driver: grad = -y, hess = 1 -> leaf = mean(y);
        # a whole scoring interval of trees is one device dispatch.  The same
        # per-tree keys are reused across classes so every class sees the
        # same bootstrap sample per iteration (DRF.java samples once/tree).
        if hist_mode == "check":
            # driver assert: the forest's mean-fit gradients (g=-y, h=1)
            # through both histogram paths must grow the same tree
            run_hist_crosscheck(
                wcodes, -targets[0] * w, w, w, edges_mat, rng,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                bin_counts=wbin_counts, plan=plan,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=1.0, reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            hist_mode = "subtract"
        # split_mode="check" — fused (batched-K for multiclass) vs the
        # sequential best_splits oracle on the real mean-fit gradients
        if split_mode == "check":
            gK = jnp.stack([-t * w for t in targets])
            hK = jnp.broadcast_to(w, gK.shape)
            kchk = jnp.stack([jax.random.fold_in(rng, k)
                              for k in range(K)]) if K > 1 else rng
            run_split_crosscheck(
                wcodes, gK if K > 1 else gK[0],
                hK if K > 1 else hK[0], w, edges_mat, kchk,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                bin_counts=wbin_counts, hist_mode=hist_mode,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=1.0, col_sample_rate=col_rate,
                reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            split_mode = "fused"
        # hist_layout="check" — dense vs node-sparse deep levels on the
        # real mean-fit gradients, then training rides the sparse path
        if hist_layout == "check":
            gK = jnp.stack([-t * w for t in targets])
            hK = jnp.broadcast_to(w, gK.shape)
            kchk = jnp.stack([jax.random.fold_in(rng, k)
                              for k in range(K)]) if K > 1 else rng
            run_layout_crosscheck(
                wcodes, gK if K > 1 else gK[0],
                hK if K > 1 else hK[0], w, edges_mat, kchk,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                bin_counts=wbin_counts,
                sparse_depth_threshold=p.sparse_depth_threshold,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=1.0, col_sample_rate=col_rate,
                reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            hist_layout = "sparse"
            model.output["hist_layout"] = hist_layout
        # tree_program="check" — the whole-tree scan program vs the
        # per-level dispatch loop on the real mean-fit gradients, then
        # training rides the scan-fused path (resolve_tree_program
        # already downgraded "check" where the scan cannot grow)
        if tree_program == "check":
            gK = jnp.stack([-t * w for t in targets])
            hK = jnp.broadcast_to(w, gK.shape)
            kchk = jnp.stack([jax.random.fold_in(rng, k)
                              for k in range(K)]) if K > 1 else rng
            run_program_crosscheck(
                wcodes, gK if K > 1 else gK[0],
                hK if K > 1 else hK[0], w, edges_mat, kchk,
                max_depth=p.max_depth, nbins=p.nbins, F=Fw, n_padded=N,
                hist_precision=p.effective_hist_precision,
                hist_mode=hist_mode, split_mode=split_mode,
                reg_lambda=p.reg_lambda, min_rows=p.min_rows,
                min_split_improvement=p.min_split_improvement,
                learn_rate=1.0, col_sample_rate=col_rate,
                reg_alpha=p.reg_alpha, gamma=p.gamma,
                min_child_weight=p.min_child_weight)
            tree_program = "scan"
        model.output["tree_program"] = tree_program
        # batched multiclass: one K-tree build per round (one hist + one
        # split launch per level for all K class trees) instead of K
        # sequential scans — identical keys (same fold_in structure), so
        # the sequential path below stays its oracle
        batched = split_mode == "fused" and K > 1
        if batched:
            scan_fn_k = make_multinomial_scan_fn(
                K, p.max_depth, p.nbins, Fw, N,
                p.effective_hist_precision, p.sample_rate, 1.0,
                bin_counts=wbin_counts, hist_mode=hist_mode,
                split_mode="fused", mode="drf", hist_layout=hist_layout,
                sparse_depth_threshold=p.sparse_depth_threshold,
                tree_program=tree_program)
        else:
            scan_fn = make_tree_scan_fn(
                "drf", 0.0, 0.0, 0.0, p.max_depth, p.nbins, Fw, N,
                p.effective_hist_precision, p.sample_rate, 1.0,
                hier=use_hier_split_search(p, N),
                bin_counts=wbin_counts, plan=plan, hist_mode=hist_mode,
                split_mode=split_mode, hist_layout=hist_layout,
                sparse_depth_threshold=p.sparse_depth_threshold,
                tree_program=tree_program)
        scalars = (p.reg_lambda, p.min_rows, p.min_split_improvement, 1.0,
                   col_rate, p.reg_alpha, p.gamma, p.min_child_weight)
        chunks = [[] for _ in range(K)]
        if prior is not None:
            for k in range(K):
                chunks[k].append(prior_stacked(prior, k if K > 1 else None))
        from ...runtime import failure
        for chunk_no, (c, t_new, score_now) in enumerate(chunk_schedule(
                p.ntrees - prior_nt, p.score_tree_interval,
                fence=getattr(self, "_stream_fence", None))):
            t_done = prior_nt + t_new
            if sparse_deep:
                # kill/resume while node-sparse deep levels are live
                failure.maybe_inject("deep_level")
            if batched:
                # chaos matrix: kill/resume mid-K-tree-round on the
                # batched path
                failure.maybe_inject("ktree_round")
                F_sum, lv, vals, cov = scan_fn_k(wcodes, Y1, w, F_sum,
                                                 edges_mat, rng, chunk_no,
                                                 c, *scalars)
                for k in range(K):
                    lv_k = [tuple(lvd[i][:, k] for i in range(4))
                            for lvd in lv]
                    chunk = StackedTrees(lv_k, vals[:, k], cov[:, k])
                    chunks[k].append(chunk)
                    if valid is not None:
                        F_v = F_v.at[:, k].add(
                            traverse_jit(chunk.levels, chunk.values, Xv))
            else:
                for k in range(K):
                    Fk0 = F_sum[:, k] if K > 1 else F_sum
                    # same (rng, chunk_no) across classes -> same bootstrap
                    # per iteration (DRF.java samples once per tree); the
                    # salt decorrelates each class tree's per-split feature
                    # subsets
                    Fk, lv, vals, cov = scan_fn(wcodes, targets[k], w, Fk0,
                                                edges_mat, rng, chunk_no, c,
                                                *scalars, k)
                    chunks[k].append(StackedTrees(lv, vals, cov))
                    if K > 1:
                        F_sum = F_sum.at[:, k].set(Fk)
                        if valid is not None:
                            F_v = F_v.at[:, k].add(
                                traverse_jit(lv, vals, Xv))
                    else:
                        F_sum = Fk
                        if valid is not None:
                            F_v = F_v + traverse_jit(lv, vals, Xv)
            job.update(t_done / p.ntrees, f"tree {t_done}/{p.ntrees}")
            from ...runtime import snapshot
            from .shared import (tree_snapshot_state,
                                 tree_snapshot_state_multi)
            init0 = np.zeros(K) if K > 1 else 0.0
            snapshot.maybe_snapshot(
                job, model,
                {"trees_done": t_done, "granularity": "tree_chunk"},
                (lambda c=[list(ch) for ch in chunks]:
                    tree_snapshot_state_multi(c, init0, binned.edges))
                if K > 1 else
                (lambda c=list(chunks[0]): tree_snapshot_state(
                    c, init0, binned.edges)))
            if not score_now:
                continue

            avg = F_sum / t_done
            raw = self._avg_to_preds(avg, di, K)
            m = make_metrics(di, raw, y, w)
            entry = {"iteration": t_done, **m.describe()}
            if valid is not None:
                mv = make_metrics(
                    di, self._avg_to_preds(F_v / t_done, di, K), y_v, w_v)
                entry.update({f"valid_{k2}": v for k2, v
                              in mv.describe().items()})
            history.append(entry)
            if p.stopping_rounds:
                key = (f"valid_{metric_name}" if valid is not None
                       else metric_name)
                series = [hh.get(key) for hh in history
                          if hh.get(key) is not None]
                if series and stop_early(series, p.stopping_rounds,
                                         p.stopping_tolerance, maximize):
                    break

        stacks = [StackedTrees.concat(ch) for ch in chunks]
        ntrees_trained = stacks[0].ntrees
        if K > 1:
            from .shared import TreeListMulti
            model.output["stacked"] = stacks
            model.output["trees"] = TreeListMulti(stacks)
        else:
            model.output["stacked"] = stacks[0]
            model.output["trees"] = TreeList(stacks[0])
        model.output["init_score"] = np.zeros(K) if K > 1 else 0.0
        model.output["ntrees_trained"] = ntrees_trained
        model.output["edges"] = binned.edges
        model.scoring_history = history
        # F_sum already holds the final ensemble scores — no re-traversal
        model.training_metrics = make_metrics(
            di, self._avg_to_preds(F_sum / max(ntrees_trained, 1), di, K),
            y, w)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    @staticmethod
    def _avg_to_preds(avg, di, K):
        if di.is_classifier and K > 1:
            pr = jnp.clip(avg, 0.0, 1.0)
            return pr / jnp.maximum(jnp.sum(pr, axis=1, keepdims=True), 1e-12)
        if di.is_classifier:
            p1 = jnp.clip(avg, 0.0, 1.0)
            return jnp.stack([1 - p1, p1], axis=1)
        return avg
