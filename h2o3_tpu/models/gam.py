"""GAM: spline basis expansion feeding the GLM solver.

Reference: ``hex/gam/GAM.java:53`` (h2o-algos, 4.7k LoC) — expands each
``gam_column`` into a spline basis (cubic regression splines at quantile
knots), then runs GLM over [basis, other features] with the usual families.

TPU-native redesign: the basis expansion is a one-pass device program per
gam column (truncated-power cubic basis at quantile knots — matmul-friendly
dense columns); everything downstream reuses the GLM driver (IRLSM on psum'd
Grams).  Smoothing via the GLM's own ridge penalty (scale_tp_penalty).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder
from .datainfo import DataInfo
from .glm import GLM, GLMParameters


@dataclasses.dataclass
class GAMParameters(GLMParameters):
    gam_columns: Sequence[str] = ()
    num_knots: int = 5
    scale: float = 0.01                 # smoothing -> ridge on basis terms


def _spline_basis(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """Truncated-power cubic basis: [x, x^2, x^3, (x-k_j)^3_+ ...]."""
    cols = [x, x ** 2, x ** 3]
    for kn in knots[1:-1]:
        cols.append(np.maximum(x - kn, 0.0) ** 3)
    return np.stack(cols, axis=1)


class GAMModel(Model):
    algo = "gam"

    def _expand(self, frame: Frame) -> Frame:
        names, vecs = [], []
        knots_map = self.output["knots"]
        scale_map = self.output["basis_scale"]
        means_map = self.output["gam_col_means"]
        for n, v in zip(frame.names, frame.vecs):
            if n in knots_map:
                # NaNs impute with the TRAINING mean (batch-independent)
                x = np.nan_to_num(v.to_numpy(), nan=means_map[n])
                B = _spline_basis(x, knots_map[n]) / scale_map[n][None, :]
                for j in range(B.shape[1]):
                    names.append(f"{n}_gam{j}")
                    vecs.append(Vec.from_numpy(B[:, j], T_NUM))
            else:
                names.append(n)
                vecs.append(v)
        return Frame(names, vecs)

    def _predict_raw(self, X):
        raise NotImplementedError("gam scores via its GLM")

    def predict(self, frame: Frame) -> Frame:
        glm = dkv.get(self.output["glm_key"])
        return glm.predict(self._expand(frame))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        glm = dkv.get(self.output["glm_key"])
        return glm.model_performance(self._expand(frame))

    @property
    def coef(self) -> dict:
        return dkv.get(self.output["glm_key"]).coef


class GAM(ModelBuilder):
    """GAM builder — H2OGeneralizedAdditiveEstimator analog."""

    algo = "gam"
    model_class = GAMModel

    def __init__(self, params: Optional[GAMParameters] = None, **kw):
        super().__init__(params or GAMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: GAMParameters = self.params
        if not p.gam_columns:
            raise ValueError("gam requires gam_columns")
        for c in p.gam_columns:
            if c not in frame.names:
                raise ValueError(f"gam column {c!r} not in frame")

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GAMModel:
        p: GAMParameters = self.params
        knots_map: Dict[str, np.ndarray] = {}
        scale_map: Dict[str, np.ndarray] = {}
        means_map: Dict[str, float] = {}
        for c in p.gam_columns:
            x = frame.vec(c).to_numpy()
            x = x[~np.isnan(x)]
            qs = np.linspace(0, 1, p.num_knots)
            knots_map[c] = np.unique(np.quantile(x, qs))
            means_map[c] = float(x.mean()) if len(x) else 0.0
        model = GAMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["knots"] = knots_map
        model.output["gam_col_means"] = means_map
        # per-basis scaling for conditioning of the truncated-power basis
        for c in p.gam_columns:
            x = np.nan_to_num(frame.vec(c).to_numpy(), nan=means_map[c])
            B = _spline_basis(x, knots_map[c])
            scale_map[c] = np.maximum(B.std(axis=0), 1e-12)
        model.output["basis_scale"] = scale_map

        expanded = model._expand(frame)
        job.update(0.3, "fitting GLM over spline basis")
        glm = GLM(response_column=p.response_column, family=p.family,
                  alpha=0.0,
                  lambda_=p.lambda_ if p.lambda_ is not None else p.scale,
                  weights_column=p.weights_column,
                  seed=p.effective_seed(),
                  max_iterations=p.max_iterations).train(
            expanded, model._expand(valid) if valid is not None else None)
        model.output["glm_key"] = glm.key
        model.output["family"] = glm.output.get("family")
        model.training_metrics = glm.training_metrics
        model.validation_metrics = glm.validation_metrics
        return model
