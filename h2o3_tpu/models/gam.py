"""GAM: cubic regression splines with curvature penalties over the GLM.

Reference: ``hex/gam/GAM.java:53`` (4.7k LoC) — each ``gam_column`` expands
into a cubic regression spline (CRS) basis at quantile knots with the
integrated-squared-second-derivative penalty matrix, sum-to-zero centered
for identifiability, then the penalized GLM runs over [basis, other
features] (GamSplines/CubicRegressionSplines + penalty_matrix plumbing).

TPU-native redesign: the CRS construction follows the standard natural-
spline form (banded second-difference system; basis values are two knot
weights + two curvature weights per row — a dense [n, K] matmul-friendly
block).  The penalty is diagonalized once per column (Demmler-Reinsch:
rotate by the centered penalty's eigenvectors) so it becomes per-column
ridge FACTORS on the shared GLM solver — no bespoke penalized solver, and
the null space (linear trend) stays unpenalized exactly as in mgcv/H2O.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder
from .datainfo import DataInfo
from .glm import GLM, GLMParameters


@dataclasses.dataclass
class GAMParameters(GLMParameters):
    gam_columns: Sequence[str] = ()
    num_knots: int = 8
    scale: float = 1.0                  # smoothing strength per gam column
    bs: str = "cr"                      # basis type (cubic regression)


def _crs_construct(knots: np.ndarray):
    """CRS machinery for one knot vector: returns (F_full, S).

    ``F_full`` [K, K] maps knot values -> second derivatives at the knots
    (natural boundary: zero curvature at the ends); ``S`` [K, K] is the
    integrated squared second derivative penalty  D' B^{-1} D  (the exact
    curvature penalty the reference's penalty_matrix encodes).
    """
    K = len(knots)
    h = np.diff(knots).astype(np.float64)
    D = np.zeros((K - 2, K))
    B = np.zeros((K - 2, K - 2))
    for i in range(K - 2):
        D[i, i] = 1.0 / h[i]
        D[i, i + 1] = -1.0 / h[i] - 1.0 / h[i + 1]
        D[i, i + 2] = 1.0 / h[i + 1]
        B[i, i] = (h[i] + h[i + 1]) / 3.0
        if i < K - 3:
            B[i, i + 1] = h[i + 1] / 6.0
            B[i + 1, i] = h[i + 1] / 6.0
    F = np.linalg.solve(B, D)                      # [K-2, K]
    F_full = np.vstack([np.zeros(K), F, np.zeros(K)])
    S = D.T @ F                                    # [K, K], PSD
    return F_full, S


def _crs_eval(x: np.ndarray, knots: np.ndarray,
              F_full: np.ndarray) -> np.ndarray:
    """Cardinal CRS basis values [n, K]: row r gives the weights such that
    f(x_r) = weights . f(knots) for the natural interpolating spline."""
    K = len(knots)
    h = np.diff(knots)
    xc = np.clip(x, knots[0], knots[-1])
    j = np.clip(np.searchsorted(knots, xc, side="right") - 1, 0, K - 2)
    kj, kj1 = knots[j], knots[j + 1]
    hj = h[j]
    am = (kj1 - xc) / hj
    ap = (xc - kj) / hj
    cm = ((kj1 - xc) ** 3 / hj - hj * (kj1 - xc)) / 6.0
    cp = ((xc - kj) ** 3 / hj - hj * (xc - kj)) / 6.0
    n = len(x)
    X = np.zeros((n, K))
    rows = np.arange(n)
    np.add.at(X, (rows, j), am)
    np.add.at(X, (rows, j + 1), ap)
    X += cm[:, None] * F_full[j] + cp[:, None] * F_full[j + 1]
    return X


def _center_and_diagonalize(Xb: np.ndarray, S: np.ndarray):
    """Sum-to-zero centering + Demmler-Reinsch diagonalization.

    Returns (T, factors): the [K, K-1] transform applied to the basis and
    the per-output-column penalty factors (eigenvalues of the centered
    penalty; ~0 = unpenalized null space — the linear trend).
    """
    K = Xb.shape[1]
    # Z: orthogonal complement of the column-mean constraint (mgcv's
    # sum-to-zero identifiability absorbing the intercept)
    c = Xb.mean(axis=0)
    q, _ = np.linalg.qr(np.concatenate([c[:, None],
                                        np.eye(K)[:, : K - 1]], axis=1))
    Z = q[:, 1:K]                                   # [K, K-1]
    Sc = Z.T @ S @ Z
    d, U = np.linalg.eigh((Sc + Sc.T) / 2)
    d = np.maximum(d, 0.0)
    T = Z @ U                                       # [K, K-1]
    return T, d


class GAMModel(Model):
    algo = "gam"

    def _expand(self, frame: Frame) -> Frame:
        names, vecs = [], []
        meta = self.output["gam_meta"]
        for n, v in zip(frame.names, frame.vecs):
            if n in meta:
                m = meta[n]
                x = np.nan_to_num(v.to_numpy(), nan=m["mean"])
                B = _crs_eval(x, m["knots"], m["F_full"]) @ m["T"]
                B = B / m["col_scale"][None, :]
                for j in range(B.shape[1]):
                    names.append(f"{n}_gam{j}")
                    vecs.append(Vec.from_numpy(B[:, j], T_NUM))
            else:
                names.append(n)
                vecs.append(v)
        return Frame(names, vecs)

    def _predict_raw(self, X):
        raise NotImplementedError("gam scores via its GLM")

    def predict(self, frame: Frame) -> Frame:
        glm = dkv.get(self.output["glm_key"])
        return glm.predict(self._expand(frame))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        glm = dkv.get(self.output["glm_key"])
        return glm.model_performance(self._expand(frame))

    @property
    def coef(self) -> dict:
        return dkv.get(self.output["glm_key"]).coef


class GAM(ModelBuilder):
    """GAM builder — H2OGeneralizedAdditiveEstimator analog."""

    algo = "gam"
    model_class = GAMModel

    def __init__(self, params: Optional[GAMParameters] = None, **kw):
        super().__init__(params or GAMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: GAMParameters = self.params
        if not p.gam_columns:
            raise ValueError("gam requires gam_columns")
        for c in p.gam_columns:
            if c not in frame.names:
                raise ValueError(f"gam column {c!r} not in frame")

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GAMModel:
        p: GAMParameters = self.params
        meta: Dict[str, dict] = {}
        factors: Dict[str, float] = {}
        for c in p.gam_columns:
            x = frame.vec(c).to_numpy()
            x = x[~np.isnan(x)]
            qs = np.linspace(0, 1, max(p.num_knots, 4))
            knots = np.unique(np.quantile(x, qs))
            if len(knots) < 4:
                raise ValueError(
                    f"gam column {c!r} has too few distinct values "
                    f"({len(knots)}) for a cubic spline")
            F_full, S = _crs_construct(knots)
            Xb = _crs_eval(np.nan_to_num(frame.vec(c).to_numpy(),
                                         nan=float(x.mean())), knots, F_full)
            T, d = _center_and_diagonalize(Xb, S)
            Bt = Xb @ T
            col_scale = np.maximum(Bt.std(axis=0), 1e-12)
            meta[c] = {"knots": knots, "F_full": F_full, "T": T,
                       "mean": float(x.mean()), "col_scale": col_scale}
            # penalty factor for the scaled column: the design column is
            # Bt/s, so its coefficient is s*beta and a factor f penalizes
            # f*s^2*beta^2 — realizing scale*d_j*beta^2 needs f = scale*d/s^2.
            # d is normalized by its largest eigenvalue (the reference
            # scales penalty matrices likewise) so scale=1 smooths mildly
            # regardless of knot spacing / data units.
            d_max = max(float(d.max()), 1e-30)
            for j, dj in enumerate(d):
                factors[f"{c}_gam{j}"] = float(
                    p.scale * (dj / d_max) / max(col_scale[j] ** 2, 1e-30))
        model = GAMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["gam_meta"] = meta

        # non-gam predictors keep the user's lambda as their factor
        base_lam = 0.0 if p.lambda_ is None else float(np.max(p.lambda_))
        expanded = model._expand(frame)
        for n in expanded.names:
            if n not in factors and n != p.response_column:
                factors[n] = base_lam
        job.update(0.3, "fitting penalized GLM over CRS basis")
        glm = GLM(response_column=p.response_column, family=p.family,
                  alpha=0.0, lambda_=1.0, penalty_factors=factors,
                  weights_column=p.weights_column,
                  seed=p.effective_seed(),
                  max_iterations=p.max_iterations).train(
            expanded, model._expand(valid) if valid is not None else None)
        model.output["glm_key"] = glm.key
        model.output["family"] = glm.output.get("family")
        model.training_metrics = glm.training_metrics
        model.validation_metrics = glm.validation_metrics
        return model
