"""GAM: spline smooths with curvature penalties over the GLM.

Reference: ``hex/gam/GAM.java:53`` (4.7k LoC) — each ``gam_column`` expands
into a spline basis with a penalty matrix, identifiability-centered, then
the penalized GLM runs over [basis, other features].  Basis families:

- ``bs="cr"`` — cubic regression splines at quantile knots with the
  integrated-squared-second-derivative penalty
  (GamSplines/CubicRegressionSplines).
- ``bs="tp"`` — thin-plate regression splines, including MULTI-predictor
  smooths (``gam_columns`` entries may be lists of columns;
  GamSplines/ThinPlateRegressionUtils.java + ThinPlateDistanceWithKnots):
  radial basis at data knots, polynomial null space projected out, the
  bending-energy penalty from the radial block.
- ``bs="is"`` — monotone I-splines (GamSplines/ISplines): integrated
  B-spline basis whose coefficients are constrained non-negative through
  the GLM's ``non_negative`` option, yielding monotone-increasing smooths
  (``splines_non_negative``, NBSplinesTypeII analog).

TPU-native redesign: bases are dense matmul-friendly blocks; each penalty
is diagonalized once per smooth (Demmler-Reinsch: rotate by the centered
penalty's eigenvectors) so it becomes per-column ridge FACTORS on the
shared GLM solver — no bespoke penalized solver, and each null space
(linear/polynomial trend) stays unpenalized exactly as in mgcv/H2O.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder
from .datainfo import DataInfo
from .glm import GLM, GLMParameters


@dataclasses.dataclass
class GAMParameters(GLMParameters):
    # entries are column names, or LISTS of names for multi-predictor
    # thin-plate smooths (the reference's nested gam_columns)
    gam_columns: Sequence = ()
    num_knots: int = 8
    scale: float = 1.0                  # smoothing strength per gam column
    # basis per smooth: "cr" | "tp" | "is" — a single string applies to
    # every smooth (the reference's bs array of 0=cr/1=tp/2=is codes)
    bs: object = "cr"
    # monotone (I-spline) smooths: constrain coefficients >= 0
    splines_non_negative: bool = True


def _crs_construct(knots: np.ndarray):
    """CRS machinery for one knot vector: returns (F_full, S).

    ``F_full`` [K, K] maps knot values -> second derivatives at the knots
    (natural boundary: zero curvature at the ends); ``S`` [K, K] is the
    integrated squared second derivative penalty  D' B^{-1} D  (the exact
    curvature penalty the reference's penalty_matrix encodes).
    """
    K = len(knots)
    h = np.diff(knots).astype(np.float64)
    D = np.zeros((K - 2, K))
    B = np.zeros((K - 2, K - 2))
    for i in range(K - 2):
        D[i, i] = 1.0 / h[i]
        D[i, i + 1] = -1.0 / h[i] - 1.0 / h[i + 1]
        D[i, i + 2] = 1.0 / h[i + 1]
        B[i, i] = (h[i] + h[i + 1]) / 3.0
        if i < K - 3:
            B[i, i + 1] = h[i + 1] / 6.0
            B[i + 1, i] = h[i + 1] / 6.0
    F = np.linalg.solve(B, D)                      # [K-2, K]
    F_full = np.vstack([np.zeros(K), F, np.zeros(K)])
    S = D.T @ F                                    # [K, K], PSD
    return F_full, S


def _crs_eval(x: np.ndarray, knots: np.ndarray,
              F_full: np.ndarray) -> np.ndarray:
    """Cardinal CRS basis values [n, K]: row r gives the weights such that
    f(x_r) = weights . f(knots) for the natural interpolating spline."""
    K = len(knots)
    h = np.diff(knots)
    xc = np.clip(x, knots[0], knots[-1])
    j = np.clip(np.searchsorted(knots, xc, side="right") - 1, 0, K - 2)
    kj, kj1 = knots[j], knots[j + 1]
    hj = h[j]
    am = (kj1 - xc) / hj
    ap = (xc - kj) / hj
    cm = ((kj1 - xc) ** 3 / hj - hj * (kj1 - xc)) / 6.0
    cp = ((xc - kj) ** 3 / hj - hj * (xc - kj)) / 6.0
    n = len(x)
    X = np.zeros((n, K))
    rows = np.arange(n)
    np.add.at(X, (rows, j), am)
    np.add.at(X, (rows, j + 1), ap)
    X += cm[:, None] * F_full[j] + cp[:, None] * F_full[j + 1]
    return X


def _tp_eta(r: np.ndarray, d: int) -> np.ndarray:
    """Thin-plate radial basis function for d input dimensions (m=2)."""
    if d == 1:
        return r ** 3 / 12.0
    if d == 2:
        with np.errstate(divide="ignore", invalid="ignore"):
            out = (r * r) * np.log(np.maximum(r, 1e-300)) / (8 * np.pi)
        return np.where(r > 0, out, 0.0)
    return -r / 8.0                         # d == 3 (odd-d general form)


def _tp_construct(Xk: np.ndarray):
    """Thin-plate machinery for one knot matrix [k, d]: returns (Z, S).

    ``Z`` [k, k-d-1] projects radial coefficients onto the null space of
    the polynomial constraint T'delta = 0 (T = [1, x1..xd] at the knots);
    ``S = Z' E Z`` is the bending-energy penalty with E the knot-knot
    radial matrix — the standard TPRS construction
    (ThinPlateRegressionUtils.java computes the same pieces distributedly).
    """
    k, d = Xk.shape
    r = np.linalg.norm(Xk[:, None, :] - Xk[None, :, :], axis=2)
    E = _tp_eta(r, d)
    T = np.concatenate([np.ones((k, 1)), Xk], axis=1)        # [k, d+1]
    q, _ = np.linalg.qr(T, mode="complete")
    Z = q[:, d + 1:]                                         # [k, k-d-1]
    S = Z.T @ E @ Z
    return Z, (S + S.T) / 2


def _tp_eval(X: np.ndarray, Xk: np.ndarray, Z: np.ndarray) -> np.ndarray:
    """Projected radial design block [n, k-d-1] for rows X [n, d]."""
    d = Xk.shape[1]
    r = np.linalg.norm(X[:, None, :] - Xk[None, :, :], axis=2)
    return _tp_eta(r, d) @ Z


def _is_basis(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """I-spline (monotone) basis [n, K]: cumulative integrals of cubic
    M-splines — each column rises 0 -> 1, so non-negative coefficients
    give a monotone-increasing smooth (GamSplines/ISplines analog)."""
    from scipy.interpolate import BSpline
    order = 4                                # cubic
    t = np.concatenate([[knots[0]] * order, knots[1:-1],
                        [knots[-1]] * order])
    nb = len(t) - order
    xc = np.clip(x, knots[0], knots[-1])
    B = np.empty((len(x), nb))
    for j in range(nb):
        coef = np.zeros(nb)
        coef[j] = 1.0
        B[:, j] = BSpline(t, coef, order - 1)(xc)
    # I_j(x) = sum of B-spline columns m >= j+1 (integrated M-splines);
    # drop the first cumulative column (constant 1 = intercept clash)
    I = np.cumsum(B[:, ::-1], axis=1)[:, ::-1]
    return I[:, 1:]


def _center_and_diagonalize(Xb: np.ndarray, S: np.ndarray):
    """Sum-to-zero centering + Demmler-Reinsch diagonalization.

    Returns (T, factors): the [K, K-1] transform applied to the basis and
    the per-output-column penalty factors (eigenvalues of the centered
    penalty; ~0 = unpenalized null space — the linear trend).
    """
    K = Xb.shape[1]
    # Z: orthogonal complement of the column-mean constraint (mgcv's
    # sum-to-zero identifiability absorbing the intercept)
    c = Xb.mean(axis=0)
    q, _ = np.linalg.qr(np.concatenate([c[:, None],
                                        np.eye(K)[:, : K - 1]], axis=1))
    Z = q[:, 1:K]                                   # [K, K-1]
    Sc = Z.T @ S @ Z
    d, U = np.linalg.eigh((Sc + Sc.T) / 2)
    d = np.maximum(d, 0.0)
    T = Z @ U                                       # [K, K-1]
    return T, d


class GAMModel(Model):
    algo = "gam"

    def _block(self, m: dict, frame: Frame) -> np.ndarray:
        """Design block [n, width] for one smooth on any frame."""
        if m["kind"] == "cr":
            x = np.nan_to_num(frame.vec(m["cols"][0]).to_numpy(),
                              nan=m["mean"])
            B = _crs_eval(x, m["knots"], m["F_full"]) @ m["T"]
            return B / m["col_scale"][None, :]
        if m["kind"] == "tp":
            X = np.stack([np.nan_to_num(frame.vec(c).to_numpy(), nan=mu)
                          for c, mu in zip(m["cols"], m["means"])], axis=1)
            Xs = (X - np.asarray(m["means"])) / np.asarray(m["sigmas"])
            B = _tp_eval(Xs, m["knots"], m["Z"]) @ m["T"]
            B = B / m["col_scale"][None, :]
            return np.concatenate([B, Xs], axis=1)   # + linear null space
        x = np.nan_to_num(frame.vec(m["cols"][0]).to_numpy(),
                          nan=m["mean"])              # "is"
        return _is_basis(x, m["knots"])

    def _expand(self, frame: Frame) -> Frame:
        meta = self.output["gam_meta"]
        smooth_cols = {c for m in meta for c in m["cols"]}
        names, vecs = [], []
        for n, v in zip(frame.names, frame.vecs):
            if n not in smooth_cols:
                names.append(n)
                vecs.append(v)
        for m in meta:
            B = self._block(m, frame)
            for j in range(B.shape[1]):
                names.append(f"{m['name']}_gam{j}")
                vecs.append(Vec.from_numpy(B[:, j], T_NUM))
        return Frame(names, vecs)

    def _predict_raw(self, X):
        raise NotImplementedError("gam scores via its GLM")

    def predict(self, frame: Frame) -> Frame:
        glm = dkv.get(self.output["glm_key"])
        return glm.predict(self._expand(frame))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        glm = dkv.get(self.output["glm_key"])
        return glm.model_performance(self._expand(frame))

    @property
    def coef(self) -> dict:
        return dkv.get(self.output["glm_key"]).coef


class GAM(ModelBuilder):
    """GAM builder — H2OGeneralizedAdditiveEstimator analog."""

    algo = "gam"
    model_class = GAMModel

    def __init__(self, params: Optional[GAMParameters] = None, **kw):
        super().__init__(params or GAMParameters(**kw))

    def _smooth_specs(self) -> List[dict]:
        """Normalize gam_columns/bs into per-smooth descriptors."""
        p: GAMParameters = self.params
        entries = [e if isinstance(e, (list, tuple)) else [e]
                   for e in p.gam_columns]
        bs = p.bs
        kinds = list(bs) if isinstance(bs, (list, tuple)) \
            else [bs] * len(entries)
        if len(kinds) != len(entries):
            raise ValueError("bs must be one kind or one per gam_columns "
                             "entry")
        code = {0: "cr", 1: "tp", 2: "is", "cr": "cr", "tp": "tp",
                "is": "is", "ms": "is"}
        out = []
        for cols, k in zip(entries, kinds):
            kind = code.get(k)
            if kind is None:
                raise ValueError(f"unknown basis {k!r} (cr | tp | is)")
            if kind != "tp" and len(cols) > 1:
                raise ValueError("multi-column smooths need bs='tp'")
            if kind == "tp" and len(cols) > 3:
                raise ValueError(
                    "thin-plate smooths support up to 3 columns (the m=2 "
                    "radial basis needs 2m > d)")
            out.append({"cols": list(cols), "kind": kind,
                        "name": "_".join(cols)})
        return out

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: GAMParameters = self.params
        if not p.gam_columns:
            raise ValueError("gam requires gam_columns")
        for s in self._smooth_specs():
            for c in s["cols"]:
                if c not in frame.names:
                    raise ValueError(f"gam column {c!r} not in frame")

    @staticmethod
    def _quantile_knots(x: np.ndarray, k: int, col: str) -> np.ndarray:
        knots = np.unique(np.quantile(x, np.linspace(0, 1, max(k, 4))))
        if len(knots) < 4:
            raise ValueError(
                f"gam column {col!r} has too few distinct values "
                f"({len(knots)}) for a spline")
        return knots

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GAMModel:
        p: GAMParameters = self.params
        meta: List[dict] = []
        factors: Dict[str, float] = {}
        nonneg: List[str] = []
        model = GAMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        for s in self._smooth_specs():
            name, cols = s["name"], s["cols"]
            if s["kind"] == "cr":
                x = frame.vec(cols[0]).to_numpy()
                x = x[~np.isnan(x)]
                knots = self._quantile_knots(x, p.num_knots, cols[0])
                F_full, S = _crs_construct(knots)
                Xb = _crs_eval(np.nan_to_num(frame.vec(cols[0]).to_numpy(),
                                             nan=float(x.mean())),
                               knots, F_full)
                T, d = _center_and_diagonalize(Xb, S)
                col_scale = np.maximum((Xb @ T).std(axis=0), 1e-12)
                meta.append({**s, "knots": knots, "F_full": F_full, "T": T,
                             "mean": float(x.mean()),
                             "col_scale": col_scale})
                # penalty factor for the scaled column: the design column
                # is Bt/s, so its coefficient is s*beta and a factor f
                # penalizes f*s^2*beta^2 — realizing scale*d_j*beta^2
                # needs f = scale*d/s^2.  d is normalized by its largest
                # eigenvalue (the reference scales penalty matrices
                # likewise) so scale=1 smooths mildly regardless of knot
                # spacing / data units.
                d_max = max(float(d.max()), 1e-30)
                for j, dj in enumerate(d):
                    factors[f"{name}_gam{j}"] = float(
                        p.scale * (dj / d_max)
                        / max(col_scale[j] ** 2, 1e-30))
            elif s["kind"] == "tp":
                Xcols, means, sigmas = [], [], []
                for c in cols:
                    xc = frame.vec(c).to_numpy()
                    mu = float(np.nanmean(xc))
                    sd = float(np.nanstd(xc)) or 1.0
                    Xcols.append(np.nan_to_num(xc, nan=mu))
                    means.append(mu)
                    sigmas.append(sd)
                X = (np.stack(Xcols, axis=1) - np.asarray(means)) \
                    / np.asarray(sigmas)
                dcols = X.shape[1]
                k = max(p.num_knots, dcols + 3)
                # deterministic space-filling knots: evenly strided rows
                # of the lexicographic sort (kmeans-free knot placement)
                order = np.lexsort(X.T[::-1])
                idx = order[np.linspace(0, len(order) - 1, k).astype(int)]
                knots = np.unique(X[idx], axis=0)
                Z, S = _tp_construct(knots)
                B = _tp_eval(X, knots, Z)
                T, d = _center_and_diagonalize(B, S)
                col_scale = np.maximum((B @ T).std(axis=0), 1e-12)
                meta.append({**s, "knots": knots, "Z": Z, "T": T,
                             "means": means, "sigmas": sigmas,
                             "col_scale": col_scale})
                # TP factors are normalized on the SCALED columns (the
                # radial basis has tiny raw magnitudes, so the CRS-style
                # d/col_scale^2 blows up): f_raw = d_j/col_scale_j^2,
                # rescaled so the stiffest direction gets exactly
                # ``scale`` — scale=1 then smooths mildly, matching the
                # CRS knob's feel.
                f_raw = np.maximum(np.asarray(d, float), 0.0) \
                    / np.maximum(col_scale ** 2, 1e-30)
                f_max = max(float(f_raw.max()), 1e-30)
                nrad = len(col_scale)
                for j in range(nrad):
                    factors[f"{name}_gam{j}"] = float(
                        p.scale * f_raw[j] / f_max)
                for j in range(dcols):            # linear null space
                    factors[f"{name}_gam{nrad + j}"] = 0.0
            else:                                 # "is" — monotone
                x = frame.vec(cols[0]).to_numpy()
                x = x[~np.isnan(x)]
                knots = self._quantile_knots(x, p.num_knots, cols[0])
                meta.append({**s, "knots": knots, "mean": float(x.mean())})
                width = _is_basis(np.asarray([knots[0]]), knots).shape[1]
                for j in range(width):
                    cname = f"{name}_gam{j}"
                    factors[cname] = float(p.scale)
                    if p.splines_non_negative:
                        nonneg.append(cname)
        model.output["gam_meta"] = meta

        # non-gam predictors keep the user's lambda as their factor
        base_lam = 0.0 if p.lambda_ is None else float(np.max(p.lambda_))
        expanded = model._expand(frame)
        for n in expanded.names:
            if n not in factors and n != p.response_column:
                factors[n] = base_lam
        job.update(0.3, "fitting penalized GLM over the spline bases")
        glm = GLM(response_column=p.response_column, family=p.family,
                  alpha=0.0, lambda_=1.0, penalty_factors=factors,
                  weights_column=p.weights_column,
                  non_negative=nonneg or False,
                  seed=p.effective_seed(),
                  max_iterations=p.max_iterations).train(
            expanded, model._expand(valid) if valid is not None else None)
        model.output["glm_key"] = glm.key
        model.output["family"] = glm.output.get("family")
        model.training_metrics = glm.training_metrics
        model.validation_metrics = glm.validation_metrics
        return model
