"""Cross-validation orchestration.

Reference: ``hex/CVModelBuilder.java:10`` + ``hex/FoldAssignment.java`` +
ModelBuilder's CV code — build N fold models (optionally in parallel),
aggregate the holdout predictions into the main model's CV metrics, then
train the final model on all data.

TPU-native redesign: fold models are independent compiled programs with
IDENTICAL shapes (holdout rows are weight-zeroed, not sliced), so every
fold reuses the first fold's executables; fold builds run concurrently on
a bounded thread pool (``models/parallel.py`` — the CVModelBuilder
"parallelization" semantics), overlapping one fold's host-side work with
another's device queue.  Holdout predictions are gathered host-side into
one array and scored with the same fused metric kernels.
"""

from __future__ import annotations

import copy
from typing import Optional

import numpy as np

from ..frame.frame import Frame
from ..runtime.job import Job
from ..metrics.core import make_metrics
import jax.numpy as jnp


def fold_assignment(n: int, nfolds: int, scheme: str, seed: int,
                    y: Optional[np.ndarray] = None) -> np.ndarray:
    """Row -> fold index (hex/FoldAssignment.java). Schemes: auto|random|
    modulo|stratified."""
    if scheme in ("auto", "random"):
        rng = np.random.default_rng(seed)
        return rng.integers(0, nfolds, size=n)
    if scheme == "modulo":
        return np.arange(n) % nfolds
    if scheme == "stratified":
        if y is None:
            raise ValueError("stratified fold assignment needs a response")
        rng = np.random.default_rng(seed)
        folds = np.zeros(n, dtype=np.int64)
        for cls in np.unique(y[~np.isnan(y)]):
            idx = np.nonzero(y == cls)[0]
            rng.shuffle(idx)
            folds[idx] = np.arange(len(idx)) % nfolds
        return folds
    raise ValueError(f"unknown fold_assignment {scheme!r}")


def cross_validate(builder, job: Job, frame: Frame, di, valid):
    """N-fold CV: fold models -> holdout preds -> CV metrics -> final model."""
    p = builder.params
    nfolds = p.nfolds
    seed = p.effective_seed()
    if p.fold_column is not None:
        fc = frame.vec(p.fold_column).to_numpy()
        _, folds = np.unique(fc, return_inverse=True)
        nfolds = folds.max() + 1
    else:
        y_host = np.asarray(di.response(frame))[: frame.nrows] \
            if di.response_column else None
        folds = fold_assignment(frame.nrows, nfolds, p.fold_assignment, seed,
                                y=y_host)

    nclasses = di.nclasses
    width = nclasses if di.is_classifier else 1
    holdout = np.full((frame.nrows, width), np.nan, dtype=np.float64)

    # Constant-shape folds: rather than slicing rows per fold (which changes
    # the padded row count and forces XLA to recompile every program per
    # fold), train each fold model on the FULL frame with holdout rows'
    # weights zeroed via a synthetic weight column.  Shapes stay identical
    # across folds, so every fold reuses the first fold's compilations.
    from ..frame.vec import Vec, T_NUM
    from .parallel import effective_parallelism, map_builds
    base_w = np.ones(frame.nrows)
    if p.weights_column is not None:
        base_w = np.nan_to_num(frame.vec(p.weights_column).to_numpy())
    cv_w_col = "_cv_weights_"
    import dataclasses as _dc
    import threading
    done = [0]
    lock = threading.Lock()

    def train_fold(f: int):
        from ..runtime import failure
        failure.maybe_inject("cv_fold")
        w_f = np.where(folds != f, base_w, 0.0)
        fold_frame = Frame(list(frame.names) + [cv_w_col],
                           list(frame.vecs) + [Vec.from_numpy(w_f, T_NUM)])
        fold_builder = type(builder)(copy.copy(p))
        fold_builder.params.nfolds = 0
        fold_builder.params.weights_column = cv_w_col
        fold_di = _dc.replace(di, weights_column=cv_w_col)
        fold_job = Job(f"{builder.algo} cv fold {f}")
        m = fold_job.run(
            lambda j: fold_builder._fit(j, fold_frame, fold_di, None))
        with lock:
            done[0] += 1
            job.update(0.7 * done[0] / nfolds,
                       f"cv fold {done[0]}/{nfolds}")
        return m

    par = effective_parallelism(p.parallelism, nfolds)
    cv_models = map_builds([lambda f=f: train_fold(f)
                            for f in range(nfolds)], par)
    X_full = cv_models[0]._score_matrix(frame)
    for f, m in enumerate(cv_models):
        hold_idx = np.nonzero(folds == f)[0]
        raw = np.asarray(m._predict_raw(X_full))[: frame.nrows]
        holdout[hold_idx] = raw.reshape(frame.nrows, width)[hold_idx]
        job.update(0.7 + 0.1 * (f + 1) / nfolds, f"cv holdout {f + 1}")

    # final model on all data
    model = builder._fit(job, frame, di, valid)
    y = di.response(frame)
    w = di.weights(frame)
    raw_pad = np.zeros((frame.padded_rows, width))
    raw_pad[: frame.nrows] = np.nan_to_num(holdout)
    model.cross_validation_metrics = make_metrics(
        di, jnp.asarray(raw_pad.squeeze() if width == 1 else raw_pad,
                        dtype=jnp.float32), y, w)
    model.output["cv_fold_models"] = [m.key for m in cv_models]
    if p.keep_cross_validation_predictions:
        model.cv_predictions = holdout
    return model
