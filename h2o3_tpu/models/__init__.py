"""Model framework + algorithms (the hex.* analog)."""

from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from .glm import GLM, GLMModel, GLMParameters
from .deeplearning import DeepLearning, DeepLearningModel, DeepLearningParameters
from .kmeans import KMeans, KMeansModel, KMeansParameters
from .pca import PCA, PCAModel, PCAParameters, SVD, SVDModel, SVDParameters
from .naivebayes import NaiveBayes, NaiveBayesModel, NaiveBayesParameters
from .quantile import Quantile, QuantileModel, QuantileParameters, quantile
from .isotonic import (IsotonicRegression, IsotonicRegressionModel,
                       IsotonicRegressionParameters)
from .tree.gbm import GBM, GBMModel, GBMParameters
from .tree.drf import DRF, DRFModel, DRFParameters
from .tree.xgboost import XGBoost, XGBoostModel, XGBoostParameters
from .ensemble import (StackedEnsemble, StackedEnsembleModel,
                       StackedEnsembleParameters)
from .grid import Grid, GridSearch
from .infogram import Infogram, InfogramModel, InfogramParameters
from .adaboost import AdaBoost, AdaBoostModel, AdaBoostParameters
from .targetencoder import (TargetEncoder, TargetEncoderModel,
                            TargetEncoderParameters)
from .glrm import GLRM, GLRMModel, GLRMParameters
from .coxph import CoxPH, CoxPHModel, CoxPHParameters
from .word2vec import Word2Vec, Word2VecModel, Word2VecParameters
from .rulefit import RuleFit, RuleFitModel, RuleFitParameters
from .aggregator import Aggregator, AggregatorModel, AggregatorParameters
from .gam import GAM, GAMModel, GAMParameters
from .tree.isofor import (IsolationForest, IsolationForestModel,
                          IsolationForestParameters,
                          ExtendedIsolationForest,
                          ExtendedIsolationForestModel,
                          ExtendedIsolationForestParameters)
from .tree.uplift import UpliftDRF, UpliftDRFModel, UpliftDRFParameters
from .tree.dt import DecisionTree, DTModel, DTParameters
from .segments import SegmentModels, train_segments
from .modelselection import (ModelSelection, ModelSelectionModel,
                             ModelSelectionParameters)
from .anovaglm import ANOVAGLM, ANOVAGLMModel, ANOVAGLMParameters
from .psvm import PSVM, PSVMModel, PSVMParameters
from .grep import Grep, GrepModel, GrepParameters, grep
