"""Model framework + algorithms (the hex.* analog)."""

from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from .glm import GLM, GLMModel, GLMParameters
