"""DeepLearning: multi-layer perceptron / autoencoder, data-parallel on TPU.

Reference: ``hex/deeplearning/`` — DeepLearning.java (driver main loop),
DeepLearningTask.java:17 (Hogwild! lock-free per-node SGD on a local weight
copy), DeepLearningTask2.java:44-61 (cluster model averaging),
Neurons.java:184/189 (per-row fprop/bprop with gemv row kernels :638),
Dropout.java, DeepLearningModelInfo.java (flat weight arrays, elastic
averaging :751-758).

TPU-native redesign (SURVEY.md §2.10): Hogwild + periodic averaging is an
artifact of JVM threads — synchronous data-parallel SGD is strictly better on
TPU, so each step is ONE jit-compiled program: minibatch gather from the
row-sharded design matrix, batched fprop/bprop as MXU matmuls (the per-row
gemv loops become [batch, features] @ [features, hidden]), gradients psum'd
over the mesh by GSPMD, optimizer update via optax (ADADELTA to match the
reference's adaptive-rate default, DeepLearningModelInfo rho/epsilon).
``train_samples_per_iteration`` keeps its reference semantics: samples
processed between scoring/early-stopping checks.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from ..metrics.core import make_metrics
from .scorekeeper import stop_early


@dataclasses.dataclass
class DeepLearningParameters(Parameters):
    hidden: Sequence[int] = (200, 200)
    activation: str = "rectifier"       # tanh|rectifier|maxout (+_with_dropout)
    epochs: float = 10.0
    mini_batch_size: int = 128           # TPU-efficient default (ref default 1)
    adaptive_rate: bool = True           # ADADELTA (rho/epsilon), ref default
    rho: float = 0.99
    epsilon: float = 1e-8
    rate: float = 0.005                  # when adaptive_rate=False
    momentum_start: float = 0.0
    momentum_stable: float = 0.0
    input_dropout_ratio: float = 0.0
    hidden_dropout_ratios: Optional[Sequence[float]] = None
    l1: float = 0.0
    l2: float = 0.0
    # custom per-row loss UDF (CDistributionFunc analog): callable
    # (pred, y) -> per-row loss, jittable; pred is logits [B, K] for
    # classifiers / autoencoders, the scalar prediction [B] otherwise.
    # NOTE: with standardize=True (the default) regression targets reach
    # the loss STANDARDIZED ((y-mean)/sigma) — scale-sensitive losses
    # (e.g. huber with a delta in raw units) should set standardize=False
    custom_loss_func: Optional[object] = None
    loss: str = "automatic"              # automatic|cross_entropy|quadratic|
    # absolute|huber
    distribution: str = "auto"
    train_samples_per_iteration: int = -2   # -2 auto, -1 all, 0 one epoch
    score_interval: float = 5.0
    initial_weight_distribution: str = "uniform_adaptive"
    initial_weight_scale: float = 1.0
    autoencoder: bool = False
    standardize: bool = True
    stopping_rounds: int = 5
    stopping_metric: str = "auto"
    stopping_tolerance: float = 0.0
    max_iterations: int = 10 ** 9        # unused; epochs governs
    # bf16 MXU compute with f32 master weights/optimizer state (mixed
    # precision — the TPU-native default); "f32" forces full precision
    # (reproducible-mode analog for scale-sensitive losses)
    precision: str = "bf16"
    # rows are permuted once on device before training so the random-offset
    # block sampler (see _build_train_steps) draws unbiased minibatches
    # even from sorted frames; reference flag of the same name
    shuffle_training_data: bool = True


def _forward_pass(activation: str, params, X, deterministic=True, rng=None,
                  dropout_in: float = 0.0, dropout_hidden=(),
                  compute_dtype=None):
    """THE DL forward pass — shared by predict-time ``Model._forward`` and
    the compiled training program (one implementation, so activation /
    dropout semantics cannot drift between training and scoring).

    ``compute_dtype=bf16`` runs the matmuls on the MXU in bf16 with f32
    accumulation (mixed precision); weights and biases stay f32 so the
    optimizer state and the autodiff transpose remain full precision.
    """
    act = _activation_fn(activation)
    maxout = act is None

    def mm(h, W):
        if compute_dtype is None:
            return h @ W
        return jnp.dot(h.astype(compute_dtype), W.astype(compute_dtype),
                       preferred_element_type=jnp.float32)

    h = X
    if not deterministic and dropout_in > 0:
        rng, k = jax.random.split(rng)
        h = h * jax.random.bernoulli(k, 1 - dropout_in, h.shape) \
            / (1 - dropout_in)
    for i, (W, b) in enumerate(params[:-1]):
        z = mm(h, W) + b
        z = z.reshape(z.shape[0], -1, 2).max(axis=2) if maxout else act(z)
        dr = dropout_hidden[i] if i < len(dropout_hidden) else 0.0
        if not deterministic and dr > 0:
            rng, k = jax.random.split(rng)
            z = z * jax.random.bernoulli(k, 1 - dr, z.shape) / (1 - dr)
        h = z
    W, b = params[-1]
    return mm(h, W) + b


def _build_train_steps(activation: str, dropout_in: float, dropout_h: tuple,
                       loss_kind: str, is_cls: bool, autoenc: bool,
                       out_dim: int, l1: float, l2: float, opt_cfg: tuple,
                       batch: int, steps_per_iter: int, n: int,
                       custom_loss=None, compute_dtype=None):
    """Build the compiled training-interval program (see _make_train_steps
    for the caching story; ``custom_loss`` bypasses the cache)."""

    def forward(params, X, rng):
        return _forward_pass(activation, params, X, deterministic=False,
                             rng=rng, dropout_in=dropout_in,
                             dropout_hidden=dropout_h,
                             compute_dtype=compute_dtype)

    def loss_fn(params, xb, yb, wb, key):
        logits = forward(params, xb, key)
        if custom_loss is not None:
            pred = logits if (is_cls or autoenc) else logits[:, 0]
            per = custom_loss(pred, xb if autoenc else yb)
        elif autoenc:
            per = jnp.mean((logits - xb) ** 2, axis=1)
        elif is_cls:
            yi = jnp.clip(yb.astype(jnp.int32), 0, out_dim - 1)
            per = optax.softmax_cross_entropy_with_integer_labels(logits, yi)
        elif loss_kind == "absolute":
            per = jnp.abs(logits[:, 0] - yb)
        elif loss_kind == "huber":
            per = optax.huber_loss(logits[:, 0], yb, delta=1.0)
        else:
            per = (logits[:, 0] - yb) ** 2
        loss = jnp.sum(per * wb) / jnp.maximum(jnp.sum(wb), 1e-12)
        if l2 > 0 or l1 > 0:
            for W, _ in params:
                loss = loss + l2 * jnp.sum(W * W) + l1 * jnp.sum(jnp.abs(W))
        return loss

    kind, *hp = opt_cfg
    if kind == "adadelta":
        tx = optax.adadelta(learning_rate=1.0, rho=hp[0], eps=hp[1])
    elif kind == "sgd_momentum":
        tx = optax.sgd(hp[0], momentum=hp[1])
    else:
        tx = optax.sgd(hp[0])

    def sgd_step(X, y, w, carry, key):
        params, opt_state = carry
        k1, k2 = jax.random.split(key)
        # random-offset contiguous block instead of a per-row gather: a
        # [batch]-row gather from a big table runs ~40M rows/s on TPU
        # (PROFILE.md "small-table gathers are poison") and capped training
        # at ~300k samples/s; dynamic_slice streams at HBM rate.  The rows
        # were permuted once up front (shuffle_training_data) and the
        # arrays carry a wraparound copy of the first `batch` rows
        # (_extend_for_blocks), so offsets draw uniformly over [0, n) and
        # every row has identical inclusion probability (a [0, n-batch]
        # range would under-sample both array ends by up to batch x).
        off = jax.random.randint(k1, (), 0, max(n, 1))
        xb = jax.lax.dynamic_slice_in_dim(X, off, batch, axis=0)
        yb = jax.lax.dynamic_slice_in_dim(y, off, batch, axis=0)
        wb = jax.lax.dynamic_slice_in_dim(w, off, batch, axis=0)
        loss, grads = jax.value_and_grad(loss_fn)(params, xb, yb, wb, k2)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    @jax.jit
    def train_steps(params, opt_state, rng0, it, X, y, w):
        # keys derive in-jit from (rng0, iteration): eager jax.random ops
        # in the driver loop cost a ~50 ms round trip each on a tunnelled
        # backend (measured round 4)
        keys = jax.random.split(jax.random.fold_in(rng0, it), steps_per_iter)
        (params, opt_state), losses = jax.lax.scan(
            functools.partial(sgd_step, X, y, w), (params, opt_state), keys)
        return params, opt_state, jnp.mean(losses)

    return train_steps, tx


@functools.lru_cache(maxsize=None)
def _shuffle_fn(n: int, padded: int):
    """One compiled row-permutation program per (n, padded) geometry."""
    @jax.jit
    def sh(X, y, w, key):
        perm = jax.random.permutation(key, n)
        idx = jnp.concatenate([perm, jnp.arange(n, padded)])
        return (jnp.take(X, idx, axis=0), jnp.take(y, idx),
                jnp.take(w, idx))
    return sh


@functools.lru_cache(maxsize=None)
def _extend_fn(n: int, batch: int):
    """Append a wraparound copy of the first `batch` rows so the block
    sampler's dynamic_slice at any offset in [0, n) stays in bounds."""
    @jax.jit
    def ext(X, y, w):
        return (jnp.concatenate([X[:n], X[:batch]], axis=0),
                jnp.concatenate([y[:n], y[:batch]]),
                jnp.concatenate([w[:n], w[:batch]]))
    return ext


@functools.lru_cache(maxsize=None)
def _make_train_steps(activation: str, dropout_in: float, dropout_h: tuple,
                      loss_kind: str, is_cls: bool, autoenc: bool,
                      out_dim: int, l1: float, l2: float, opt_cfg: tuple,
                      batch: int, steps_per_iter: int, n: int,
                      compute_dtype=None):
    """Compiled training-interval program, CACHED ACROSS train() calls.

    The per-call ``@jax.jit def train_steps`` pattern recompiled (and paid
    the remote backend's multi-second first-execution penalty) on every
    train() — bench.py's warmup model compiled a program the timed model
    then could not reuse (measured on chip: the timed MNIST run spent most
    of its wall clock there, reporting 2.7k samples/s).  Everything the
    program closes over is reconstructed from hashable config; the data
    (X, y, w) are traced arguments, so any same-shaped training run reuses
    the executable.  Returns (train_steps, tx).
    """
    return _build_train_steps(activation, dropout_in, dropout_h, loss_kind,
                              is_cls, autoenc, out_dim, l1, l2, opt_cfg,
                              batch, steps_per_iter, n,
                              compute_dtype=compute_dtype)


def _activation_fn(name: str):
    base = name.replace("_with_dropout", "")
    if base == "tanh":
        return jnp.tanh
    if base == "rectifier":
        return jax.nn.relu
    if base == "maxout":
        return None                      # handled specially (pairwise max)
    raise ValueError(f"unknown activation {name!r}")


class DeepLearningModel(Model):
    algo = "deeplearning"

    def _forward(self, params, X, deterministic=True, rng=None,
                 dropout_in=0.0, dropout_hidden=()):
        return _forward_pass(self.params.activation, params, X,
                             deterministic=deterministic, rng=rng,
                             dropout_in=dropout_in,
                             dropout_hidden=tuple(dropout_hidden))

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        params = [(jnp.asarray(W), jnp.asarray(b))
                  for W, b in self.output["weights"]]
        logits = self._forward(params, X)
        if self.params.autoencoder:
            return logits
        if self.datainfo.is_classifier:
            return jax.nn.softmax(logits, axis=1)
        mu = logits[:, 0]
        if self.datainfo.standardize:
            mu = mu * self.datainfo.response_sigma + self.datainfo.response_mean
        return mu

    def predict(self, frame: Frame) -> Frame:
        if not self.params.autoencoder:
            return super().predict(frame)
        # autoencoder predict = per-design-column reconstruction, named and
        # un-scaled like the reference (DeepLearningModel.scoreAutoEncoder
        # reverses standardization and names columns reconstr_<coef>)
        from ..frame.vec import Vec, T_NUM, T_CAT
        di = self.datainfo
        R = np.asarray(self._predict_raw(
            di.make_matrix(frame)))[: frame.nrows].astype(np.float64)
        if di.standardize:
            for s in di.specs:
                if s.type != T_CAT:
                    R[:, s.offset] = R[:, s.offset] * s.sigma + s.mean
        cnames = di.coef_names
        names, vecs = [], []
        for j in range(R.shape[1]):
            cn = cnames[j] if j < len(cnames) else str(j)
            names.append(f"reconstr_{cn}")
            vecs.append(Vec.from_numpy(R[:, j], T_NUM))
        return Frame(names, vecs)

    def anomaly(self, frame: Frame) -> Frame:
        """Autoencoder per-row reconstruction MSE (DL anomaly detection)."""
        from ..frame.vec import Vec, T_NUM
        di = self.datainfo
        X = di.make_matrix(frame)
        R = self._predict_raw(X)
        err = np.asarray(jnp.mean((R - X) ** 2, axis=1))[: frame.nrows]
        return Frame(["Reconstruction.MSE"], [Vec.from_numpy(err, T_NUM)])


class DeepLearning(ModelBuilder):
    algo = "deeplearning"
    model_class = DeepLearningModel

    def __init__(self, params: Optional[DeepLearningParameters] = None, **kw):
        super().__init__(params or DeepLearningParameters(**kw))
        self.supervised = not self.params.autoencoder

    def _init_params(self, rng, sizes: List[int], maxout: bool):
        p = self.params
        params = []
        keys = jax.random.split(rng, len(sizes) - 1)
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            units = fan_out * (2 if maxout and i < len(sizes) - 2 else 1)
            if p.initial_weight_distribution == "uniform_adaptive":
                # reference's UniformAdaptive: +-sqrt(6/(fan_in+fan_out))
                scale = math.sqrt(6.0 / (fan_in + units))
                W = jax.random.uniform(keys[i], (fan_in, units), jnp.float32,
                                       -scale, scale)
            elif p.initial_weight_distribution == "normal":
                W = p.initial_weight_scale * jax.random.normal(
                    keys[i], (fan_in, units), jnp.float32)
            else:
                W = jax.random.uniform(keys[i], (fan_in, units), jnp.float32,
                                       -p.initial_weight_scale,
                                       p.initial_weight_scale)
            params.append((W, jnp.zeros(units, jnp.float32)))
        return params

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> DeepLearningModel:
        p: DeepLearningParameters = self.params
        X = di.make_matrix(frame)
        n = frame.nrows
        is_cls = di.is_classifier and not p.autoencoder
        if p.autoencoder:
            y = jnp.zeros(X.shape[0], jnp.float32)
            out_dim = X.shape[1]
        elif is_cls:
            y = di.response(frame)
            out_dim = di.nclasses
        else:
            y = di.response(frame)
            if di.standardize:
                y = (y - di.response_mean) / di.response_sigma
            y = jnp.nan_to_num(y)
            out_dim = 1
        w = di.weights(frame)

        maxout = p.activation.startswith("maxout")
        sizes = [X.shape[1], *p.hidden, out_dim]
        seed = p.effective_seed()
        rng = jax.random.PRNGKey(seed)
        rng, k0 = jax.random.split(rng)
        model = DeepLearningModel(job.dest_key or dkv.make_key(self.algo),
                                  p, di)
        params = self._init_params(k0, sizes, maxout)
        if p.checkpoint:
            prior = dkv.get(p.checkpoint)
            if prior is None:
                raise ValueError(f"checkpoint {p.checkpoint!r} not found")
            params = [(jnp.asarray(W), jnp.asarray(b))
                      for W, b in prior.output["weights"]]

        if p.adaptive_rate:
            opt_cfg = ("adadelta", p.rho, p.epsilon)
        elif p.momentum_stable > 0 or p.momentum_start > 0:
            opt_cfg = ("sgd_momentum", p.rate,
                       p.momentum_stable or p.momentum_start)
        else:
            opt_cfg = ("sgd", p.rate)

        loss_kind = p.loss
        if loss_kind == "automatic":
            loss_kind = "cross_entropy" if is_cls else "quadratic"
        dropout_h = tuple(p.hidden_dropout_ratios or ())
        if p.activation.endswith("_with_dropout") and not dropout_h:
            dropout_h = tuple(0.5 for _ in p.hidden)

        batch = min(p.mini_batch_size, n)
        X0 = X                      # unshuffled view for final scoring
        if p.shuffle_training_data:
            rng, ks = jax.random.split(rng)
            X, y, w = _shuffle_fn(n, X.shape[0])(X, y, w, ks)
        X, y, w = _extend_fn(n, batch)(X, y, w)
        cd = jnp.bfloat16 if p.precision == "bf16" else None

        # iteration sizing: train_samples_per_iteration semantics
        tspi = p.train_samples_per_iteration
        if tspi in (-1, 0):
            samples_per_iter = n
        elif tspi == -2:
            samples_per_iter = max(n // 10, batch * 16)   # auto-tune analog
        else:
            samples_per_iter = max(int(tspi), batch)
        total_samples = int(p.epochs * n)
        steps_per_iter = max(samples_per_iter // batch, 1)
        n_iters = max(total_samples // (steps_per_iter * batch), 1)

        if p.custom_loss_func is None:
            # cached across train() calls: same architecture/config/shapes
            # reuse one executable (no recompile, no first-exec penalty)
            train_steps, tx = _make_train_steps(
                p.activation, p.input_dropout_ratio, dropout_h, loss_kind,
                is_cls, p.autoencoder, out_dim, p.l1, p.l2, opt_cfg,
                batch, steps_per_iter, n, compute_dtype=cd)
        else:
            # custom python loss: not hashable — same builder, uncached
            train_steps, tx = _build_train_steps(
                p.activation, p.input_dropout_ratio, dropout_h, loss_kind,
                is_cls, p.autoencoder, out_dim, p.l1, p.l2, opt_cfg,
                batch, steps_per_iter, n, custom_loss=p.custom_loss_func,
                compute_dtype=cd)

        opt_state = tx.init(params)
        # Commit params/opt_state to the replicated sharding explicitly:
        # the jit executable cache keys on input sharding+committedness, and
        # fresh eager arrays ("unspecified") vs committed arrays from a
        # previous run's outputs would compile TWO executables for the same
        # program (measured: a 5.7 s recompile inside bench.py's timed DL
        # run, while the warmup had compiled the other variant).
        from jax.sharding import NamedSharding, PartitionSpec
        from ..runtime.cluster import cluster
        rep = NamedSharding(cluster().mesh, PartitionSpec())
        params = jax.device_put(params, rep)
        opt_state = jax.device_put(opt_state, rep)

        # Per-iteration host fetches of the mean loss cost a full round
        # trip each on a remote-tunnelled accelerator and starved the MXU
        # at ~3k samples/s (PROFILE.md).  Dispatch stays per-iteration
        # (async — XLA pipelines the queued steps; cancellation and fault
        # injection keep their per-iteration semantics), but the loss is
        # only FETCHED per iteration when early stopping needs it on host;
        # otherwise the whole history is one fetch at the end.
        history = []
        device_losses = []
        seen = 0
        import time as _time
        t0 = _time.time()
        from ..runtime import failure, scheduler
        stopped_at = n_iters
        for it in range(n_iters):
            failure.maybe_inject("dl_iter")
            # per-iteration device-lease yield (tree drivers yield at
            # chunk boundaries): co-resident jobs interleave here
            scheduler.DEVICE_LEASE.yield_turn()
            params, opt_state, mean_loss = train_steps(params, opt_state,
                                                       rng, it, X, y, w)
            seen += steps_per_iter * batch
            # progress snapshot: weights-so-far + remaining-epochs cursor;
            # resume() restores weights via the checkpoint path and trains
            # only the remaining epochs (throttled/async/best-effort)
            from ..runtime import snapshot as _snapshot
            _snapshot.maybe_snapshot(
                job, model,
                {"epochs_done": seen / n, "iteration": it,
                 "resume_params": {
                     "epochs": max(p.epochs - seen / n, 1e-3)}},
                lambda ps=params: {
                    "weights": [(np.asarray(W), np.asarray(b))
                                for W, b in ps],
                    "epochs_trained": seen / n,
                    "samples_trained": seen})
            if p.stopping_rounds:
                entry = {"iteration": it, "epochs": seen / n,
                         "samples": seen, "training_loss": float(mean_loss),
                         "samples_per_sec": seen / max(_time.time() - t0,
                                                       1e-9)}
                history.append(entry)
                job.update((it + 1) / n_iters,
                           f"epoch {seen / n:.2f} "
                           f"loss {float(mean_loss):.5f}")
                if stop_early(
                        [h["training_loss"] for h in history],
                        p.stopping_rounds, p.stopping_tolerance,
                        maximize=False):
                    stopped_at = it + 1
                    break
            else:
                device_losses.append(mean_loss)       # device scalar only
                job.update((it + 1) / n_iters, f"epoch {seen / n:.2f}")
        if not p.stopping_rounds and device_losses:
            # batched device_get: one prefetch pass, no per-n_iters
            # jnp.stack program compile
            iter_losses = np.asarray(jax.device_get(device_losses))
            dt = max(_time.time() - t0, 1e-9)
            seen = 0
            for it in range(stopped_at):
                seen += steps_per_iter * batch
                history.append({
                    "iteration": it, "epochs": seen / n, "samples": seen,
                    "training_loss": float(iter_losses[it]),
                    "samples_per_sec": seen / (dt * (it + 1) / stopped_at)})

        model.output["weights"] = [(np.asarray(W), np.asarray(b))
                                   for W, b in params]
        model.output["epochs_trained"] = seen / n
        model.output["samples_trained"] = seen
        model.scoring_history = history
        if not p.autoencoder:
            raw = model._predict_raw(X0)
            yy = di.response(frame) if is_cls else jnp.nan_to_num(di.response(frame))
            model.training_metrics = make_metrics(di, raw, yy, di.weights(frame))
            if valid is not None:
                model.validation_metrics = model.model_performance(valid)
        return model
