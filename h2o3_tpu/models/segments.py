"""Segment models: train one model per data segment.

Reference: ``hex/segments/SegmentModels.java:18`` + ``SegmentModelsBuilder``
(h2o.train_segments in h2o-py): partition the frame by the segment
columns' value tuples and run the same builder spec on every partition,
collecting per-segment models and statuses.

TPU-native redesign: segments are discovered with the device group-by
dense-rank, rows move with the device filter; segments train sequentially
on the full mesh (each segment's training is itself data-parallel over all
chips, which beats one-chip-per-segment for the common few-large-segments
case; trivially switchable to mesh-slice parallelism later).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.observability import record


@dataclasses.dataclass
class SegmentResult:
    segment: dict
    model_key: Optional[str]
    status: str                  # SUCCEEDED | FAILED
    error: Optional[str] = None
    nrows: int = 0


class SegmentModels:
    """Result container — hex/segments/SegmentModels.java analog."""

    def __init__(self, key: str, results: List[SegmentResult]):
        self.key = key
        self.results = results
        dkv.put(key, self)

    def as_frame(self) -> Frame:
        cols: Dict[str, np.ndarray] = {}
        segs = [r.segment for r in self.results]
        for name in segs[0]:
            cols[name] = np.asarray([s[name] for s in segs], dtype=object)
        cols["model"] = np.asarray(
            [r.model_key or "" for r in self.results], dtype=object)
        cols["status"] = np.asarray([r.status for r in self.results],
                                    dtype=object)
        cols["errors"] = np.asarray([r.error or "" for r in self.results],
                                    dtype=object)
        return Frame.from_numpy(cols)

    def model(self, **segment) -> object:
        for r in self.results:
            if all(str(r.segment.get(k)) == str(v)
                   for k, v in segment.items()):
                if r.model_key is None:
                    raise KeyError(f"segment {segment} failed: {r.error}")
                return dkv.get(r.model_key)
        raise KeyError(f"no segment {segment}")


def train_segments(builder_factory: Callable[[], object], frame: Frame,
                   segment_columns: Union[str, Sequence[str]],
                   segments: Optional[Frame] = None,
                   valid: Optional[Frame] = None) -> SegmentModels:
    """h2o.train_segments analog.

    ``builder_factory`` returns a FRESH builder per segment (builders hold
    per-run state); ``segments`` optionally restricts to listed tuples.
    """
    from ..rapids import ops
    segment_columns = [segment_columns] if isinstance(segment_columns, str) \
        else list(segment_columns)
    uniq = ops.group_by(frame, segment_columns,
                        {frame.names[0]: ["count"]})
    wanted: Optional[set] = None
    if segments is not None:
        wanted = set()
        cols = [segments.vec(c).decoded() for c in segment_columns]
        for i in range(segments.nrows):
            wanted.add(tuple(str(c[i]) for c in cols))

    results: List[SegmentResult] = []
    seg_cols = [uniq.vec(c).decoded() for c in segment_columns]
    for i in range(uniq.nrows):
        seg = {c: seg_cols[j][i] for j, c in enumerate(segment_columns)}
        if wanted is not None and \
                tuple(str(v) for v in seg.values()) not in wanted:
            continue
        mask = np.ones(frame.nrows, bool)
        for c, v in seg.items():
            col = frame.vec(c).decoded()
            mask &= np.asarray([str(x) == str(v) for x in col])
        sub = ops.filter_rows(frame, mask)
        sub_valid = None
        if valid is not None:
            vmask = np.ones(valid.nrows, bool)
            for c, v in seg.items():
                col = valid.vec(c).decoded()
                vmask &= np.asarray([str(x) == str(v) for x in col])
            if vmask.any():
                sub_valid = ops.filter_rows(valid, vmask)
        try:
            b = builder_factory()
            m = b.train(sub.drop(segment_columns), sub_valid.drop(
                segment_columns) if sub_valid is not None else None)
            results.append(SegmentResult(seg, m.key, "SUCCEEDED",
                                         nrows=sub.nrows))
            record("segment_trained", segment=str(seg), model=m.key)
        except Exception as e:                          # noqa: BLE001
            results.append(SegmentResult(seg, None, "FAILED", repr(e),
                                         nrows=sub.nrows))
    return SegmentModels(dkv.make_key("segment_models"), results)
