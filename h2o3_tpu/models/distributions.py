"""Loss distributions for gradient boosting / deep learning.

Reference: ``hex/Distribution.java`` + ``hex/LinkFunction.java`` — per-family
gradient ("pseudo-residual"), Newton denominators for leaf fitting
(gbm/GBM.java fitBestConstants:534), initial prediction, and inverse link.

TPU-native redesign: each distribution exposes vectorized (grad, hess) of the
loss w.r.t. the raw score F(x) — one fused elementwise pass feeding the
histogram kernel; leaf values become the Newton step -G/(H+lambda), which
reproduces the reference's per-distribution leaf-fit formulas (e.g. bernoulli
sum(resid)/sum(p(1-p))).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Distribution:
    name = "gaussian"

    def init_score(self, y, w):
        """Initial raw score F0 (the reference's initial prediction)."""
        return jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12)

    def grad_hess(self, y, f):
        """d loss/d f and d2 loss/d f2 per row (negative gradient is the
        pseudo-residual)."""
        return f - y, jnp.ones_like(f)

    def linkinv(self, f):
        return f

    def deviance(self, y, f, w):
        return jnp.sum(w * (y - f) ** 2)


class Gaussian(Distribution):
    pass


class Bernoulli(Distribution):
    name = "bernoulli"

    def init_score(self, y, w):
        p = jnp.clip(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12),
                     1e-6, 1 - 1e-6)
        return jnp.log(p / (1 - p))

    def grad_hess(self, y, f):
        p = jax.nn.sigmoid(f)
        return p - y, jnp.maximum(p * (1 - p), 1e-10)

    def linkinv(self, f):
        return jax.nn.sigmoid(f)

    def deviance(self, y, f, w):
        p = jnp.clip(jax.nn.sigmoid(f), 1e-15, 1 - 1e-15)
        return -2 * jnp.sum(w * (y * jnp.log(p) + (1 - y) * jnp.log1p(-p)))


class Poisson(Distribution):
    name = "poisson"

    def init_score(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.log(m)

    def grad_hess(self, y, f):
        mu = jnp.exp(jnp.clip(f, -30, 30))
        return mu - y, mu

    def linkinv(self, f):
        return jnp.exp(jnp.clip(f, -30, 30))

    def deviance(self, y, f, w):
        mu = self.linkinv(f)
        t = jnp.where(y > 0, y * jnp.log(y / jnp.maximum(mu, 1e-15)), 0.0)
        return 2 * jnp.sum(w * (t - (y - mu)))


class Gamma(Distribution):
    name = "gamma"

    def init_score(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.log(m)

    def grad_hess(self, y, f):
        mu = jnp.exp(jnp.clip(f, -30, 30))
        return 1.0 - y / jnp.maximum(mu, 1e-15), y / jnp.maximum(mu, 1e-15)

    def linkinv(self, f):
        return jnp.exp(jnp.clip(f, -30, 30))

    def deviance(self, y, f, w):
        mu = jnp.maximum(self.linkinv(f), 1e-15)
        ys = jnp.maximum(y, 1e-15)
        return 2 * jnp.sum(w * (-jnp.log(ys / mu) + (ys - mu) / mu))


class Tweedie(Distribution):
    name = "tweedie"

    def __init__(self, p: float = 1.5):
        self.p = float(p)

    def init_score(self, y, w):
        m = jnp.maximum(jnp.sum(w * y) / jnp.maximum(jnp.sum(w), 1e-12), 1e-6)
        return jnp.log(m)

    def grad_hess(self, y, f):
        p = self.p
        f = jnp.clip(f, -30, 30)
        grad = jnp.exp(f * (2 - p)) - y * jnp.exp(f * (1 - p))
        hess = (2 - p) * jnp.exp(f * (2 - p)) - (1 - p) * y * jnp.exp(f * (1 - p))
        return grad, jnp.maximum(hess, 1e-10)

    def linkinv(self, f):
        return jnp.exp(jnp.clip(f, -30, 30))


class Laplace(Distribution):
    name = "laplace"

    def init_score(self, y, w):
        return jnp.nanmedian(jnp.where(w > 0, y, jnp.nan))

    def grad_hess(self, y, f):
        return jnp.sign(f - y), jnp.ones_like(f)

    def deviance(self, y, f, w):
        return jnp.sum(w * jnp.abs(y - f))


class Quantile(Distribution):
    name = "quantile"

    def __init__(self, alpha: float = 0.5):
        self.alpha = float(alpha)

    def init_score(self, y, w):
        return jnp.nanquantile(jnp.where(w > 0, y, jnp.nan), self.alpha)

    def grad_hess(self, y, f):
        g = jnp.where(y >= f, -self.alpha, 1 - self.alpha)
        return g, jnp.ones_like(f)

    def deviance(self, y, f, w):
        e = y - f
        return jnp.sum(w * jnp.where(e >= 0, self.alpha * e,
                                     (self.alpha - 1) * e))


class Huber(Distribution):
    name = "huber"

    def __init__(self, delta: float = 0.9):
        self.delta = float(delta)   # reference huber_alpha quantile analog

    def grad_hess(self, y, f):
        e = f - y
        d = self.delta
        g = jnp.where(jnp.abs(e) <= d, e, d * jnp.sign(e))
        return g, jnp.ones_like(f)

    def deviance(self, y, f, w):
        e = jnp.abs(y - f)
        d = self.delta
        return jnp.sum(w * jnp.where(e <= d, 0.5 * e * e, d * (e - 0.5 * d)))


class Multinomial(Distribution):
    """Handled specially by GBM (K trees/iteration on softmax grads)."""
    name = "multinomial"


class CustomDistribution(Distribution):
    """User-supplied loss — the water/udf/CDistributionFunc analog.

    The reference ships custom distribution UDFs to the cluster as
    uploaded code (DkvClassLoader); here the cluster is SPMD so a plain
    Python object works.  Provide ``grad_hess(y, f) -> (g, h)`` (or just
    ``gradient(y, f)``; unit Hessian assumed), plus optional
    ``linkinv(f)``, ``init_score(y, w)``, ``deviance(y, f, w)`` — all
    jittable elementwise math, mirroring this module's protocol.
    """

    name = "custom"

    def __init__(self, fn):
        if not (hasattr(fn, "grad_hess") or hasattr(fn, "gradient")):
            raise ValueError(
                "custom_distribution_func needs grad_hess(y, f) or "
                "gradient(y, f)")
        self.fn = fn

    def init_score(self, y, w):
        if hasattr(self.fn, "init_score"):
            return self.fn.init_score(y, w)
        return super().init_score(y, w)

    def grad_hess(self, y, f):
        if hasattr(self.fn, "grad_hess"):
            return self.fn.grad_hess(y, f)
        g = self.fn.gradient(y, f)
        return g, jnp.ones_like(f)

    def linkinv(self, f):
        if hasattr(self.fn, "linkinv"):
            return self.fn.linkinv(f)
        return f

    def deviance(self, y, f, w):
        if hasattr(self.fn, "deviance"):
            return self.fn.deviance(y, f, w)
        return super().deviance(y, f, w)


def make_distribution(name: str, nclasses: int = 1, **kw) -> Distribution:
    custom = kw.get("custom_distribution_func")
    if custom is not None:
        return CustomDistribution(custom)
    name = (name or "auto").lower()
    if name == "custom":
        raise ValueError(
            "distribution='custom' requires custom_distribution_func")
    if name == "auto":
        if nclasses == 2:
            return Bernoulli()
        if nclasses > 2:
            return Multinomial()
        return Gaussian()
    if name == "tweedie":
        return Tweedie(kw.get("tweedie_power", 1.5))
    if name == "quantile":
        return Quantile(kw.get("quantile_alpha", 0.5))
    if name == "huber":
        return Huber(kw.get("huber_alpha", 0.9))
    return {"gaussian": Gaussian, "bernoulli": Bernoulli,
            "binomial": Bernoulli, "poisson": Poisson, "gamma": Gamma,
            "laplace": Laplace, "multinomial": Multinomial}[name]()
