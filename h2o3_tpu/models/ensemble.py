"""Stacked Ensembles: metalearner over base-model holdout predictions.

Reference: ``hex/ensemble/StackedEnsemble.java:38`` — base models trained
with common nfolds + keep_cross_validation_predictions supply the level-one
frame (their CV holdout predictions); a metalearner (GLM default, or
GBM/DRF/DeepLearning) is trained on it; ``blending_frame`` switches to
holdout blending instead of CV stacking.

TPU-native redesign: the level-one "frame" is a small dense matrix assembled
host-side from each base model's holdout predictions; the metalearner is any
ModelBuilder in this package, trained as usual on the sharded level-one
design.  Ensemble scoring chains two compiled passes (base batch predict →
metalearner predict)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Union

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class StackedEnsembleParameters(Parameters):
    base_models: Sequence[Union[str, Model]] = ()
    metalearner_algorithm: str = "auto"     # auto|glm|gbm|drf|deeplearning
    metalearner_params: Optional[dict] = None
    metalearner_nfolds: int = 0
    blending_frame: Optional[Frame] = None


def _resolve(m: Union[str, Model]) -> Model:
    if isinstance(m, Model):
        return m
    got = dkv.get(m)
    if got is None:
        raise KeyError(f"base model {m!r} not found in DKV")
    return got


def _base_columns(model: Model, raw: np.ndarray) -> List[np.ndarray]:
    """Level-one columns contributed by one base model's raw predictions."""
    di = model.datainfo
    if di.is_classifier and di.nclasses == 2:
        return [raw[:, 1]]                       # p(positive)
    if di.is_classifier:
        return [raw[:, k] for k in range(di.nclasses)]
    return [raw.reshape(len(raw))]


class StackedEnsembleModel(Model):
    algo = "stackedensemble"

    def _level_one(self, frame: Frame) -> Frame:
        cols = {}
        for key in self.output["base_model_keys"]:
            bm = _resolve(key)
            X = bm._score_matrix(frame)
            raw = np.asarray(bm._predict_raw(X))[: frame.nrows]
            raw = raw.reshape(frame.nrows, -1)
            for i, col in enumerate(_base_columns(bm, raw)):
                cols[f"{key}_p{i}"] = col
        lf = Frame.from_numpy(cols)
        resp = self.params.response_column
        if resp in frame.names:
            # carry the response through unchanged (keeps cat identity)
            lf = Frame(lf.names + [resp], lf.vecs + [frame.vec(resp)])
        return lf

    def _predict_raw(self, X):
        raise NotImplementedError("ensemble scores via its base models")

    def predict(self, frame: Frame) -> Frame:
        meta = _resolve(self.output["metalearner_key"])
        return meta.predict(self._level_one(frame))

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        meta = _resolve(self.output["metalearner_key"])
        return meta.model_performance(self._level_one(frame))


class StackedEnsemble(ModelBuilder):
    """SE builder — H2OStackedEnsembleEstimator analog."""

    algo = "stackedensemble"
    model_class = StackedEnsembleModel

    def __init__(self, params: Optional[StackedEnsembleParameters] = None,
                 **kw):
        super().__init__(params or StackedEnsembleParameters(**kw))

    def _make_metalearner(self, di: DataInfo) -> ModelBuilder:
        p: StackedEnsembleParameters = self.params
        algo = p.metalearner_algorithm
        mp = dict(p.metalearner_params or {})
        mp.setdefault("response_column", p.response_column)
        mp.setdefault("nfolds", p.metalearner_nfolds)
        mp.setdefault("seed", p.seed)
        if algo in ("auto", "glm"):
            from .glm import GLM
            mp.setdefault("lambda_", 1e-5)
            return GLM(**mp)
        if algo == "gbm":
            from .tree.gbm import GBM
            return GBM(**mp)
        if algo == "drf":
            from .tree.drf import DRF
            return DRF(**mp)
        if algo == "deeplearning":
            from .deeplearning import DeepLearning
            return DeepLearning(**mp)
        raise ValueError(f"unknown metalearner_algorithm {algo!r}")

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: StackedEnsembleParameters = self.params
        if not p.base_models:
            raise ValueError("stackedensemble requires base_models")
        if p.blending_frame is None:
            for m in p.base_models:
                bm = _resolve(m)
                if bm.cv_predictions is None:
                    raise ValueError(
                        f"base model {bm.key} has no CV holdout predictions; "
                        "train with nfolds>1 and "
                        "keep_cross_validation_predictions=True, or supply "
                        "a blending_frame")

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> StackedEnsembleModel:
        p: StackedEnsembleParameters = self.params
        base = [_resolve(m) for m in p.base_models]
        model = StackedEnsembleModel(
            job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["base_model_keys"] = [m.key for m in base]

        # level-one training matrix
        lf_frame = p.blending_frame if p.blending_frame is not None else frame
        cols = {}
        for bm in base:
            if p.blending_frame is not None:
                X = bm._score_matrix(lf_frame)
                raw = np.asarray(bm._predict_raw(X))[: lf_frame.nrows]
            else:
                raw = np.asarray(bm.cv_predictions)
            raw = raw.reshape(lf_frame.nrows, -1)
            for i, col in enumerate(_base_columns(bm, raw)):
                cols[f"{bm.key}_p{i}"] = col
        rv = lf_frame.vec(p.response_column)
        lone = Frame.from_numpy(cols)
        names = list(lone.names) + [p.response_column]
        vecs = list(lone.vecs) + [rv]
        lone = Frame(names, vecs)

        job.update(0.3, "training metalearner")
        meta_builder = self._make_metalearner(di)
        meta = meta_builder.train(lone)
        model.output["metalearner_key"] = meta.key
        model.output["metalearner_algo"] = meta.algo
        model.training_metrics = meta.training_metrics
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
