"""ANOVA GLM: Type-III sum-of-squares significance per predictor.

Reference: ``hex/anovaglm/ANOVAGLM.java`` — for each predictor, compare the
full GLM against the GLM with that predictor removed; the deviance
difference over its degrees of freedom gives the F statistic (gaussian)
or the likelihood-ratio chi-square (other families), with p-values from
the corresponding distribution.

TPU-native redesign: the leave-one-out refits reuse the device-resident
design columns; each fit is the standard jit-compiled IRLSM.  Pure host
control flow around compiled programs — same shape as ModelSelection.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .glm import GLM


@dataclasses.dataclass
class ANOVAGLMParameters(Parameters):
    family: str = "auto"
    alpha: float = 0.0
    lambda_: float = 0.0


class ANOVAGLMModel(Model):
    algo = "anovaglm"

    def result(self) -> Frame:
        rows = self.output["anova_table"]
        return Frame.from_numpy({
            "predictor": np.asarray([r["predictor"] for r in rows],
                                    dtype=object),
            "df": np.asarray([r["df"] for r in rows], np.float64),
            "sum_of_squares": np.asarray([r["ss"] for r in rows],
                                         np.float64),
            "mean_square": np.asarray([r["ms"] for r in rows], np.float64),
            "f_value": np.asarray([r["f"] for r in rows], np.float64),
            "p_value": np.asarray([r["p"] for r in rows], np.float64),
        })

    def _predict_raw(self, X):
        return dkv.get(self.output["full_model"])._predict_raw(X)


class ANOVAGLM(ModelBuilder):
    algo = "anovaglm"
    model_class = ANOVAGLMModel

    def __init__(self, params: Optional[ANOVAGLMParameters] = None, **kw):
        super().__init__(params or ANOVAGLMParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di, valid) -> ANOVAGLMModel:
        from scipy import stats as sstats
        p: ANOVAGLMParameters = self.params
        predictors = [s.name for s in di.specs]
        extra = [p.response_column] + ([p.weights_column]
                                       if p.weights_column else [])

        def fit(cols: List[str]):
            return GLM(response_column=p.response_column,
                       weights_column=p.weights_column,
                       family=p.family, alpha=p.alpha, lambda_=p.lambda_,
                       seed=p.effective_seed()).train(frame[cols + extra])

        full = fit(predictors)
        gaussian = not full.datainfo.is_classifier and \
            full.output.get("family", "gaussian") == "gaussian"
        n_obs = frame.nrows
        # residual deviance of the full model = SSE for gaussian
        dev_full = full.output["residual_deviance"]
        df_model_full = sum(s.width if s.type == "cat" else 1
                            for s in full.datainfo.specs)
        df_resid = max(n_obs - df_model_full - 1, 1)
        rows = []
        for i, name in enumerate(predictors):
            reduced = fit([c for c in predictors if c != name])
            dev_red = reduced.output["residual_deviance"]
            spec = next(s for s in di.specs if s.name == name)
            df = float(max(spec.width - 1, 1)) if spec.type == "cat" \
                else 1.0
            ss = max(dev_red - dev_full, 0.0)
            ms = ss / df
            if gaussian:
                f = ms / max(dev_full / df_resid, 1e-300)
                pv = float(sstats.f.sf(f, df, df_resid))
            else:
                # likelihood-ratio chi-square for non-gaussian families
                f = ss / df
                pv = float(sstats.chi2.sf(ss, df))
            rows.append({"predictor": name, "df": df, "ss": ss, "ms": ms,
                         "f": f, "p": pv})
            job.update((i + 1) / len(predictors), name)

        model = ANOVAGLMModel(job.dest_key or dkv.make_key(self.algo),
                              p, di)
        model.output["anova_table"] = rows
        model.output["full_model"] = full.key
        model.training_metrics = full.training_metrics
        return model
