"""Cox proportional hazards: Newton iterations with cumulative risk sets.

Reference: ``hex/coxph/CoxPH.java:28`` — partial-likelihood Newton with
Efron/Breslow tie handling; per-iteration MRTasks accumulate risk-set sums.

TPU-native redesign: rows sorted by survival time descending, so every risk
set is a prefix — the per-event sums S0 = sum(exp(eta)), S1 = sum(exp(eta)x),
S2 = sum(exp(eta)xx') become cumulative sums on device (one fused program
per Newton step); ties share the risk set via an inclusive tie boundary
(Breslow).  The [P, P] Newton solve runs on host.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class CoxPHParameters(Parameters):
    start_column: Optional[str] = None       # not yet supported
    stop_column: str = ""                    # survival time
    event_column: str = ""                   # 1 = event, 0 = censored
    ties: str = "breslow"
    max_iterations: int = 20
    standardize: bool = True


@jax.jit
def _cox_stats(X, event, tie_end, beta):
    """(neg log PL, gradient, hessian) with prefix-cumsum risk sets.

    Rows pre-sorted by time DESC; ``tie_end[i]`` = last index sharing
    row i's time (inclusive), so risk-set sums read the cumsum there.
    """
    eta = X @ beta
    eta = eta - jnp.max(eta)
    r = jnp.exp(eta)
    S0 = jnp.cumsum(r)
    S1 = jnp.cumsum(r[:, None] * X, axis=0)
    XX = X[:, :, None] * X[:, None, :]
    S2 = jnp.cumsum(r[:, None, None] * XX, axis=0)
    s0 = S0[tie_end]
    s1 = S1[tie_end]
    s2 = S2[tie_end]
    m = s1 / s0[:, None]
    ll = jnp.sum(event * (eta - jnp.log(s0)))
    grad = jnp.sum(event[:, None] * (X - m), axis=0)
    hess_i = s2 / s0[:, None, None] - m[:, :, None] * m[:, None, :]
    hess = jnp.sum(event[:, None, None] * hess_i, axis=0)
    return -ll, grad, hess


class CoxPHModel(Model):
    algo = "coxph"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        beta = jnp.asarray(self.output["beta_std"], jnp.float32)
        return X @ beta                       # linear predictor (log hazard)

    def predict(self, frame: Frame) -> Frame:
        X = self.datainfo.make_matrix(frame)
        lp = np.asarray(self._predict_raw(X))[: frame.nrows]
        return Frame(["lp"], [Vec.from_numpy(lp.astype(np.float64), T_NUM)])

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        return {"concordance": self._concordance(frame)}

    def _concordance(self, frame: Frame) -> float:
        p: CoxPHParameters = self.params
        lp = self.predict(frame).vecs[0].to_numpy()
        t = frame.vec(p.stop_column).to_numpy()
        e = frame.vec(p.event_column).to_numpy()
        num = den = 0
        ev = np.flatnonzero(e > 0)
        for i in ev:
            at_risk = t > t[i]
            den += at_risk.sum()
            num += (lp[i] > lp[at_risk]).sum() \
                + 0.5 * (lp[i] == lp[at_risk]).sum()
        return float(num / max(den, 1))


class CoxPH(ModelBuilder):
    """CoxPH builder — H2OCoxProportionalHazardsEstimator analog."""

    algo = "coxph"
    model_class = CoxPHModel
    supervised = False                       # its own response contract

    def __init__(self, params: Optional[CoxPHParameters] = None, **kw):
        super().__init__(params or CoxPHParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        p: CoxPHParameters = self.params
        if not p.stop_column or not p.event_column:
            raise ValueError("coxph requires stop_column and event_column")
        if p.ties != "breslow":
            raise ValueError(f"ties={p.ties!r} not implemented (breslow only)")
        if p.start_column is not None:
            raise ValueError("start_column (interval data) not yet supported")
        for c in (p.stop_column, p.event_column):
            if c not in frame.names:
                raise ValueError(f"column {c!r} not in frame")

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None,
            ignored_columns=list(p.ignored_columns) + [p.stop_column,
                                                       p.event_column],
            weights_column=p.weights_column, standardize=p.standardize,
            add_intercept=False,             # no intercept in Cox
            missing_values_handling=p.missing_values_handling)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> CoxPHModel:
        p: CoxPHParameters = self.params
        t = frame.vec(p.stop_column).to_numpy()
        e = frame.vec(p.event_column).to_numpy()
        ok = ~(np.isnan(t) | np.isnan(e))
        order = np.argsort(-t[ok], kind="stable")
        idx = np.flatnonzero(ok)[order]
        X_full = np.asarray(di.make_matrix(frame))[: frame.nrows]
        Xs = jnp.asarray(X_full[idx], jnp.float32)
        ts = t[idx]
        ev = jnp.asarray(e[idx], jnp.float32)
        # inclusive end of each tie block (time DESC -> ties contiguous)
        n = len(ts)
        tie_end = np.searchsorted(-ts, -ts, side="right") - 1
        tie_end = jnp.asarray(tie_end, jnp.int32)

        P = di.nfeatures
        if P > 64:
            raise ValueError(
                "coxph: >64 expanded features would make the cumulative "
                "S2 risk-set tensor (N x P x P) exceed HBM; reduce features")
        beta = np.zeros(P)
        nll_prev = np.inf
        for it in range(p.max_iterations):
            nll, grad, hess = _cox_stats(Xs, ev, tie_end,
                                         jnp.asarray(beta, jnp.float32))
            nll = float(nll)
            g = np.asarray(grad, np.float64)
            H = np.asarray(hess, np.float64)
            step = np.linalg.solve(H + 1e-8 * np.eye(P), g)
            beta = beta + step
            job.update((it + 1) / p.max_iterations,
                       f"iter={it} -logPL={nll:.5g}")
            if abs(nll_prev - nll) < 1e-9 * max(abs(nll), 1.0):
                break
            nll_prev = nll

        model = CoxPHModel(job.dest_key or dkv.make_key(self.algo), p, di)
        # de-standardized coefficients for reporting
        coef = beta.copy()
        ci = 0
        for s in di.specs:
            if s.width == 1 and di.standardize:
                coef[ci] = beta[ci] / s.sigma
            ci += s.width
        model.output.update({
            "beta_std": beta, "coef": dict(zip(di.coef_names, coef)),
            "neg_log_partial_likelihood": nll, "iterations": it + 1,
            "n_events": int(np.sum(e[ok] > 0)),
        })
        model.training_metrics = {
            "neg_log_partial_likelihood": nll,
            "concordance": model._concordance(frame)}
        return model
