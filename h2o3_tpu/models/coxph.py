"""Cox proportional hazards: Newton iterations with cumulative risk sets.

Reference: ``hex/coxph/CoxPH.java:28`` — partial-likelihood Newton with
Efron/Breslow tie handling, optional stratification (separate baseline
hazard per stratum), counting-process (start, stop] intervals, and
observation weights; per-iteration MRTasks accumulate risk-set sums.

TPU-native redesign: rows sorted by (stratum, time DESC) make every risk
set a stratum-local PREFIX, so the per-event sums S0 = sum(w e^eta),
S1 = sum(w e^eta x), S2 = sum(w e^eta xx') are cumulative sums read at the
tie boundary minus the stratum offset — one fused device program per
Newton step.  Counting-process data subtracts a second prefix (rows sorted
by start DESC) at a host-precomputed position: {start_j >= t} is a prefix
of that ordering.  Efron's tie correction uses segment sums over tie
groups (event-only sums t0/t1/t2 and within-group event ranks), all inside
the same program.  The [P, P] Newton solve runs on host.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM, T_CAT
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class CoxPHParameters(Parameters):
    start_column: Optional[str] = None       # counting-process entry time
    stop_column: str = ""                    # survival time
    event_column: str = ""                   # 1 = event, 0 = censored
    stratify_by: Optional[str] = None        # separate baseline per stratum
    ties: str = "efron"                      # efron | breslow (ref default)
    max_iterations: int = 20
    standardize: bool = True
    # covariate interactions (CoxPHModel.java:52-53 _interactions /
    # _interaction_pairs).  Combined with counting-process episodes
    # (start/stop rows + a period indicator) these express TIME-VARYING
    # coefficients: interact a covariate with the period factor and each
    # period gets its own hazard ratio.
    interactions: Optional[Sequence[str]] = None        # all pairs among
    interaction_pairs: Optional[Sequence] = None        # explicit (a, b)


@functools.partial(jax.jit, static_argnames=("efron", "use_start"))
def _cox_stats(X, w, event, tie_end, strat_first, gid, grank, gsize,
               perm2, bpos, bstart, beta, efron: bool, use_start: bool):
    """(neg log PL, gradient, hessian) via stratified prefix risk sets."""
    n, P = X.shape
    eta = X @ beta
    eta = eta - jnp.max(eta)
    r = w * jnp.exp(eta)
    rX = r[:, None] * X
    rXX = r[:, None, None] * (X[:, :, None] * X[:, None, :])

    def pref(a):
        c = jnp.cumsum(a, axis=0)
        cp = jnp.concatenate([jnp.zeros_like(a[:1]), c], axis=0)
        # stratum-local prefix ending at the tie boundary
        return cp[tie_end + 1] - cp[strat_first]

    S0, S1, S2 = pref(r), pref(rX), pref(rXX)
    if use_start:
        # subtract rows with start >= t: a STRATUM-LOCAL prefix of the
        # start-DESC ordering (bstart = stratum's offset in that ordering)
        def pref2(a):
            a2 = a[perm2]
            c = jnp.cumsum(a2, axis=0)
            cp = jnp.concatenate([jnp.zeros_like(a[:1]), c], axis=0)
            return cp[bpos] - cp[bstart]
        S0 = S0 - pref2(r)
        S1 = S1 - pref2(rX)
        S2 = S2 - pref2(rXX)

    ew = event * w
    if efron:
        nseg = n
        t0 = jax.ops.segment_sum(event * r, gid, num_segments=nseg)[gid]
        t1 = jax.ops.segment_sum(event[:, None] * rX, gid,
                                 num_segments=nseg)[gid]
        t2 = jax.ops.segment_sum(event[:, None, None] * rXX, gid,
                                 num_segments=nseg)[gid]
        frac = jnp.where(gsize > 0, grank / jnp.maximum(gsize, 1.0), 0.0)
        d0 = jnp.maximum(S0 - frac * t0, 1e-30)
        d1 = S1 - frac[:, None] * t1
        d2 = S2 - frac[:, None, None] * t2
    else:
        d0 = jnp.maximum(S0, 1e-30)
        d1, d2 = S1, S2

    m = d1 / d0[:, None]
    ll = jnp.sum(ew * (eta - jnp.log(d0)))
    grad = jnp.sum(ew[:, None] * (X - m), axis=0)
    hess_i = d2 / d0[:, None, None] - m[:, :, None] * m[:, None, :]
    hess = jnp.sum(ew[:, None, None] * hess_i, axis=0)
    return -ll, grad, hess


def _interaction_list(p: "CoxPHParameters") -> List[tuple]:
    pairs = [tuple(x) for x in (p.interaction_pairs or ())]
    if p.interactions:
        import itertools
        pairs += list(itertools.combinations(p.interactions, 2))
    return pairs


def expand_interactions(frame: Frame, pairs: Sequence[tuple]) -> Frame:
    """Add product columns for covariate interactions.

    num x num -> one ``a:b`` product column; cat x num -> one slope
    column per level (``cat.level:num`` — the per-level coefficients ARE
    the time-varying betas when the cat is a period indicator);
    cat x cat -> the crossed factor ``a_b``.
    """
    names, vecs = list(frame.names), list(frame.vecs)
    for a, b in pairs:
        va, vb = frame.vec(a), frame.vec(b)
        if va.type == T_CAT and vb.type == T_CAT:
            ca, cb = va.to_numpy(), vb.to_numpy()
            lb = len(vb.domain)
            codes = np.where((ca < 0) | (cb < 0), -1, ca * lb + cb)
            domain = [f"{x}_{y}" for x in va.domain for y in vb.domain]
            names.append(f"{a}_{b}")
            vecs.append(Vec.from_numpy(codes.astype(np.int32), T_CAT,
                                       domain=domain))
        elif va.type == T_CAT or vb.type == T_CAT:
            cat, num, cn, nn = (va, vb, a, b) if va.type == T_CAT \
                else (vb, va, b, a)
            codes = cat.to_numpy()
            x = np.nan_to_num(num.to_numpy())
            for li, lvl in enumerate(cat.domain):
                names.append(f"{cn}.{lvl}:{nn}")
                vecs.append(Vec.from_numpy(
                    np.where(codes == li, x, 0.0), T_NUM))
        else:
            names.append(f"{a}:{b}")
            vecs.append(Vec.from_numpy(
                np.nan_to_num(va.to_numpy())
                * np.nan_to_num(vb.to_numpy()), T_NUM))
    return Frame(names, vecs)


class CoxPHModel(Model):
    algo = "coxph"

    def _with_interactions(self, frame: Frame) -> Frame:
        pairs = [tuple(x) for x in
                 self.output.get("interaction_pairs", ())]
        if pairs and not all(
                (f"{a}:{b}" in frame.names or f"{a}_{b}" in frame.names
                 or any(n.startswith(f"{a}.") and n.endswith(f":{b}")
                        or n.startswith(f"{b}.") and n.endswith(f":{a}")
                        for n in frame.names))
                for a, b in pairs):
            return expand_interactions(frame, pairs)
        return frame

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        beta = jnp.asarray(self.output["beta_std"], jnp.float32)
        return X @ beta                       # linear predictor (log hazard)

    def predict(self, frame: Frame) -> Frame:
        frame = self._with_interactions(frame)
        X = self.datainfo.make_matrix(frame)
        lp = np.asarray(self._predict_raw(X))[: frame.nrows]
        return Frame(["lp"], [Vec.from_numpy(lp.astype(np.float64), T_NUM)])

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        return {"concordance": self._concordance(frame)}

    def _concordance(self, frame: Frame) -> float:
        from ..metrics.gainslift import concordance_index
        p: CoxPHParameters = self.params
        lp = self.predict(frame).vecs[0].to_numpy()
        t = frame.vec(p.stop_column).to_numpy()
        e = frame.vec(p.event_column).to_numpy()
        return concordance_index(t, e > 0, lp)


class CoxPH(ModelBuilder):
    """CoxPH builder — H2OCoxProportionalHazardsEstimator analog."""

    algo = "coxph"
    model_class = CoxPHModel
    supervised = False                       # its own response contract

    def __init__(self, params: Optional[CoxPHParameters] = None, **kw):
        super().__init__(params or CoxPHParameters(**kw))

    def train(self, frame: Frame, valid: Optional[Frame] = None):
        pairs = _interaction_list(self.params)
        if pairs:
            frame = expand_interactions(frame, pairs)
            if valid is not None:
                valid = expand_interactions(valid, pairs)
        return super().train(frame, valid)

    def _validate(self, frame: Frame) -> None:
        p: CoxPHParameters = self.params
        if not p.stop_column or not p.event_column:
            raise ValueError("coxph requires stop_column and event_column")
        if p.ties not in ("efron", "breslow"):
            raise ValueError(f"ties={p.ties!r}: efron|breslow")
        for c in (p.stop_column, p.event_column):
            if c not in frame.names:
                raise ValueError(f"column {c!r} not in frame")
        if p.start_column and p.start_column not in frame.names:
            raise ValueError(f"start column {p.start_column!r} not in frame")
        if p.stratify_by and p.stratify_by not in frame.names:
            raise ValueError(f"strata column {p.stratify_by!r} not in frame")

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        drop = [p.stop_column, p.event_column]
        if p.start_column:
            drop.append(p.start_column)
        if p.stratify_by:
            drop.append(p.stratify_by)
        return DataInfo.fit(
            frame, response_column=None,
            ignored_columns=list(p.ignored_columns) + drop,
            weights_column=p.weights_column, standardize=p.standardize,
            add_intercept=False,             # no intercept in Cox
            missing_values_handling=p.missing_values_handling)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> CoxPHModel:
        p: CoxPHParameters = self.params
        t = frame.vec(p.stop_column).to_numpy().astype(np.float64)
        e = frame.vec(p.event_column).to_numpy().astype(np.float64)
        start = frame.vec(p.start_column).to_numpy().astype(np.float64) \
            if p.start_column else None
        if p.stratify_by:
            sv = frame.vec(p.stratify_by)
            strat = sv.to_numpy() if sv.type == T_CAT else \
                np.unique(sv.to_numpy(), return_inverse=True)[1]
        else:
            strat = np.zeros(frame.nrows, np.int64)
        wcol = np.ones(frame.nrows)
        if p.weights_column and p.weights_column in frame.names:
            wcol = np.nan_to_num(frame.vec(p.weights_column).to_numpy())
        ok = ~(np.isnan(t) | np.isnan(e))
        if start is not None:
            ok &= ~np.isnan(start)
        rows = np.flatnonzero(ok)
        # sort by (stratum, -stop): strata contiguous, time DESC inside
        order = np.lexsort((-t[rows], strat[rows]))
        idx = rows[order]
        ts, es, ws = t[idx], e[idx], wcol[idx]
        ss = strat[idx]
        n = len(idx)
        X_full = np.asarray(di.make_matrix(frame))[: frame.nrows]
        Xs = jnp.asarray(X_full[idx], jnp.float32)

        # stratum boundaries + tie blocks within stratum (vectorized:
        # rows already sorted by (stratum, -time), so both are run-length
        # structures readable from boundary flags)
        new_strat = np.concatenate([[True], ss[1:] != ss[:-1]])
        strat_id = np.cumsum(new_strat) - 1
        strat_first = np.flatnonzero(new_strat)[strat_id]
        new_tie = new_strat | np.concatenate([[True], ts[1:] != ts[:-1]])
        gid = np.cumsum(new_tie) - 1
        group_last = np.concatenate([np.flatnonzero(new_tie)[1:] - 1,
                                     [n - 1]])
        tie_end = group_last[gid]
        # within-group event rank + group event count (Efron)
        ev = es > 0
        cum_ev = np.cumsum(ev)
        gstarts = np.flatnonzero(new_tie)
        ev_before = np.concatenate([[0], cum_ev[gstarts[1:] - 1]])[gid]
        grank = np.where(ev, cum_ev - 1 - ev_before, 0.0)
        gsize = (cum_ev[tie_end] - ev_before) * 1.0
        # counting-process second ordering (start DESC within stratum)
        use_start = start is not None
        if use_start:
            st = start[idx]
            perm2 = np.lexsort((-st, ss))
            st2 = st[perm2]
            ss2 = ss[perm2]
            # stratum offsets within the perm2 ordering
            uniq_s, s_starts = np.unique(ss2, return_index=True)
            lookup = dict(zip(uniq_s, s_starts))
            ends = dict(zip(uniq_s, np.append(s_starts[1:], n)))
            bstart = np.asarray([lookup[s] for s in ss], np.int64)
            bend = np.asarray([ends[s] for s in ss], np.int64)
            # cnt = #{start >= t_i} within stratum, vectorized per stratum
            bpos = np.zeros(n, np.int64)
            for s in uniq_s:
                lo, hi = lookup[s], ends[s]
                sel = ss == s
                bpos[sel] = lo + np.searchsorted(
                    -st2[lo:hi], -ts[sel], side="right")
        else:
            perm2 = np.zeros(n, np.int64)
            bpos = np.zeros(n, np.int64)
            bstart = np.zeros(n, np.int64)

        P = di.nfeatures
        if P > 64:
            raise ValueError(
                "coxph: >64 expanded features would make the cumulative "
                "S2 risk-set tensor (N x P x P) exceed HBM; reduce features")
        args = (jnp.asarray(ws, jnp.float32), jnp.asarray(es, jnp.float32),
                jnp.asarray(tie_end, jnp.int32),
                jnp.asarray(strat_first, jnp.int32),
                jnp.asarray(gid, jnp.int32), jnp.asarray(grank, jnp.float32),
                jnp.asarray(gsize, jnp.float32),
                jnp.asarray(perm2, jnp.int32), jnp.asarray(bpos, jnp.int32),
                jnp.asarray(bstart, jnp.int32))
        beta = np.zeros(P)
        nll = np.inf
        nll_prev = np.inf
        for it in range(p.max_iterations):
            nll, grad, hess = _cox_stats(
                Xs, *args, jnp.asarray(beta, jnp.float32),
                efron=p.ties == "efron", use_start=use_start)
            nll = float(nll)
            g2 = np.asarray(grad, np.float64)
            H = np.asarray(hess, np.float64)
            step = np.linalg.solve(H + 1e-8 * np.eye(P), g2)
            beta = beta + step
            job.update((it + 1) / p.max_iterations,
                       f"iter={it} -logPL={nll:.5g}")
            if abs(nll_prev - nll) < 1e-9 * max(abs(nll), 1.0):
                break
            nll_prev = nll

        model = CoxPHModel(job.dest_key or dkv.make_key(self.algo), p, di)
        # de-standardized coefficients for reporting
        coef = beta.copy()
        ci = 0
        for s in di.specs:
            if s.width == 1 and di.standardize:
                coef[ci] = beta[ci] / s.sigma
            ci += s.width
        model.output.update({
            "beta_std": beta, "coef": dict(zip(di.coef_names, coef)),
            "neg_log_partial_likelihood": nll, "iterations": it + 1,
            "n_events": int(np.sum(e[ok] > 0)), "ties": p.ties,
            "interaction_pairs": _interaction_list(p),
        })
        model.training_metrics = {
            "neg_log_partial_likelihood": nll,
            "concordance": model._concordance(frame)}
        return model
