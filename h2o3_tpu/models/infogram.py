"""Infogram / admissible ML — the h2o-admissibleml module analog.

Reference: ``h2o-admissibleml/src/main/java/hex/Infogram/Infogram.java:21``.

Two modes (Infogram.java:182 ``_buildCore``):

- **Core infogram** (no ``protected_columns``): for each predictor X_i a
  model is trained WITHOUT X_i; the last model uses ALL predictors
  (buildTrainingFrames, Infogram.java:538-563).  Net information raw_i =
  max(0, cmi_all − cmi_without_i), scaled by the max
  (InfogramUtils.calculateFinalCMI:213).  Relevance = full-model variable
  importance (extractRelevance:608).
- **Fair infogram** (``protected_columns`` set): model_i = protected ∪
  {X_i}; the last model uses protected columns only.  raw_i = max(0,
  cmi_i − cmi_protected).  Relevance comes from a model on all
  predictors MINUS the protected columns.

Raw CMI of a model = mean log2 predicted-probability of the TRUE class
over rows with positive probability/weight (EstimateCMI.java:29-38) — an
estimate of −H(y | features) whose differences estimate conditional
mutual information.

``admissible_index = sqrt((relevance² + cmi²)/2)`` (distance from the
ideal (1,1) corner's opposite origin, copyGenerateAdmissibleIndex:401);
a feature is *admissible* when both indices clear their thresholds.

TPU notes: the underlying models are this package's GBM/DRF/GLM — the
per-model work is the usual device pipeline; the infogram layer itself is
pure orchestration.  CMI evaluation is one fused device gather+log+mean.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo

_LOG2 = float(np.log(2.0))


@dataclasses.dataclass
class InfogramParameters(Parameters):
    algorithm: str = "gbm"                 # gbm | drf | glm
    infogram_algorithm_params: Optional[dict] = None
    protected_columns: Optional[Sequence[str]] = None
    total_information_threshold: float = -1.0   # core x-axis threshold
    net_information_threshold: float = -1.0     # core y-axis threshold
    relevance_index_threshold: float = -1.0     # fair x-axis threshold
    safety_index_threshold: float = -1.0        # fair y-axis threshold
    top_n_features: int = 50
    data_fraction: float = 1.0


class InfogramModel(Model):
    algo = "infogram"

    def predict(self, frame: Frame) -> Frame:
        raise NotImplementedError(
            "Infogram is a diagnostic, not a scorer: read "
            "output['admissible_score'] / admissible_features, then train "
            "a downstream model on the admissible columns")

    def admissible_score_frame(self) -> List[dict]:
        return self.output["admissible_score"]

    @property
    def admissible_features(self) -> List[str]:
        return self.output["admissible_features"]


class Infogram(ModelBuilder):
    algo = "infogram"
    model_class = InfogramModel

    def __init__(self, params: Optional[InfogramParameters] = None, **kw):
        super().__init__(params or InfogramParameters(**kw))
        self._seed = None

    def _builder_cls(self):
        from . import GBM, DRF, GLM
        return {"gbm": GBM, "drf": DRF, "glm": GLM}[
            self.params.algorithm.lower()]

    def _sub_params(self) -> dict:
        p = self.params
        base = dict(p.infogram_algorithm_params or {})
        if self._seed is None:
            self._seed = p.effective_seed()
        base.setdefault("seed", self._seed)
        if p.algorithm.lower() in ("gbm", "drf"):
            base.setdefault("ntrees", 20)
            base.setdefault("max_depth", 5)
        elif p.algorithm.lower() == "glm":
            base.setdefault("family", "auto")
        base["response_column"] = p.response_column
        if p.weights_column:
            base["weights_column"] = p.weights_column
        return base

    def _train_sub(self, frame: Frame, cols: List[str]):
        p = self.params
        keep = list(cols) + [p.response_column]
        if p.weights_column:
            keep.append(p.weights_column)
        sub = frame[keep]
        return self._builder_cls()(**self._sub_params()).train(sub)

    @staticmethod
    def _mean_log2_prob(model, frame: Frame, y: np.ndarray,
                        w: Optional[np.ndarray]) -> float:
        """EstimateCMI.java:29-38 — mean log2 p(true class) over rows."""
        probs = np.asarray(model._predict_raw(
            model._score_matrix(frame)))[: frame.nrows]
        p_true = probs[np.arange(len(y)), y]
        ok = (p_true > 0) & np.isfinite(p_true) & (y >= 0)
        if w is not None:
            ok &= w > 0
        if not ok.any():
            return 0.0
        return float(np.mean(np.log(p_true[ok])) / _LOG2)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> InfogramModel:
        p: InfogramParameters = self.params
        if not di.is_classifier:
            raise ValueError("infogram requires a categorical response")
        protected = list(p.protected_columns or [])
        build_core = not protected
        for c in protected:
            if c not in frame.names:
                raise ValueError(f"protected column {c!r} not in frame")

        # threshold resolution (Infogram.java:184-240)
        if build_core:
            rel_thr = p.total_information_threshold
            cmi_thr = p.net_information_threshold
        else:
            rel_thr = p.relevance_index_threshold
            cmi_thr = p.safety_index_threshold
        rel_thr = 0.1 if rel_thr == -1 else rel_thr
        cmi_thr = 0.1 if cmi_thr == -1 else cmi_thr

        if 0 < p.data_fraction < 1.0:
            frame = frame.split_frame([p.data_fraction],
                                      seed=p.effective_seed())[0]

        skip = {p.response_column, p.weights_column, p.fold_column,
                *protected, *(p.ignored_columns or ())}
        predictors = [c for c in frame.names
                      if c not in skip and c is not None]
        y = np.asarray(frame.vec(p.response_column).to_numpy()).astype(int)
        w = None
        if p.weights_column:
            w = np.asarray(frame.vec(p.weights_column).to_numpy())

        model = InfogramModel(job.dest_key, p, di)

        # relevance model: all predictors (core) / all minus protected
        # (fair) — extractRelevance (Infogram.java:608-622)
        full = self._train_sub(frame, predictors)
        from ..explain import _varimp_of
        vi = _varimp_of(full) or {}
        # fold one-hot names back onto source columns
        rel: Dict[str, float] = {c: 0.0 for c in predictors}
        for name, v in vi.items():
            col = name.split(".", 1)[0] if name not in rel else name
            if col in rel:
                rel[col] += float(v)
        if len(predictors) > p.top_n_features:
            ranked = sorted(predictors, key=lambda c: -rel[c])
            predictors = ranked[: p.top_n_features]
        max_rel = max(rel[c] for c in predictors) or 1.0
        relevance = {c: rel[c] / max_rel for c in predictors}

        # per-predictor CMI models + the reference point
        cmi_raw: Dict[str, float] = {}
        n_models = len(predictors) + 1
        if build_core:
            base_cmi = self._mean_log2_prob(full, frame, y, w)
            for i, c in enumerate(predictors):
                others = [o for o in predictors if o != c]
                m = self._train_sub(frame, others)
                cmi_raw[c] = max(0.0, base_cmi
                                 - self._mean_log2_prob(m, frame, y, w))
                job.update((i + 2) / (n_models + 1),
                           f"infogram model {i + 2}/{n_models}")
        else:
            base_model = self._train_sub(frame, protected)
            base_cmi = self._mean_log2_prob(base_model, frame, y, w)
            for i, c in enumerate(predictors):
                m = self._train_sub(frame, protected + [c])
                cmi_raw[c] = max(0.0, self._mean_log2_prob(m, frame, y, w)
                                 - base_cmi)
                job.update((i + 2) / (n_models + 1),
                           f"infogram model {i + 2}/{n_models}")
        max_cmi = max(cmi_raw.values(), default=0.0)
        scale = 1.0 / max_cmi if max_cmi > 0 else 0.0
        cmi = {c: cmi_raw[c] * scale for c in predictors}

        rows = []
        for c in predictors:
            r, s = relevance[c], cmi[c]
            rows.append({
                "column": c,
                "admissible": float(r >= rel_thr and s >= cmi_thr),
                "admissible_index": float(np.sqrt((r * r + s * s) / 2.0)),
                "relevance": r, "cmi": s, "cmi_raw": cmi_raw[c]})
        rows.sort(key=lambda d: -d["admissible_index"])
        model.output.update({
            "admissible_score": rows,
            "admissible_features": [d["column"] for d in rows
                                    if d["admissible"]],
            "relevance_threshold": rel_thr,
            "cmi_threshold": cmi_thr,
            "build_core": build_core,
            "protected_columns": protected,
            "nmodels_trained": n_models,
            "model_category": "Infogram",
        })
        return model
