"""DataInfo: the shared featurization layer feeding every algorithm.

Reference: ``hex/DataInfo.java`` (h2o-algos, ~1.5k LoC) — converts a Frame
into the algorithm's numeric view: categorical one-hot/enum expansion,
standardization, NA imputation, interaction terms; shared by GLM/DL/GAM/
CoxPH/KMeans.  Test-time adaptation (``Model.adaptTestForTrain``,
hex/Model.java:1683) aligns incoming frames to the training layout.

TPU-native redesign: featurization is a single fused XLA program per frame —
categorical codes expand to one-hot via a broadcast compare (an MXU-friendly
dense [rows, features] block), numerics are imputed/standardized in the same
pass, and the result is a row-sharded float32 matrix.  The fitted state
(domains, means, sigmas, layout) is a small host-side dataclass that also
performs test adaptation, guaranteeing train/test layout agreement.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM, T_TIME
from ..runtime.cluster import cluster


MEAN_IMPUTATION = "mean_imputation"
SKIP = "skip"


@dataclasses.dataclass
class ColumnSpec:
    name: str
    type: str                       # T_NUM / T_TIME / T_CAT
    domain: Optional[List[str]]     # cat labels (training-time)
    mean: float                     # imputation value / centering
    sigma: float                    # scaling (1.0 when not standardizing)
    time_base: float = 0.0
    offset: int = 0                 # first output column index
    width: int = 1                  # number of output columns


@dataclasses.dataclass
class DataInfo:
    """Fitted featurization: layout + per-column adaptation state."""

    specs: List[ColumnSpec]
    response_column: Optional[str]
    response_domain: Optional[List[str]]
    weights_column: Optional[str]
    offset_column: Optional[str]
    standardize: bool
    use_all_factor_levels: bool
    missing_values_handling: str
    add_intercept: bool
    nfeatures: int
    response_mean: float = 0.0
    response_sigma: float = 1.0

    # ------------------------------------------------------------ properties
    @property
    def coef_names(self) -> List[str]:
        names = []
        for s in self.specs:
            if s.type == T_CAT:
                lo = 0 if self.use_all_factor_levels else 1
                names += [f"{s.name}.{lbl}" for lbl in s.domain[lo:]]
                names.append(f"{s.name}.missing(NA)")
            else:
                names.append(s.name)
        if self.add_intercept:
            names.append("Intercept")
        return names

    @property
    def nclasses(self) -> int:
        return len(self.response_domain) if self.response_domain else 1

    @property
    def is_classifier(self) -> bool:
        return self.response_domain is not None

    # -------------------------------------------------------------- fitting
    @staticmethod
    def fit(frame: Frame, response_column: Optional[str] = None,
            ignored_columns: Sequence[str] = (),
            weights_column: Optional[str] = None,
            offset_column: Optional[str] = None,
            standardize: bool = True,
            use_all_factor_levels: bool = False,
            missing_values_handling: str = MEAN_IMPUTATION,
            add_intercept: bool = True,
            force_classification: bool = False) -> "DataInfo":
        skip = set(ignored_columns) | {response_column, weights_column,
                                       offset_column, None}
        # one batched pass for every column's rollups — the per-column
        # lazy path costs a dispatch round trip per column (wide frames)
        frame.warm_rollups()
        specs: List[ColumnSpec] = []
        offset = 0
        for name, vec in zip(frame.names, frame.vecs):
            if name in skip or vec.data is None:   # str/uuid never featurized
                continue
            if vec.type == T_CAT:
                dom = list(vec.domain or [])
                lo = 0 if use_all_factor_levels else 1
                width = max(len(dom) - lo, 0) + 1          # +1 NA bucket
                specs.append(ColumnSpec(name, T_CAT, dom, 0.0, 1.0,
                                        offset=offset, width=width))
            else:
                r = vec.rollups()
                mean = r.mean if np.isfinite(r.mean) else 0.0
                sigma = r.sigma if (standardize and np.isfinite(r.sigma)
                                    and r.sigma > 0) else 1.0
                specs.append(ColumnSpec(name, vec.type, None, mean, sigma,
                                        time_base=vec.time_base,
                                        offset=offset, width=1))
            offset += specs[-1].width
        if not specs:
            raise ValueError("no usable feature columns")

        resp_domain = None
        rmean, rsigma = 0.0, 1.0
        if response_column is not None:
            rv = frame.vec(response_column)
            if rv.type == T_CAT:
                resp_domain = list(rv.domain or [])
            elif force_classification:
                vals = np.unique(rv.to_numpy())
                vals = vals[np.isfinite(vals)]
                resp_domain = [str(int(v)) if v == int(v) else str(v)
                               for v in vals]
            else:
                rr = rv.rollups()
                rmean = rr.mean if np.isfinite(rr.mean) else 0.0
                rsigma = rr.sigma if np.isfinite(rr.sigma) and rr.sigma > 0 else 1.0
        nfeat = offset + (1 if add_intercept else 0)
        return DataInfo(specs, response_column, resp_domain, weights_column,
                        offset_column, standardize, use_all_factor_levels,
                        missing_values_handling, add_intercept, nfeat,
                        response_mean=rmean, response_sigma=rsigma)

    # ---------------------------------------------------------- application
    def make_matrix(self, frame: Frame, standardize: Optional[bool] = None) -> jax.Array:
        """[padded_rows, nfeatures] float32 design matrix, row-sharded.

        One fused XLA pass: numeric impute+standardize, categorical one-hot
        with NA bucket, optional intercept column.  Unseen test levels map to
        the NA bucket (the reference's adaptTestForTrain ``skipMissing`` /
        makeNA path, hex/Model.java:1683).

        Memoized in the Frame's ``_matrix_cache`` (so ``Frame.spill()``
        evicts it under HBM pressure like every other device view): repeated
        train/predict over the same Frame reuse one device matrix.  Runs of
        numeric columns are processed as ONE batched block — per-column
        eager ops cost a ~1.4 ms dispatch each on a tunnelled backend
        (784 columns = seconds).
        """
        standardize = self.standardize if standardize is None else standardize
        key = ("__design__", standardize, self._design_signature())
        hit = frame._matrix_cache.get(key)
        if hit is not None:
            return hit
        cl = cluster()
        cols = []          # list of [padded, k] blocks in spec order
        num_run: list = []

        def flush_numeric():
            if not num_run:
                return
            specs_r, arrs = zip(*num_run)
            num_run.clear()
            X = jnp.stack(arrs, axis=0).astype(jnp.float32)  # [C, padded]
            means = jnp.asarray([s.mean for s in specs_r],
                                jnp.float32)[:, None]
            X = jnp.where(jnp.isnan(X), means, X)
            if standardize:
                sigmas = jnp.asarray([s.sigma for s in specs_r],
                                     jnp.float32)[:, None]
                X = (X - means) / sigmas
            cols.append(X.T)

        for s in self.specs:
            vec = frame.vec(s.name)
            if s.type == T_CAT:
                flush_numeric()
                codes = self._aligned_codes(vec, s)
                lo = 0 if self.use_all_factor_levels else 1
                width = s.width - 1
                levels = jnp.arange(lo, lo + width, dtype=jnp.int32)
                onehot = (codes[:, None] == levels[None, :]).astype(jnp.float32)
                na = (codes < 0).astype(jnp.float32)[:, None]
                cols.append(jnp.concatenate([onehot, na], axis=1))
            else:
                x = vec.data
                if s.type == T_TIME and abs(vec.time_base - s.time_base) > 0:
                    x = x + (vec.time_base - s.time_base) / 1000.0
                num_run.append((s, x))
        flush_numeric()
        if self.add_intercept:
            cols.append(jnp.ones((frame.padded_rows, 1), jnp.float32))
        mat = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=1)
        from ..runtime.cluster import put_sharded
        mat = put_sharded(mat, cl.matrix_sharding)
        frame._matrix_cache[key] = mat
        return mat

    def _design_signature(self) -> tuple:
        """Memo key for the design layout, computed once per DataInfo.
        The key is the signature TUPLE itself (hashable), not its hash():
        a 64-bit hash collision between two layouts over the same Frame
        would silently return the wrong cached design matrix."""
        sig = self.__dict__.get("_design_sig")
        if sig is None:
            sig = (
                tuple((s.name, s.type, tuple(s.domain or ()), s.mean,
                       s.sigma, s.time_base, s.offset, s.width)
                      for s in self.specs),
                self.use_all_factor_levels, self.add_intercept,
                self.missing_values_handling)
            object.__setattr__(self, "_design_sig", sig)
        return sig

    def _aligned_codes(self, vec: Vec, s: ColumnSpec) -> jax.Array:
        """Map a (possibly differently-coded) cat Vec onto training codes."""
        if vec.type != T_CAT:
            # numeric column where a cat was expected: treat values as codes
            return jnp.where(jnp.isnan(vec.data), -1,
                             vec.data).astype(jnp.int32)
        if vec.domain == s.domain:
            return vec.data
        remap = np.full(max(len(vec.domain or []), 1), -1, dtype=np.int32)
        lookup = {lbl: i for i, lbl in enumerate(s.domain)}
        for i, lbl in enumerate(vec.domain or []):
            remap[i] = lookup.get(lbl, -1)
        remap_dev = jnp.asarray(remap)
        codes = vec.data
        return jnp.where(codes < 0, -1, remap_dev[jnp.clip(codes, 0, None)])

    def response(self, frame: Frame) -> jax.Array:
        """Response as float32 [padded]: cat codes for classifiers else values.

        Memoized per frame (spill-evicted): the eager op chain costs a
        dispatch round trip per op on a tunnelled backend."""
        key = ("__response__", self.response_column,
               tuple(self.response_domain) if self.response_domain is not None
               else None, self._design_signature())
        hit = frame._matrix_cache.get(key)
        if hit is not None:
            return hit
        out = self._response_uncached(frame)
        frame._matrix_cache[key] = out
        return out

    def _response_uncached(self, frame: Frame) -> jax.Array:
        rv = frame.vec(self.response_column)
        if self.response_domain is not None:
            if rv.type == T_CAT:
                spec = ColumnSpec(self.response_column, T_CAT,
                                  self.response_domain, 0.0, 1.0)
                return self._aligned_codes(rv, spec).astype(jnp.float32)
            # numeric response trained as classification (force_classification)
            vals = np.array([float(v) for v in self.response_domain],
                            dtype=np.float32)
            vals_dev = jnp.asarray(vals)
            x = rv.data
            code = jnp.argmin(jnp.abs(x[:, None] - vals_dev[None, :]), axis=1)
            exact = jnp.any(x[:, None] == vals_dev[None, :], axis=1)
            return jnp.where(exact, code, -1).astype(jnp.float32)
        return rv.numeric_data()

    def weights(self, frame: Frame) -> jax.Array:
        """Row weights x validity mask — 0 on padding and (optionally) NA rows.

        Memoized per frame (spill-evicted), like ``response``."""
        key = ("__weights__", self.weights_column, self.response_column,
               tuple(self.response_domain) if self.response_domain is not None
               else None, self.missing_values_handling,
               self._design_signature())
        hit = frame._matrix_cache.get(key)
        if hit is not None:
            return hit
        out = self._weights_uncached(frame)
        frame._matrix_cache[key] = out
        return out

    def _weights_uncached(self, frame: Frame) -> jax.Array:
        w = frame.valid_mask().astype(jnp.float32)
        if self.weights_column is not None:
            w = w * jnp.nan_to_num(frame.vec(self.weights_column).numeric_data())
        if self.response_column is not None:
            y = self.response(frame)
            w = w * jnp.where(jnp.isnan(y) | (y < -0.5) if self.response_domain
                              else jnp.isnan(y), 0.0, 1.0)
        if self.missing_values_handling == SKIP:
            for s in self.specs:
                vec = frame.vec(s.name)
                if s.type == T_CAT:
                    w = w * (self._aligned_codes(vec, s) >= 0)
                else:
                    w = w * ~jnp.isnan(vec.data)
        return w

    def offsets(self, frame: Frame) -> Optional[jax.Array]:
        if self.offset_column is None:
            return None
        return jnp.nan_to_num(frame.vec(self.offset_column).numeric_data())
