"""ModelSelection: best-subset GLM search — ``hex/modelselection`` analog.

Reference: ``hex/modelselection/ModelSelection.java`` with modes maxr
(sequential-replacement best subset), maxrsweep (same search evaluated
with sweep operators on the cross-product matrix, ModelSelection.java:89
/ ModelSelectionUtils sweep implementations — no GLM builds inside the
search loop), forward (greedy direction), and backward (drop smallest
|z|).  The result reports the best predictor subset per size with its
R^2 (gaussian) / deviance metric, mirroring the reference's result frame.

TPU-native redesign: candidate GLMs reuse the device-resident design block
(the frame matrix cache) and each fit is the usual jit-compiled IRLSM;
maxrsweep computes ONE cross-product matrix on device (an MXU matmul,
psum-reduced over the row shards) and runs the cheap O(p^2) sweep updates
on host — the search is pure host control flow.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .glm import GLM, GLMParameters


@dataclasses.dataclass
class ModelSelectionParameters(Parameters):
    mode: str = "maxr"                   # maxr | maxrsweep | forward | backward
    max_predictor_number: int = 0        # 0 = all
    min_predictor_number: int = 1
    family: str = "auto"
    alpha: float = 0.0
    lambda_: float = 0.0
    intercept: bool = True
    # maxrsweep only: also build a GLM per best subset (reference's
    # build_glm_model); off by default — the sweeps already yield the
    # coefficients
    build_glm_model: bool = False


class ModelSelectionModel(Model):
    algo = "modelselection"

    def result(self) -> Frame:
        """Per-size best subsets — the reference's result() frame."""
        rows = self.output["subsets"]
        return Frame.from_numpy({
            "model_size": np.asarray([r["size"] for r in rows], np.float64),
            "best_r2_value": np.asarray([r["metric"] for r in rows],
                                        np.float64),
            "predictor_names": np.asarray(
                [", ".join(r["predictors"]) for r in rows], dtype=object),
            "model_id": np.asarray([r["model_key"] for r in rows],
                                   dtype=object),
        })

    def best_model(self, size: Optional[int] = None) -> Model:
        if self.output.get("mode") == "maxrsweep" and not getattr(
                self.params, "build_glm_model", False):
            raise ValueError(
                "maxrsweep ran without build_glm_model=True; read "
                "coefficients from result()/output['subsets'] instead")
        rows = self.output["subsets"]
        if size is None:
            row = max(rows, key=lambda r: r["metric"])
        else:
            row = next(r for r in rows if r["size"] == size)
        return dkv.get(row["model_key"])

    def coef(self, size: int) -> Dict[str, float]:
        return dict(self.best_model(size).coef)

    def _predict_raw(self, X):
        raise NotImplementedError("use best_model(size).predict(...)")


class ModelSelection(ModelBuilder):
    algo = "modelselection"
    model_class = ModelSelectionModel

    def __init__(self, params: Optional[ModelSelectionParameters] = None,
                 **kw):
        super().__init__(params or ModelSelectionParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di, valid) -> ModelSelectionModel:
        p: ModelSelectionParameters = self.params
        predictors = [s.name for s in di.specs]
        maxp = p.max_predictor_number or len(predictors)
        maxp = min(maxp, len(predictors))

        def fit_subset(cols: Sequence[str]) -> Model:
            m = GLM(response_column=p.response_column,
                    weights_column=p.weights_column,
                    family=p.family, alpha=p.alpha,
                    lambda_=p.lambda_, seed=p.effective_seed()) \
                .train(frame[list(cols) + [p.response_column]
                             + ([p.weights_column] if p.weights_column
                                else [])])
            return m

        def metric(m: Model) -> float:
            tm = m.training_metrics
            r2 = getattr(tm, "r2", float("nan"))
            if np.isfinite(r2):
                return float(r2)
            return float(getattr(tm, "auc", float("nan")))

        subsets: List[dict] = []
        if p.mode in ("maxr", "forward"):
            chosen: List[str] = []
            for size in range(1, maxp + 1):
                best = None
                for cand in predictors:
                    if cand in chosen:
                        continue
                    m = fit_subset(chosen + [cand])
                    v = metric(m)
                    if best is None or v > best[0]:
                        best = (v, cand, m)
                chosen.append(best[1])
                best_m, best_v = best[2], best[0]
                if p.mode == "maxr" and size >= 2:
                    # sequential replacement: try swapping each chosen
                    # predictor for each unchosen one (maxr refinement)
                    improved = True
                    while improved:
                        improved = False
                        for i, old in enumerate(list(chosen)):
                            for cand in predictors:
                                if cand in chosen:
                                    continue
                                trial = list(chosen)
                                trial[i] = cand
                                m2 = fit_subset(trial)
                                v2 = metric(m2)
                                if v2 > best_v + 1e-10:
                                    chosen = trial
                                    best_m, best_v = m2, v2
                                    improved = True
                subsets.append({"size": size, "predictors": list(chosen),
                                "metric": best_v,
                                "model_key": best_m.key})
                job.update(size / maxp, f"size {size}/{maxp}")
        elif p.mode == "backward":
            chosen = list(predictors)
            m = fit_subset(chosen)
            subsets.append({"size": len(chosen), "predictors": list(chosen),
                            "metric": metric(m), "model_key": m.key})
            while len(chosen) > max(p.min_predictor_number, 1):
                # drop the predictor with the smallest |standardized coef|
                coefs = dict(m.coef_norm)
                drop = None
                drop_mag = np.inf
                for name in chosen:
                    mags = [abs(v) for k, v in coefs.items()
                            if k == name or k.startswith(f"{name}.")]
                    mag = max(mags) if mags else 0.0
                    if mag < drop_mag:
                        drop_mag, drop = mag, name
                chosen.remove(drop)
                m = fit_subset(chosen)
                subsets.append({"size": len(chosen),
                                "predictors": list(chosen),
                                "metric": metric(m), "model_key": m.key})
                job.update(1 - len(chosen) / len(predictors),
                           f"size {len(chosen)}")
            subsets.reverse()
        elif p.mode == "maxrsweep":
            subsets = self._maxrsweep(job, frame, di, p, predictors, maxp,
                                      fit_subset)
        else:
            raise ValueError(f"unknown mode {p.mode!r}")

        model = ModelSelectionModel(
            job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["subsets"] = subsets
        model.output["mode"] = p.mode
        best = max(subsets, key=lambda r: r["metric"])
        if best.get("model_key"):
            model.training_metrics = dkv.get(
                best["model_key"]).training_metrics
        return model

    # -- maxrsweep: sweep-operator subset search (ModelSelection.java:89) --
    @staticmethod
    def _sweep(M: np.ndarray, idx: Sequence[int]) -> Optional[np.ndarray]:
        """Symmetric sweep of M on the given pivots; None if singular."""
        M = M.copy()
        for k in idx:
            d = M[k, k]
            if abs(d) < 1e-10:
                return None
            col = M[:, k].copy()
            rowk = M[k, :].copy()
            M -= np.outer(col, rowk) / d
            M[:, k] = col / d
            M[k, :] = rowk / d
            M[k, k] = -1.0 / d
        return M

    def _maxrsweep(self, job: Job, frame: Frame, di, p, predictors, maxp,
                   fit_subset) -> List[dict]:
        """maxr's sequential-replacement search, but each candidate subset
        is scored by sweeping the cross-product matrix instead of fitting
        a GLM: err(S) = CPM swept on S's design columns (+ intercept),
        read at the [y, y] cell; coefficients fall out at [cols, y]."""
        import jax.numpy as jnp
        if di.is_classifier:
            raise ValueError("maxrsweep supports regression only "
                             "(ModelSelection.java:134)")
        X = di.make_matrix(frame)                  # [padded, cols+icpt]
        y = di.response(frame)
        w = di.weights(frame)
        y = jnp.where(w > 0, jnp.nan_to_num(y), 0.0)
        Z = jnp.concatenate([X, y[:, None]], axis=1)
        CPM = np.asarray((Z * w[:, None]).T @ Z, dtype=np.float64)
        names = di.coef_names                      # expanded design names
        yi = CPM.shape[0] - 1                      # y cell index
        icpt = [names.index("Intercept")] if "Intercept" in names else []
        groups: Dict[str, List[int]] = {}
        for pred in predictors:
            groups[pred] = [j for j, nm in enumerate(names)
                            if nm == pred or nm.startswith(pred + ".")]

        def sweep_cols(M: np.ndarray, cols: Sequence[int]) -> np.ndarray:
            """Sweep pivots in order, skipping singular ones (empty
            one-hot levels)."""
            for k in cols:
                nxt = self._sweep(M, [k])
                if nxt is not None:
                    M = nxt
            return M

        # incremental search: the classical sweep trick — keep the matrix
        # swept on the chosen set; evaluating a candidate sweeps ONLY its
        # own columns (O(g*p^2)), never the whole subset again
        base = sweep_cols(CPM, icpt)
        sst = float(base[yi, yi])
        sse_none = sst if sst > 0 else 1.0

        def r2(sse: float) -> float:
            return 1.0 - sse / sse_none

        subsets: List[dict] = []
        chosen: List[str] = []
        M_chosen = base
        best_sse = sse_none
        for size in range(1, maxp + 1):
            best = None
            for cand in predictors:
                if cand in chosen:
                    continue
                v = float(sweep_cols(M_chosen, groups[cand])[yi, yi])
                if best is None or v < best[0]:
                    best = (v, cand)
            chosen.append(best[1])
            best_sse = best[0]
            M_chosen = sweep_cols(M_chosen, groups[best[1]])
            if size >= 2:                          # sequential replacement
                improved = True
                while improved:
                    improved = False
                    for i in range(len(chosen)):
                        # un-swept base + everything but position i, ONCE;
                        # each candidate then adds only its own columns
                        keep = [j for c in chosen if c != chosen[i]
                                for j in groups[c]]
                        M_minus = sweep_cols(base, keep)
                        for cand in predictors:
                            if cand in chosen:
                                continue
                            v = float(sweep_cols(
                                M_minus, groups[cand])[yi, yi])
                            if v < best_sse - 1e-10:
                                chosen[i] = cand
                                best_sse = v
                                M_chosen = sweep_cols(M_minus,
                                                      groups[cand])
                                improved = True
                                break
                        if improved:
                            break
            row = {"size": size, "predictors": list(chosen),
                   "metric": r2(best_sse), "model_key": None}
            if p.build_glm_model:
                m = fit_subset(chosen)
                row["model_key"] = m.key
            else:
                cols = icpt + [j for c in chosen for j in groups[c]]
                M = M_chosen
                # de-standardize: x_std=(x-m)/s => b_raw=b_std/s and the
                # intercept absorbs -sum(b_std*m/s) (GLM's reporting units)
                mean_s = {}
                for s in di.specs:
                    if s.type != "cat":
                        mean_s[s.name] = (s.mean, s.sigma)
                coefs = {}
                icpt_adj = 0.0
                for j in cols:
                    nm = names[j]
                    if nm == "Intercept":
                        continue
                    b = float(M[j, yi])
                    if nm in mean_s:
                        m_, s_ = mean_s[nm]
                        coefs[nm] = b / s_
                        icpt_adj += b * m_ / s_
                    else:
                        coefs[nm] = b
                if icpt:
                    coefs["Intercept"] = float(M[icpt[0], yi]) - icpt_adj
                row["coefficients"] = coefs
            subsets.append(row)
            job.update(size / maxp, f"maxrsweep size {size}/{maxp}")
        return subsets
