"""ModelSelection: best-subset GLM search — ``hex/modelselection`` analog.

Reference: ``hex/modelselection/ModelSelection.java`` with modes maxr
(sequential-replacement best subset), forward (maxrsweep's greedy
direction), and backward (drop smallest |z|).  Each candidate subset is a
GLM fit; the result reports the best predictor subset per size with its
R^2 (gaussian) / deviance metric, mirroring the reference's result frame.

TPU-native redesign: candidate GLMs reuse the device-resident design block
(the frame matrix cache) and each fit is the usual jit-compiled IRLSM —
the search is pure host control flow, trivially parallelizable over mesh
slices later.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .glm import GLM, GLMParameters


@dataclasses.dataclass
class ModelSelectionParameters(Parameters):
    mode: str = "maxr"                   # maxr | forward | backward
    max_predictor_number: int = 0        # 0 = all
    min_predictor_number: int = 1
    family: str = "auto"
    alpha: float = 0.0
    lambda_: float = 0.0
    intercept: bool = True


class ModelSelectionModel(Model):
    algo = "modelselection"

    def result(self) -> Frame:
        """Per-size best subsets — the reference's result() frame."""
        rows = self.output["subsets"]
        return Frame.from_numpy({
            "model_size": np.asarray([r["size"] for r in rows], np.float64),
            "best_r2_value": np.asarray([r["metric"] for r in rows],
                                        np.float64),
            "predictor_names": np.asarray(
                [", ".join(r["predictors"]) for r in rows], dtype=object),
            "model_id": np.asarray([r["model_key"] for r in rows],
                                   dtype=object),
        })

    def best_model(self, size: Optional[int] = None) -> Model:
        rows = self.output["subsets"]
        if size is None:
            row = max(rows, key=lambda r: r["metric"])
        else:
            row = next(r for r in rows if r["size"] == size)
        return dkv.get(row["model_key"])

    def coef(self, size: int) -> Dict[str, float]:
        return dict(self.best_model(size).coef)

    def _predict_raw(self, X):
        raise NotImplementedError("use best_model(size).predict(...)")


class ModelSelection(ModelBuilder):
    algo = "modelselection"
    model_class = ModelSelectionModel

    def __init__(self, params: Optional[ModelSelectionParameters] = None,
                 **kw):
        super().__init__(params or ModelSelectionParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di, valid) -> ModelSelectionModel:
        p: ModelSelectionParameters = self.params
        predictors = [s.name for s in di.specs]
        maxp = p.max_predictor_number or len(predictors)
        maxp = min(maxp, len(predictors))

        def fit_subset(cols: Sequence[str]) -> Model:
            m = GLM(response_column=p.response_column,
                    weights_column=p.weights_column,
                    family=p.family, alpha=p.alpha,
                    lambda_=p.lambda_, seed=p.effective_seed()) \
                .train(frame[list(cols) + [p.response_column]
                             + ([p.weights_column] if p.weights_column
                                else [])])
            return m

        def metric(m: Model) -> float:
            tm = m.training_metrics
            r2 = getattr(tm, "r2", float("nan"))
            if np.isfinite(r2):
                return float(r2)
            return float(getattr(tm, "auc", float("nan")))

        subsets: List[dict] = []
        if p.mode in ("maxr", "forward"):
            chosen: List[str] = []
            for size in range(1, maxp + 1):
                best = None
                for cand in predictors:
                    if cand in chosen:
                        continue
                    m = fit_subset(chosen + [cand])
                    v = metric(m)
                    if best is None or v > best[0]:
                        best = (v, cand, m)
                chosen.append(best[1])
                best_m, best_v = best[2], best[0]
                if p.mode == "maxr" and size >= 2:
                    # sequential replacement: try swapping each chosen
                    # predictor for each unchosen one (maxr refinement)
                    improved = True
                    while improved:
                        improved = False
                        for i, old in enumerate(list(chosen)):
                            for cand in predictors:
                                if cand in chosen:
                                    continue
                                trial = list(chosen)
                                trial[i] = cand
                                m2 = fit_subset(trial)
                                v2 = metric(m2)
                                if v2 > best_v + 1e-10:
                                    chosen = trial
                                    best_m, best_v = m2, v2
                                    improved = True
                subsets.append({"size": size, "predictors": list(chosen),
                                "metric": best_v,
                                "model_key": best_m.key})
                job.update(size / maxp, f"size {size}/{maxp}")
        elif p.mode == "backward":
            chosen = list(predictors)
            m = fit_subset(chosen)
            subsets.append({"size": len(chosen), "predictors": list(chosen),
                            "metric": metric(m), "model_key": m.key})
            while len(chosen) > max(p.min_predictor_number, 1):
                # drop the predictor with the smallest |standardized coef|
                coefs = dict(m.coef_norm)
                drop = None
                drop_mag = np.inf
                for name in chosen:
                    mags = [abs(v) for k, v in coefs.items()
                            if k == name or k.startswith(f"{name}.")]
                    mag = max(mags) if mags else 0.0
                    if mag < drop_mag:
                        drop_mag, drop = mag, name
                chosen.remove(drop)
                m = fit_subset(chosen)
                subsets.append({"size": len(chosen),
                                "predictors": list(chosen),
                                "metric": metric(m), "model_key": m.key})
                job.update(1 - len(chosen) / len(predictors),
                           f"size {len(chosen)}")
            subsets.reverse()
        else:
            raise ValueError(f"unknown mode {p.mode!r}")

        model = ModelSelectionModel(
            job.dest_key or dkv.make_key(self.algo), p, di)
        model.output["subsets"] = subsets
        model.output["mode"] = p.mode
        best = max(subsets, key=lambda r: r["metric"])
        model.training_metrics = dkv.get(best["model_key"]).training_metrics
        return model
