"""GLRM: generalized low-rank model via alternating least squares on MXU.

Reference: ``hex/glrm/GLRM.java:52`` — alternating minimization of
loss(A, XY) + gamma_x rx(X) + gamma_y ry(Y), X held as extra vecs across the
cluster; quadratic and many other losses/regularizers.

TPU-native redesign: quadratic loss + ridge regularizers have closed-form
alternating solves — each iteration is two tall-skinny matmuls plus a [k,k]
host Cholesky (X update row-parallel over the mesh, Y update feature-
parallel).  Missing cells are mean-imputed into the standardized design
before factorization (the reference's em-style impute start).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from .pca import _transform_flags


@dataclasses.dataclass
class GLRMParameters(Parameters):
    k: int = 1
    gamma_x: float = 0.0
    gamma_y: float = 0.0
    transform: str = "none"
    max_iterations: int = 100
    init: str = "svd"                  # svd | random
    recover_svd: bool = False


class GLRMModel(Model):
    algo = "glrm"

    def _predict_raw(self, X):
        raise NotImplementedError("glrm reconstructs via transform()")

    def transform(self, frame: Frame) -> Frame:
        """Project new rows onto the archetypes -> X factor frame."""
        Xt = self._std(frame)
        Y = jnp.asarray(self.output["archetypes"], jnp.float32)
        G = Y @ Y.T + self.params.gamma_x * jnp.eye(Y.shape[0])
        Xf = np.asarray(Xt @ Y.T @ jnp.linalg.inv(G))[: frame.nrows]
        return Frame([f"Arch{i+1}" for i in range(Xf.shape[1])],
                     [Vec.from_numpy(Xf[:, i].astype(np.float64), T_NUM)
                      for i in range(Xf.shape[1])])

    def reconstruct(self, frame: Frame) -> Frame:
        Xf = self.transform(frame)
        Xm = np.stack([v.to_numpy() for v in Xf.vecs], axis=1)
        Y = np.asarray(self.output["archetypes"])
        R = Xm @ Y
        mu = np.asarray(self.output["_mu"])
        sd = np.asarray(self.output["_sd"])
        R = R / np.where(sd == 0, 1, sd)[None, :] + mu[None, :]
        names = self.output["feature_names"]
        return Frame([f"reconstr_{n}" for n in names],
                     [Vec.from_numpy(R[:, i], T_NUM)
                      for i in range(R.shape[1])])

    def _std(self, frame: Frame) -> jax.Array:
        di = self.datainfo
        X = di.make_matrix(frame, standardize=False)
        mu = jnp.asarray(self.output["_mu"], jnp.float32)
        sd = jnp.asarray(self.output["_sd"], jnp.float32)
        return (X - mu[None, :]) * sd[None, :]

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        Xt = self._std(frame)
        Y = jnp.asarray(self.output["archetypes"], jnp.float32)
        G = Y @ Y.T + self.params.gamma_x * jnp.eye(Y.shape[0])
        Xf = Xt @ Y.T @ jnp.linalg.inv(G)
        R = Xt - Xf @ Y
        w = self.datainfo.weights(frame)
        return {"objective": float(jnp.sum(jnp.sum(R * R, axis=1) * w))}


class GLRM(ModelBuilder):
    """GLRM builder — H2OGeneralizedLowRankEstimator analog (quadratic)."""

    algo = "glrm"
    model_class = GLRMModel
    supervised = False

    def __init__(self, params: Optional[GLRMParameters] = None, **kw):
        super().__init__(params or GLRMParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            standardize=False, use_all_factor_levels=True,
            add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GLRMModel:
        p: GLRMParameters = self.params
        k = min(p.k, di.nfeatures)
        X0 = di.make_matrix(frame, standardize=False)
        w = di.weights(frame)
        n = jnp.maximum(jnp.sum(w), 1.0)
        mu = jnp.sum(X0 * w[:, None], axis=0) / n
        var = jnp.sum((X0 - mu[None, :]) ** 2 * w[:, None], axis=0) \
            / jnp.maximum(n - 1.0, 1.0)
        demean, descale = _transform_flags(p.transform)
        mu_t = mu if demean else jnp.zeros_like(mu)
        sd_t = jnp.where(var > 0, 1.0 / jnp.sqrt(var), 1.0) if descale \
            else jnp.ones_like(var)
        A = (X0 - mu_t[None, :]) * sd_t[None, :] * (w[:, None] > 0)

        rng = np.random.default_rng(p.effective_seed())
        if p.init == "svd":
            G = np.asarray(A.T @ A, np.float64)
            vals, vecs = np.linalg.eigh(G)
            Y = vecs[:, np.argsort(vals)[::-1][:k]].T
        else:
            Y = rng.normal(size=(k, di.nfeatures)) / np.sqrt(k)
        Y = jnp.asarray(Y, jnp.float32)

        Ik = jnp.eye(k, dtype=jnp.float32)

        @jax.jit
        def step(Y):
            Gx = Y @ Y.T + p.gamma_x * Ik
            X = A @ Y.T @ jnp.linalg.inv(Gx)
            Gy = X.T @ X + p.gamma_y * Ik
            Y2 = jnp.linalg.inv(Gy) @ (X.T @ A)
            R = A - X @ Y2
            obj = jnp.sum(R * R) + p.gamma_x * jnp.sum(X * X) \
                + p.gamma_y * jnp.sum(Y2 * Y2)
            return X, Y2, obj

        prev = np.inf
        for it in range(p.max_iterations):
            X, Y, obj = step(Y)
            obj = float(obj)
            job.update(it / p.max_iterations, f"iter={it} obj={obj:.5g}")
            if prev - obj < 1e-7 * max(abs(prev), 1.0):
                break
            prev = obj

        model = GLRMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "archetypes": np.asarray(Y, np.float64),
            "objective": obj,
            "iterations": it + 1,
            "feature_names": di.coef_names,
            "_mu": np.asarray(mu_t, np.float64),
            "_sd": np.asarray(sd_t, np.float64),
        })
        if p.recover_svd:
            Xh = np.asarray(X, np.float64)
            u, s, vt = np.linalg.svd(Xh @ np.asarray(Y), full_matrices=False)
            model.output["singular_values"] = s[:k]
        model.training_metrics = {"objective": obj}
        return model
