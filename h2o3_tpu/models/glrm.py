"""GLRM: generalized low-rank model via alternating least squares on MXU.

Reference: ``hex/glrm/GLRM.java:52`` — alternating minimization of
loss(A, XY) + gamma_x rx(X) + gamma_y ry(Y), X held as extra vecs across the
cluster; quadratic and many other losses/regularizers.

TPU-native redesign: quadratic loss + ridge regularizers have closed-form
alternating solves — each iteration is two tall-skinny matmuls plus a [k,k]
host Cholesky (X update row-parallel over the mesh, Y update feature-
parallel).  Missing cells are mean-imputed into the standardized design
before factorization (the reference's em-style impute start).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo
from .pca import _transform_flags


@dataclasses.dataclass
class GLRMParameters(Parameters):
    k: int = 1
    gamma_x: float = 0.0
    gamma_y: float = 0.0
    transform: str = "none"
    max_iterations: int = 100
    init: str = "svd"                  # svd | random
    recover_svd: bool = False
    # loss/regularizer zoo (GlrmLoss/GlrmRegularizer enums)
    loss: str = "quadratic"            # quadratic|absolute|huber|poisson|
    # hinge|logistic
    multi_loss: str = "categorical"    # loss for categorical blocks
    loss_by_col: Optional[dict] = None  # {column: loss}
    regularization_x: str = "none"     # none|quadratic|l1|non_negative|
    # one_sparse|simplex
    regularization_y: str = "none"


# ------------------------------------------------------- losses (GlrmLoss)
def _loss_value_grad(name: str):
    """Elementwise loss l(u, a) and dl/du (u = reconstruction)."""
    if name == "quadratic":
        return (lambda u, a: (u - a) ** 2,
                lambda u, a: 2 * (u - a))
    if name == "absolute":
        return (lambda u, a: jnp.abs(u - a),
                lambda u, a: jnp.sign(u - a))
    if name == "huber":
        return (lambda u, a: jnp.where(jnp.abs(u - a) <= 1,
                                       0.5 * (u - a) ** 2,
                                       jnp.abs(u - a) - 0.5),
                lambda u, a: jnp.clip(u - a, -1.0, 1.0))
    if name == "poisson":
        return (lambda u, a: jnp.exp(jnp.clip(u, -30, 30)) - a * u,
                lambda u, a: jnp.exp(jnp.clip(u, -30, 30)) - a)
    if name == "hinge":                 # a in {0,1} -> s in {-1,+1}
        return (lambda u, a: jnp.maximum(0.0, 1 - (2 * a - 1) * u),
                lambda u, a: jnp.where((2 * a - 1) * u < 1,
                                       -(2 * a - 1), 0.0))
    if name == "logistic":
        return (lambda u, a: jnp.log1p(jnp.exp(-jnp.clip(
            (2 * a - 1) * u, -30, 30))),
                lambda u, a: -(2 * a - 1) / (1 + jnp.exp(jnp.clip(
                    (2 * a - 1) * u, -30, 30))))
    if name == "categorical":           # one-vs-all hinge over the block
        return (lambda u, a: jnp.maximum(0.0, 1 - (2 * a - 1) * u),
                lambda u, a: jnp.where((2 * a - 1) * u < 1,
                                       -(2 * a - 1), 0.0))
    raise ValueError(f"unknown glrm loss {name!r}")


# ------------------------------------------- regularizers (GlrmRegularizer)
def _prox(name: str, M, step_gamma):
    """Proximal operator applied row-wise (X) / matrix-wise (Y)."""
    if name == "none":
        return M
    if name == "quadratic":
        return M / (1.0 + 2.0 * step_gamma)
    if name == "l1":
        return jnp.sign(M) * jnp.maximum(jnp.abs(M) - step_gamma, 0.0)
    if name == "non_negative":
        return jnp.maximum(M, 0.0)
    if name == "one_sparse":            # keep the largest entry per row
        keep = jnp.argmax(jnp.abs(M), axis=-1, keepdims=True)
        mask = jnp.arange(M.shape[-1])[None, :] == keep
        return jnp.where(mask, jnp.maximum(M, 0.0), 0.0)
    if name == "simplex":               # project rows onto the simplex
        s = jnp.sort(M, axis=-1)[:, ::-1]
        css = jnp.cumsum(s, axis=-1) - 1
        idx = jnp.arange(1, M.shape[-1] + 1)
        cond = s - css / idx > 0
        rho = jnp.sum(cond, axis=-1, keepdims=True)
        theta = jnp.take_along_axis(css, rho - 1, axis=-1) / rho
        return jnp.maximum(M - theta, 0.0)
    raise ValueError(f"unknown glrm regularizer {name!r}")


def _reg_value(name: str, M, gamma):
    if name == "quadratic":
        return gamma * jnp.sum(M * M)
    if name == "l1":
        return gamma * jnp.sum(jnp.abs(M))
    return 0.0


class GLRMModel(Model):
    algo = "glrm"

    def _predict_raw(self, X):
        raise NotImplementedError("glrm reconstructs via transform()")

    def transform(self, frame: Frame) -> Frame:
        """Project new rows onto the archetypes -> X factor frame."""
        Xt = self._std(frame)
        Y = jnp.asarray(self.output["archetypes"], jnp.float32)
        G = Y @ Y.T + self.params.gamma_x * jnp.eye(Y.shape[0])
        Xf = np.asarray(Xt @ Y.T @ jnp.linalg.inv(G))[: frame.nrows]
        return Frame([f"Arch{i+1}" for i in range(Xf.shape[1])],
                     [Vec.from_numpy(Xf[:, i].astype(np.float64), T_NUM)
                      for i in range(Xf.shape[1])])

    def reconstruct(self, frame: Frame) -> Frame:
        Xf = self.transform(frame)
        Xm = np.stack([v.to_numpy() for v in Xf.vecs], axis=1)
        Y = np.asarray(self.output["archetypes"])
        R = Xm @ Y
        mu = np.asarray(self.output["_mu"])
        sd = np.asarray(self.output["_sd"])
        R = R / np.where(sd == 0, 1, sd)[None, :] + mu[None, :]
        names = self.output["feature_names"]
        return Frame([f"reconstr_{n}" for n in names],
                     [Vec.from_numpy(R[:, i], T_NUM)
                      for i in range(R.shape[1])])

    def _std(self, frame: Frame) -> jax.Array:
        di = self.datainfo
        X = di.make_matrix(frame, standardize=False)
        mu = jnp.asarray(self.output["_mu"], jnp.float32)
        sd = jnp.asarray(self.output["_sd"], jnp.float32)
        return (X - mu[None, :]) * sd[None, :]

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        Xt = self._std(frame)
        Y = jnp.asarray(self.output["archetypes"], jnp.float32)
        G = Y @ Y.T + self.params.gamma_x * jnp.eye(Y.shape[0])
        Xf = Xt @ Y.T @ jnp.linalg.inv(G)
        R = Xt - Xf @ Y
        w = self.datainfo.weights(frame)
        return {"objective": float(jnp.sum(jnp.sum(R * R, axis=1) * w))}


class GLRM(ModelBuilder):
    """GLRM builder — H2OGeneralizedLowRankEstimator analog (quadratic)."""

    algo = "glrm"
    model_class = GLRMModel
    supervised = False

    def __init__(self, params: Optional[GLRMParameters] = None, **kw):
        super().__init__(params or GLRMParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            standardize=False, use_all_factor_levels=True,
            add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> GLRMModel:
        p: GLRMParameters = self.params
        k = min(p.k, di.nfeatures)
        X0 = di.make_matrix(frame, standardize=False)
        w = di.weights(frame)
        n = jnp.maximum(jnp.sum(w), 1.0)
        mu = jnp.sum(X0 * w[:, None], axis=0) / n
        var = jnp.sum((X0 - mu[None, :]) ** 2 * w[:, None], axis=0) \
            / jnp.maximum(n - 1.0, 1.0)
        demean, descale = _transform_flags(p.transform)
        mu_t = mu if demean else jnp.zeros_like(mu)
        sd_t = jnp.where(var > 0, 1.0 / jnp.sqrt(var), 1.0) if descale \
            else jnp.ones_like(var)
        A = (X0 - mu_t[None, :]) * sd_t[None, :] * (w[:, None] > 0)
        self._last_mu, self._last_sd = mu_t, sd_t

        rng = np.random.default_rng(p.effective_seed())
        if p.init == "svd":
            G = np.asarray(A.T @ A, np.float64)
            vals, vecs = np.linalg.eigh(G)
            Y = vecs[:, np.argsort(vals)[::-1][:k]].T
        else:
            Y = rng.normal(size=(k, di.nfeatures)) / np.sqrt(k)
        Y = jnp.asarray(Y, jnp.float32)

        # per-design-column losses: numeric -> loss/loss_by_col; categorical
        # one-hot blocks -> multi_loss with {0,1} targets
        loss_by_col = dict(p.loss_by_col or {})
        col_loss: list = []
        for spec in di.specs:
            name = loss_by_col.get(spec.name,
                                   p.multi_loss if spec.type == "cat"
                                   else p.loss)
            col_loss.extend([name] * spec.width)
        col_loss = col_loss[: di.nfeatures]
        all_quadratic = all(c == "quadratic" for c in col_loss)
        plain_regs = p.regularization_x in ("none", "quadratic") and \
            p.regularization_y in ("none", "quadratic")
        if not (all_quadratic and plain_regs):
            return self._fit_proximal(job, di, A, w, Y, col_loss, k, p)

        Ik = jnp.eye(k, dtype=jnp.float32)

        @jax.jit
        def step(Y):
            Gx = Y @ Y.T + p.gamma_x * Ik
            X = A @ Y.T @ jnp.linalg.inv(Gx)
            Gy = X.T @ X + p.gamma_y * Ik
            Y2 = jnp.linalg.inv(Gy) @ (X.T @ A)
            R = A - X @ Y2
            obj = jnp.sum(R * R) + p.gamma_x * jnp.sum(X * X) \
                + p.gamma_y * jnp.sum(Y2 * Y2)
            return X, Y2, obj

        prev = np.inf
        for it in range(p.max_iterations):
            X, Y, obj = step(Y)
            obj = float(obj)
            job.update(it / p.max_iterations, f"iter={it} obj={obj:.5g}")
            if prev - obj < 1e-7 * max(abs(prev), 1.0):
                break
            prev = obj

        model = GLRMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "archetypes": np.asarray(Y, np.float64),
            "objective": obj,
            "iterations": it + 1,
            "feature_names": di.coef_names,
            "_mu": np.asarray(mu_t, np.float64),
            "_sd": np.asarray(sd_t, np.float64),
        })
        if p.recover_svd:
            Xh = np.asarray(X, np.float64)
            u, s, vt = np.linalg.svd(Xh @ np.asarray(Y), full_matrices=False)
            model.output["singular_values"] = s[:k]
        model.training_metrics = {"objective": obj}
        return model

    # ----------------------------------------------- proximal (loss zoo)
    def _fit_proximal(self, job, di, A, w, Y0, col_loss, k, p) -> GLRMModel:
        """Proximal alternating gradient — the general GlrmLoss/Regularizer
        path (GLRM.java's update_x/update_y with step halving)."""
        n, F = A.shape
        obs = (w[:, None] > 0).astype(jnp.float32)
        loss_names = sorted(set(col_loss))
        masks = {nm: jnp.asarray([1.0 if c == nm else 0.0
                                  for c in col_loss], jnp.float32)
                 for nm in loss_names}

        def total_loss_grad(U):
            L = jnp.zeros_like(U)
            G = jnp.zeros_like(U)
            for nm in loss_names:
                lv, lg = _loss_value_grad(nm)
                m = masks[nm][None, :]
                L = L + m * lv(U, A)
                G = G + m * lg(U, A)
            return jnp.sum(L * obs), G * obs

        @jax.jit
        def prox_iter(X, Y, step):
            _, G = total_loss_grad(X @ Y)
            X2 = _prox(p.regularization_x, X - step * (G @ Y.T),
                       step * p.gamma_x)
            _, G2 = total_loss_grad(X2 @ Y)
            Y2t = _prox(p.regularization_y, (Y - step * (X2.T @ G2)).T,
                        step * p.gamma_y).T
            lv, _ = total_loss_grad(X2 @ Y2t)
            obj = lv + _reg_value(p.regularization_x, X2, p.gamma_x) \
                + _reg_value(p.regularization_y, Y2t, p.gamma_y)
            return X2, Y2t, obj

        rng = np.random.default_rng(p.effective_seed())
        X = jnp.asarray(rng.normal(size=(n, k)) * 0.1, jnp.float32)
        Y = Y0
        step = 1.0 / max(float(jnp.abs(A).max()) * F, 1.0)
        lv0, _ = total_loss_grad(X @ Y)
        prev = float(lv0 + _reg_value(p.regularization_x, X, p.gamma_x)
                     + _reg_value(p.regularization_y, Y, p.gamma_y))
        it = 0
        for it in range(p.max_iterations):
            X2, Y2, obj = prox_iter(X, Y, step)
            obj = float(obj)
            if obj <= prev or not np.isfinite(prev):
                X, Y, prev = X2, Y2, obj
                step *= 1.05                    # accept, grow (GLRM.java)
            else:
                step *= 0.5                     # reject, halve
                if step < 1e-12:
                    break
            job.update(it / p.max_iterations, f"iter={it} obj={prev:.5g}")

        model = GLRMModel(job.dest_key or dkv.make_key(self.algo), p, di)
        mu_t = self._last_mu
        sd_t = self._last_sd
        model.output.update({
            "archetypes": np.asarray(Y, np.float64),
            "objective": prev, "iterations": it + 1,
            "feature_names": di.coef_names,
            "_mu": np.asarray(mu_t, np.float64),
            "_sd": np.asarray(sd_t, np.float64),
            "x_factor": np.asarray(X, np.float64),
        })
        model.training_metrics = {"objective": prev}
        return model
