"""Model / ModelBuilder: the training + scoring contract every algo follows.

Reference: ``hex/ModelBuilder.java:25`` (param validation, train/valid
adaptation, CV orchestration, Driver running computeImpl) and
``hex/Model.java`` (Parameters/Output, ``score()`` -> BigScore MRTask ->
per-row ``score0``, hex/Model.java:1901-1994).

TPU-native redesign: a ModelBuilder validates parameters, fits a DataInfo,
runs the algorithm's jit-compiled training program under a Job, and returns a
Model holding small host-side learned state (coefficients, trees, weights).
Scoring is a single batched SPMD program over the row-sharded design matrix —
the BigScore-per-row-score0 pattern collapses into one matmul-shaped pass.
Save/load is plain pickle of the host state (the portable MOJO-analog lives
in ``h2o3_tpu/export``).
"""

from __future__ import annotations

import dataclasses
import pickle
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM
from ..runtime import dkv
from ..runtime.job import Job, JobCancelled
from .datainfo import DataInfo, MEAN_IMPUTATION


@dataclasses.dataclass
class Parameters:
    """Common training parameters — analog of hex.Model.Parameters."""

    response_column: Optional[str] = None
    ignored_columns: Sequence[str] = ()
    weights_column: Optional[str] = None
    offset_column: Optional[str] = None
    seed: int = -1
    max_iterations: int = 50
    standardize: bool = True
    missing_values_handling: str = MEAN_IMPUTATION
    # early stopping (hex/ScoreKeeper.java:319)
    stopping_rounds: int = 0
    stopping_metric: str = "auto"
    stopping_tolerance: float = 1e-3
    # checkpointing (hex/Model.java:521,543)
    checkpoint: Optional[str] = None
    export_checkpoints_dir: Optional[str] = None
    # in-training progress snapshots (runtime/snapshot.py): min seconds
    # between snapshot writes for THIS job; -1 defers to the cluster-wide
    # H2O3_TPU_SNAPSHOT_INTERVAL (default 30), 0 snapshots at every
    # opportunity.  Only effective when H2O3_TPU_RECOVERY_DIR is active.
    snapshot_interval: float = -1.0
    # class balancing (hex/Model.Parameters _balance_classes): applied
    # as per-class weights (deterministic equivalent of the reference's
    # oversampling) folded into the weights column for training+metrics
    balance_classes: bool = False
    class_sampling_factors: Optional[Sequence[float]] = None
    # cross-validation
    nfolds: int = 0
    fold_column: Optional[str] = None
    fold_assignment: str = "auto"          # auto|random|modulo|stratified
    keep_cross_validation_predictions: bool = False
    # custom metric UDF: (predictions, y, w) -> (name, value)
    # (water/udf/CMetricFunc analog)
    custom_metric_func: Optional[Any] = None
    # concurrent fold/member model building (hex/CVModelBuilder.java:16
    # "parallelization" + hex/ParallelModelBuilder.java): 0 = auto
    # (bounded pool), 1 = sequential, n>1 = exactly n builder threads
    parallelism: int = 0
    # cluster-scheduler placement (runtime/scheduler.py): dispatch
    # priority (None = PRIORITY_BUILD; lower runs first), device budget
    # as a mesh fraction in (0, 1] or an explicit chip count >= 1
    # (None = the scheduler's default share), and how many times a job
    # interrupted by a dead host may be requeued from its progress
    # snapshot before it is failed
    priority: Optional[int] = None
    device_budget: Optional[float] = None
    retry_budget: int = 0
    # streaming ingest (ingest/stream.py): train on already-landed rows
    # behind the StreamingFrame watermark, re-binning at chunk fences as
    # more data lands; per-segment row coverage is recorded into
    # model.output["stream_coverage"].  Only tree builders support it.
    stream: bool = False
    # warm start: continue boosting from a prior model — a Model, a DKV
    # key, or a saved-model path.  Public face of the checkpoint
    # machinery; bit-identical to passing checkpoint=<key>.
    warm_start: Optional[Any] = None

    def effective_seed(self) -> int:
        return np.random.default_rng().integers(2**31) if self.seed in (-1, None) \
            else int(self.seed)


class Model:
    """A trained model: params + output + host-side learned state."""

    algo = "model"

    def __init__(self, key: str, params: Parameters, datainfo: DataInfo):
        self.key = key
        self.params = params
        self.datainfo = datainfo
        self.output: Dict[str, Any] = {}
        self.training_metrics = None
        self.validation_metrics = None
        self.cross_validation_metrics = None
        self.cv_predictions: Optional[np.ndarray] = None
        self.scoring_history: List[dict] = []
        dkv.put(key, self)

    # ---------------------------------------------------------------- scoring
    def _predict_raw(self, X: jax.Array) -> jax.Array:
        """[padded, nclasses] class probabilities or [padded] regression preds.

        The score0 analog — subclasses implement this as a pure jittable
        function of the design matrix.
        """
        raise NotImplementedError

    def _score_matrix(self, frame: Frame) -> jax.Array:
        """The matrix ``_predict_raw`` expects.  Default: the standardized
        one-hot design; tree models override with the raw-value design."""
        return self.datainfo.make_matrix(frame)

    def predict(self, frame: Frame) -> Frame:
        """Score a frame — returns a Frame shaped like the reference's preds.

        Classification: ``predict`` (label) + one probability column per
        class.  Regression: single ``predict`` column.
        """
        di = self.datainfo
        raw = np.asarray(self._predict_raw(self._score_matrix(frame)))
        raw = raw[: frame.nrows]
        if di.is_classifier:
            dom = di.response_domain
            labels = np.argmax(raw, axis=1)
            if raw.shape[1] == 2:
                thr = self.default_threshold()
                labels = (raw[:, 1] >= thr).astype(np.int64)
            names = ["predict"] + [str(d) for d in dom]
            vecs = [Vec.from_numpy(labels.astype(np.int32), T_CAT,
                                   domain=[str(d) for d in dom])]
            vecs += [Vec.from_numpy(raw[:, k], T_NUM) for k in range(raw.shape[1])]
            return Frame(names, vecs)
        return Frame(["predict"], [Vec.from_numpy(raw.astype(np.float64), T_NUM)])

    def default_threshold(self) -> float:
        m = self.training_metrics
        thr = getattr(m, "max_f1_threshold", None) if m is not None else None
        return float(thr) if thr is not None else 0.5

    def model_performance(self, frame: Optional[Frame] = None):
        """Compute metrics on a frame (None -> training metrics)."""
        if frame is None:
            return self.training_metrics
        from ..metrics.core import make_metrics
        di = self.datainfo
        raw = self._predict_raw(self._score_matrix(frame))
        y = di.response(frame)
        w = di.weights(frame)
        return make_metrics(di, raw, y, w, distribution=getattr(
            self.params, "distribution", None),
            custom_metric_func=self.params.custom_metric_func)

    # ------------------------------------------------------------ persistence
    # Model artifacts are pickles; load() may face bytes from outside this
    # process (POST /3/Models.upload.bin), so deserialization is allow-
    # listed: this package's CLASSES (never functions — blocks e.g.
    # h2o3_tpu.persist.delete as a gadget), numpy array reconstruction,
    # and stdlib containers.  save() already converts device arrays to
    # numpy, so legitimate artifacts never need anything else.  Known
    # limitation: a model whose params hold a user callable (custom
    # metric fn) will not reload — security of the upload route wins.
    _UNPICKLE_CLASS_MODULES = ("h2o3_tpu", "numpy", "collections",
                               "builtins")
    _UNPICKLE_CALLABLES = {
        "numpy._core.multiarray._reconstruct",
        "numpy.core.multiarray._reconstruct",
        "numpy._core.multiarray.scalar",
        "numpy.core.multiarray.scalar",
        "numpy._core.numeric._frombuffer",
        "numpy.core.numeric._frombuffer",
    }

    def save(self, path: str) -> str:
        """Save the model to any persist URI (local, gcs://, s3://, …)."""
        from .. import persist
        state = self.__dict__.copy()
        if isinstance(state.get("output"), dict):
            # "stacked" duplicates output["trees"] as raw device arrays;
            # it is rebuilt lazily on first scoring after load
            state["output"] = {k: v for k, v in state["output"].items()
                               if k != "stacked"}
        state = jax.tree.map(
            lambda v: np.asarray(v) if isinstance(v, jax.Array) else v, state)
        with persist.open_write(path) as f:
            pickle.dump((type(self), state), f)
        return path

    def download_mojo(self, path: str) -> str:
        """Export the portable scoring artifact (MOJO analog)."""
        from ..export.mojo import export_mojo
        return export_mojo(self, path)

    @staticmethod
    def load(path: str) -> "Model":
        from .. import persist
        with persist.open_read(path) as f:
            cls, state = _RestrictedUnpickler(f).load()
        m = object.__new__(cls)
        m.__dict__.update(state)
        dkv.put(m.key, m)
        return m

    def summary(self) -> dict:
        return {"key": self.key, "algo": self.algo, **{
            k: v for k, v in self.output.items()
            if isinstance(v, (int, float, str, bool, list))}}

    def __repr__(self):
        return f"<{type(self).__name__} {self.key}>"


class _RestrictedUnpickler(pickle.Unpickler):
    """Allowlisted unpickling for model artifacts (see Model.save note)."""

    def find_class(self, module, name):
        full = f"{module}.{name}"
        if full in Model._UNPICKLE_CALLABLES:
            return super().find_class(module, name)
        root = module.split(".", 1)[0]
        if root in Model._UNPICKLE_CLASS_MODULES:
            obj = super().find_class(module, name)
            # classes only: reconstructing instances is fine, but plain
            # functions (persist.delete, builtins.exec, np.f2py helpers…)
            # are exactly what pickle gadgets invoke
            if isinstance(obj, type):
                return obj
        raise pickle.UnpicklingError(
            f"model artifact references disallowed global {full}")


class ModelBuilder:
    """Base builder — analog of hex.ModelBuilder.trainModel()."""

    algo = "model"
    model_class = Model
    supervised = True

    def __init__(self, params: Parameters):
        self.params = params
        self.job: Optional[Job] = None

    # -- hooks ---------------------------------------------------------------
    def _validate(self, frame: Frame) -> None:
        p = self.params
        if self.supervised:
            if not p.response_column:
                raise ValueError(f"{self.algo}: response_column is required")
            if p.response_column not in frame.names:
                raise ValueError(
                    f"response_column {p.response_column!r} not in frame")

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame,
            response_column=p.response_column if self.supervised else None,
            ignored_columns=p.ignored_columns,
            weights_column=p.weights_column,
            offset_column=p.offset_column,
            standardize=p.standardize,
            missing_values_handling=p.missing_values_handling,
            force_classification=getattr(self, "_force_classification", False))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> Model:
        raise NotImplementedError

    # -- driver --------------------------------------------------------------
    def _apply_balance(self, frame: Frame):
        """balance_classes as per-class weights: returns (frame,
        params_override or None).  The override is installed only for
        the duration of the run (xgboost's _xgb_w_ pattern) and the
        fitted model's DataInfo keeps the USER's weights column so
        scoring new frames honors their weights, not the synthetic
        training column."""
        p = self.params
        if not getattr(p, "balance_classes", False) or not self.supervised:
            return frame, None
        rvec = frame.vec(p.response_column)
        if rvec.type != T_CAT:
            return frame, None              # regression: nothing to balance
        k = rvec.cardinality
        if k <= 0:
            raise ValueError(
                "balance_classes needs a categorical response with a "
                "domain (got a cat column without one)")
        codes = np.asarray(rvec.data)[: frame.nrows]
        counts = np.bincount(codes[codes >= 0], minlength=k).astype(float)
        counts[counts == 0] = 1.0
        if p.class_sampling_factors is not None:
            factors = np.asarray(p.class_sampling_factors, float)
        else:
            factors = counts.sum() / (k * counts)
        if len(factors) != k:
            raise ValueError(
                f"class_sampling_factors needs {k} entries, got "
                f"{len(factors)}")
        w = np.where(codes >= 0, factors[np.clip(codes, 0, k - 1)], 0.0)
        if p.weights_column:
            w = w * frame.vec(p.weights_column).to_numpy()
        out = frame.with_vec("_balance_weights_",
                             Vec.from_numpy(w.astype(np.float64), T_NUM))
        return out, dataclasses.replace(
            p, weights_column="_balance_weights_")

    def _balance_valid(self, valid, orig):
        """Mirror the synthetic weights name onto the validation frame
        with the USER's weights (or ones): validation metrics are never
        class-balanced, matching the reference."""
        if valid is None or "_balance_weights_" in valid.names:
            return valid
        uv = valid.vec(orig.weights_column).to_numpy() \
            if orig.weights_column else np.ones(valid.nrows)
        return valid.with_vec(
            "_balance_weights_",
            Vec.from_numpy(np.asarray(uv, np.float64), T_NUM))

    #: set True by builders whose _fit honors params.checkpoint (the tree
    #: family) — gates warm_start= and StreamingFrame training, which are
    #: both built on checkpoint continuation
    _supports_checkpoint = False

    def train(self, frame: Frame, valid: Optional[Frame] = None,
              warm_start: Optional[Any] = None) -> Model:
        """Blocking train — the trainModel/Driver.computeImpl path.

        ``warm_start`` (also available as a parameter) continues boosting
        from a prior model — a Model, a DKV key, or a saved-model path —
        and is bit-identical to checkpoint continuation.  A
        ``StreamingFrame`` trains in stream mode: boosting starts on the
        rows already landed behind the watermark and re-bins at chunk
        fences as more data arrives.
        """
        ws = warm_start if warm_start is not None else self.params.warm_start
        if ws is not None:
            if not self._supports_checkpoint:
                raise ValueError(
                    f"{self.algo} does not support warm_start (no "
                    "checkpoint continuation)")
            orig = self.params
            try:
                self.params = dataclasses.replace(
                    orig, warm_start=None,
                    checkpoint=self._resolve_warm_start(ws))
                return self.train(frame, valid)
            finally:
                self.params = orig
        if not isinstance(frame, Frame) and hasattr(frame, "watermark"):
            return self._train_stream(frame, valid)
        self._validate(frame)
        frame, bal = self._apply_balance(frame)
        orig = self.params
        if bal is not None:
            self.params = bal
            valid = self._balance_valid(valid, orig)
        try:
            di = self._make_datainfo(frame)
            self.job = Job(f"{self.algo} train",
                           dest_key=dkv.make_key(self.algo))
            if getattr(self, "_stream_ctx", None) is not None:
                self.job.stream = self._stream_ctx.progress()
            return self.job.run(self._make_driver(
                frame, di, valid,
                orig_params=orig if bal is not None else None))
        finally:
            self.params = orig

    def _resolve_warm_start(self, ws) -> str:
        """Normalize a warm_start (Model | DKV key | saved path) to the
        DKV key checkpoint continuation expects."""
        if isinstance(ws, Model):
            if dkv.get(ws.key) is None:
                dkv.put(ws.key, ws)
            return ws.key
        if isinstance(ws, str):
            if dkv.get(ws) is not None:
                return ws
            import os
            if os.path.exists(ws):
                return Model.load(ws).key
            raise ValueError(
                f"warm_start {ws!r} is neither a DKV model key nor a "
                "saved model file")
        raise ValueError(f"warm_start must be a Model, key, or path, "
                         f"got {type(ws).__name__}")

    def _train_stream(self, sf, valid: Optional[Frame] = None) -> Model:
        """Train while a StreamingFrame lands: boost on the visible
        prefix, cut at a chunk fence when enough new rows arrive (or the
        landed-fraction tree budget is spent), re-bin the grown prefix
        with the prior's edges, and continue as a checkpoint segment.
        Bit-identity with batch training holds for the degenerate
        single-segment case; multi-segment runs record their per-segment
        row coverage in ``model.output["stream_coverage"]``.
        """
        import math

        from ..runtime.config import config
        from ..runtime.observability import inc

        if not self._supports_checkpoint:
            raise ValueError(
                f"{self.algo} cannot train on a StreamingFrame (no "
                "checkpoint continuation to re-bin against)")
        cfg = config()
        sf.start()
        sf.wait_rows(max(cfg.stream_min_rows, 1))
        p0 = self.params
        ntrees = getattr(p0, "ntrees", None)
        if ntrees is None:
            raise ValueError(f"{self.algo} has no ntrees — stream mode "
                             "is for the tree family")
        model, prior_key, prior_nt = None, p0.checkpoint, 0
        if prior_key is not None:
            prior = dkv.get(prior_key) if isinstance(prior_key, str) \
                else prior_key
            prior_nt = prior.output["ntrees_trained"]
        coverage: List[dict] = []
        self._stream_ctx = sf
        last_rows = 0
        try:
            while True:
                wm = sf.watermark
                total = sf.total_rows
                full = sf.complete and (total is None or wm >= total)
                r = cfg.stream_round_rows
                rows_vis = wm if (full or r <= 0) \
                    else ((wm // r) * r or wm)
                if not full and rows_vis <= last_rows:
                    # quantization floored us back onto the last segment:
                    # wait for more rows before cutting a new one
                    sf.wait_growth(max(last_rows, 1),
                                   cfg.stream_grow_min_frac)
                    continue
                if full:
                    # the landing thread's finalize assembles the
                    # registered frame anyway — wait for it instead of
                    # assembling a duplicate
                    vis = sf.frame()
                else:
                    vis = sf.visible_frame(
                        limit=rows_vis if rows_vis < wm else None)
                rows0 = vis.nrows
                grow = max(1, int(rows0 * cfg.stream_grow_min_frac))
                seg_prior_nt = prior_nt
                cut = {"hit": False}

                def fence(t_rel: int, _rows0=rows0, _grow=grow,
                          _pnt=seg_prior_nt, _cut=cut) -> bool:
                    if self.job is not None:
                        self.job.stream = sf.progress()
                    wm_now = sf.watermark
                    if sf.complete:
                        # grab the tail as soon as the stream runs out
                        # (or keep going: this segment IS the full data)
                        _cut["hit"] = wm_now > _rows0
                        return _cut["hit"]
                    tot = sf.total_rows
                    if not tot:
                        # size unknown: fall back to growth-based cuts
                        _cut["hit"] = wm_now >= _rows0 + _grow
                        return _cut["hit"]
                    # pace trees to the landed fraction; the budget rises
                    # as rows land mid-segment, so a fast stream defers
                    # the cut and a stalled one forces it (the outer
                    # loop then blocks in wait_growth — that's the pause)
                    budget = max(_pnt + 1, math.ceil(
                        ntrees * min(1.0, wm_now / tot)))
                    _cut["hit"] = _pnt + t_rel >= budget
                    return _cut["hit"]

                self._stream_fence = fence
                self.params = dataclasses.replace(
                    p0, checkpoint=prior_key, stream=False)
                try:
                    model = self.train(vis, valid)
                finally:
                    self._stream_fence = None
                    self.params = p0
                prior_key = model.key
                prior_nt = model.output["ntrees_trained"]
                coverage.append({"trees": int(prior_nt),
                                 "rows": int(rows0)})
                if len(coverage) > 1:
                    inc("stream_rebin_total", algo=self.algo)
                sf.consume(rows0)
                last_rows = rows0
                if prior_nt >= ntrees:
                    break
                if full and not cut["hit"]:
                    break                # early stop on the full data
                sf.wait_growth(rows0, cfg.stream_grow_min_frac)
        finally:
            self._stream_ctx = None
            self.params = p0
        model.output["stream_coverage"] = coverage
        model.output["stream_segments"] = len(coverage)
        if self.job is not None:
            self.job.stream = sf.progress()
        return model

    def _make_driver(self, frame: Frame, di: DataInfo,
                     valid: Optional[Frame], orig_params=None):
        """The full training driver (CV, post-fit hooks, checkpoint export)
        shared by the blocking and async entry points.  ``orig_params``
        is set when balance_classes installed a temporary params
        override: the driver restores it when done and journals/scores
        with the user's own parameters."""
        def _driver(job: Job) -> Model:
            from ..runtime import recovery
            # reuse a submit-time (or previous-life) journal entry: a
            # requeued job keeps its snapshot pointer for the next resume
            journal = job.journal_uri or recovery.journal_start(
                self, frame, job, params=orig_params)
            job.journal_uri = journal      # gates in-training snapshots
            try:
                # the device lease serializes compiled-program launches
                # across co-resident jobs (XLA in-process collectives
                # deadlock on concurrent launches); chunk_fence yields
                # it at every chunk boundary so jobs still interleave
                from ..runtime import scheduler as _sched
                with _sched.device_slot():
                    model = self._driver_body(job, frame, di, valid, journal)
            except BaseException as e:
                # cancelled / deterministically failing jobs must not be
                # resurrected as if the process had died — but a failure
                # caused by a dead/dying member stays 'running' in the
                # journal so recovery.resume() resurrects it after restart
                from ..runtime import failure
                if isinstance(e, JobCancelled) or not (
                        isinstance(e, failure.NodeFailedError)
                        or failure.cluster_degraded()):
                    recovery.journal_fail(journal, repr(e))
                raise
            finally:
                if orig_params is not None:
                    self.params = orig_params
            if orig_params is not None:
                # scoring frames carry the USER's weights column (if
                # any), never the synthetic training-only balance column
                model.datainfo = dataclasses.replace(
                    model.datainfo,
                    weights_column=orig_params.weights_column)
            return model
        return _driver

    def _driver_body(self, job: "Job", frame: Frame, di: DataInfo,
                     valid: Optional[Frame], journal) -> Model:
            from ..runtime import recovery
            t0 = time.time()
            if self.params.nfolds and self.params.nfolds > 1:
                model = self._train_cv(job, frame, di, valid)
            else:
                model = self._fit(job, frame, di, valid)
            model.output.setdefault("run_time_s", time.time() - t0)
            model.output.setdefault("training_frame_rows", frame.nrows)
            self._post_fit(model, frame, valid)
            if self.params.export_checkpoints_dir:
                import os
                os.makedirs(self.params.export_checkpoints_dir, exist_ok=True)
                model.save(os.path.join(self.params.export_checkpoints_dir,
                                        model.key + ".bin"))
            recovery.journal_done(journal)
            return model

    def _post_fit(self, model: Model, frame: Frame,
                  valid: Optional[Frame]) -> None:
        """Hook after _fit (calibration, etc.); default no-op."""

    def train_async(self, frame: Frame, valid: Optional[Frame] = None,
                    priority: Optional[int] = None,
                    user: Optional[str] = None) -> Job:
        """Queue training on the cluster scheduler; returns the Job.

        The h2o.train(..., async) analog over the fair-share scheduler
        (runtime/scheduler.py): poll ``job.status`` / ``/3/Jobs`` or
        ``job.join()`` for the model.  Placement comes from the params —
        ``priority`` (arg overrides), ``device_budget``,
        ``retry_budget`` — and the journal entry is written at SUBMIT
        time, so even a queued-but-unstarted job survives a coordinator
        restart via ``scheduler.readmit()``.
        """
        from ..runtime import recovery
        from ..runtime.job import scheduler, JobScheduler
        self._validate(frame)
        frame, bal = self._apply_balance(frame)
        orig_async = self.params
        if bal is not None:
            # stays installed while the queued driver runs; the driver's
            # finally restores it (concurrent reuse of one builder with
            # balance_classes is not supported)
            self.params = bal
            valid = self._balance_valid(valid, orig_async)
        p = self.params
        di = self._make_datainfo(frame)
        self.job = Job(f"{self.algo} train",
                       dest_key=dkv.make_key(self.algo))
        self.job.journal_uri = recovery.journal_start(
            self, frame, self.job,
            params=orig_async if bal is not None else None)
        if priority is None:
            priority = JobScheduler.PRIORITY_BUILD \
                if p.priority is None else p.priority
        try:
            return scheduler().submit(
                self.job,
                self._make_driver(frame, di, valid,
                                  orig_params=orig_async
                                  if bal is not None else None),
                priority=priority,
                device_budget=p.device_budget,
                retry_budget=p.retry_budget or 0,
                user=user)
        except BaseException as e:
            # admission rejected: the submit-time journal entry must not
            # be resurrected as if the process had died
            recovery.journal_fail(self.job.journal_uri, repr(e))
            raise

    # -- cross-validation (hex/CVModelBuilder.java:10) -----------------------
    def _train_cv(self, job: Job, frame: Frame, di: DataInfo,
                  valid: Optional[Frame]) -> Model:
        from .cv import cross_validate
        return cross_validate(self, job, frame, di, valid)
