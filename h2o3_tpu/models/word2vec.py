"""Word2Vec: skip-gram with negative sampling as embedding matmuls.

Reference: ``hex/word2vec/Word2Vec.java:15`` — distributed skip-gram with
per-node training and model averaging (the DL Hogwild pattern); input is a
string column of words, sentences delimited by NA rows.

TPU-native redesign: pair generation (windows, vocabulary, unigram^0.75
negative table) is host-side; training is minibatched SGNS on device — each
step gathers [B, D] center/context/negative embeddings, computes the
sigmoid losses, and scatter-adds the updates (jnp .at[].add), all in one
jit.  Synchronous minibatch SGD replaces Hogwild (SURVEY.md §2.10).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_NUM, T_STR
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class Word2VecParameters(Parameters):
    vec_size: int = 100
    window_size: int = 5
    min_word_freq: int = 5
    epochs: int = 5
    learn_rate: float = 0.025       # init_learning_rate
    negative_samples: int = 5
    sent_sample_rate: float = 1e-3  # frequent-word subsampling
    batch_size: int = 8192


@jax.jit
def _sgns_step(U, V, center, context, neg, lr):
    """One SGNS minibatch: returns updated (U, V)."""
    u = U[center]                                  # [B, D]
    vpos = V[context]                              # [B, D]
    vneg = V[neg]                                  # [B, k, D]
    spos = jax.nn.sigmoid(jnp.sum(u * vpos, axis=1))         # [B]
    sneg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", u, vneg))  # [B, k]
    gpos = (spos - 1.0)[:, None]                   # dL/d(u.vpos)
    gneg = sneg[:, :, None]                        # dL/d(u.vneg)
    du = gpos * vpos + jnp.einsum("bk,bkd->bd", sneg, vneg)
    U = U.at[center].add(-lr * du)
    V = V.at[context].add(-lr * gpos * u)
    V = V.at[neg].add(-lr * gneg * u[:, None, :])
    return U, V


class Word2VecModel(Model):
    algo = "word2vec"

    def find_synonyms(self, word: str, count: int = 10) -> Dict[str, float]:
        vocab: Dict[str, int] = self.output["vocab"]
        if word not in vocab:
            return {}
        E = self.output["embeddings"]
        v = E[vocab[word]]
        sims = E @ v / (np.linalg.norm(E, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(sims)[::-1]
        words = self.output["words"]
        out = {}
        for i in order:
            if words[i] != word:
                out[words[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, frame: Frame, aggregate_method: str = "none"):
        """Word -> embedding frame; 'average' pools NA-delimited sequences."""
        vocab = self.output["vocab"]
        E = self.output["embeddings"]
        col = frame.vecs[0]
        words = col.host_data if col.data is None else col.decoded()
        D = E.shape[1]
        if aggregate_method == "none":
            M = np.zeros((frame.nrows, D))
            for i, wd in enumerate(words):
                j = vocab.get(str(wd), -1)
                M[i] = E[j] if j >= 0 else np.nan
        else:
            seqs, cur = [], []
            for wd in words:
                if wd is None or (isinstance(wd, float) and np.isnan(wd)):
                    seqs.append(cur)
                    cur = []
                else:
                    cur.append(str(wd))
            seqs.append(cur)
            seqs = [s for s in seqs if s]
            M = np.zeros((len(seqs), D))
            for i, s in enumerate(seqs):
                vs = [E[vocab[wd]] for wd in s if wd in vocab]
                M[i] = np.mean(vs, axis=0) if vs else np.nan
        return Frame([f"C{i+1}" for i in range(D)],
                     [Vec.from_numpy(M[:, i], T_NUM) for i in range(D)])

    def _predict_raw(self, X):
        raise NotImplementedError("word2vec transforms, not predicts")

    def model_performance(self, frame=None):
        return self.training_metrics


class Word2Vec(ModelBuilder):
    """Word2Vec builder — H2OWord2vecEstimator analog."""

    algo = "word2vec"
    model_class = Word2VecModel
    supervised = False

    def __init__(self, params: Optional[Word2VecParameters] = None, **kw):
        super().__init__(params or Word2VecParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        if frame.ncols != 1:
            raise ValueError("word2vec expects a single words column")

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        return None                      # no tabular featurization

    def _fit(self, job: Job, frame: Frame, di, valid) -> Word2VecModel:
        p: Word2VecParameters = self.params
        col = frame.vecs[0]
        raw = col.host_data if col.data is None else col.decoded()
        rng = np.random.default_rng(p.effective_seed())

        # vocabulary (NA rows delimit sentences)
        sents: List[List[str]] = []
        cur: List[str] = []
        for wd in raw:
            if wd is None or (isinstance(wd, float) and np.isnan(wd)):
                if cur:
                    sents.append(cur)
                cur = []
            else:
                cur.append(str(wd))
        if cur:
            sents.append(cur)
        freq: Dict[str, int] = {}
        for s in sents:
            for wd in s:
                freq[wd] = freq.get(wd, 0) + 1
        words = sorted([w for w, c in freq.items() if c >= p.min_word_freq])
        vocab = {w: i for i, w in enumerate(words)}
        V = len(words)
        if V < 2:
            raise ValueError("word2vec: vocabulary too small "
                             f"(min_word_freq={p.min_word_freq})")
        counts = np.array([freq[w] for w in words], np.float64)
        total = counts.sum()
        # subsample frequent words (word2vec's t-threshold)
        keep_p = np.minimum(
            1.0, np.sqrt(p.sent_sample_rate / (counts / total))
            + p.sent_sample_rate / (counts / total))
        neg_table = counts ** 0.75
        neg_table /= neg_table.sum()

        # generate skip-gram pairs host-side
        centers, contexts = [], []
        for s in sents:
            ids = [vocab[wd] for wd in s if wd in vocab
                   and rng.random() < keep_p[vocab[wd]]]
            for i, c in enumerate(ids):
                win = rng.integers(1, p.window_size + 1)
                for j in range(max(0, i - win), min(len(ids), i + win + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise ValueError("word2vec: no training pairs generated")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        D = p.vec_size
        U = jnp.asarray(rng.uniform(-0.5 / D, 0.5 / D, (V, D)), jnp.float32)
        Vc = jnp.zeros((V, D), jnp.float32)
        B = min(p.batch_size, len(centers))
        npairs = len(centers)
        steps_per_epoch = max(npairs // B, 1)
        total_steps = int(p.epochs) * steps_per_epoch
        step_i = 0
        for epoch in range(int(p.epochs)):
            perm = rng.permutation(npairs)
            for b in range(steps_per_epoch):
                sl = perm[b * B:(b + 1) * B]
                if len(sl) < B:
                    sl = np.concatenate([sl, perm[: B - len(sl)]])
                neg = rng.choice(V, size=(B, p.negative_samples),
                                 p=neg_table).astype(np.int32)
                lr = p.learn_rate * max(
                    1e-4, 1.0 - step_i / max(total_steps, 1))
                U, Vc = _sgns_step(U, Vc, jnp.asarray(centers[sl]),
                                   jnp.asarray(contexts[sl]),
                                   jnp.asarray(neg), lr)
                step_i += 1
            job.update((epoch + 1) / p.epochs, f"epoch {epoch + 1}")

        model = Word2VecModel(job.dest_key or dkv.make_key(self.algo), p, di)
        model.output.update({
            "embeddings": np.asarray(U, np.float64),
            "vocab": vocab, "words": words, "vocab_size": V,
            "pairs_trained": npairs * int(p.epochs),
        })
        model.training_metrics = {"vocab_size": V, "pairs": npairs}
        return model
