"""AdaBoost: SAMME boosting of shallow tpu_hist trees.

Reference: ``hex/adaboost/AdaBoost.java`` (h2o-algos) — binary AdaBoost with
weak tree learners; per-iteration alpha from the weighted error, row weights
multiplied by exp(+-alpha).

TPU-native redesign: the weak learner is one shallow regression tree on the
signed target fit through the same single-dispatch device build as GBM; the
weight update / error reduction is one fused elementwise pass.  Scoring is
the margin of the alpha-weighted stacked-tree traversal.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import ModelBuilder
from .datainfo import DataInfo
from .tree.binning import fit_bins, edges_matrix
from .tree.shared import (SharedTree, SharedTreeModel, SharedTreeParameters,
                          build_tree, stack_trees, traverse_jit)
from ..metrics.core import make_metrics


@dataclasses.dataclass
class AdaBoostParameters(SharedTreeParameters):
    nlearners: int = 50
    max_depth: int = 3
    learn_rate: float = 0.5          # shrinkage on alphas
    min_rows: float = 5.0


class AdaBoostModel(SharedTreeModel):
    algo = "adaboost"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        levels, values = stack_trees(self.output["trees"])
        margin = traverse_jit(levels, values, X)     # alphas folded in values
        p1 = 1.0 / (1.0 + jnp.exp(-2.0 * margin))
        return jnp.stack([1 - p1, p1], axis=1)


class AdaBoost(SharedTree):
    """AdaBoost builder — H2OAdaBoostEstimator analog (binary)."""

    algo = "adaboost"
    model_class = AdaBoostModel
    _force_classification = True

    def __init__(self, params: Optional[AdaBoostParameters] = None, **kw):
        ModelBuilder.__init__(self, params or AdaBoostParameters(**kw))

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> AdaBoostModel:
        p: AdaBoostParameters = self.params
        if not di.is_classifier or di.nclasses != 2:
            raise ValueError("adaboost requires a binary response")
        y = di.response(frame)
        w0 = di.weights(frame)
        binned = fit_bins(frame, [s.name for s in di.specs], nbins=p.nbins,
                          histogram_type=p.histogram_type,
                          seed=p.effective_seed())
        codes = binned.codes
        edges_mat = jnp.asarray(edges_matrix(binned.edges, p.nbins),
                                jnp.float32)
        ysign = jnp.where(y > 0.5, 1.0, -1.0) * (w0 > 0)
        rng = jax.random.PRNGKey(p.effective_seed())
        D = w0 / jnp.maximum(jnp.sum(w0), 1e-12)

        model = AdaBoostModel(job.dest_key or dkv.make_key(self.algo), p, di)
        trees: List = []
        for t in range(p.nlearners):
            rng, k = jax.random.split(rng)
            # regression weak learner on the signed target, weights D
            tree, leaf = build_tree(
                codes, -ysign * D, D, D, edges_mat, p.nbins, p.max_depth,
                p.reg_lambda, p.min_rows / max(frame.nrows, 1),
                p.min_split_improvement, 1.0, k, p.col_sample_rate, None,
                hist_precision=p.effective_hist_precision)
            h = jnp.sign(jnp.asarray(tree.values)[leaf])
            h = jnp.where(h == 0, 1.0, h)
            err = jnp.sum(D * (h != ysign) * (w0 > 0))
            err = jnp.clip(err, 1e-10, 1 - 1e-10)
            alpha = 0.5 * jnp.log((1 - err) / err) * p.learn_rate
            alpha_h = float(alpha)
            if alpha_h <= 0:
                break
            # fold alpha into leaf signs so scoring is plain traversal
            tree.values = np.sign(np.asarray(tree.values)) * alpha_h
            tree.values[tree.values == 0] = alpha_h
            trees.append(tree)
            D = D * jnp.exp(-alpha * ysign * h)
            D = D / jnp.maximum(jnp.sum(D), 1e-12)
            job.update((t + 1) / p.nlearners,
                       f"learner {t+1} err={float(err):.4f}")

        model.output.update({"trees": trees, "ntrees_trained": len(trees),
                             "nclass_trees": 1, "init_score": 0.0})
        raw = model._predict_raw(model._design(frame))
        model.training_metrics = make_metrics(di, raw, y, w0)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
