"""KMeans: Lloyd iterations as MXU distance matmuls over the row-sharded mesh.

Reference: ``hex/kmeans/KMeans.java:26`` (h2o-algos) — Lloyd iterations as
MRTasks with per-chunk partial sums reduced across the cluster; init methods
Random / PlusPlus / Furthest / User; ``estimate_k`` heuristic grows k while
the within-SS improvement is large; categorical columns one-hot expanded and
standardization on by default.

TPU-native redesign: one jitted Lloyd step — the [rows, k] distance block is
``|x|^2 - 2 X C^T + |c|^2`` (an MXU matmul), assignment is an argmin, and the
new centers are the one-hot-assignment matmul ``A^T X`` (MXU again); XLA's
partitioner inserts the cross-device psums that replace the MRTask reduce
tree.  No per-row scalar loops anywhere.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class KMeansParameters(Parameters):
    k: int = 1
    estimate_k: bool = False
    init: str = "furthest"            # random | plus_plus | furthest | user
    user_points: Optional[np.ndarray] = None
    max_iterations: int = 10
    standardize: bool = True


class ModelMetricsClustering:
    """totss / tot_withinss / betweenss + per-cluster breakdown.

    Analog of ``hex/ModelMetricsClustering.java``.
    """

    def __init__(self, totss, tot_withinss, withinss, sizes):
        self.totss = float(totss)
        self.tot_withinss = float(tot_withinss)
        self.betweenss = self.totss - self.tot_withinss
        self.withinss = [float(v) for v in withinss]
        self.size = [int(v) for v in sizes]

    def describe(self) -> dict:
        return {"totss": self.totss, "tot_withinss": self.tot_withinss,
                "betweenss": self.betweenss, "withinss": self.withinss,
                "size": self.size}

    def __repr__(self):
        return (f"ModelMetricsClustering(totss={self.totss:.4g}, "
                f"tot_withinss={self.tot_withinss:.4g}, "
                f"betweenss={self.betweenss:.4g}, k={len(self.size)})")


@partial(jax.jit, static_argnames=())
def _lloyd_step(X, w, centers):
    """One Lloyd iteration: assignment + new center sums + SS stats."""
    d2 = (jnp.sum(X * X, axis=1, keepdims=True)
          - 2.0 * X @ centers.T
          + jnp.sum(centers * centers, axis=1)[None, :])
    d2 = jnp.maximum(d2, 0.0)
    assign = jnp.argmin(d2, axis=1)
    mind2 = jnp.min(d2, axis=1)
    k = centers.shape[0]
    A = (assign[:, None] == jnp.arange(k)[None, :]).astype(X.dtype) * w[:, None]
    sums = A.T @ X                         # [k, P] — MXU + psum across shards
    counts = jnp.sum(A, axis=0)            # [k]
    withinss = jnp.sum(A * mind2[:, None], axis=0)
    return assign, sums, counts, withinss


@jax.jit
def _min_d2(X, w, centers):
    d2 = (jnp.sum(X * X, axis=1, keepdims=True)
          - 2.0 * X @ centers.T
          + jnp.sum(centers * centers, axis=1)[None, :])
    return jnp.maximum(jnp.min(d2, axis=1), 0.0) * w


class KMeansModel(Model):
    algo = "kmeans"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        centers = jnp.asarray(self.output["centers_std"], jnp.float32)
        d2 = (jnp.sum(X * X, axis=1, keepdims=True)
              - 2.0 * X @ centers.T
              + jnp.sum(centers * centers, axis=1)[None, :])
        return jnp.argmin(d2, axis=1).astype(jnp.float32)

    def predict(self, frame: Frame) -> Frame:
        from ..frame.vec import Vec, T_CAT
        X = self.datainfo.make_matrix(frame)
        labels = np.asarray(self._predict_raw(X))[: frame.nrows].astype(np.int32)
        k = len(self.output["centers"])
        return Frame(["predict"], [Vec.from_numpy(
            labels, T_CAT, domain=[str(i) for i in range(k)])])

    def model_performance(self, frame: Optional[Frame] = None):
        if frame is None:
            return self.training_metrics
        di = self.datainfo
        X = di.make_matrix(frame)
        w = di.weights(frame)
        centers = jnp.asarray(self.output["centers_std"], jnp.float32)
        _, _, counts, withinss = _lloyd_step(X, w, centers)
        gmean = jnp.sum(X * w[:, None], axis=0) / jnp.maximum(jnp.sum(w), 1.0)
        totss = float(jnp.sum(_min_d2(X, w, gmean[None, :])))
        return ModelMetricsClustering(totss, float(jnp.sum(withinss)),
                                      np.asarray(withinss), np.asarray(counts))


class KMeans(ModelBuilder):
    """KMeans builder — h2o.kmeans / H2OKMeansEstimator analog."""

    algo = "kmeans"
    model_class = KMeansModel
    supervised = False

    def __init__(self, params: Optional[KMeansParameters] = None, **kw):
        super().__init__(params or KMeansParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        return DataInfo.fit(
            frame, response_column=None, ignored_columns=p.ignored_columns,
            weights_column=p.weights_column, standardize=p.standardize,
            use_all_factor_levels=True, add_intercept=False,
            missing_values_handling=p.missing_values_handling)

    # ------------------------------------------------------------------ init
    def _init_centers(self, X, w, k: int, rng: np.random.Generator,
                      di: DataInfo) -> np.ndarray:
        p: KMeansParameters = self.params
        N = X.shape[0]
        wh = np.asarray(w)
        valid_idx = np.flatnonzero(wh > 0)
        if p.init == "user":
            if p.user_points is None:
                raise ValueError("init='user' requires user_points")
            pts = np.asarray(p.user_points, np.float64)
            if pts.shape[1] != X.shape[1]:
                if any(s.width > 1 for s in di.specs):
                    raise ValueError(
                        "init='user' with categorical features requires "
                        f"points in the one-hot-expanded space "
                        f"([k, {X.shape[1]}]), got {pts.shape}")
                raise ValueError(
                    f"user_points must be [k, {X.shape[1]}], got {pts.shape}")
            if p.standardize:
                means = np.array([s.mean for s in di.specs for _ in
                                  range(s.width)])
                sigmas = np.array([s.sigma for s in di.specs for _ in
                                   range(s.width)])
                pts = (pts - means) / sigmas
            return pts.astype(np.float32)
        if p.init == "random":
            idx = rng.choice(valid_idx, size=k, replace=False)
            return np.asarray(X[idx])
        # plus_plus / furthest: sequential greedy seeding by distance
        first = int(rng.choice(valid_idx))
        centers = [np.asarray(X[first])]
        for _ in range(1, k):
            d2 = np.asarray(_min_d2(X, w, jnp.asarray(np.stack(centers))))
            if p.init == "furthest":
                nxt = int(np.argmax(d2))
            else:                                  # plus_plus: D^2 sampling
                s = d2.sum()
                probs = d2 / s if s > 0 else wh / wh.sum()
                nxt = int(rng.choice(len(d2), p=probs))
            centers.append(np.asarray(X[nxt]))
        return np.stack(centers)

    # ------------------------------------------------------------------- fit
    def _run_lloyd(self, job, X, w, centers0: np.ndarray, tag: str):
        p: KMeansParameters = self.params
        centers = jnp.asarray(centers0, jnp.float32)
        k = centers.shape[0]
        prev_tot = np.inf
        iters = 0
        for it in range(max(p.max_iterations, 1)):
            _, sums, counts, withinss = _lloyd_step(X, w, centers)
            counts_h = np.asarray(counts, np.float64)
            sums_h = np.asarray(sums, np.float64)
            new = np.where(counts_h[:, None] > 0,
                           sums_h / np.maximum(counts_h[:, None], 1e-12),
                           np.asarray(centers, np.float64))
            tot = float(jnp.sum(withinss))
            job.update(it / max(p.max_iterations, 1),
                       f"{tag} iter={it} tot_withinss={tot:.5g}")
            shift = float(np.max(np.abs(new - np.asarray(centers, np.float64))))
            centers = jnp.asarray(new, jnp.float32)
            iters = it + 1
            if tot >= prev_tot * (1 - 1e-6) and shift < 1e-7:
                break
            prev_tot = tot
        _, _, counts, withinss = _lloyd_step(X, w, centers)
        return (np.asarray(centers, np.float64), np.asarray(withinss),
                np.asarray(counts), float(jnp.sum(withinss)), iters)

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> KMeansModel:
        p: KMeansParameters = self.params
        rng = np.random.default_rng(p.effective_seed())
        X = di.make_matrix(frame)
        w = di.weights(frame)
        gmean = jnp.sum(X * w[:, None], axis=0) / jnp.maximum(jnp.sum(w), 1.0)
        totss = float(jnp.sum(_min_d2(X, w, gmean[None, :])))

        if p.estimate_k:
            # grow k while tot_withinss improves enough (KMeans.java estimate_k)
            best = None
            prev = totss
            for k in range(1, max(p.k, 2) + 1):
                c0 = self._init_centers(X, w, k, rng, di)
                res = self._run_lloyd(job, X, w, c0, f"k={k}")
                # accept k+1 only on a substantial drop: splitting an
                # already-coherent Gaussian cluster yields ~= (1 - 0.32/k),
                # real structure yields far more
                if best is None or res[3] < prev * 0.8:
                    best, prev, best_k = res, res[3], k
                else:
                    break
            centers, withinss, counts, tot, iters = best
            k = best_k
        else:
            k = p.k
            c0 = self._init_centers(X, w, k, rng, di)
            centers, withinss, counts, tot, iters = self._run_lloyd(
                job, X, w, c0, f"k={k}")

        model = KMeansModel(job.dest_key or dkv.make_key(self.algo), p, di)
        # de-standardized centers for reporting (KMeansModel.Output._centers)
        destd = centers.copy()
        if p.standardize:
            col = 0
            for s in di.specs:
                if s.width == 1:
                    destd[:, col] = centers[:, col] * s.sigma + s.mean
                col += s.width
        model.output.update({
            "centers": destd, "centers_std": centers, "k": int(k),
            "iterations": iters, "coef_names": di.coef_names,
        })
        model.training_metrics = ModelMetricsClustering(
            totss, tot, withinss, counts)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
