"""Naive Bayes: all per-class sufficient statistics as one-hot matmuls.

Reference: ``hex/naivebayes/NaiveBayes.java`` — an MRTask accumulates
per-(class, feature-level) counts for categoricals and per-class mean/sdev
for numerics; Laplace smoothing, ``min_sdev``/``eps_sdev`` floors, apriori
class probabilities; scoring sums log-likelihoods per row.

TPU-native redesign: the entire sufficient-statistics pass is two MXU
matmuls — ``Y_onehot.T @ X`` and ``Y_onehot.T @ X**2`` over the row-sharded
one-hot design matrix (categorical level counts and numeric moment sums fall
out of the same product); scoring is one ``X @ log_prob_table`` matmul plus a
small per-class Gaussian term.  The MRTask reduce tree becomes the XLA psum.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import T_CAT
from ..runtime import dkv
from ..runtime.job import Job
from .base import Model, ModelBuilder, Parameters
from .datainfo import DataInfo


@dataclasses.dataclass
class NaiveBayesParameters(Parameters):
    laplace: float = 0.0
    min_sdev: float = 1e-3
    eps_sdev: float = 0.0
    min_prob: float = 1e-3
    eps_prob: float = 0.0
    standardize: bool = False
    compute_metrics: bool = True


@jax.jit
def _class_moments(X, Y, w):
    """[K,P] weighted per-class sums of X and X^2, plus class weights."""
    Yw = Y * w[:, None]
    M1 = Yw.T @ X
    M2 = Yw.T @ (X * X)
    nk = jnp.sum(Yw, axis=0)
    return M1, M2, nk


class NaiveBayesModel(Model):
    algo = "naivebayes"

    def _predict_raw(self, X: jax.Array) -> jax.Array:
        out = self.output
        log_cat = jnp.asarray(out["_log_cat_table"], jnp.float32)   # [P, K]
        mu = jnp.asarray(out["_num_mu"], jnp.float32)               # [K, Pn]
        inv2v = jnp.asarray(out["_num_inv2var"], jnp.float32)       # [K, Pn]
        logsd = jnp.asarray(out["_num_logsd"], jnp.float32)         # [K, Pn]
        num_idx = jnp.asarray(out["_num_idx"], jnp.int32)
        logprior = jnp.asarray(out["_log_prior"], jnp.float32)      # [K]

        ll = X @ log_cat + logprior[None, :]
        if num_idx.shape[0]:
            Xn = X[:, num_idx]                                       # [N, Pn]
            diff = Xn[:, None, :] - mu[None, :, :]                   # [N, K, Pn]
            ll = ll - jnp.sum(diff * diff * inv2v[None] + logsd[None], axis=2)
        ll = ll - jnp.max(ll, axis=1, keepdims=True)
        probs = jnp.exp(ll)
        return probs / jnp.sum(probs, axis=1, keepdims=True)


class NaiveBayes(ModelBuilder):
    """NaiveBayes builder — h2o.naiveBayes / H2ONaiveBayesEstimator analog."""

    algo = "naivebayes"
    model_class = NaiveBayesModel

    def __init__(self, params: Optional[NaiveBayesParameters] = None, **kw):
        super().__init__(params or NaiveBayesParameters(**kw))

    def _make_datainfo(self, frame: Frame) -> DataInfo:
        p = self.params
        di = DataInfo.fit(
            frame, response_column=p.response_column,
            ignored_columns=p.ignored_columns,
            weights_column=p.weights_column, standardize=False,
            use_all_factor_levels=True, add_intercept=False,
            missing_values_handling=p.missing_values_handling)
        if not di.is_classifier:
            raise ValueError("naivebayes requires a categorical response")
        return di

    def _fit(self, job: Job, frame: Frame, di: DataInfo,
             valid: Optional[Frame]) -> NaiveBayesModel:
        p: NaiveBayesParameters = self.params
        X = di.make_matrix(frame)
        y = di.response(frame)
        w = di.weights(frame)
        K = di.nclasses
        Y = (jnp.clip(y, 0, K - 1).astype(jnp.int32)[:, None]
             == jnp.arange(K)[None, :]).astype(jnp.float32)
        M1, M2, nk = _class_moments(X, Y, w)
        M1 = np.asarray(M1, np.float64)
        M2 = np.asarray(M2, np.float64)
        nk = np.asarray(nk, np.float64)
        n = nk.sum()

        P = di.nfeatures
        log_cat = np.zeros((P, K))
        num_idx, num_mu, num_var = [], [], []
        for s in di.specs:
            sl = slice(s.offset, s.offset + s.width)
            if s.type == T_CAT:
                counts = M1[:, sl].T                        # [W, K] level counts
                # NA bucket (last level of the block) contributes nothing at
                # score time (NaiveBayes.java skips NAs); drop it from the
                # denominator too.
                denom = counts[:-1].sum(axis=0) + p.laplace * (s.width - 1)
                probs = (counts + p.laplace) / np.maximum(denom[None, :], 1e-30)
                # NaiveBayes.java: probability <= eps_prob replaced by min_prob
                probs = np.where(probs <= max(p.eps_prob, 1e-30),
                                 p.min_prob, probs)
                log_cat[sl, :] = np.log(probs)
                log_cat[s.offset + s.width - 1, :] = 0.0
            else:
                mu_k = M1[:, s.offset] / np.maximum(nk, 1e-30)
                var_k = M2[:, s.offset] / np.maximum(nk, 1e-30) - mu_k**2
                sd_k = np.sqrt(np.maximum(var_k, 0.0) * nk
                               / np.maximum(nk - 1.0, 1.0))
                # NaiveBayes.java: sdev <= eps_sdev replaced by min_sdev
                sd_k = np.where(sd_k <= max(p.eps_sdev, 1e-30),
                                p.min_sdev, sd_k)
                num_idx.append(s.offset)
                num_mu.append(mu_k)
                num_var.append(sd_k**2)
        prior = nk / max(n, 1e-30)

        model = NaiveBayesModel(job.dest_key or dkv.make_key(self.algo), p, di)
        if num_idx:
            mu = np.stack(num_mu, axis=1)                   # [K, Pn]
            var = np.stack(num_var, axis=1)
        else:
            mu = np.zeros((K, 0)); var = np.ones((K, 0))
        model.output.update({
            "apriori": prior,
            "levels": list(di.response_domain),
            "coef_names": di.coef_names,
            "_log_cat_table": log_cat,
            "_num_idx": np.asarray(num_idx, np.int64),
            "_num_mu": mu,
            "_num_inv2var": 1.0 / (2.0 * var),
            "_num_logsd": 0.5 * np.log(2 * np.pi * var),
            "_log_prior": np.log(np.maximum(prior, 1e-30)),
        })
        if p.compute_metrics:
            from ..metrics.core import make_metrics
            raw = model._predict_raw(X)
            model.training_metrics = make_metrics(di, raw, y, w)
            if valid is not None:
                model.validation_metrics = model.model_performance(valid)
        return model
