"""External-executor training: offload model builds to a second cluster.

Reference: ``h2o-extensions/xgboost/src/main/java/hex/tree/xgboost/remote/
SteamExecutorStarter.java`` — H2O can delegate an XGBoost build to an
external executor cluster (provisioned via Steam), ship the data over,
train there, and pull the model back into the local cluster.

TPU-native redesign: any algo (not just XGBoost) offloads over the plain
REST surface — data ships via /3/PostFile + /3/Parse, the build runs on
the remote mesh, and the model returns as the portable binary artifact
and is installed in the LOCAL registry, where it scores like any
locally trained model.  No Steam control plane: the executor is simply
a second ``deploy.serve`` cluster the caller has credentials for.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from .client import H2OConnection, connect


class ExternalExecutor:
    """A second h2o3_tpu cluster used as a training executor."""

    def __init__(self, url_or_conn, **connect_kw):
        self.conn: H2OConnection = (
            url_or_conn if isinstance(url_or_conn, H2OConnection)
            else connect(url_or_conn, **connect_kw))

    def train(self, algo: str, training_frame, cleanup: bool = True,
              destination_frame: Optional[str] = None, **params):
        """Offload one build: ship data, train remotely, install the
        resulting model locally and return it.

        ``training_frame`` may be a local Frame (shipped via PostFile)
        or a RemoteFrame/key already on the executor.
        """
        from .models.base import Model
        from .client import RemoteFrame

        shipped = None
        if isinstance(training_frame, (RemoteFrame, str)):
            remote_frame = training_frame
        else:
            shipped = self.conn.upload_frame(
                training_frame, destination_frame=destination_frame)
            remote_frame = shipped
        remote_model = self.conn.train(algo, remote_frame, **params)
        raw = self.conn._fetch_bytes(
            f"/3/Models.fetch.bin/{remote_model.key}")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "model.bin")
            with open(p, "wb") as f:
                f.write(raw)
            model = Model.load(p)
        if cleanup:
            try:
                self.conn.remove(remote_model.key)
                if shipped is not None:
                    self.conn.remove(shipped.key)
            except Exception:           # noqa: BLE001 — best-effort GC
                pass
        from .runtime import dkv
        dkv.put(model.key, model)       # install in the LOCAL registry
        return model


def train_remote(url_or_conn, algo: str, training_frame, **params):
    """One-shot offload (SteamExecutorStarter.startXGBoost analog)."""
    executor_kw = {k: params.pop(k) for k in
                   ("username", "password", "cafile", "insecure",
                    "use_session") if k in params}
    return ExternalExecutor(url_or_conn, **executor_kw).train(
        algo, training_frame, **params)
