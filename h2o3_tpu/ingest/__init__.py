"""Streaming ingest plane — train while data lands.

``StreamingFrame`` admits newline-aligned byte ranges (CSV) or row
groups (parquet) as they tokenize, exposing a landed-row watermark the
tree drivers' ``stream=`` mode trains behind.  See ``ingest/stream.py``
and docs/operations.md "Streaming ingest & warm-start".
"""

from .stream import StreamingFrame

__all__ = ["StreamingFrame"]
