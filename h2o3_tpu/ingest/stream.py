"""StreamingFrame — parse-while-train ingest (ROADMAP item 4).

The batch pipeline (``frame/parse.py``) tokenizes newline-aligned byte
ranges in parallel but only hands the caller a finished Frame, so ingest
is dead time on the training critical path.  ``StreamingFrame`` runs the
SAME ranged plan (``native.range_plan`` — the byte cuts ``parse_view``
executes) on a background thread and lands each range as it tokenizes:

- **watermark** — the contiguous prefix of landed rows.  Ranges land in
  plan order, so the watermark is also the total landed count; the tree
  drivers' ``stream=`` mode trains on ``visible_frame()`` prefixes
  behind it and re-bins at chunk fences as it advances.
- **per-shard readiness** — a mesh host's row block is ready once the
  watermark passes its upper row bound (``lineage.shard_row_bounds``).
- **backpressure** — with ``H2O3_TPU_STREAM_BUFFER_ROWS`` set, the
  landing thread blocks while landed-but-unconsumed rows exceed the
  bound; trainers mark consumption via :meth:`consume`.
- **incremental lineage** — every landed range is stamped into a
  partial ``!lineage/<key>`` record (``lineage.stream_record_range``),
  so a host death mid-stream re-parses ONLY the missing ranges on
  :meth:`resume` (the chaos row in tools/chaos.sh proves this by arming
  the ``parse_range`` injection point).

Parquet sources ride the same machinery at row-group granularity (the
ranged ``parse_arrow`` path), firing the ``parse_group`` injection
point per group.

Bitwise parity with the batch parse is by construction: ranges are
tokenized by the same native engine, text columns decode through
``_decode_text_column`` with per-range offsets, and final Vec assembly
goes through ``_column_to_vec`` — tests/test_stream.py pins it.

Metrics: ``ingest_landed_rows``, ``ingest_watermark_lag_seconds``
gauges; the drivers add ``stream_rebin_total`` per segment transition
(docs/operations.md "Streaming ingest & warm-start").
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..runtime import dkv
from ..runtime.config import config


class StreamError(RuntimeError):
    """The landing thread died; ``resume()`` re-parses missing ranges."""


class StreamingFrame:
    """A frame whose rows land while consumers already read the prefix.

    Usage::

        sf = StreamingFrame("big.csv")
        sf.start()
        model = H2OGradientBoostingEstimator(stream=True, ...).train(sf)
        fr = sf.frame()              # the finished, registered Frame
    """

    def __init__(self, path: str, destination_frame: Optional[str] = None,
                 header: Optional[bool] = None, sep: Optional[str] = None,
                 col_types: Optional[Dict[str, str]] = None,
                 col_names: Optional[List[str]] = None):
        if not isinstance(path, str) or not os.path.isfile(path):
            raise ValueError(f"StreamingFrame needs a local file, got "
                             f"{path!r}")
        self.path = os.path.abspath(path)
        self.key = destination_frame or dkv.make_key(
            "stream_" + os.path.basename(path))
        self._header = header
        self._sep = sep
        self._col_types = dict(col_types or {})
        self._col_names = list(col_names) if col_names else None
        low = path.lower()
        self.fmt = "parquet" if low.endswith((".parquet", ".pq")) else "csv"
        self._lock = threading.Condition()
        self._ranges: Dict[int, dict] = {}   # row_lo -> landed range
        self._plan: Optional[list] = None    # [(lo, hi, row_lo, rows)]
        self.total_rows: Optional[int] = None
        self.watermark = 0                   # contiguous landed prefix rows
        self.landed_rows = 0
        self.complete = False
        self.error: Optional[BaseException] = None
        self._consumed = 0
        self._bp_waits = 0
        self._wm_t = time.monotonic()        # last watermark advance
        self._t0: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._frame = None
        self._stamp_lineage = False
        if self.fmt == "csv":
            self._open_csv()
        else:
            self._open_parquet()

    # ------------------------------------------------------------- openers
    def _open_csv(self) -> None:
        import mmap as _mmap
        from ..frame.parse import _guess_numeric
        with open(self.path, "rb") as f:
            self._mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
        view = np.frombuffer(self._mm, np.uint8)
        self._sepc = self._sep if self._sep is not None else ","
        first_nl = self._mm.find(b"\n")
        first = bytes(view[: first_nl if first_nl >= 0 else len(view)]) \
            .decode(errors="replace")
        head_cells = [c.strip().strip('"') for c in first.split(self._sepc)]
        self.has_header = (not _guess_numeric(head_cells)) \
            if self._header is None else bool(self._header)
        self._body_off = first_nl + 1 \
            if self.has_header and first_nl >= 0 else 0
        self._body = view[self._body_off:]
        from .. import native
        self.ncols = native.ncols_of(self._body, self._sepc) \
            if native.load() is not None else len(head_cells)
        if self._col_names:
            self.names = list(self._col_names)
        elif self.has_header:
            self.names = head_cells
        else:
            self.names = [f"C{i+1}" for i in range(self.ncols or 0)]

    def _open_parquet(self) -> None:
        import pyarrow.parquet as pq
        self._pf = pq.ParquetFile(self.path)
        self.names = [str(n) for n in self._pf.schema_arrow.names]
        self.ncols = len(self.names)
        self.total_rows = int(self._pf.metadata.num_rows)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "StreamingFrame":
        """Begin landing ranges on a background thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            if self.complete:
                return self
            self.error = None
            self._t0 = self._t0 or time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name=f"ingest-{self.key}", daemon=True)
            self._thread.start()
        return self

    def resume(self) -> "StreamingFrame":
        """Restart after a landing failure — ONLY ranges missing from the
        landed set (equivalently: absent from the partial lineage record)
        re-parse; everything already landed is kept."""
        return self.start()

    def _run(self) -> None:
        try:
            if self.fmt == "csv":
                self._run_csv()
            else:
                self._run_parquet()
            self._finalize()
        except BaseException as e:       # noqa: BLE001 — surfaced to waiters
            with self._lock:
                self.error = e
                self._lock.notify_all()

    # ------------------------------------------------------------- CSV plan
    def _csv_plan(self) -> list:
        from .. import native
        if self._plan is not None:
            return self._plan
        plan = None
        if len(self._body) and native.load() is not None:
            # plan granularity is a watermark/lineage concept, not a
            # parallelism one (ranges land sequentially): cut the body
            # into H2O3_PARSE_RANGE_MIN-sized ranges regardless of how
            # many cores this host has
            range_min = int(os.environ.get("H2O3_PARSE_RANGE_MIN",
                                           1 << 22))
            n_ranges = min(256, max(1, len(self._body) // max(range_min, 1)))
            plan = native.range_plan(self._body, self._sepc,
                                     threads=max(n_ranges, 2))
        if plan is None:
            # native fast path unavailable: the whole body is one range
            # (landed via the strict engines in _land_whole)
            plan = [(0, len(self._body), 0, -1)]
        self._plan = plan
        if plan[-1][3] >= 0:
            self.total_rows = plan[-1][2] + plan[-1][3]
        cfg = config()
        self._stamp_lineage = (
            cfg.lineage_enabled
            and os.path.getsize(self.path) <= cfg.lineage_max_mb * 1e6)
        if self._stamp_lineage and not self._ranges:
            from ..frame import lineage
            lineage.stream_record_start(
                self.key, self.path,
                {"header": self.has_header, "sep": self._sep,
                 "format": "csv", "body_off": int(self._body_off)},
                total_bytes=len(self._body))
        return plan

    def _run_csv(self) -> None:
        from .. import native
        from ..runtime import failure
        plan = self._csv_plan()
        if plan[0][3] < 0:
            self._land_whole()
            return
        for (a, b, row_lo, rows) in plan:
            with self._lock:
                if row_lo in self._ranges:
                    continue             # resume: already landed
            self._backpressure_wait()
            failure.maybe_inject("parse_range")
            span = self._body[a:b]
            out = native.parse_bytes(span, self._sepc, ncols=self.ncols)
            if out is None:
                raise StreamError(f"range [{a},{b}) of {self.path!r} "
                                  "failed native tokenization")
            vals, flags, offs, consumed = out
            if consumed != len(span) or len(vals) != rows:
                raise StreamError(
                    f"range [{a},{b}) of {self.path!r} parsed to "
                    f"{len(vals)} rows (planned {rows}) — blank lines or "
                    "quoting defeat the ranged plan; use batch parse")
            sha = hashlib.sha1(
                np.ascontiguousarray(span).tobytes()).hexdigest() \
                if self._stamp_lineage else None
            self._land({"row_lo": row_lo, "rows": rows, "vals": vals,
                        "flags": flags, "offs": offs, "span": span})
            if self._stamp_lineage:
                from ..frame import lineage
                lineage.stream_record_range(self.key, {
                    "lo": int(a + self._body_off),
                    "hi": int(b + self._body_off),
                    "row_lo": int(row_lo), "rows": int(rows),
                    "src_sha1": sha})

    def _land_whole(self) -> None:
        """Strict-engine fallback: parse the whole source as one landed
        range (no overlap, but identical semantics and results)."""
        from ..frame.parse import parse_csv
        fr = parse_csv(self.path, destination_frame=self.key,
                       header=self._header, sep=self._sep,
                       col_types=self._col_types, col_names=self._col_names)
        with self._lock:
            self._frame = fr
            self.names = list(fr.names)
            self.total_rows = fr.nrows
            self._ranges[0] = {"row_lo": 0, "rows": fr.nrows, "whole": True}
            self._advance(fr.nrows)

    # --------------------------------------------------------- parquet plan
    def _run_parquet(self) -> None:
        from ..runtime import failure
        cfg = config()
        self._stamp_lineage = (
            cfg.lineage_enabled
            and os.path.getsize(self.path) <= cfg.lineage_max_mb * 1e6)
        md = self._pf.metadata
        g_rows = [md.row_group(i).num_rows for i in range(md.num_row_groups)]
        self._plan = g_rows           # progress(): one "range" per group
        if self._stamp_lineage and not self._ranges:
            from ..frame import lineage
            lineage.stream_record_start(
                self.key, self.path, {"format": "parquet"},
                total_bytes=os.path.getsize(self.path))
        row_lo = 0
        for gi, rows in enumerate(g_rows):
            lo = row_lo
            row_lo += rows
            with self._lock:
                if lo in self._ranges:
                    continue             # resume: already landed
            self._backpressure_wait()
            failure.maybe_inject("parse_group")
            tbl = self._pf.read_row_group(gi)
            self._land({"row_lo": lo, "rows": rows, "table": tbl,
                        "group": gi})
            if self._stamp_lineage:
                from ..frame import lineage
                lineage.stream_record_range(self.key, {
                    "group": gi, "row_lo": int(lo), "rows": int(rows),
                    "src_sha1": None})

    # ------------------------------------------------------------- landing
    def _backpressure_wait(self) -> None:
        cap = config().stream_buffer_rows
        if cap <= 0:
            return
        with self._lock:
            while self.landed_rows - self._consumed > cap \
                    and self.error is None:
                self._bp_waits += 1
                self._lock.wait(0.05)

    def _land(self, rec: dict) -> None:
        with self._lock:
            self._ranges[rec["row_lo"]] = rec
            self._advance()
            self._lock.notify_all()

    def _advance(self, force_rows: Optional[int] = None) -> None:
        """Recompute watermark = contiguous landed prefix (lock held)."""
        if force_rows is not None:
            wm = force_rows
        else:
            wm = 0
            while wm in self._ranges:
                wm += self._ranges[wm]["rows"]
        self.landed_rows = sum(r["rows"] for r in self._ranges.values())
        if wm > self.watermark:
            self.watermark = wm
            self._wm_t = time.monotonic()
        try:
            from ..runtime.observability import set_gauge
            set_gauge("ingest_landed_rows", float(self.landed_rows),
                      frame=self.key)
            set_gauge("ingest_watermark_lag_seconds",
                      round(time.monotonic() - self._wm_t, 3),
                      frame=self.key)
        except Exception:                # noqa: BLE001 — metrics optional
            pass

    # ------------------------------------------------------------ consumers
    def consume(self, rows: int) -> None:
        """Mark rows [0, rows) as consumed — releases backpressure."""
        with self._lock:
            self._consumed = max(self._consumed, int(rows))
            self._lock.notify_all()

    def wait_rows(self, rows: int, timeout: Optional[float] = None) -> int:
        """Block until the watermark reaches ``rows`` (or the stream
        completes / fails).  Returns the watermark."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self.watermark < rows and not self.complete:
                if self.error is not None:
                    raise StreamError(
                        f"stream {self.key} failed: "
                        f"{self.error!r}") from self.error
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    break
                self._lock.wait(config().stream_poll_s
                                if left is None
                                else min(left, config().stream_poll_s))
            return self.watermark

    def wait_growth(self, rows: int, frac: float,
                    timeout: Optional[float] = None) -> int:
        """Block until the watermark exceeds ``rows`` by ``frac`` (or any
        growth when ``frac`` rounds to zero rows), stream end included."""
        target = rows + max(1, int(rows * frac))
        return self.wait_rows(min(target, self.total_rows or target),
                              timeout=timeout)

    def shard_ready(self, i: int) -> bool:
        """True when mesh host ``i``'s row block has fully landed."""
        from ..frame import lineage
        from ..runtime.cluster import cluster
        if self.total_rows is None:
            return False
        bounds = lineage.shard_row_bounds(self.total_rows,
                                          cluster().n_hosts)
        if i >= len(bounds):
            return False
        return self.complete or self.watermark >= bounds[i][1]

    def progress(self) -> dict:
        """Live status dict — surfaced in ``GET /3/Jobs`` via
        ``Job.stream`` while a streaming train runs."""
        with self._lock:
            from ..runtime.cluster import cluster
            n_hosts = cluster().n_hosts
            return {
                "frame": self.key, "source": self.path, "format": self.fmt,
                "landed_rows": self.landed_rows,
                "watermark": self.watermark,
                "total_rows": self.total_rows,
                "complete": self.complete,
                "ranges_landed": len(self._ranges),
                "ranges_total": len(self._plan) if self._plan else None,
                "consumed": self._consumed,
                "backpressure_waits": self._bp_waits,
                "shards_ready": [self.shard_ready(i)
                                 for i in range(n_hosts)],
                "watermark_lag_s": round(time.monotonic() - self._wm_t, 3),
            }

    # ------------------------------------------------------------- assembly
    def _landed_prefix(self) -> list:
        """Landed ranges under the watermark, in row order (lock held)."""
        out, wm = [], 0
        while wm in self._ranges:
            out.append(self._ranges[wm])
            wm += self._ranges[wm]["rows"]
        return out

    def _assemble_csv(self, ranges: list, limit: Optional[int] = None):
        from ..frame.parse import _column_to_vec, _decode_text_column
        names, vecs = list(self.names), []
        for j, name in enumerate(names):
            text = any(r["flags"][:, j].any() for r in ranges)
            if text:
                col = np.concatenate([
                    _decode_text_column(r["span"], r["offs"], j)
                    for r in ranges]) if ranges else np.zeros(0, object)
            else:
                col = np.concatenate([r["vals"][:, j] for r in ranges]) \
                    if ranges else np.zeros(0, np.float64)
            if limit is not None:
                col = col[:limit]
            vecs.append(_column_to_vec(col, name,
                                       self._col_types.get(name)))
        return names, vecs

    def _assemble_parquet(self, ranges: list, limit: Optional[int] = None):
        import pyarrow as pa
        from ..frame.parse import arrow_table_to_vecs
        tables = [r["table"] for r in ranges]
        table = pa.concat_tables(tables) if tables \
            else self._pf.schema_arrow.empty_table()
        if limit is not None:
            table = table.slice(0, limit)
        return arrow_table_to_vecs(table)

    def visible_frame(self, limit: Optional[int] = None):
        """An UNREGISTERED Frame of the rows behind the watermark — what
        the streaming tree drivers train each segment on.  ``limit``
        truncates to the first N visible rows (the stream driver uses it
        to quantize segment shapes for jit-cache reuse).  Column types
        are guessed from the visible prefix; the final registered frame
        re-guesses over all rows."""
        from ..frame.frame import Frame
        with self._lock:
            if self._frame is not None and self.complete and limit is None:
                return self._frame
            ranges = self._landed_prefix()
        if self.fmt == "csv":
            names, vecs = self._assemble_csv(ranges, limit)
        else:
            names, vecs = self._assemble_parquet(ranges, limit)
        fr = Frame(names, vecs)
        fr.source_uri = self.path
        return fr

    def _finalize(self) -> None:
        from ..frame.frame import Frame
        with self._lock:
            if self._frame is not None:      # _land_whole already built it
                self.complete = True
                self._lock.notify_all()
                return
            ranges = self._landed_prefix()
            landed = sum(r["rows"] for r in ranges)
            if landed != self.landed_rows:
                raise StreamError(
                    f"stream {self.key}: landed ranges are not contiguous "
                    f"({landed} prefix rows of {self.landed_rows} landed)")
        if self.fmt == "csv":
            names, vecs = self._assemble_csv(ranges)
        else:
            names, vecs = self._assemble_parquet(ranges)
        fr = Frame(names, vecs, key=self.key)
        fr.source_uri = self.path
        from ..frame import lineage
        if self.fmt == "csv":
            lineage.record_parse(fr, self.path, header=self._header,
                                 sep=self._sep, col_types=self._col_types,
                                 col_names=self._col_names)
        else:
            lineage.record_parse_columnar(fr, self.path)
        with self._lock:
            self._frame = fr
            self.total_rows = fr.nrows
            self.complete = True
            self._advance(fr.nrows)
            self._lock.notify_all()

    def frame(self, timeout: Optional[float] = None):
        """Join the stream and return the finished, registered Frame."""
        self.start()
        # joining means every row will be taken: release backpressure so
        # the landing thread can run the stream out
        self.consume(1 << 62)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self.complete:
                if self.error is not None:
                    raise StreamError(
                        f"stream {self.key} failed: "
                        f"{self.error!r}") from self.error
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(f"stream {self.key} incomplete "
                                       f"after {timeout}s")
                self._lock.wait(0.05 if left is None else min(left, 0.05))
            return self._frame

    def __repr__(self):
        return (f"<StreamingFrame {self.key} {self.fmt} "
                f"{self.watermark}/{self.total_rows} rows>")
