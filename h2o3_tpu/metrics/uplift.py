"""Uplift metrics: AUUC (qini/gain/lift) and the qini coefficient.

Reference: ``hex/AUUC.java`` — rows ranked by predicted uplift are bucketed
(default 1000 bins); per-bucket treatment/control response sums give the
uplift curve, its area (AUUC), and the normalized qini coefficient.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np


@dataclasses.dataclass
class ModelMetricsUplift:
    nobs: float
    auuc_qini: float
    auuc_gain: float
    auuc_lift: float
    qini_coefficient: float
    ate: float                     # average treatment effect (observed)

    def describe(self) -> Dict[str, float]:
        return {"auuc_qini": self.auuc_qini, "auuc_gain": self.auuc_gain,
                "auuc_lift": self.auuc_lift,
                "qini": self.qini_coefficient, "ate": self.ate}

    @property
    def r2(self):
        return float("nan")


def uplift_metrics(pred_uplift, y, treatment, weights=None,
                   nbins: int = 1000) -> ModelMetricsUplift:
    """AUUC over the uplift ranking (AUUC.java semantics).

    qini(k) = Y1_t(k) - Y1_c(k) * N_t(k)/N_c(k) over the top-k ranked rows;
    AUUC = mean over buckets; the qini coefficient normalizes against the
    random-ranking diagonal.
    """
    p = np.asarray(pred_uplift, np.float64)
    yy = np.asarray(y, np.float64)
    t = np.asarray(treatment, np.float64)
    w = np.ones_like(p) if weights is None else np.asarray(weights,
                                                           np.float64)
    order = np.argsort(-p, kind="stable")
    yy, t, w = yy[order], t[order], w[order]
    n = len(p)
    nbins = min(nbins, n)
    edges = np.linspace(0, n, nbins + 1).astype(int)[1:]

    cy1t = np.cumsum(w * yy * t)
    cnt = np.cumsum(w * t)
    cy1c = np.cumsum(w * yy * (1 - t))
    cnc = np.cumsum(w * (1 - t))
    k = edges - 1
    y1t, ntr = cy1t[k], cnt[k]
    y1c, nc = cy1c[k], cnc[k]
    ratio = ntr / np.maximum(nc, 1e-12)
    qini = y1t - y1c * ratio
    gain = (y1t / np.maximum(ntr, 1e-12)
            - y1c / np.maximum(nc, 1e-12)) * (ntr + nc)
    lift = (y1t / np.maximum(ntr, 1e-12)
            - y1c / np.maximum(nc, 1e-12))
    auuc_qini = float(np.mean(qini))
    auuc_gain = float(np.mean(gain))
    auuc_lift = float(np.mean(lift))
    # random-ranking baseline: linear ramp to the final qini value
    final = qini[-1]
    random_auuc = float(np.mean(np.linspace(final / nbins, final, nbins)))
    qini_coef = float((auuc_qini - random_auuc)
                      / max(abs(random_auuc), 1e-12)) \
        if abs(random_auuc) > 1e-12 else float("nan")
    ate = float(y1t[-1] / max(ntr[-1], 1e-12)
                - y1c[-1] / max(nc[-1], 1e-12))
    return ModelMetricsUplift(nobs=float(np.sum(w)), auuc_qini=auuc_qini,
                              auuc_gain=auuc_gain, auuc_lift=auuc_lift,
                              qini_coefficient=qini_coef, ate=ate)
