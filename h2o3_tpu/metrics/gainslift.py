"""Gains/Lift table for binomial models — ``hex/GainsLift.java`` analog.

The reference buckets rows into (default) 16 quantile groups of the
predicted probability and reports per-group response/capture/lift plus the
Kolmogorov-Smirnov statistic.  Here the table derives from the same
400-bin score histograms the AUC computation uses (metrics/core.py), so no
extra device pass is needed: group boundaries are score-quantiles read off
the cumulative histogram.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def gains_lift_table(thresholds: np.ndarray, tps: np.ndarray,
                     fps: np.ndarray, groups: int = 16) -> Dict[str, list]:
    """Build the table from descending-threshold cumulatives.

    ``tps[k]``/``fps[k]`` = weighted positives/negatives with score >=
    thresholds[k].  Returns the reference's column set
    (GainsLift.java createTable).
    """
    npos = float(tps[-1])
    nneg = float(fps[-1])
    n = npos + nneg
    if n <= 0 or npos <= 0:
        return {"group": [], "cumulative_data_fraction": [], "lift": [],
                "kolmogorov_smirnov": []}
    cum_frac = (tps + fps) / n
    base_rate = npos / n

    rows = []
    prev_frac = 0.0
    prev_capture = 0.0
    ks_max = 0.0
    for g in range(1, groups + 1):
        target = g / groups
        k = int(np.searchsorted(cum_frac, target, side="left"))
        k = min(k, len(cum_frac) - 1)
        frac = float(cum_frac[k])
        if frac <= prev_frac and g < groups:
            continue                      # ties collapse groups (reference)
        capture = float(tps[k]) / npos    # cumulative capture rate
        resp_cum = float(tps[k]) / max(float(tps[k] + fps[k]), 1e-12)
        d_frac = frac - prev_frac
        d_capture = capture - prev_capture
        lift = (d_capture / d_frac) if d_frac > 0 else 0.0
        cum_lift = capture / max(frac, 1e-12)
        resp_rate = lift * base_rate
        ks = float(tps[k]) / npos - float(fps[k]) / max(nneg, 1e-12)
        ks_max = max(ks_max, ks)
        rows.append({
            "group": len(rows) + 1,
            "cumulative_data_fraction": frac,
            "lower_threshold": float(thresholds[k]),
            "lift": lift,
            "cumulative_lift": cum_lift,
            "response_rate": resp_rate,
            "cumulative_response_rate": capture / max(frac, 1e-12)
            * base_rate,
            "capture_rate": d_capture,
            "cumulative_capture_rate": capture,
            "gain": 100.0 * (lift - 1.0),
            "cumulative_gain": 100.0 * (cum_lift - 1.0),
            "kolmogorov_smirnov": ks,
        })
        prev_frac, prev_capture = frac, capture
    table: Dict[str, list] = {k: [r[k] for r in rows] for k in rows[0]} \
        if rows else {}
    table["_ks"] = [ks_max]
    return table


def concordance_index(event_time: np.ndarray, event: np.ndarray,
                      risk: np.ndarray, weights=None) -> float:
    """Survival concordance (Harrell's C) — CoxPH concordance analog.

    Comparable pairs: i with an observed event and t_i < t_j.  Concordant
    when the earlier-event row has the HIGHER risk score.  O(n^2) in
    blocked numpy — fine for coordinator-side metric computation.
    """
    t = np.asarray(event_time, np.float64)
    e = np.asarray(event, bool)
    r = np.asarray(risk, np.float64)
    w = np.ones_like(t) if weights is None else np.asarray(weights,
                                                           np.float64)
    ok = np.isfinite(t) & np.isfinite(r)
    t, e, r, w = t[ok], e[ok], r[ok], w[ok]
    num = den = 0.0
    idx = np.flatnonzero(e)
    for i in idx:
        later = t > t[i]
        pw = w[i] * w[later]
        den += pw.sum()
        num += pw[r[i] > r[later]].sum() + 0.5 * pw[r[i] == r[later]].sum()
    return float(num / den) if den > 0 else float("nan")
