"""Model metrics: binomial / multinomial / regression, computed on device.

Reference: the ``hex/ModelMetrics*`` hierarchy (30+ classes) + ``hex/AUC2.java``
(exact AUC via a 400-bin treatment of the score distribution), GainsLift,
ConfusionMatrix — accumulated per-row by MetricBuilders inside the BigScore
MRTask and tree-reduced.

TPU-native redesign: each metric family is ONE fused XLA pass over the
row-sharded (predictions, response, weights) arrays — weighted histograms over
a fixed threshold grid replace AUC2's per-row treatment insertion, and the
reduce tree is GSPMD's automatic ``psum``.  Host-side dataclasses hold the
resulting scalars, mirroring the reference's metrics schema names.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

NBINS = 400  # AUC2's default number of threshold bins (hex/AUC2.java)


def _merge_custom(self, base: dict) -> dict:
    """Merge a custom-metric UDF result (plain data attr; picklable)."""
    cm = getattr(self, "custom_metric", None)
    if cm:
        return {**base, cm["name"]: cm["value"]}
    return base


# =========================================================== binomial kernels
@functools.partial(jax.jit, static_argnums=(3,))
def _binomial_hist_kernel(p1, y, w, nbins: int):
    """Weighted histograms of P(class1) for positives and negatives.

    Bin i covers scores in [i/nbins, (i+1)/nbins); returns (pos[nbins],
    neg[nbins], logloss_sum, se_sum, wsum, wpos).

    The histogram is a blocked one-hot matmul (HIGHEST precision keeps f32
    weights exact), not a scatter-add: TPU serializes scatters — measured
    0.58 s per 10M-row metrics call, ~40x the MXU formulation.
    """
    p1c = jnp.clip(p1, 1e-15, 1 - 1e-15)
    idx = jnp.clip((p1 * nbins).astype(jnp.int32), 0, nbins - 1)
    pos_w = w * (y == 1)
    neg_w = w * (y == 0)
    n = p1.shape[0]
    blk = max(min(n, 1 << 20), 1)          # n == 0: zero-block scan
    nblk = -(-n // blk)
    pad = nblk * blk - n
    idxp = jnp.pad(idx, (0, pad)).reshape(nblk, blk)
    S = jnp.pad(jnp.stack([pos_w, neg_w], axis=1),
                [(0, pad), (0, 0)]).reshape(nblk, blk, 2)
    biota = jax.lax.broadcasted_iota(jnp.int32, (nbins, 1), 0)

    def body(acc, args):
        ib, sb = args
        oh = (biota == ib[None, :]).astype(jnp.float32)      # [nbins, blk]
        return acc + jnp.dot(oh, sb,
                             precision=jax.lax.Precision.HIGHEST), None

    hist, _ = jax.lax.scan(body, jnp.zeros((nbins, 2), jnp.float32),
                           (idxp, S))
    pos, neg = hist[:, 0], hist[:, 1]
    ll = -jnp.sum(w * (y * jnp.log(p1c) + (1 - y) * jnp.log1p(-p1c)))
    se = jnp.sum(w * (y - p1) ** 2)
    # ONE packed result -> one device->host fetch (each fetch is a full
    # round trip on a tunnelled backend, ~67 ms measured)
    return jnp.concatenate([pos, neg,
                            jnp.stack([ll, se, jnp.sum(w),
                                       jnp.sum(pos_w)])])


@dataclasses.dataclass
class ConfusionMatrix:
    """2x2 (or KxK) confusion matrix at a threshold, rows=actual."""
    table: np.ndarray
    domain: List[str]

    def __repr__(self):
        return f"ConfusionMatrix({self.domain}):\n{self.table}"


@dataclasses.dataclass
class ModelMetricsBinomial:
    nobs: float
    auc: float
    pr_auc: float
    gini: float
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    max_f1: float
    max_f1_threshold: float
    accuracy: float
    domain: List[str]
    cm: ConfusionMatrix
    # ROC curve arrays (descending thresholds), for gains/lift & plots
    thresholds: np.ndarray
    tps: np.ndarray
    fps: np.ndarray

    @property
    def r2(self) -> float:
        return float("nan")

    def confusion_matrix(self) -> ConfusionMatrix:
        return self.cm

    def gains_lift(self, groups: int = 16) -> dict:
        """Quantile gains/lift table — hex/GainsLift.java analog."""
        from .gainslift import gains_lift_table
        return gains_lift_table(self.thresholds, self.tps, self.fps,
                                groups=groups)

    @property
    def ks(self) -> float:
        """Kolmogorov-Smirnov statistic (max TPR - FPR over thresholds)."""
        npos = float(self.tps[-1])
        nneg = float(self.fps[-1])
        if npos <= 0 or nneg <= 0:
            return float("nan")
        return float(np.max(self.tps / npos - self.fps / nneg))

    def describe(self) -> dict:
        return _merge_custom(self, {
            "auc": self.auc, "pr_auc": self.pr_auc, "logloss": self.logloss,
            "rmse": self.rmse, "gini": self.gini,
            "mean_per_class_error": self.mean_per_class_error,
            "max_f1": self.max_f1, "threshold": self.max_f1_threshold,
            "ks": self.ks})


def binomial_metrics(p1, y, w, domain: Optional[List[str]] = None
                     ) -> ModelMetricsBinomial:
    """AUC2-equivalent metrics from P(class1), labels {0,1}, weights."""
    packed = np.asarray(_binomial_hist_kernel(
        jnp.asarray(p1), jnp.asarray(y), jnp.asarray(w), NBINS), np.float64)
    pos, neg = packed[:NBINS], packed[NBINS: 2 * NBINS]
    ll, se, wsum, wpos = packed[2 * NBINS:]
    n = float(wsum)
    npos = float(wpos)
    nneg = n - npos
    # descending-threshold cumulatives: predict-1 iff score >= threshold
    tps = np.cumsum(pos[::-1])          # true positives at each threshold
    fps = np.cumsum(neg[::-1])          # false positives
    thresholds = (np.arange(NBINS)[::-1]) / NBINS
    tpr = tps / max(npos, 1e-12)
    fpr = fps / max(nneg, 1e-12)
    # trapezoid AUC over the ROC polyline (prepend origin)
    auc = float(np.trapezoid(np.concatenate([[0.0], tpr]),
                         np.concatenate([[0.0], fpr])))
    prec = tps / np.maximum(tps + fps, 1e-12)
    rec = tpr
    pr_auc = float(np.trapezoid(np.concatenate([[prec[0]], prec]),
                            np.concatenate([[0.0], rec])))
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-12)
    best = int(np.argmax(f1))
    thr = float(thresholds[best])
    tp, fp = tps[best], fps[best]
    fn, tn = npos - tp, nneg - fp
    cm = ConfusionMatrix(np.array([[tn, fp], [fn, tp]]),
                         list(domain or ["0", "1"]))
    per_class_err = 0.5 * (fp / max(nneg, 1e-12) + fn / max(npos, 1e-12))
    return ModelMetricsBinomial(
        nobs=n, auc=auc, pr_auc=pr_auc, gini=2 * auc - 1,
        logloss=float(ll) / max(n, 1e-12), mse=float(se) / max(n, 1e-12),
        rmse=float(np.sqrt(float(se) / max(n, 1e-12))),
        mean_per_class_error=float(per_class_err),
        max_f1=float(f1[best]), max_f1_threshold=thr,
        accuracy=float((tp + tn) / max(n, 1e-12)),
        domain=list(domain or ["0", "1"]), cm=cm,
        thresholds=thresholds, tps=tps, fps=fps)


# ======================================================== multinomial kernels
@functools.partial(jax.jit, static_argnums=(3,))
def _multinomial_kernel(probs, y, w, nclasses: int):
    yi = jnp.clip(y.astype(jnp.int32), 0, nclasses - 1)
    p_true = jnp.clip(probs[jnp.arange(probs.shape[0]), yi], 1e-15, 1.0)
    ll = -jnp.sum(w * jnp.log(p_true))
    pred = jnp.argmax(probs, axis=1)
    # weighted KxK confusion matrix (actual, predicted)
    flat = yi * nclasses + pred
    cm = jnp.zeros(nclasses * nclasses, jnp.float32).at[flat].add(w)
    se = jnp.sum(w * jnp.sum((probs - jax.nn.one_hot(yi, nclasses)) ** 2, axis=1))
    # hit ratios: rank of true class
    order = jnp.argsort(-probs, axis=1)
    match = (order == yi[:, None])
    ranks = jnp.argmax(match, axis=1)
    topk = jnp.zeros(nclasses, jnp.float32).at[ranks].add(w)
    # packed: one fetch (see _binomial_hist_kernel)
    return jnp.concatenate([jnp.stack([ll, se, jnp.sum(w)]), cm, topk])


@dataclasses.dataclass
class ModelMetricsMultinomial:
    nobs: float
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    accuracy: float
    domain: List[str]
    cm: ConfusionMatrix
    hit_ratios: np.ndarray

    def confusion_matrix(self) -> ConfusionMatrix:
        return self.cm

    def describe(self) -> dict:
        return _merge_custom(self, {
            "logloss": self.logloss, "rmse": self.rmse,
            "mean_per_class_error": self.mean_per_class_error,
            "accuracy": self.accuracy})


def multinomial_metrics(probs, y, w, domain: List[str]
                        ) -> ModelMetricsMultinomial:
    k = len(domain)
    packed = np.asarray(_multinomial_kernel(
        jnp.asarray(probs), jnp.asarray(y), jnp.asarray(w), k), np.float64)
    ll, se, wsum = packed[:3]
    cm = packed[3: 3 + k * k].reshape(k, k)
    topk = packed[3 + k * k:]
    n = float(wsum)
    row = cm.sum(axis=1)
    diag = np.diag(cm)
    per_class = np.where(row > 0, 1 - diag / np.maximum(row, 1e-12), 0.0)
    hit = np.cumsum(topk) / max(n, 1e-12)
    return ModelMetricsMultinomial(
        nobs=n, logloss=float(ll) / max(n, 1e-12),
        mse=float(se) / max(n, 1e-12),
        rmse=float(np.sqrt(float(se) / max(n, 1e-12))),
        mean_per_class_error=float(per_class[row > 0].mean()) if (row > 0).any() else 0.0,
        accuracy=float(diag.sum() / max(n, 1e-12)),
        domain=list(domain), cm=ConfusionMatrix(cm, list(domain)),
        hit_ratios=hit)


# ========================================================== regression kernel
@jax.jit
def _regression_kernel(pred, y, w):
    err = y - pred
    se = jnp.sum(w * err * err)
    ae = jnp.sum(w * jnp.abs(err))
    wsum = jnp.sum(w)
    ybar = jnp.sum(w * y) / jnp.maximum(wsum, 1e-12)
    sst = jnp.sum(w * (y - ybar) ** 2)
    # rmsle guarded against negatives
    ok = (pred > -1) & (y > -1)
    sle = jnp.sum(jnp.where(ok & (w > 0),
                            w * (jnp.log1p(jnp.clip(pred, -1 + 1e-12, None))
                                 - jnp.log1p(jnp.clip(y, -1 + 1e-12, None))) ** 2,
                            0.0))
    # packed: one fetch (see _binomial_hist_kernel)
    return jnp.stack([se, ae, wsum, sst, sle])


@dataclasses.dataclass
class ModelMetricsRegression:
    nobs: float
    mse: float
    rmse: float
    mae: float
    rmsle: float
    r2: float
    mean_residual_deviance: float

    def describe(self) -> dict:
        return _merge_custom(self, {
            "rmse": self.rmse, "mae": self.mae, "r2": self.r2,
            "mean_residual_deviance": self.mean_residual_deviance})


def regression_metrics(pred, y, w, deviance_sum: Optional[float] = None
                       ) -> ModelMetricsRegression:
    se, ae, wsum, sst, sle = np.asarray(_regression_kernel(
        jnp.asarray(pred), jnp.asarray(y), jnp.asarray(w)), np.float64)
    n = max(float(wsum), 1e-12)
    mse = float(se) / n
    return ModelMetricsRegression(
        nobs=float(wsum), mse=mse, rmse=float(np.sqrt(mse)),
        mae=float(ae) / n, rmsle=float(np.sqrt(max(float(sle), 0.0) / n)),
        r2=float(1.0 - float(se) / max(float(sst), 1e-12)),
        mean_residual_deviance=(deviance_sum / n if deviance_sum is not None
                                else mse))


# ============================================================ unified factory
def make_metrics(di, raw, y, w, distribution=None, deviance_sum=None,
                 custom_metric_func=None):
    """Dispatch on the DataInfo's response type — the BigScore metric step.

    ``custom_metric_func``: optional UDF ``(predictions, y, w) -> (name,
    value)`` — the water/udf/CMetricFunc analog; the result is attached to
    the metrics object and surfaces in ``describe()``.
    """
    if di.is_classifier:
        dom = [str(d) for d in di.response_domain]
        if len(dom) == 2:
            p1 = raw[:, 1] if raw.ndim == 2 else raw
            m = binomial_metrics(p1, y, w, domain=dom)
        else:
            m = multinomial_metrics(raw, y, w, domain=dom)
    else:
        pred = raw[:, 0] if raw.ndim == 2 else raw
        m = regression_metrics(pred, jnp.nan_to_num(y), w,
                               deviance_sum=deviance_sum)
    if custom_metric_func is not None:
        name, value = custom_metric_func(np.asarray(raw), np.asarray(y),
                                         np.asarray(w))
        # plain data attribute (picklable); describe() merges it
        m.custom_metric = {"name": str(name), "value": float(value)}
    return m
