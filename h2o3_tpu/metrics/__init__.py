"""Model metrics (the hex.ModelMetrics* analog)."""

from .core import (ConfusionMatrix, ModelMetricsBinomial,
                   ModelMetricsMultinomial, ModelMetricsRegression,
                   binomial_metrics, multinomial_metrics, regression_metrics,
                   make_metrics)
