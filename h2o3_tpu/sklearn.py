"""scikit-learn adapter layer — the h2o-py ``h2o/sklearn`` analog.

Reference: ``h2o-py/h2o/sklearn/__init__.py`` wraps every estimator in
sklearn-compatible classes so they compose with Pipeline/GridSearchCV.
Here a small duck-typed base implements the sklearn estimator protocol
(get_params/set_params/fit/predict/predict_proba/score — enough for
clone() and Pipeline) around any builder class; numpy X/y round-trip
through a device Frame.  No hard scikit-learn dependency: the classes
work standalone, and pass sklearn.base.clone when sklearn is present.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .frame.frame import Frame

__all__ = [
    "H2OGradientBoostingClassifier", "H2OGradientBoostingRegressor",
    "H2ORandomForestClassifier", "H2ORandomForestRegressor",
    "H2OXGBoostClassifier", "H2OXGBoostRegressor",
    "H2OGLMClassifier", "H2OGLMRegressor",
    "H2ODeepLearningClassifier", "H2ODeepLearningRegressor",
    "H2OKMeans",
]

_RESPONSE = "_sklearn_target"


class _Base:
    """sklearn estimator protocol around one builder class."""

    _builder_name: str = ""
    _classifier: bool = False
    _extra_params: Dict[str, object] = {}

    def __init__(self, **params):
        # fitted-state attributes (model_, classes_, n_features_in_) are
        # NOT pre-created: sklearn's check_is_fitted keys on their absence
        self._params = dict(params)

    # ------------------------------------------------- sklearn protocol
    def get_params(self, deep: bool = True) -> dict:
        return dict(self._params)

    def set_params(self, **params) -> "_Base":
        self._params.update(params)
        return self

    def __repr__(self):
        args = ", ".join(f"{k}={v!r}" for k, v in self._params.items())
        return f"{type(self).__name__}({args})"

    def __sklearn_tags__(self):
        # sklearn >= 1.6 Pipeline/clone consult estimator tags; build the
        # default set lazily so scikit-learn stays an optional dependency
        from sklearn.utils import (Tags, TargetTags, ClassifierTags,
                                   RegressorTags)
        if self._classifier:
            return Tags(estimator_type="classifier",
                        target_tags=TargetTags(required=True),
                        classifier_tags=ClassifierTags())
        return Tags(estimator_type="regressor",
                    target_tags=TargetTags(required=False),
                    regressor_tags=RegressorTags())

    # -------------------------------------------------------- plumbing
    def _builder(self, **kw):
        from . import models
        cls = getattr(models, self._builder_name)
        return cls(**{**self._extra_params, **self._params, **kw})

    def _frame(self, X, y=None) -> Frame:
        X = np.asarray(X, dtype=np.float64)
        if X.ndim != 2:
            raise ValueError(
                f"expected 2-D X, got shape {X.shape}; reshape a single "
                "feature with X.reshape(-1, 1)")
        cols = {f"x{j}": X[:, j] for j in range(X.shape[1])}
        if y is not None:
            if self._classifier:
                y = np.asarray(y)
                self.classes_ = np.unique(y)
                cols[_RESPONSE] = np.asarray(
                    [str(v) for v in y], dtype=object)
            else:
                cols[_RESPONSE] = np.asarray(y, dtype=np.float64)
            self.n_features_in_ = X.shape[1]
        return Frame.from_numpy(cols)

    def _check_fitted(self):
        if getattr(self, "model_", None) is None:
            raise RuntimeError(
                f"{type(self).__name__} is not fitted; call fit(X, y)")

    # ------------------------------------------------------------- api
    def _fit_overrides(self) -> dict:
        return {}

    def fit(self, X, y=None) -> "_Base":
        from .runtime.cluster import cluster
        cluster()                        # boots the mesh on first use
        fr = self._frame(X, y)
        self.model_ = self._builder(response_column=_RESPONSE,
                                    **self._fit_overrides()).train(fr)
        return self

    def predict(self, X) -> np.ndarray:
        self._check_fitted()
        preds = self.model_.predict(self._frame(X))
        if self._classifier:
            labels = preds.vec("predict").decoded()
            lut = {str(c): c for c in self.classes_}
            return np.asarray([lut.get(str(v), v) for v in labels])
        return preds.vec("predict").to_numpy()

    def score(self, X, y) -> float:
        yhat = self.predict(X)
        y = np.asarray(y)
        if self._classifier:
            return float(np.mean(yhat == y))
        ss_res = float(np.sum((y - yhat) ** 2))
        ss_tot = float(np.sum((y - np.mean(y)) ** 2)) or 1.0
        return 1.0 - ss_res / ss_tot


def _predict_proba(self, X) -> np.ndarray:
    self._check_fitted()
    preds = self.model_.predict(self._frame(X))
    return np.stack([preds.vec(str(c)).to_numpy()
                     for c in self.classes_], axis=1)


def _make(name: str, builder: str, classifier: bool,
          extra: Optional[dict] = None) -> type:
    ns = {
        "_builder_name": builder,
        "_classifier": classifier,
        "_extra_params": extra or {},
        "__doc__": f"sklearn-style wrapper over models.{builder} "
                   f"({'classification' if classifier else 'regression'}).",
    }
    if classifier:
        # only classifiers expose predict_proba: sklearn utilities probe
        # with hasattr, so regressors must not carry the method at all
        ns["predict_proba"] = _predict_proba
    cls = type(name, (_Base,), ns)
    cls.__module__ = __name__
    return cls


H2OGradientBoostingClassifier = _make(
    "H2OGradientBoostingClassifier", "GBM", True)
H2OGradientBoostingRegressor = _make(
    "H2OGradientBoostingRegressor", "GBM", False)
H2ORandomForestClassifier = _make("H2ORandomForestClassifier", "DRF", True)
H2ORandomForestRegressor = _make("H2ORandomForestRegressor", "DRF", False)
H2OXGBoostClassifier = _make("H2OXGBoostClassifier", "XGBoost", True)
H2OXGBoostRegressor = _make("H2OXGBoostRegressor", "XGBoost", False)
class H2OGLMClassifier(_make("H2OGLMClassifier", "GLM", True)):
    """GLM classifier; family follows the class count (h2o-py does the
    same) unless the user passes family explicitly."""

    def _fit_overrides(self) -> dict:
        if "family" in self._params:
            return {}
        return {"family": "binomial" if len(self.classes_) == 2
                else "multinomial"}
H2OGLMRegressor = _make("H2OGLMRegressor", "GLM", False,
                        {"family": "gaussian"})
H2ODeepLearningClassifier = _make(
    "H2ODeepLearningClassifier", "DeepLearning", True)
H2ODeepLearningRegressor = _make(
    "H2ODeepLearningRegressor", "DeepLearning", False)


class H2OKMeans(_Base):
    """sklearn-style KMeans (fit/predict = cluster labels)."""

    _builder_name = "KMeans"

    def fit(self, X, y=None) -> "H2OKMeans":
        from .runtime.cluster import cluster
        cluster()
        fr = self._frame(X)
        self.n_features_in_ = fr.ncols
        self.model_ = self._builder().train(fr)
        return self

    def predict(self, X) -> np.ndarray:
        self._check_fitted()
        return self.model_.predict(self._frame(X)) \
            .vec("predict").to_numpy().astype(int)
