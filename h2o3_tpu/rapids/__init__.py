"""Rapids analog: dataframe munging as sharded device programs.

Reference: ``water/rapids/`` — a Lisp-like expression language with 221
``Ast*`` primitives in 17 categories (mungers, operators, reducers, matrix,
string, time, …), plus distributed radix sort/merge
(``RadixOrder.java``/``BinaryMerge.java``) and group-by (``AstGroup``).

TPU-native redesign: in-process munging primitives are plain functions
over the sharded Frame/Vec, and the REMOTE contract still exists — the
expression-string interpreter (ast.py, /99/Rapids) and the lazy client DAG
(expr.py) mirror h2o-py's ExprNode protocol for REST clients.  Row-scale work (sort keys, segment aggregation, joins,
filters) runs as compiled device programs: sort = ``jnp.argsort`` (TPU
bitonic network, the RadixOrder analog), group-by = one-hot/segment sums
psum'd over the mesh, merge = binary search against the sorted build side
(the BinaryMerge analog).
"""

from .ops import (sort, group_by, merge, rbind, cbind, filter_rows, unique,
                  table, ifelse, hist, impute, cut, scale, interaction,
                  var, cor)
from .strings import (toupper, tolower, trim, lstrip, rstrip, substring,
                      sub, gsub, nchar, strsplit, countmatches)
from .ast import rapids
from .expr import lazy, LazyFrame
