"""Rapids analog: dataframe munging as sharded device programs.

Reference: ``water/rapids/`` — a Lisp-like expression language with 221
``Ast*`` primitives in 17 categories (mungers, operators, reducers, matrix,
string, time, …), plus distributed radix sort/merge
(``RadixOrder.java``/``BinaryMerge.java``) and group-by (``AstGroup``).

TPU-native redesign: there is no expression-string interpreter — the client
IS Python, so munging primitives are plain functions/operators over the
sharded Frame/Vec (the lazy-DAG-to-Rapids compile step in h2o-py exists only
because the reference's client is remote; here frames are already
device-resident).  Row-scale work (sort keys, segment aggregation, joins,
filters) runs as compiled device programs: sort = ``jnp.argsort`` (TPU
bitonic network, the RadixOrder analog), group-by = one-hot/segment sums
psum'd over the mesh, merge = binary search against the sorted build side
(the BinaryMerge analog).
"""

from .ops import (sort, group_by, merge, rbind, cbind, filter_rows, unique,
                  table, ifelse, hist)
