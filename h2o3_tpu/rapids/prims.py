"""Rapids primitive registry: the breadth tier of the expression language.

Reference: the 224 ``Ast*`` classes under
``water/rapids/ast/prims/{math,reducers,mungers,operators,advmath,matrix,
search,repeaters,string,time,timeseries,assign,misc}`` — op tokens here
match each class's ``str()`` exactly (e.g. ``AstMktime.str() == "mktime"``,
month/day arguments 0-based per ``AstMktime.java:55-56``).

Each handler receives ``(sess, args)`` with UNevaluated AST nodes and
evaluates what it needs via ``sess._ev`` — lambda values (``ast.Lambda``)
pass through unevaluated application.  Dense numeric work (distance,
mmult, cumulative reducers) runs on device; string/time/reshape prims are
host-side like the reference's per-chunk Java loops.
"""

from __future__ import annotations

import re
from typing import List

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM, T_STR, T_TIME
from ..runtime import dkv

PRIMS = {}


def prim(name):
    def deco(fn):
        PRIMS[name] = fn
        return fn
    return deco


# ------------------------------------------------------------------ helpers
def _fr(x, name="x") -> Frame:
    return Frame([name], [x]) if isinstance(x, Vec) else x


def _mat(fr: Frame) -> jnp.ndarray:
    """[padded, C] numeric view (cats as codes)."""
    return jnp.stack([v.numeric_data() for v in fr.vecs], axis=1)


def _num_frame(arr, names, nrows) -> Frame:
    arr = jnp.atleast_2d(arr)
    return Frame(list(names)[: arr.shape[1]],
                 [Vec(arr[:, j].astype(jnp.float32), T_NUM, nrows)
                  for j in range(arr.shape[1])])


def _np_frame(cols: dict) -> Frame:
    return Frame.from_numpy(cols)


def _host(fr: Frame) -> np.ndarray:
    return np.column_stack([v.to_numpy() if v.type in (T_STR, T_CAT)
                            else np.asarray(v.to_numpy(), np.float64)
                            for v in fr.vecs])


def _scalar(x) -> float:
    return float(x)


def _mask_rows(fr: Frame, X) -> jnp.ndarray:
    return jnp.arange(X.shape[0]) < fr.nrows


# ------------------------------------------------------------------ math
_EXTRA_UNARY = {
    "acosh": jnp.arccosh, "asinh": jnp.arcsinh, "atanh": jnp.arctanh,
    "cospi": lambda x: jnp.cos(jnp.pi * x),
    "sinpi": lambda x: jnp.sin(jnp.pi * x),
    "tanpi": lambda x: jnp.tan(jnp.pi * x),
    "none": lambda x: x,
}


def _gamma_fns():
    from jax.scipy.special import gammaln, digamma, polygamma

    def gamma(x):
        # |Gamma(x)| = exp(gammaln(x)); for x < 0 the sign alternates per
        # unit interval: negative exactly when floor(x) is odd
        odd_floor = jnp.mod(jnp.floor(x), 2.0) != 0.0
        sign = jnp.where((x < 0) & odd_floor, -1.0, 1.0)
        return sign * jnp.exp(gammaln(x))

    return {
        "gamma": gamma,
        "lgamma": gammaln,
        "digamma": digamma,
        "trigamma": lambda x: polygamma(1, x),
    }


def _unary_prim(fn):
    def h(sess, args):
        fr = _fr(sess._ev(args[0]))
        X = _mat(fr)
        return _num_frame(fn(X).astype(jnp.float32), fr.names, fr.nrows)
    return h


for _name, _fn in _EXTRA_UNARY.items():
    PRIMS[_name] = _unary_prim(_fn)
for _name, _fn in _gamma_fns().items():
    PRIMS[_name] = _unary_prim(_fn)


@prim("signif")
def _signif(sess, args):
    fr = _fr(sess._ev(args[0]))
    digits = int(sess._ev(args[1])) if len(args) > 1 else 6
    X = np.asarray(_mat(fr), np.float64)

    def sig(v):
        with np.errstate(divide="ignore", invalid="ignore"):
            mag = np.floor(np.log10(np.abs(v)))
        mag = np.where(np.isfinite(mag), mag, 0)
        f = 10.0 ** (digits - 1 - mag)
        return np.round(v * f) / f
    return _num_frame(jnp.asarray(sig(X), jnp.float32), fr.names, fr.nrows)


# ------------------------------------------------------------------ operators
def _logical_scalar(sess, args, op):
    l = sess._ev(args[0])
    r = sess._ev(args[1])
    from .ast import _binop
    out = _binop("&" if op == "&&" else "|", l, r)
    return out


PRIMS["&&"] = lambda s, a: _logical_scalar(s, a, "&&")
PRIMS["||"] = lambda s, a: _logical_scalar(s, a, "||")


def _alias(name, target):
    def h(sess, args):
        from .ast import _binop
        return _binop(target, sess._ev(args[0]), sess._ev(args[1]))
    PRIMS[name] = h


_alias("%/%", "intDiv")
_alias("%%", "%")


# ------------------------------------------------------------------ reducers
def _red(name, fn):
    def h(sess, args):
        fr = _fr(sess._ev(args[0]))
        X = _mat(fr)[: fr.nrows]         # static slice: padding excluded
        return _scalar(fn(X))
    PRIMS[name] = h


_red("all", lambda X: float(bool(jnp.all(jnp.nan_to_num(X, nan=1.0) != 0))))
_red("any", lambda X: float(bool(jnp.any(jnp.nan_to_num(X, nan=0.0) != 0))))
_red("any.na", lambda X: float(bool(jnp.any(jnp.isnan(X)))))
_red("naCnt", lambda X: float(jnp.sum(jnp.isnan(X))))
_red("prod", lambda X: float(jnp.prod(X)))
_red("prod.na", lambda X: float(jnp.nanprod(X)))
_red("sumNA", lambda X: float(jnp.nansum(X)))
_red("maxNA", lambda X: float(jnp.nanmax(X)))
_red("minNA", lambda X: float(jnp.nanmin(X)))
_red("h2o.mad", lambda X: float(1.4826 * jnp.nanmedian(
    jnp.abs(X - jnp.nanmedian(X)))))


def _cum_prim(fn):
    def h(sess, args):
        fr = _fr(sess._ev(args[0]))
        axis = 0
        if len(args) > 1:
            axis = int(sess._ev(args[1]))
        Xp = _mat(fr)
        out = fn(Xp[: fr.nrows], axis=1 if axis else 0)
        out = jnp.pad(out, [(0, Xp.shape[0] - fr.nrows), (0, 0)])
        return _num_frame(out, fr.names, fr.nrows)
    return h


def _cummax(X, axis=0):
    import jax
    return jax.lax.associative_scan(jnp.maximum, X, axis=axis)


def _cummin(X, axis=0):
    import jax
    return jax.lax.associative_scan(jnp.minimum, X, axis=axis)


PRIMS["cumsum"] = _cum_prim(jnp.cumsum)
PRIMS["cumprod"] = _cum_prim(jnp.cumprod)
PRIMS["cummax"] = _cum_prim(_cummax)
PRIMS["cummin"] = _cum_prim(_cummin)


@prim("sumaxis")
def _sumaxis(sess, args):
    fr = _fr(sess._ev(args[0]))
    na_rm = bool(sess._ev(args[1])) if len(args) > 1 else False
    axis = int(sess._ev(args[2])) if len(args) > 2 else 0
    Xp = _mat(fr)
    X = Xp[: fr.nrows]
    fn = jnp.nansum if na_rm else jnp.sum
    if axis == 1:                       # row sums -> one column
        out = fn(X, axis=1)
        return _num_frame(jnp.pad(out, (0, Xp.shape[0] - fr.nrows))
                          [:, None], ["sum"], fr.nrows)
    return _num_frame(fn(X, axis=0)[None, :], fr.names, 1)


@prim("topn")
def _topn(sess, args):
    """(topn frame col nPercent getBottomN) -> [row_idx, value] frame
    (AstTopN: nPercent of rows, 0 = top/bottom 1 row grab)."""
    fr = sess._ev(args[0])
    col = sess._col_names(fr, sess._ev(args[1]))[0]
    npct = float(sess._ev(args[2]))
    bottom = bool(int(sess._ev(args[3]))) if len(args) > 3 else False
    x = np.asarray(fr.vec(col).to_numpy(), np.float64)
    live = np.flatnonzero(~np.isnan(x))
    k = max(1, int(round(npct / 100.0 * len(live))))
    order = np.argsort(x[live])
    pick = live[order[:k]] if bottom else live[order[-k:][::-1]]
    return _np_frame({"Row Indices": pick.astype(np.float64),
                      col: x[pick]})


# ------------------------------------------------------------------ matrix
@prim("t")
def _transpose(sess, args):
    fr = _fr(sess._ev(args[0]))
    X = np.asarray(_mat(fr))[: fr.nrows].T        # [C, n]
    return _np_frame({f"c{j}": X[:, j] for j in range(X.shape[1])} or
                     {"c0": np.zeros(0)})


@prim("x")
def _mmult(sess, args):
    a = _fr(sess._ev(args[0]))
    b = _fr(sess._ev(args[1]))
    A = _mat(a)[: a.nrows]
    B = _mat(b)[: b.nrows]
    out = A @ B                                    # MXU matmul
    return _num_frame(jnp.pad(out, [(0, _mat(a).shape[0] - a.nrows),
                                    (0, 0)]),
                      [f"c{j}" for j in range(out.shape[1])], a.nrows)


# ------------------------------------------------------------------ search
@prim("match")
def _match(sess, args):
    """(match frame table nomatch start_index) — AstMatch."""
    fr = _fr(sess._ev(args[0]))
    table = sess._ev(args[1])
    if not isinstance(table, list):
        table = [table]
    nomatch = sess._ev(args[2]) if len(args) > 2 else float("nan")
    start = int(sess._ev(args[3])) if len(args) > 3 else 1
    vals = fr.vecs[0].to_numpy()
    fill = float(nomatch) if nomatch is not None else np.nan
    out = np.full(len(vals), fill)
    # one lut over both spellings: numeric table entries match numeric
    # cells, everything else matches by string
    lut = {}
    for i, t in enumerate(table):
        lut[str(t)] = i + start
        if isinstance(t, float) and t.is_integer():
            lut[str(int(t))] = i + start
    for i, x in enumerate(vals[: fr.nrows]):
        if x is None or (isinstance(x, float) and np.isnan(x)):
            continue
        key = str(int(x)) if isinstance(x, float) and x.is_integer() \
            else str(x)
        if key in lut:
            out[i] = lut[key]
    return _np_frame({"match": out})


@prim("which")
def _which(sess, args):
    fr = _fr(sess._ev(args[0]))
    x = np.asarray(fr.vecs[0].to_numpy(), np.float64)[: fr.nrows]
    idx = np.flatnonzero(np.nan_to_num(x) != 0).astype(np.float64)
    return _np_frame({"which": idx})


def _which_extreme(maximize):
    def h(sess, args):
        fr = _fr(sess._ev(args[0]))
        # na_rm arg (args[1]) is accepted for API parity; NaNs are always
        # skipped and an all-NaN slice yields NaN (never an exception)
        axis = int(sess._ev(args[2])) if len(args) > 2 else 0
        X = np.asarray(_mat(fr), np.float64)[: fr.nrows]
        f = np.nanargmax if maximize else np.nanargmin
        if axis == 1:
            out = np.array([f(r) if not np.all(np.isnan(r)) else np.nan
                            for r in X], np.float64)
            return _np_frame({"which.max" if maximize else "which.min":
                              out})
        out = np.array([f(X[:, j]) if not np.all(np.isnan(X[:, j]))
                        else np.nan for j in range(X.shape[1])],
                       np.float64)
        return _np_frame({n: out[j: j + 1]
                          for j, n in enumerate(fr.names)})
    return h


PRIMS["which.max"] = _which_extreme(True)
PRIMS["which.min"] = _which_extreme(False)


# ------------------------------------------------------------------ repeaters
@prim("rep_len")
def _rep_len(sess, args):
    x = sess._ev(args[0])
    n = int(sess._ev(args[1]))
    if isinstance(x, (Frame, Vec)):
        fr = _fr(x)
        v = np.asarray(fr.vecs[0].to_numpy())[: fr.nrows]
        out = np.resize(v, n)
        return _np_frame({fr.names[0]: out})
    return _np_frame({"rep_len": np.full(n, float(x))})


@prim("seq")
def _seq(sess, args):
    frm, to = float(sess._ev(args[0])), float(sess._ev(args[1]))
    by = float(sess._ev(args[2])) if len(args) > 2 else \
        (1.0 if to >= frm else -1.0)
    return _np_frame({"seq": np.arange(frm, to + by * 0.5, by)})


@prim("seq_len")
def _seq_len(sess, args):
    n = int(sess._ev(args[0]))
    return _np_frame({"seq_len": np.arange(1, n + 1, dtype=np.float64)})


# ------------------------------------------------------------------ advmath
@prim("skewness")
def _skewness(sess, args):
    fr = _fr(sess._ev(args[0]))
    X = np.asarray(_mat(fr), np.float64)[: fr.nrows]
    vals = []
    for j in range(X.shape[1]):
        v = X[:, j]
        v = v[~np.isnan(v)]
        n = len(v)
        s = v.std(ddof=1)
        vals.append(float(n / ((n - 1) * (n - 2))
                          * np.sum(((v - v.mean()) / s) ** 3))
                    if n > 2 and s else np.nan)
    return vals if len(vals) > 1 else vals[0]


@prim("kurtosis")
def _kurtosis(sess, args):
    fr = _fr(sess._ev(args[0]))
    X = np.asarray(_mat(fr), np.float64)[: fr.nrows]
    vals = []
    for j in range(X.shape[1]):
        v = X[:, j]
        v = v[~np.isnan(v)]
        n = len(v)
        s2 = v.var(ddof=1)
        vals.append(float(np.sum((v - v.mean()) ** 4) / (n * s2 * s2))
                    if n > 1 and s2 else np.nan)
    return vals if len(vals) > 1 else vals[0]


@prim("mode")
def _mode(sess, args):
    fr = _fr(sess._ev(args[0]))
    v = fr.vecs[0]
    vals, counts = np.unique(
        np.asarray(v.numeric_data())[: fr.nrows], return_counts=True)
    ok = ~np.isnan(vals)
    vals, counts = vals[ok], counts[ok]
    return float(vals[np.argmax(counts)]) if len(vals) else float("nan")


@prim("h2o.runif")
def _runif(sess, args):
    fr = sess._ev(args[0])
    seed = int(sess._ev(args[1])) if len(args) > 1 else -1
    rng = np.random.default_rng(None if seed in (-1,) else seed)
    return _np_frame({"rnd": rng.random(fr.nrows)})


@prim("kfold_column")
def _kfold(sess, args):
    fr = sess._ev(args[0])
    nfolds = int(sess._ev(args[1]))
    seed = int(sess._ev(args[2])) if len(args) > 2 else -1
    from ..models.cv import fold_assignment
    folds = fold_assignment(fr.nrows, nfolds, "random",
                            seed if seed != -1 else 0)
    return _np_frame({"fold": folds.astype(np.float64)})


@prim("modulo_kfold_column")
def _modulo_kfold(sess, args):
    fr = sess._ev(args[0])
    nfolds = int(sess._ev(args[1]))
    return _np_frame({"fold": (np.arange(fr.nrows) % nfolds)
                      .astype(np.float64)})


@prim("stratified_kfold_column")
def _strat_kfold(sess, args):
    fr = _fr(sess._ev(args[0]))
    nfolds = int(sess._ev(args[1]))
    seed = int(sess._ev(args[2])) if len(args) > 2 else -1
    from ..models.cv import fold_assignment
    y = np.asarray(fr.vecs[0].numeric_data())[: fr.nrows]
    folds = fold_assignment(fr.nrows, nfolds, "stratified",
                            seed if seed != -1 else 0, y=y)
    return _np_frame({"fold": folds.astype(np.float64)})


@prim("h2o.random_stratified_split")
def _strat_split(sess, args):
    fr = _fr(sess._ev(args[0]))
    test_frac = float(sess._ev(args[1]))
    seed = int(sess._ev(args[2])) if len(args) > 2 else -1
    rng = np.random.default_rng(None if seed == -1 else seed)
    y = np.asarray(fr.vecs[0].numeric_data())[: fr.nrows]
    out = np.zeros(fr.nrows)
    for cls in np.unique(y[~np.isnan(y)]):
        idx = np.flatnonzero(y == cls)
        rng.shuffle(idx)
        k = int(round(test_frac * len(idx)))
        out[idx[:k]] = 1.0
    return Frame(["test_train_split"],
                 [Vec.from_numpy(
                     np.where(out > 0, "test", "train").astype(object),
                     T_CAT, domain=["train", "test"])])


@prim("distance")
def _distance(sess, args):
    """(distance x y measure) — AstDistance; [nx, ny] matrix on the MXU."""
    a = _fr(sess._ev(args[0]))
    b = _fr(sess._ev(args[1]))
    measure = str(sess._ev(args[2])).lower() if len(args) > 2 else "l2"
    A = _mat(a)[: a.nrows]
    B = _mat(b)[: b.nrows]
    if measure in ("cosine", "cosine_sq"):
        An = A / jnp.maximum(jnp.linalg.norm(A, axis=1, keepdims=True),
                             1e-12)
        Bn = B / jnp.maximum(jnp.linalg.norm(B, axis=1, keepdims=True),
                             1e-12)
        D = An @ Bn.T
        if measure == "cosine_sq":
            D = D * D
    elif measure in ("l1",):
        D = jnp.sum(jnp.abs(A[:, None, :] - B[None, :, :]), axis=-1)
    else:                                           # l2
        a2 = jnp.sum(A * A, axis=1)[:, None]
        b2 = jnp.sum(B * B, axis=1)[None, :]
        D = jnp.sqrt(jnp.maximum(a2 + b2 - 2.0 * (A @ B.T), 0.0))
    D = np.asarray(D)
    return _np_frame({f"C{j + 1}": D[:, j] for j in range(D.shape[1])})


# ------------------------------------------------------------------ mungers
@prim("any.factor")
def _anyfactor(sess, args):
    fr = sess._ev(args[0])
    return float(any(v.type == T_CAT for v in fr.vecs))


@prim("is.factor")
def _isfactor(sess, args):
    fr = _fr(sess._ev(args[0]))
    return [float(v.type == T_CAT) for v in fr.vecs] \
        if fr.ncols > 1 else float(fr.vecs[0].type == T_CAT)


@prim("is.numeric")
def _isnumeric(sess, args):
    fr = _fr(sess._ev(args[0]))
    return [float(v.type in (T_NUM, T_TIME)) for v in fr.vecs] \
        if fr.ncols > 1 else float(fr.vecs[0].type in (T_NUM, T_TIME))


@prim("is.character")
def _ischaracter(sess, args):
    fr = _fr(sess._ev(args[0]))
    return [float(v.type == T_STR) for v in fr.vecs] \
        if fr.ncols > 1 else float(fr.vecs[0].type == T_STR)


@prim("as.character")
def _ascharacter(sess, args):
    fr = _fr(sess._ev(args[0]))
    out = []
    for v in fr.vecs:
        vals = v.to_numpy()
        if v.type in (T_NUM, T_TIME):
            svals = np.asarray(
                ["" if np.isnan(x) else (str(int(x)) if float(x).is_integer()
                                         else str(x)) for x in vals],
                object)
        else:
            svals = np.asarray([("" if x is None else str(x))
                                for x in vals], object)
        out.append(Vec.from_numpy(svals, T_STR))
    return Frame(fr.names, out)


@prim("levels")
def _levels(sess, args):
    fr = _fr(sess._ev(args[0]))
    width = max([v.cardinality for v in fr.vecs if v.type == T_CAT] or [0])
    names, vecs = [], []
    for n, v in zip(fr.names, fr.vecs):
        dom = (v.domain or []) if v.type == T_CAT else []
        names.append(n)
        vecs.append(Vec.from_numpy(
            np.asarray(dom + [""] * (width - len(dom)), object), T_STR))
    return Frame(names, vecs)


@prim("nlevels")
def _nlevels(sess, args):
    fr = _fr(sess._ev(args[0]))
    v = fr.vecs[0]
    return float(v.cardinality if v.type == T_CAT else 0)


@prim("setLevel")
def _setlevel(sess, args):
    """(setLevel frame level) — every row becomes `level`."""
    fr = _fr(sess._ev(args[0]))
    level = str(sess._ev(args[1]))
    v = fr.vecs[0]
    if v.type != T_CAT or level not in (v.domain or []):
        raise ValueError(f"setLevel: {level!r} not in domain")
    vals = np.asarray([level] * fr.nrows, object)
    return Frame(fr.names, [Vec.from_numpy(vals, T_CAT, domain=v.domain)])


@prim("setDomain")
def _setdomain(sess, args):
    fr = _fr(sess._ev(args[0]))
    # (setDomain frame inPlace [levels])
    levels = sess._ev(args[-1])
    v = fr.vecs[0]
    codes = np.asarray(v.numeric_data())[: fr.nrows]
    dom = [str(x) for x in levels]
    vals = np.asarray([dom[int(c)] if not np.isnan(c) and
                       int(c) < len(dom) else None
                       for c in codes], object)
    return Frame(fr.names, [Vec.from_numpy(vals, T_CAT, domain=dom)])


@prim("appendLevels")
def _appendlevels(sess, args):
    fr = _fr(sess._ev(args[0]))
    extra = [str(x) for x in sess._ev(args[1])]
    v = fr.vecs[0]
    dom = list(v.domain or []) + [x for x in extra
                                  if x not in (v.domain or [])]
    vals = v.to_numpy()
    return Frame(fr.names, [Vec.from_numpy(vals, T_CAT, domain=dom)])


@prim("relevel")
def _relevel(sess, args):
    """(relevel frame level) — move level to the front of the domain."""
    fr = _fr(sess._ev(args[0]))
    level = str(sess._ev(args[1]))
    v = fr.vecs[0]
    dom = list(v.domain or [])
    if level not in dom:
        raise ValueError(f"relevel: {level!r} not in domain")
    dom = [level] + [d for d in dom if d != level]
    return Frame(fr.names, [Vec.from_numpy(v.to_numpy(), T_CAT,
                                           domain=dom)])


@prim("relevel.by.freq")
def _relevel_freq(sess, args):
    fr = _fr(sess._ev(args[0]))
    v = fr.vecs[0]
    vals = v.to_numpy()
    from collections import Counter
    counts = Counter(x for x in vals if x is not None)
    dom = [d for d, _ in counts.most_common()]
    dom += [d for d in (v.domain or []) if d not in dom]
    return Frame(fr.names, [Vec.from_numpy(vals, T_CAT, domain=dom)])


@prim("columnsByType")
def _columns_by_type(sess, args):
    fr = sess._ev(args[0])
    want = str(sess._ev(args[1])).lower() if len(args) > 1 else "numeric"
    sel = {
        "numeric": lambda v: v.type == T_NUM,
        "categorical": lambda v: v.type == T_CAT,
        "string": lambda v: v.type == T_STR,
        "time": lambda v: v.type == T_TIME,
        "bad": lambda v: False,
    }.get(want, lambda v: v.type == T_NUM)
    idx = [float(j) for j, v in enumerate(fr.vecs) if sel(v)]
    return _np_frame({"columns": np.asarray(idx, np.float64)})


@prim("na.omit")
def _naomit(sess, args):
    fr = sess._ev(args[0])
    keep = np.ones(fr.nrows, bool)
    for v in fr.vecs:
        x = v.to_numpy()
        if v.type in (T_NUM, T_TIME):
            keep &= ~np.isnan(np.asarray(x, np.float64))
        else:
            keep &= np.asarray([s is not None and s == s for s in x])
    return fr.rows(np.flatnonzero(keep))


@prim("filterNACols")
def _filter_na_cols(sess, args):
    fr = sess._ev(args[0])
    frac = float(sess._ev(args[1])) if len(args) > 1 else 0.1
    keep = []
    for j, v in enumerate(fr.vecs):
        miss = v.rollups().nmissing if hasattr(v, "rollups") else 0
        if miss / max(fr.nrows, 1) < frac:
            keep.append(float(j))
    return _np_frame({"columns": np.asarray(keep, np.float64)})


@prim("h2o.fillna")
def _fillna(sess, args):
    """(h2o.fillna frame method axis maxlen) — forward/backward fill."""
    fr = sess._ev(args[0])
    method = str(sess._ev(args[1])).lower() if len(args) > 1 else "forward"
    axis = int(sess._ev(args[2])) if len(args) > 2 else 0
    maxlen = int(sess._ev(args[3])) if len(args) > 3 else 1

    def fill1d(col):
        col = col.copy()
        if method == "backward":
            col = col[::-1]
        run = 0
        for i in range(1, len(col)):
            if np.isnan(col[i]):
                if run < maxlen and not np.isnan(col[i - 1]):
                    col[i] = col[i - 1]
                    run += 1
            else:
                run = 0
        return col[::-1] if method == "backward" else col

    X = np.asarray(_mat(fr), np.float64)[: fr.nrows].copy()
    X = np.apply_along_axis(fill1d, 0 if axis == 0 else 1, X)
    return _np_frame({n: X[:, j] for j, n in enumerate(fr.names)})


@prim("flatten")
def _flatten(sess, args):
    fr = _fr(sess._ev(args[0]))
    v = fr.vecs[0]
    if fr.nrows != 1:
        raise ValueError("flatten expects a 1x1 frame")
    if v.type in (T_NUM, T_TIME):
        return float(np.asarray(v.to_numpy(), np.float64)[0])
    return str(v.to_numpy()[0])


@prim("getrow")
def _getrow(sess, args):
    fr = sess._ev(args[0])
    if fr.nrows != 1:
        raise ValueError("getrow expects a single-row frame")
    return [float(np.asarray(v.to_numpy(), np.float64)[0])
            if v.type in (T_NUM, T_TIME) else v.to_numpy()[0]
            for v in fr.vecs]


@prim("melt")
def _melt(sess, args):
    """(melt frame [id_vars] [value_vars] var_name value_name skipna)."""
    fr = sess._ev(args[0])
    id_vars = sess._col_names(fr, sess._ev(args[1]))
    vv = sess._ev(args[2]) if len(args) > 2 and args[2] is not None else None
    value_vars = sess._col_names(fr, vv) if vv else \
        [c for c in fr.names if c not in id_vars]
    var_name = str(sess._ev(args[3])) if len(args) > 3 else "variable"
    value_name = str(sess._ev(args[4])) if len(args) > 4 else "value"
    skipna = bool(sess._ev(args[5])) if len(args) > 5 else False
    n = fr.nrows
    out_id = {c: [] for c in id_vars}
    out_var, out_val = [], []
    host_ids = {c: _decoded(fr.vec(c)) for c in id_vars}
    for vcol in value_vars:
        vals = np.asarray(fr.vec(vcol).to_numpy(), np.float64)[:n]
        mask = ~np.isnan(vals) if skipna else np.ones(n, bool)
        idx = np.flatnonzero(mask)
        for c in id_vars:
            out_id[c].append(np.asarray(host_ids[c])[idx])
        out_var.append(np.full(len(idx), vcol, object))
        out_val.append(vals[idx])
    cols = {}
    for c in id_vars:
        merged = np.concatenate(out_id[c]) if out_id[c] else np.zeros(0)
        cols[c] = merged
    cols[var_name] = np.concatenate(out_var) if out_var else \
        np.zeros(0, object)
    cols[value_name] = np.concatenate(out_val) if out_val else np.zeros(0)
    return _np_frame(cols)


@prim("pivot")
def _pivot(sess, args):
    """(pivot frame index column value) — AstPivot."""
    fr = sess._ev(args[0])
    index = sess._col_names(fr, sess._ev(args[1]))[0]
    column = sess._col_names(fr, sess._ev(args[2]))[0]
    value = sess._col_names(fr, sess._ev(args[3]))[0]
    idx_vec = fr.vec(index)
    idx_vals = _decoded(idx_vec)
    col_vals = _decoded(fr.vec(column))
    val_vals = np.asarray(fr.vec(value).to_numpy(), np.float64)
    uidx = sorted(set(str(x) for x in idx_vals[: fr.nrows]))
    ucol = sorted(set(str(x) for x in col_vals[: fr.nrows]))
    pos_i = {v: i for i, v in enumerate(uidx)}
    pos_c = {v: i for i, v in enumerate(ucol)}
    M = np.full((len(uidx), len(ucol)), np.nan)
    for i in range(fr.nrows):
        M[pos_i[str(idx_vals[i])], pos_c[str(col_vals[i])]] = val_vals[i]
    if idx_vec.type in (T_NUM, T_TIME):
        cols = {index: np.asarray([float(x) for x in uidx])}
    else:
        cols = {index: np.asarray(uidx, object)}
    for j, c in enumerate(ucol):
        cols[c] = M[:, j]
    return _np_frame(cols)


@prim("rename")
def _rename(sess, args):
    fr = sess._ev(args[0])
    old = sess._ev(args[1])
    new = sess._ev(args[2])
    return fr.rename({str(old): str(new)})


@prim("rank_within_groupby")
def _rank_within(sess, args):
    """(rank_within_groupby fr [groupby] [sortcols] [asc] name sort2by)."""
    fr = sess._ev(args[0])
    by = sess._col_names(fr, sess._ev(args[1]))
    sortcols = sess._col_names(fr, sess._ev(args[2]))
    asc = sess._ev(args[3]) if len(args) > 3 else []
    name = str(sess._ev(args[4])) if len(args) > 4 else "New_Rank_column"
    keys = [np.asarray(fr.vec(c).numeric_data())[: fr.nrows] for c in by]
    svals = [np.asarray(fr.vec(c).numeric_data())[: fr.nrows]
             for c in sortcols]
    if asc:
        flips = [(-1.0 if not a else 1.0) for a in
                 (asc if isinstance(asc, list) else [asc])]
        svals = [v * flips[i] if i < len(flips) else v
                 for i, v in enumerate(svals)]
    order = np.lexsort(tuple(reversed(keys + svals)))
    group_id = np.zeros(fr.nrows, np.int64)
    gk = np.column_stack(keys)
    _, group_id = np.unique(gk, axis=0, return_inverse=True)
    rank = np.zeros(fr.nrows)
    seen = {}
    for i in order:
        g = group_id[i]
        seen[g] = seen.get(g, 0) + 1
        rank[i] = seen[g]
    from ..frame.vec import T_NUM as _TN
    return Frame(list(fr.names) + [name],
                 list(fr.vecs) + [Vec.from_numpy(rank, _TN)])


# ------------------------------------------------------------------ assign
@prim("append")
def _append(sess, args):
    fr = sess._ev(args[0])
    val = sess._ev(args[1])
    name = str(sess._ev(args[2]))
    if isinstance(val, (int, float)):
        v = Vec.from_numpy(np.full(fr.nrows, float(val)), T_NUM)
    else:
        v = _fr(val).vecs[0]
    names = list(fr.names)
    vecs = list(fr.vecs)
    if name in names:
        vecs[names.index(name)] = v
    else:
        names.append(name)
        vecs.append(v)
    return Frame(names, vecs)


@prim(":=")
def _rect_assign(sess, args):
    """(:= frame rhs col_sel row_sel) — AstRectangleAssign."""
    fr = sess._ev(args[0])
    rhs = sess._ev(args[1])
    col_sel = sess._ev(args[2])
    row_sel = sess._ev(args[3]) if len(args) > 3 else None
    cols = sess._col_names(fr, col_sel)
    if row_sel is None or (isinstance(row_sel, list) and not row_sel):
        rows = np.arange(fr.nrows)
    elif isinstance(row_sel, Frame):
        m = np.asarray(row_sel.vecs[0].numeric_data())[: fr.nrows]
        rows = np.flatnonzero(np.nan_to_num(m) != 0)
    elif isinstance(row_sel, list):
        rows = np.asarray(row_sel, np.int64)
    else:
        rows = np.asarray([int(row_sel)])
    new_vecs = list(fr.vecs)
    names = list(fr.names)
    for k, c in enumerate(cols):
        j = names.index(c)
        v = fr.vecs[j]
        if isinstance(rhs, (int, float)):
            vals = np.asarray(v.to_numpy()).copy()
            if v.type in (T_NUM, T_TIME):
                vals = np.asarray(vals, np.float64)
            vals[rows] = float(rhs)
            new_vecs[j] = Vec.from_numpy(vals, v.type, domain=v.domain)
        elif isinstance(rhs, str):
            vals = np.asarray(v.to_numpy(), object).copy()
            vals[rows] = rhs
            dom = v.domain
            if v.type == T_CAT and dom is not None and rhs not in dom:
                dom = list(dom) + [rhs]
            new_vecs[j] = Vec.from_numpy(vals, v.type, domain=dom)
        else:
            rf = _fr(rhs)
            src = rf.vecs[min(k, rf.ncols - 1)]
            vals = np.asarray(v.to_numpy()).copy()
            sv = src.to_numpy()
            if v.type in (T_NUM, T_TIME):
                vals = np.asarray(vals, np.float64)
                vals[rows] = np.asarray(sv, np.float64)[: len(rows)]
            else:
                vals = np.asarray(vals, object)
                vals[rows] = np.asarray(sv, object)[: len(rows)]
            new_vecs[j] = Vec.from_numpy(vals, v.type, domain=v.domain)
    return Frame(names, new_vecs, key=fr.key)


# ------------------------------------------------------------------ misc
@prim("ls")
def _ls(sess, args):
    keys = sorted(dkv.keys(""))
    return Frame(["key"], [Vec.from_numpy(np.asarray(keys, object),
                                          T_STR)])


# ------------------------------------------------------------------ string
@prim("strlen")
def _strlen(sess, args):
    from .strings import nchar
    fr = _fr(sess._ev(args[0]))
    return Frame(fr.names, [nchar(v) for v in fr.vecs])


@prim("tokenize")
def _tokenize(sess, args):
    """(tokenize frame regex) — hex/RegexTokenizer.java:42-60: every string
    column of a row is split; rows' token runs are delimited by NA rows.
    Output: one string column, the Word2Vec ingestion format."""
    fr = sess._ev(args[0])
    regex = str(sess._ev(args[1]))
    pat = re.compile(regex)
    out: List = []
    host_cols = [v.to_numpy() for v in fr.vecs]
    for v in fr.vecs:
        if v.type not in (T_STR, T_CAT):
            raise ValueError("tokenize() requires all input columns to be "
                             "of a String type")
    for i in range(fr.nrows):
        for col in host_cols:
            s = col[i]
            if s is None or (isinstance(s, float) and np.isnan(s)):
                continue
            for tok in pat.split(str(s)):
                if tok:
                    out.append(tok)
        out.append(None)
    return Frame(["tokens"], [Vec.from_numpy(np.asarray(out, object),
                                             T_STR)])


@prim("grep")
def _grep(sess, args):
    """(grep frame regex ignore_case invert output_logical)."""
    fr = _fr(sess._ev(args[0]))
    regex = str(sess._ev(args[1]))
    ignore_case = bool(sess._ev(args[2])) if len(args) > 2 else False
    invert = bool(sess._ev(args[3])) if len(args) > 3 else False
    logical = bool(sess._ev(args[4])) if len(args) > 4 else False
    pat = re.compile(regex, re.IGNORECASE if ignore_case else 0)
    vals = fr.vecs[0].to_numpy()
    hit = np.asarray([bool(pat.search(str(s))) if s is not None else False
                      for s in vals[: fr.nrows]])
    if invert:
        hit = ~hit
    if logical:
        return _np_frame({"grep": hit.astype(np.float64)})
    return _np_frame({"grep": np.flatnonzero(hit).astype(np.float64)})


@prim("entropy")
def _entropy(sess, args):
    fr = _fr(sess._ev(args[0]))
    vals = fr.vecs[0].to_numpy()
    out = np.full(fr.nrows, np.nan)
    for i, s in enumerate(vals[: fr.nrows]):
        if s is None:
            continue
        s = str(s)
        if not s:
            out[i] = 0.0
            continue
        _, counts = np.unique(list(s), return_counts=True)
        p = counts / counts.sum()
        out[i] = float(-np.sum(p * np.log2(p)))
    return _np_frame({"entropy": out})


@prim("strDistance")
def _str_distance(sess, args):
    """(strDistance fr1 fr2 measure compare_empty) — Levenshtein and
    Jaccard measures (reference delegates to a string-distance library)."""
    a = _fr(sess._ev(args[0])).vecs[0].to_numpy()
    b = _fr(sess._ev(args[1])).vecs[0].to_numpy()
    measure = str(sess._ev(args[2])).lower() if len(args) > 2 else "lv"
    n = min(len(a), len(b))

    def lv(x, y):
        if x is None or y is None:
            return np.nan
        x, y = str(x), str(y)
        prev = list(range(len(y) + 1))
        for i, cx in enumerate(x, 1):
            cur = [i]
            for j, cy in enumerate(y, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (cx != cy)))
            prev = cur
        return float(prev[-1])

    def jaccard(x, y):
        if x is None or y is None:
            return np.nan
        sx, sy = set(str(x)), set(str(y))
        return float(len(sx & sy) / len(sx | sy)) if sx | sy else 1.0

    fn = jaccard if measure == "jaccard" else lv
    out = np.asarray([fn(a[i], b[i]) for i in range(n)])
    return _np_frame({"distance": out})


@prim("num_valid_substrings")
def _num_valid_substrings(sess, args):
    fr = _fr(sess._ev(args[0]))
    path = str(sess._ev(args[1]))
    with open(path) as f:
        words = set(w.strip() for w in f if w.strip())
    vals = fr.vecs[0].to_numpy()
    out = np.full(fr.nrows, np.nan)
    for i, s in enumerate(vals[: fr.nrows]):
        if s is None:
            continue
        s = str(s)
        cnt = 0
        for lo in range(len(s)):
            for hi in range(lo + 2, len(s) + 1):
                if s[lo:hi] in words:
                    cnt += 1
        out[i] = cnt
    return _np_frame({"num_valid_substrings": out})


# ------------------------------------------------------------------ time
def _decoded(v: Vec) -> np.ndarray:
    """Host labels for cats, host values otherwise."""
    return v.decoded() if v.type == T_CAT else v.to_numpy()


def _millis_to_dt(fr: Frame):
    # per-column to_numpy, NOT the f32 device matrix: epoch millis
    # (~1.6e12) lose ~2 minutes of precision in float32; T_TIME columns
    # keep exact f64 host-side (Vec.to_numpy)
    ms = np.column_stack([np.asarray(v.to_numpy(), np.float64)
                          for v in fr.vecs])[: fr.nrows]
    dt = (np.where(np.isnan(ms), 0, ms)).astype("int64") \
        .astype("datetime64[ms]")
    return dt, np.isnan(ms)


def _time_field(extract):
    def h(sess, args):
        fr = _fr(sess._ev(args[0]))
        dt, nan = _millis_to_dt(fr)
        out = extract(dt).astype(np.float64)
        out[nan] = np.nan
        pad = int(fr.vecs[0].numeric_data().shape[0]) - fr.nrows
        return _num_frame(
            jnp.asarray(np.pad(out, [(0, pad), (0, 0)])),
            fr.names, fr.nrows)
    return h


PRIMS["year"] = _time_field(
    lambda dt: dt.astype("datetime64[Y]").astype(int) + 1970)
PRIMS["month"] = _time_field(
    lambda dt: dt.astype("datetime64[M]").astype(int) % 12 + 1)
PRIMS["day"] = _time_field(
    lambda dt: (dt.astype("datetime64[D]")
                - dt.astype("datetime64[M]")).astype(int) + 1)
PRIMS["dayOfWeek"] = _time_field(
    lambda dt: (dt.astype("datetime64[D]").astype(int) + 3) % 7)
PRIMS["hour"] = _time_field(
    lambda dt: (dt - dt.astype("datetime64[D]"))
    .astype("timedelta64[h]").astype(int))
PRIMS["minute"] = _time_field(
    lambda dt: ((dt - dt.astype("datetime64[D]"))
                .astype("timedelta64[m]").astype(int)) % 60)
PRIMS["second"] = _time_field(
    lambda dt: ((dt - dt.astype("datetime64[D]"))
                .astype("timedelta64[s]").astype(int)) % 60)
PRIMS["millis"] = _time_field(
    lambda dt: dt.astype("int64").astype(np.float64))
PRIMS["week"] = _time_field(
    lambda dt: ((dt.astype("datetime64[D]")
                 - dt.astype("datetime64[Y]")).astype(int)) // 7 + 1)


@prim("mktime")
def _mktime(sess, args):
    """(mktime year month day hour minute second msec) — months and days
    0-based (AstMktime.java:55-56)."""
    parts = []
    nrows = 1
    for a in args:
        v = sess._ev(a)
        if isinstance(v, (Frame, Vec)):
            fr = _fr(v)
            nrows = fr.nrows
            parts.append(np.asarray(_mat(fr), np.float64)[: nrows, 0])
        else:
            parts.append(float(v))
    parts = [np.full(nrows, p) if np.isscalar(p) else p for p in parts]
    while len(parts) < 7:
        parts.append(np.zeros(nrows))
    y, mo, d, h, mi, s, ms = parts[:7]
    out = np.zeros(nrows)
    for i in range(nrows):
        t = (np.datetime64(f"{int(y[i]):04d}-01-01")
             + np.timedelta64(0, "ms"))
        t = (np.datetime64(f"{int(y[i]):04d}-01", "M")
             + np.timedelta64(int(mo[i]), "M"))
        t = t.astype("datetime64[D]") + np.timedelta64(int(d[i]), "D")
        t = t.astype("datetime64[ms]") \
            + np.timedelta64(int(h[i]), "h") \
            + np.timedelta64(int(mi[i]), "m") \
            + np.timedelta64(int(s[i]), "s") \
            + np.timedelta64(int(ms[i]), "ms")
        out[i] = t.astype("int64")
    v = Vec.from_numpy(out, T_TIME)
    return Frame(["mktime"], [v])


@prim("moment")
def _moment(sess, args):
    return _mktime(sess, args)


@prim("as.Date")
def _as_date(sess, args):
    """(as.Date frame format) — string/cat column -> epoch millis."""
    import datetime as _dt
    fr = _fr(sess._ev(args[0]))
    fmt = str(sess._ev(args[1]))
    # translate Java SimpleDateFormat to strptime
    pyfmt = fmt.replace("yyyy", "%Y").replace("yy", "%y") \
        .replace("MM", "%m").replace("dd", "%d").replace("HH", "%H") \
        .replace("mm", "%M").replace("ss", "%S")
    vals = fr.vecs[0].to_numpy()
    out = np.full(fr.nrows, np.nan)
    for i, s in enumerate(vals[: fr.nrows]):
        if s is None:
            continue
        try:
            t = _dt.datetime.strptime(str(s), pyfmt)
            out[i] = t.replace(tzinfo=_dt.timezone.utc).timestamp() * 1000
        except ValueError:
            pass
    return Frame(fr.names, [Vec.from_numpy(out, T_TIME)])


_TZ = ["UTC"]


@prim("getTimeZone")
def _get_tz(sess, args):
    return _TZ[0]


@prim("setTimeZone")
def _set_tz(sess, args):
    _TZ[0] = str(sess._ev(args[0]))
    return _TZ[0]


@prim("listTimeZones")
def _list_tz(sess, args):
    import zoneinfo
    zones = sorted(zoneinfo.available_timezones())
    return _np_frame({"timezone": np.asarray(zones, object)})


# ------------------------------------------------------------------ timeseries
@prim("difflag1")
def _difflag1(sess, args):
    fr = _fr(sess._ev(args[0]))
    x = np.asarray(_mat(fr), np.float64)[: fr.nrows, 0]
    return _np_frame({fr.names[0]: np.diff(x)})


def _norm_ppf(q):
    from jax.scipy.special import ndtri
    return np.asarray(ndtri(np.asarray(q, np.float64)))


def _isax_impl(sess, args):
    fr = sess._ev(args[0])
    num_words = int(sess._ev(args[1]))
    max_card = int(sess._ev(args[2]))
    X = np.asarray(_mat(fr), np.float64)[: fr.nrows]
    mu = X.mean(axis=1, keepdims=True)
    sd = X.std(axis=1, keepdims=True)
    Z = (X - mu) / np.where(sd == 0, 1, sd)
    C = X.shape[1]
    bounds = np.linspace(0, C, num_words + 1).astype(int)
    paa = np.stack([Z[:, bounds[k]: max(bounds[k + 1], bounds[k] + 1)]
                    .mean(axis=1) for k in range(num_words)], axis=1)
    cuts = _norm_ppf(np.arange(1, max_card) / max_card)
    codes = np.searchsorted(cuts, paa)               # [n, words]
    strs = np.asarray(["^".join(str(int(c)) for c in row)
                       for row in codes], object)
    cols = {"iSax_index": strs}
    for k in range(num_words):
        cols[f"iSax_word_{k}"] = codes[:, k].astype(np.float64)
    return _np_frame(cols)


PRIMS["isax"] = _isax_impl
