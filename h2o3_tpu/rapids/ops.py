"""Munging primitives over sharded Frames (the water/rapids Ast* analogs).

sort/merge/group_by/filter run device-side (see device.py for the
RadixOrder/BinaryMerge redesign); host round-trips are limited to O(1)
scalars, group-count-sized arrays, and string-typed payloads.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame import lineage
from ..frame.vec import Vec, T_CAT, T_NUM, T_STR, T_TIME
from ..runtime.cluster import cluster, fetch
from . import device as dev


def sort(frame: Frame, by: Union[str, Sequence[str]],
         ascending: Union[bool, Sequence[bool]] = True) -> Frame:
    """Multi-key sort — AstSort / RadixOrder analog, fully on device."""
    by = [by] if isinstance(by, str) else list(by)
    asc = [ascending] * len(by) if isinstance(ascending, bool) \
        else list(ascending)
    if len(asc) != len(by):
        raise ValueError("ascending must match by")
    keys = [dev.sort_key(frame.vec(c)) for c in by]
    order = dev.lex_order(keys, asc)
    return lineage.derive(dev.gather_rows(frame, order, frame.nrows), frame,
                          {"op": "sort", "by": by,
                           "ascending": [bool(a) for a in asc]})


def filter_rows(frame: Frame, mask) -> Frame:
    """Boolean row filter — AstRowSlice analog (device compaction)."""
    if isinstance(mask, Vec):
        m = (mask.data != 0) & mask.valid_mask()
        if mask.type != T_CAT:
            m = m & ~jnp.isnan(mask.data)
    else:
        host = np.zeros(frame.padded_rows, bool)
        host[: frame.nrows] = np.asarray(mask)[: frame.nrows].astype(bool)
        m = jnp.asarray(host)
    m = m & (jnp.arange(frame.padded_rows) < frame.nrows)
    n_out = int(jnp.sum(m))
    order = jnp.argsort(~m, stable=True)          # kept rows first, in order
    return dev.gather_rows(frame, order, n_out)


def rbind(*frames: Frame) -> Frame:
    """Stack frames vertically — AstRBind analog."""
    base = frames[0]
    for fr in frames[1:]:
        if fr.names != base.names:
            raise ValueError("rbind: column names differ")
    vecs = []
    for i, name in enumerate(base.names):
        vs = [fr.vecs[i] for fr in frames]
        t = vs[0].type
        if t == T_CAT:
            # unify domains
            domain = []
            seen = {}
            for v in vs:
                for lbl in (v.domain or []):
                    if lbl not in seen:
                        seen[lbl] = len(domain)
                        domain.append(lbl)
            codes = []
            for v in vs:
                remap = np.array([seen[lbl] for lbl in (v.domain or [])],
                                 dtype=np.int32)
                c = v.to_numpy()
                codes.append(np.where(c < 0, -1,
                                      remap[np.clip(c, 0, None)]))
            vecs.append(Vec.from_numpy(np.concatenate(codes), T_CAT,
                                       domain=domain))
        elif vs[0].data is None:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.host_data for v in vs]), t))
        else:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.host_data if t == T_TIME else v.to_numpy()
                                for v in vs]), t))
    return Frame(base.names, vecs)


def cbind(*frames: Frame) -> Frame:
    """Stack frames horizontally — AstCBind analog."""
    names, vecs = [], []
    for fr in frames:
        for n, v in zip(fr.names, fr.vecs):
            nn = n
            k = 0
            while nn in names:
                k += 1
                nn = f"{n}{k}"
            names.append(nn)
            vecs.append(v)
    return Frame(names, vecs)


def unique(vec: Vec) -> np.ndarray:
    """Distinct values — AstUnique analog."""
    if vec.type == T_CAT:
        codes = np.unique(vec.to_numpy())
        return np.asarray([vec.domain[c] for c in codes if c >= 0])
    x = np.asarray(jnp.sort(dev.sort_key(vec)))[: vec.nrows]
    x = x[np.isfinite(x)]
    return np.unique(x)


def table(vec: Vec, weights: Optional[Vec] = None) -> Dict[str, float]:
    """Value counts — AstTable analog (device segment-sum for cats)."""
    if vec.type == T_CAT:
        K = len(vec.domain or [])
        codes = vec.data
        w = (vec.valid_mask() & (codes >= 0)).astype(jnp.float32)
        if weights is not None:
            w = w * weights.numeric_data()
        gid = jnp.where(codes >= 0, codes, K)
        counts = np.asarray(jax.ops.segment_sum(
            w, gid, num_segments=K + 1))[:K]
        return {vec.domain[i]: float(counts[i]) for i in range(K)}
    vals, counts = np.unique(vec.to_numpy()[~np.isnan(vec.to_numpy())],
                             return_counts=True)
    return {str(v): int(c) for v, c in zip(vals, counts)}


def ifelse(cond, yes, no) -> Vec:
    """Vectorized conditional — AstIfElse analog."""
    c = cond.data if isinstance(cond, Vec) else jnp.asarray(cond)
    y = yes.data if isinstance(yes, Vec) else yes
    n = no.data if isinstance(no, Vec) else no
    nrows = cond.nrows if isinstance(cond, Vec) else len(np.asarray(cond))
    out = jnp.where(c != 0, y, n)
    return Vec(out.astype(jnp.float32), T_NUM, nrows)


def hist(vec: Vec, breaks: int = 20) -> Tuple[np.ndarray, np.ndarray]:
    """Histogram counts — AstHist analog (device bucketize + segment-sum)."""
    r = vec.rollups()
    lo, hi = r.vmin, r.vmax
    if not np.isfinite(lo) or not np.isfinite(hi) or hi <= lo:
        return np.zeros(breaks), np.linspace(0, 1, breaks + 1)
    edges = np.linspace(lo, hi, breaks + 1)
    x = vec.data
    idx = jnp.clip(((x - lo) / (hi - lo) * breaks).astype(jnp.int32),
                   0, breaks - 1)
    valid = vec.valid_mask() & ~jnp.isnan(x)
    gid = jnp.where(valid, idx, breaks)
    counts = np.asarray(jax.ops.segment_sum(
        jnp.ones_like(x), gid, num_segments=breaks + 1))[:breaks]
    return counts, edges


def interaction(frame: Frame, factors: Sequence[str], pairwise: bool = True,
                max_factors: int = 100, min_occurrence: int = 1) -> Frame:
    """Categorical interaction columns — hex/Interaction analog.

    ``pairwise``: one column per factor pair; otherwise a single column
    over the full tuple.  Levels rank by frequency; beyond ``max_factors``
    (or under ``min_occurrence``) they collapse into "other".
    """
    from itertools import combinations
    factors = list(factors)
    for f in factors:
        if frame.vec(f).type != T_CAT:
            raise ValueError(f"interaction factor {f!r} must be categorical")
    if pairwise and len(factors) >= 2:
        groups = list(combinations(factors, 2))
    else:
        groups = [tuple(factors)]
    out = frame
    for grp in groups:
        labels = None
        for f in grp:
            v = frame.vec(f)
            dec = v.decoded()
            part = np.asarray(["NA" if x is None else str(x) for x in dec],
                              dtype=object)
            labels = part if labels is None else \
                np.asarray([a + "_" + b for a, b in zip(labels, part)],
                           dtype=object)
        uniq, counts = np.unique(labels, return_counts=True)
        order = np.argsort(-counts)
        keep = [u for u, c in zip(uniq[order], counts[order])
                if c >= min_occurrence][:max_factors]
        keepset = set(keep)
        col = np.asarray([x if x in keepset else "other" for x in labels],
                         dtype=object)
        out = out.with_vec("_".join(grp), Vec.from_numpy(col, T_CAT))
    return out


def impute(frame: Frame, column: str, method: str = "mean",
           combine_method: str = "interpolate") -> Frame:
    """Fill a column's NAs in place of a new frame — AstImpute analog.

    ``method``: mean | median | mode.  Numeric columns use mean/median;
    categorical use mode (most frequent level).
    """
    v = frame.vec(column)
    if method not in ("mean", "median", "mode"):
        raise ValueError(f"impute method {method!r}: mean | median | mode")
    if v.type != T_CAT and method == "mode":
        raise ValueError("impute method='mode' is for categorical columns")
    if v.type == T_CAT:
        t = table(v)
        if not t:
            return frame
        mode_lbl = max(t, key=t.get)
        code = (v.domain or []).index(mode_lbl)
        data = jnp.where(v.data < 0, code, v.data)
        newv = Vec(data, T_CAT, v.nrows, domain=v.domain)
        return _impute_lin(frame.with_vec(column, newv), frame,
                           column, method, combine_method)
    qmethod = {"interpolate": "linear", "lo": "lower",
               "hi": "higher", "low": "lower", "high": "higher",
               "average": "linear"}.get(combine_method, "linear")
    if v.type == T_TIME:
        # fill in the EXACT host ms payload and rebuild (keeps time_base)
        host = np.array(v.to_numpy(), copy=True)
        finite = np.isfinite(host)
        if not finite.any():
            return frame
        fill = float(np.nanquantile(host, 0.5, method=qmethod)) \
            if method == "median" else float(host[finite].mean())
        host[~finite] = fill
        return _impute_lin(frame.with_vec(column, Vec.from_numpy(host, T_TIME)),
                           frame, column, method, combine_method)
    if method == "median":
        x = v.to_numpy()
        fill = float(np.nanquantile(x, 0.5, method=qmethod)) \
            if np.isfinite(x).any() else 0.0
    else:
        fill = v.mean()
    data = jnp.where(jnp.isnan(v.data), jnp.float32(fill), v.data)
    return _impute_lin(frame.with_vec(column, Vec(data, v.type, v.nrows)),
                       frame, column, method, combine_method)


def _impute_lin(out: Frame, base: Frame, column: str, method: str,
                combine_method: str) -> Frame:
    return lineage.derive(out, base, {"op": "impute", "column": column,
                                      "method": method,
                                      "combine_method": combine_method})


def cut(vec: Vec, breaks: Sequence[float],
        labels: Optional[Sequence[str]] = None,
        include_lowest: bool = False, right: bool = True) -> Vec:
    """Numeric -> categorical by interval — AstCut analog."""
    edges = jnp.asarray(list(breaks), jnp.float32)
    x = vec.data
    idx = jnp.searchsorted(edges, x, side="left" if right else "right") - 1
    nb = len(breaks) - 1
    if include_lowest:
        idx = jnp.where(x == edges[0], 0, idx)
    bad = jnp.isnan(x) | (idx < 0) | (idx >= nb)
    codes = jnp.where(bad, -1, idx).astype(jnp.int32)
    if labels is None:
        b = list(breaks)
        if right:
            lb0 = "[" if include_lowest else "("
            labels = [f"{lb0 if i == 0 else '('}{b[i]},{b[i+1]}]"
                      for i in range(nb)]
        else:
            labels = [f"[{b[i]},{b[i+1]})" for i in range(nb)]
    return Vec(codes, T_CAT, vec.nrows, domain=list(labels))


def scale(frame: Frame, center: bool = True,
          scale_: bool = True) -> Frame:
    """Standardize numeric columns — AstScale analog (device pass)."""
    vecs = []
    for v in frame.vecs:
        if v.type == T_NUM:
            r = v.rollups()
            mu = r.mean if center else 0.0
            sd = r.sigma if (scale_ and r.sigma and r.sigma > 0) else 1.0
            vecs.append(Vec((v.data - mu) / sd, T_NUM, v.nrows))
        else:
            vecs.append(v)
    return lineage.derive(Frame(frame.names, vecs), frame,
                          {"op": "scale", "center": bool(center),
                           "scale": bool(scale_)})


# ---------------------------------------------------------------- group-by
_AGGS = ("count", "sum", "mean", "min", "max", "var", "sd")


def _device_keys(frame: Frame, by: List[str],
                 cat_remap: Optional[Dict[str, Dict[str, int]]] = None
                 ) -> List[jax.Array]:
    """Key columns as float32 device arrays; NA and padding -> +inf."""
    keys = []
    for name in by:
        v = frame.vec(name)
        if v.type == T_CAT:
            if cat_remap is not None and name in cat_remap:
                remap = cat_remap[name]
                tbl = jnp.asarray(np.array(
                    [remap[lbl] for lbl in (v.domain or [])] or [0],
                    np.float32))
                k = tbl[jnp.clip(v.data, 0, None)]
                k = jnp.where(v.data < 0, jnp.inf, k)
            else:
                k = dev.sort_key(v)
        elif v.data is None:
            raise TypeError(f"column {name!r} is host-only (string key)")
        else:
            k = jnp.where(jnp.isnan(v.data), jnp.inf, v.data)
        pad = jnp.arange(frame.padded_rows) >= frame.nrows
        keys.append(jnp.where(pad, jnp.inf, k))
    return keys


def group_by(frame: Frame, by: Union[str, Sequence[str]],
             aggs: Dict[str, Sequence[str]]) -> Frame:
    """Grouped aggregation — AstGroup analog, device segment-sums.

    ``aggs``: {column: [agg, ...]} with aggs from count/sum/mean/min/max/
    var/sd.  Group ids come from a device lexicographic dense-rank; every
    aggregate is a ``segment_sum``/``segment_min``/``segment_max`` with the
    rank as segment id (O(N) HBM, no [N, G] one-hot).  Rows with NA in any
    key column are dropped, mirroring AstGroup's default NA handling.
    """
    by = [by] if isinstance(by, str) else list(by)
    for col, fns in aggs.items():
        for fn in fns:
            if fn not in _AGGS:
                raise ValueError(f"unknown agg {fn!r} (have {_AGGS})")
    keys = _device_keys(frame, by)
    valid = jnp.ones(frame.padded_rows, bool)
    for k in keys:
        valid = valid & jnp.isfinite(k)
    # collapse ALL columns of any-NA rows to +inf before ranking: a
    # partial-NA tuple must not consume a dense rank below G (it would
    # leave a phantom empty group behind when its rows are rerouted)
    keys = [jnp.where(valid, k, jnp.inf) for k in keys]
    rank = dev.dense_rank(keys)
    G = int(jnp.max(jnp.where(valid, rank, -1))) + 1
    if G <= 0:
        return Frame.from_numpy(
            {**{n: np.array([], object) for n in by},
             **{f"{fn}_{c}": np.array([]) for c, fns in aggs.items()
                for fn in fns}})
    # any-NA-key rows -> overflow segment (AstGroup drops them); minimum()
    # alone would keep partially-NA tuples that rank below G
    gid = jnp.where(valid, jnp.minimum(rank, G), G)
    nseg = G + 1

    # one representative row per group, for key decode
    rep = jax.ops.segment_max(jnp.arange(frame.padded_rows, dtype=jnp.int32),
                              gid, num_segments=nseg)[:G]
    out_cols: Dict[str, np.ndarray] = {}
    types: Dict[str, str] = {}
    domains: Dict[str, Sequence[str]] = {}
    for name in by:
        v = frame.vec(name)
        if v.type == T_CAT:
            codes = np.asarray(fetch(v.data[rep]))
            out_cols[name] = codes.astype(np.int32)
            types[name] = T_CAT
            domains[name] = v.domain or []
        else:
            out_cols[name] = np.asarray(fetch(v.data[rep]), np.float64)

    counts = None
    for col, fns in aggs.items():
        x = frame.vec(col).numeric_data()
        ok = (~jnp.isnan(x)).astype(jnp.float32)
        xz = jnp.nan_to_num(x)
        s1 = jax.ops.segment_sum(xz * ok, gid, num_segments=nseg)
        n = jax.ops.segment_sum(ok, gid, num_segments=nseg)
        mean = s1 / jnp.maximum(n, 1e-30)
        n_h = np.asarray(n, np.float64)[:G]
        s1_h = np.asarray(s1, np.float64)[:G]
        counts = n_h if counts is None else counts
        if any(f in ("min", "max") for f in fns):
            big = jnp.float32(3.4e38)
            mn = np.asarray(jax.ops.segment_min(
                jnp.where(jnp.isnan(x), big, x), gid,
                num_segments=nseg))[:G]
            mx = np.asarray(jax.ops.segment_max(
                jnp.where(jnp.isnan(x), -big, x), gid,
                num_segments=nseg))[:G]
        if any(f in ("var", "sd") for f in fns):
            # residual pass: numerically stable vs (E[x^2] - E[x]^2) in f32
            resid = (xz - mean[gid]) * ok
            ss = np.asarray(jax.ops.segment_sum(
                resid * resid, gid, num_segments=nseg), np.float64)[:G]
        for fn in fns:
            key = f"{fn}_{col}"
            if fn == "count":
                out_cols[key] = n_h
            elif fn == "sum":
                out_cols[key] = s1_h
            elif fn == "mean":
                out_cols[key] = s1_h / np.maximum(n_h, 1e-300)
            elif fn == "min":
                out_cols[key] = mn
            elif fn == "max":
                out_cols[key] = mx
            else:
                var = ss / np.maximum(n_h - 1, 1e-300)
                out_cols[key] = np.sqrt(var) if fn == "sd" else var
    return Frame.from_numpy(out_cols, types=types, domains=domains)


# -------------------------------------------------------------------- merge
def _na_vec(template: Vec, n: int) -> Vec:
    """All-NA vec of the template's type (outer-join fill)."""
    if template.type == T_CAT:
        return Vec.from_numpy(np.full(n, -1, np.int32), T_CAT,
                              domain=template.domain)
    if template.data is None:
        return Vec(None, template.type, n,
                   host_data=np.array([None] * n, dtype=object))
    if template.type == T_TIME:
        return Vec.from_numpy(np.full(n, np.nan), T_TIME)
    return Vec.from_numpy(np.full(n, np.nan), template.type)


def _unmatched_right(left: Frame, right: Frame, by: List[str]) -> Frame:
    """Right rows whose key matches NO left row (device rank membership)."""
    cat_remap: Dict[str, Dict[str, int]] = {}
    for name in by:
        lv, rv = left.vec(name), right.vec(name)
        if lv.type == T_CAT:
            shared: Dict[str, int] = {}
            for lbl in (lv.domain or []) + (rv.domain or []):
                if lbl not in shared:
                    shared[lbl] = len(shared)
            cat_remap[name] = shared
    lkeys = _device_keys(left, by, cat_remap)
    rkeys = _device_keys(right, by, cat_remap)
    pl, pr = left.padded_rows, right.padded_rows
    rank = dev.dense_rank([jnp.concatenate([l, r])
                           for l, r in zip(lkeys, rkeys)])
    lrank, rrank = rank[:pl], rank[pl:]
    lvalid = jnp.ones(pl, bool)
    for k in lkeys:
        lvalid &= jnp.isfinite(k)
    rvalid = jnp.ones(pr, bool)
    for k in rkeys:
        rvalid &= jnp.isfinite(k)
    nseg = pl + pr + 2
    big = jnp.int32(nseg - 1)
    lcount = jax.ops.segment_sum(
        jnp.where(lvalid, 1, 0), jnp.where(lvalid, lrank, big),
        num_segments=nseg)
    unmatched = rvalid & (lcount[rrank] == 0)
    return filter_rows(right, Vec(unmatched.astype(jnp.float32), T_NUM,
                                  right.nrows))


def merge(left: Frame, right: Frame, by: Union[str, Sequence[str]],
          how: str = "inner") -> Frame:
    """Join — AstMerge / BinaryMerge analog, device sort-merge.

    Single- or multi-key equi-join.  Keys from both frames are dense-ranked
    together on device; match ranges come from per-rank segment tables and
    duplicate expansion from a prefix-sum ownership scan (device.py).  Output
    keeps left-row order with duplicate matches adjacent.  NA keys never
    match (BinaryMerge semantics).
    """
    by = [by] if isinstance(by, str) else list(by)
    if how == "right":
        # all.y: a left join from the other side, columns re-laid out to
        # the conventional (left cols, right-only cols) order
        out = merge(right, left, by, how="left")
        lcols = [n for n in left.names if n not in by]
        rcols = [n for n in right.names if n not in by]
        return out[by + [c for c in lcols if c in out.names]
                   + [c for c in rcols if c in out.names]]
    if how == "outer":
        li = merge(left, right, by, how="left")
        extra = _unmatched_right(left, right, by)
        if extra.nrows == 0:
            return li
        # align to the left-join layout, NA-filling left-only columns with
        # TYPE-correct NA vecs (cat -> -1 codes with the left domain)
        cols = li.names
        aligned = []
        for c in cols:
            if c in extra.names:
                aligned.append(extra.vec(c))
            else:
                aligned.append(_na_vec(left.vec(c), extra.nrows))
        return rbind(li, Frame(cols, aligned))
    if how not in ("inner", "left"):
        raise ValueError("merge supports how='inner'|'left'|'right'|'outer'")
    # unify categorical key domains host-side (small); codes remap on device
    cat_remap: Dict[str, Dict[str, int]] = {}
    for name in by:
        lv, rv = left.vec(name), right.vec(name)
        if (lv.data is None) or (rv.data is None):
            raise TypeError(f"merge key {name!r} is a string column; "
                            "convert to categorical first")
        if (lv.type == T_CAT) != (rv.type == T_CAT):
            raise TypeError(f"merge key {name!r} has mismatched types")
        if lv.type == T_CAT:
            shared: Dict[str, int] = {}
            for lbl in (lv.domain or []) + (rv.domain or []):
                if lbl not in shared:
                    shared[lbl] = len(shared)
            cat_remap[name] = shared
    lkeys = _device_keys(left, by, cat_remap)
    rkeys = _device_keys(right, by, cat_remap)
    pl, pr = left.padded_rows, right.padded_rows
    rank = dev.dense_rank([jnp.concatenate([l, r])
                           for l, r in zip(lkeys, rkeys)])
    lrank, rrank = rank[:pl], rank[pl:]
    lvalid = jnp.ones(pl, bool)
    for k in lkeys:
        lvalid &= jnp.isfinite(k)
    rvalid = jnp.ones(pr, bool)
    for k in rkeys:
        rvalid &= jnp.isfinite(k)
    nseg = pl + pr + 2
    big = jnp.int32(nseg - 1)
    lrank = jnp.where(lvalid, lrank, big)
    rrank = jnp.where(rvalid, rrank, big)

    rorder = jnp.argsort(rrank, stable=True)
    rsorted = rrank[rorder]
    # per-rank [start, count) into rsorted — replaces per-row binary search
    rstart = jax.ops.segment_min(jnp.arange(pr, dtype=jnp.int32), rsorted,
                                 num_segments=nseg)
    rcount = jax.ops.segment_sum(jnp.ones(pr, jnp.int32), rsorted,
                                 num_segments=nseg)
    lo = rstart[lrank]
    counts = jnp.where(lvalid, rcount[lrank], 0)
    if how == "left":
        out_counts = jnp.where(jnp.arange(pl) < left.nrows,
                               jnp.maximum(counts, 1), 0)
    else:
        out_counts = counts
    starts = jnp.cumsum(out_counts) - out_counts
    m = int(starts[-1] + out_counts[-1]) if pl else 0
    cl = cluster()
    p_out = cl.pad_rows(m)

    li = dev.expand_starts(starts, out_counts, p_out)
    li = jnp.clip(li, 0, max(pl - 1, 0))
    off = jnp.arange(p_out) - starts[li]
    matched = counts[li] > 0
    rpos = jnp.clip(lo[li] + jnp.where(matched, off, 0), 0, max(pr - 1, 0))
    ridx = jnp.where(matched, rorder[rpos], -1)

    out = dev.gather_rows(left, li, m)
    rcols = [n for n in right.names if n not in by]
    if rcols:
        rsub = dev.gather_rows(right[rcols], jnp.where(ridx >= 0, ridx, 0),
                               m, na_mask=ridx < 0)
        out = cbind(out, rsub)
    return out


def var(frame: Frame, cols: Optional[Sequence[str]] = None,
        use: str = "complete.obs") -> Dict[str, np.ndarray]:
    """Covariance matrix — h2o.var / CovarianceTask analog.

    ``use``: "complete.obs" drops rows with any NA across the selected
    columns (the reference's default for frames); "everything"
    propagates NaN like R.  Device path: masked mean-centering, then
    one X^T X matmul (MXU) over the row-sharded matrix.
    """
    cols = list(cols) if cols is not None else \
        [n for n in frame.names if frame.vec(n).is_numeric]
    M = frame.matrix(cols)                     # [padded, F]
    # categorical codes use -1 as the NA sentinel; align with numeric NaN
    is_cat = np.array([frame.vec(c).type == T_CAT for c in cols])
    if is_cat.any():
        M = jnp.where(jnp.asarray(is_cat)[None, :] & (M == -1), jnp.nan, M)
    valid = frame.valid_mask()
    finite = jnp.isfinite(M)
    if use == "complete.obs":
        row_ok = valid & finite.all(axis=1)
    elif use == "everything":
        row_ok = valid
    else:
        raise ValueError(f"unknown use={use!r}")
    n = float(row_ok.sum())
    if n < 2:                                  # R/h2o return NA here
        return {"columns": cols,
                "matrix": np.full((len(cols), len(cols)), np.nan)}
    Mz = jnp.where(row_ok[:, None], jnp.where(finite, M, jnp.nan), 0.0)
    # complete.obs rows carry no NaN; "everything" lets NaN propagate
    # per column pair, matching R's semantics
    mean = Mz.sum(axis=0) / n
    D = (Mz - mean) * row_ok.astype(M.dtype)[:, None]
    C = jnp.einsum("rf,rg->fg", D, D,
                   precision=jax.lax.Precision.HIGHEST) / (n - 1.0)
    return {"columns": cols, "matrix": np.asarray(C, dtype=np.float64)}


def cor(frame: Frame, cols: Optional[Sequence[str]] = None,
        use: str = "complete.obs") -> Dict[str, np.ndarray]:
    """Pearson correlation matrix — h2o.cor analog (from ``var``)."""
    v = var(frame, cols, use=use)
    C = v["matrix"]
    sd = np.sqrt(np.diag(C))
    with np.errstate(invalid="ignore", divide="ignore"):
        R = np.clip(C / np.outer(sd, sd), -1.0, 1.0)
    return {"columns": v["columns"], "matrix": R}
