"""Munging primitives over sharded Frames (the water/rapids Ast* analogs)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM, T_STR, T_TIME


def _sort_key(vec: Vec) -> jax.Array:
    """Ascending sort key with NaN/NA last."""
    if vec.type == T_CAT:
        codes = vec.data.astype(jnp.float32)
        return jnp.where(codes < 0, jnp.inf, codes)
    return jnp.where(jnp.isnan(vec.data), jnp.inf, vec.data)


def _take_rows(frame: Frame, order: np.ndarray) -> Frame:
    """Reorder/select rows by host index array (handles str columns too)."""
    vecs = []
    for v in frame.vecs:
        if v.data is None:                       # str/uuid: host payload
            vecs.append(Vec.from_numpy(v.host_data[order], v.type))
            continue
        host = v.to_numpy()[order]
        if v.type == T_TIME:
            vecs.append(Vec.from_numpy(v.host_data[order], T_TIME))
        elif v.type == T_CAT:
            vecs.append(Vec.from_numpy(host.astype(np.int32), T_CAT,
                                       domain=v.domain))
        else:
            vecs.append(Vec.from_numpy(host, v.type))
    return Frame(frame.names, vecs)


def sort(frame: Frame, by: Union[str, Sequence[str]],
         ascending: Union[bool, Sequence[bool]] = True) -> Frame:
    """Multi-key sort — AstSort / RadixOrder analog.

    Keys are argsorted on device (TPU sort network); multi-key order comes
    from successive stable argsorts, least-significant key first.
    """
    by = [by] if isinstance(by, str) else list(by)
    asc = [ascending] * len(by) if isinstance(ascending, bool) \
        else list(ascending)
    if len(asc) != len(by):
        raise ValueError("ascending must match by")
    order = jnp.arange(frame.padded_rows)
    for col, a in reversed(list(zip(by, asc))):
        key = _sort_key(frame.vec(col))
        key = key if a else jnp.where(jnp.isinf(key), key, -key)
        keyed = key[order]
        order = order[jnp.argsort(keyed, stable=True)]
    order_h = np.asarray(order)
    order_h = order_h[order_h < frame.nrows][: frame.nrows]
    return _take_rows(frame, order_h)


def filter_rows(frame: Frame, mask) -> Frame:
    """Boolean row filter — AstRowSlice analog."""
    mask = np.asarray(mask)[: frame.nrows].astype(bool)
    return _take_rows(frame, np.flatnonzero(mask))


def rbind(*frames: Frame) -> Frame:
    """Stack frames vertically — AstRBind analog."""
    base = frames[0]
    for fr in frames[1:]:
        if fr.names != base.names:
            raise ValueError("rbind: column names differ")
    vecs = []
    for i, name in enumerate(base.names):
        vs = [fr.vecs[i] for fr in frames]
        t = vs[0].type
        if t == T_CAT:
            # unify domains
            domain = []
            seen = {}
            for v in vs:
                for lbl in (v.domain or []):
                    if lbl not in seen:
                        seen[lbl] = len(domain)
                        domain.append(lbl)
            codes = []
            for v in vs:
                remap = np.array([seen[lbl] for lbl in (v.domain or [])],
                                 dtype=np.int32)
                c = v.to_numpy()
                codes.append(np.where(c < 0, -1,
                                      remap[np.clip(c, 0, None)]))
            vecs.append(Vec.from_numpy(np.concatenate(codes), T_CAT,
                                       domain=domain))
        elif vs[0].data is None:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.host_data for v in vs]), t))
        else:
            vecs.append(Vec.from_numpy(
                np.concatenate([v.host_data if t == T_TIME else v.to_numpy()
                                for v in vs]), t))
    return Frame(base.names, vecs)


def cbind(*frames: Frame) -> Frame:
    """Stack frames horizontally — AstCBind analog."""
    names, vecs = [], []
    for fr in frames:
        for n, v in zip(fr.names, fr.vecs):
            nn = n
            k = 0
            while nn in names:
                k += 1
                nn = f"{n}{k}"
            names.append(nn)
            vecs.append(v)
    return Frame(names, vecs)


def unique(vec: Vec) -> np.ndarray:
    """Distinct values — AstUnique analog."""
    if vec.type == T_CAT:
        codes = np.unique(vec.to_numpy())
        return np.asarray([vec.domain[c] for c in codes if c >= 0])
    x = np.asarray(jnp.sort(_sort_key(vec)))[: vec.nrows]
    x = x[np.isfinite(x)]
    return np.unique(x)


def table(vec: Vec, weights: Optional[Vec] = None) -> Dict[str, float]:
    """Value counts — AstTable analog (one-hot matmul on device for cats)."""
    if vec.type == T_CAT:
        K = len(vec.domain or [])
        codes = vec.data
        w = vec.valid_mask().astype(jnp.float32) * (codes >= 0)
        if weights is not None:
            w = w * weights.numeric_data()
        onehot = (codes[:, None] == jnp.arange(K)[None, :])
        counts = np.asarray(jnp.sum(onehot * w[:, None], axis=0))
        return {vec.domain[i]: float(counts[i]) for i in range(K)}
    vals, counts = np.unique(vec.to_numpy()[~np.isnan(vec.to_numpy())],
                             return_counts=True)
    return {str(v): int(c) for v, c in zip(vals, counts)}


def ifelse(cond, yes, no) -> Vec:
    """Vectorized conditional — AstIfElse analog."""
    c = cond.data if isinstance(cond, Vec) else jnp.asarray(cond)
    y = yes.data if isinstance(yes, Vec) else yes
    n = no.data if isinstance(no, Vec) else no
    nrows = cond.nrows if isinstance(cond, Vec) else len(np.asarray(cond))
    out = jnp.where(c != 0, y, n)
    return Vec(out.astype(jnp.float32), T_NUM, nrows)


def hist(vec: Vec, breaks: int = 20) -> Tuple[np.ndarray, np.ndarray]:
    """Histogram counts — AstHist analog (device bucketize + one-hot sum)."""
    r = vec.rollups()
    lo, hi = r.vmin, r.vmax
    if not np.isfinite(lo) or not np.isfinite(hi) or hi <= lo:
        return np.zeros(breaks), np.linspace(0, 1, breaks + 1)
    edges = np.linspace(lo, hi, breaks + 1)
    x = vec.data
    idx = jnp.clip(((x - lo) / (hi - lo) * breaks).astype(jnp.int32),
                   0, breaks - 1)
    valid = vec.valid_mask() & ~jnp.isnan(x)
    onehot = (idx[:, None] == jnp.arange(breaks)[None, :]) * valid[:, None]
    counts = np.asarray(jnp.sum(onehot, axis=0))
    return counts, edges


# ---------------------------------------------------------------- group-by
_AGGS = ("count", "sum", "mean", "min", "max", "var", "sd")


def _group_codes(frame: Frame, by: List[str]):
    """Combined group code per row + the list of group key tuples."""
    cols = []
    for name in by:
        v = frame.vec(name)
        if v.type == T_CAT:
            cols.append((v.to_numpy(), v.domain))
        else:
            x = v.to_numpy()
            vals, inv = np.unique(x[~np.isnan(x)], return_inverse=True)
            codes = np.full(len(x), -1, np.int64)
            codes[~np.isnan(x)] = inv
            cols.append((codes, [str(u) for u in vals]))
    combo = np.zeros(frame.nrows, np.int64)
    mult = 1
    valid = np.ones(frame.nrows, bool)
    for codes, dom in cols:
        c = codes[: frame.nrows]
        valid &= c >= 0
        combo = combo + np.where(c >= 0, c, 0) * mult
        mult *= max(len(dom), 1)
    uniq, inv = np.unique(combo[valid], return_inverse=True)
    group_of_row = np.full(frame.nrows, -1, np.int64)
    group_of_row[valid] = inv
    # decode group keys
    keys = []
    for u in uniq:
        key = []
        rem = u
        for codes, dom in cols:
            key.append(dom[rem % max(len(dom), 1)])
            rem //= max(len(dom), 1)
        keys.append(tuple(key))
    return group_of_row, keys


def group_by(frame: Frame, by: Union[str, Sequence[str]],
             aggs: Dict[str, Sequence[str]]) -> Frame:
    """Grouped aggregation — AstGroup analog.

    ``aggs``: {column: [agg, ...]} with aggs from count/sum/mean/min/max/
    var/sd.  Group discovery is host-side (small); the per-group
    aggregation is a one-hot segment matmul on device, psum'd by XLA.
    """
    by = [by] if isinstance(by, str) else list(by)
    for col, fns in aggs.items():
        for fn in fns:
            if fn not in _AGGS:
                raise ValueError(f"unknown agg {fn!r} (have {_AGGS})")
    group_of_row, keys = _group_codes(frame, by)
    G = len(keys)
    padded = frame.padded_rows
    gid = np.full(padded, G, np.int32)          # padding -> overflow bucket
    gid[: frame.nrows] = np.where(group_of_row >= 0, group_of_row, G)
    gid_dev = jnp.asarray(gid)

    out_cols: Dict[str, np.ndarray] = {}
    for i, name in enumerate(by):
        out_cols[name] = np.asarray([k[i] for k in keys], dtype=object)

    onehot = jax.nn.one_hot(gid_dev, G, dtype=jnp.float32)   # [N, G]
    counts = None
    for col, fns in aggs.items():
        x = frame.vec(col).numeric_data()
        ok = (~jnp.isnan(x)).astype(jnp.float32)
        xz = jnp.nan_to_num(x)
        s1 = np.asarray(xz * ok @ onehot, np.float64)
        n = np.asarray(ok @ onehot, np.float64)
        counts = n if counts is None else counts
        if any(f in ("min", "max") for f in fns):
            big = jnp.float32(3.4e38)
            xmin = jnp.where(jnp.isnan(x), big, x)
            xmax = jnp.where(jnp.isnan(x), -big, x)
            mn = np.asarray(jax.ops.segment_min(xmin, gid_dev,
                                                num_segments=G + 1))[:G]
            mx = np.asarray(jax.ops.segment_max(xmax, gid_dev,
                                                num_segments=G + 1))[:G]
        if any(f in ("var", "sd") for f in fns):
            s2 = np.asarray((xz * xz) * ok @ onehot, np.float64)
        for fn in fns:
            key = f"{fn}_{col}"
            if fn == "count":
                out_cols[key] = n
            elif fn == "sum":
                out_cols[key] = s1
            elif fn == "mean":
                out_cols[key] = s1 / np.maximum(n, 1e-300)
            elif fn == "min":
                out_cols[key] = mn
            elif fn == "max":
                out_cols[key] = mx
            else:
                mean = s1 / np.maximum(n, 1e-300)
                var = (s2 / np.maximum(n, 1e-300) - mean**2) \
                    * n / np.maximum(n - 1, 1e-300)
                var = np.maximum(var, 0.0)
                out_cols[key] = np.sqrt(var) if fn == "sd" else var
    return Frame.from_numpy(out_cols)


# -------------------------------------------------------------------- merge
def merge(left: Frame, right: Frame, by: Union[str, Sequence[str]],
          how: str = "inner") -> Frame:
    """Join — AstMerge / BinaryMerge analog.

    Single- or multi-key equi-join.  The match step runs on device
    (binary search against the sorted build side); rows are expanded
    host-side when the build side has duplicate keys.
    """
    by = [by] if isinstance(by, str) else list(by)
    if how not in ("inner", "left"):
        raise ValueError("merge supports how='inner'|'left'")
    lkeys = _merge_key(left, by)
    rkeys = _merge_key(right, by)
    order = np.argsort(rkeys, kind="stable")
    rsorted = rkeys[order]
    lo = np.searchsorted(rsorted, lkeys, side="left")
    hi = np.searchsorted(rsorted, lkeys, side="right")
    counts = hi - lo
    matched = counts > 0

    lidx, ridx = [], []
    for i in np.flatnonzero(matched):
        span = order[lo[i]: hi[i]]
        lidx.extend([i] * len(span))
        ridx.extend(span)
    lidx = np.asarray(lidx, np.int64)
    ridx = np.asarray(ridx, np.int64)
    if how == "left":
        miss = np.flatnonzero(~matched)
        lidx = np.concatenate([lidx, miss])
        ridx = np.concatenate([ridx, np.full(len(miss), -1)])
        srt = np.argsort(lidx, kind="stable")
        lidx, ridx = lidx[srt], ridx[srt]

    out = _take_rows(left, lidx)
    rcols = [n for n in right.names if n not in by]
    rsub = _take_rows(right[rcols], np.where(ridx >= 0, ridx, 0)) \
        if rcols else None
    if rsub is not None:
        vecs = []
        for n, v in zip(rsub.names, rsub.vecs):
            if how == "left" and (ridx < 0).any() and v.data is not None \
                    and v.type != T_CAT:
                host = np.array(v.to_numpy(), copy=True)
                host[ridx < 0] = np.nan
                v = Vec.from_numpy(host, v.type)
            elif how == "left" and (ridx < 0).any() and v.type == T_CAT:
                host = np.array(v.to_numpy(), copy=True)
                host[ridx < 0] = -1
                v = Vec.from_numpy(host.astype(np.int32), T_CAT,
                                   domain=v.domain)
            vecs.append(v)
        out = cbind(out, Frame(rsub.names, vecs))
    return out


def _merge_key(frame: Frame, by: List[str]) -> np.ndarray:
    """Rows -> hashable composite key array (string form for stability)."""
    parts = []
    for name in by:
        v = frame.vec(name)
        if v.type == T_CAT:
            dom = np.asarray(list(v.domain or []) + ["<NA>"], dtype=object)
            c = v.to_numpy()
            parts.append(dom[np.where(c < 0, len(dom) - 1, c)])
        elif v.data is None:
            parts.append(v.host_data.astype(str))
        else:
            parts.append(v.to_numpy().astype(str))
    if len(parts) == 1:
        return parts[0].astype(str)
    return np.array(["\x1f".join(t) for t in zip(*[p.astype(str)
                                                   for p in parts])])
