"""String munging ops — the water/rapids/ast/prims/string Ast* analogs.

toupper/tolower/trim/substring/replace (sub/gsub)/split/nchar/concat work
on string AND categorical columns: categorical columns transform their
DOMAIN only (the reference's trick — O(cardinality), codes untouched),
string columns map the host payload.
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_STR


def _map_vec(vec: Vec, fn) -> Vec:
    """Apply a str->str function to a cat (domain-only) or str column."""
    if vec.type == T_CAT:
        new_domain = [fn(lbl) for lbl in (vec.domain or [])]
        # transformed labels may collide (e.g. tolower): remap codes
        uniq: List[str] = []
        remap = {}
        for i, lbl in enumerate(new_domain):
            if lbl not in remap:
                remap[lbl] = len(uniq)
                uniq.append(lbl)
        table = np.asarray([remap[lbl] for lbl in new_domain], np.int32)
        codes = vec.to_numpy()
        new_codes = np.where(codes >= 0, table[np.clip(codes, 0, None)], -1)
        return Vec.from_numpy(new_codes.astype(np.int32), T_CAT,
                              domain=uniq)
    if vec.type == T_STR:
        out = np.array([None if v is None else fn(str(v))
                        for v in vec.host_data[: vec.nrows]], dtype=object)
        return Vec(None, T_STR, vec.nrows, host_data=out)
    raise TypeError(f"string op on {vec.type} column")


def toupper(vec: Vec) -> Vec:
    return _map_vec(vec, str.upper)


def tolower(vec: Vec) -> Vec:
    return _map_vec(vec, str.lower)


def trim(vec: Vec) -> Vec:
    return _map_vec(vec, str.strip)


def lstrip(vec: Vec, chars: Optional[str] = None) -> Vec:
    return _map_vec(vec, lambda s: s.lstrip(chars))


def rstrip(vec: Vec, chars: Optional[str] = None) -> Vec:
    return _map_vec(vec, lambda s: s.rstrip(chars))


def substring(vec: Vec, start: int, end: Optional[int] = None) -> Vec:
    return _map_vec(vec, lambda s: s[start:end])


def sub(vec: Vec, pattern: str, replacement: str) -> Vec:
    """Replace the FIRST regex match (AstSub)."""
    pat = re.compile(pattern)
    return _map_vec(vec, lambda s: pat.sub(replacement, s, count=1))


def gsub(vec: Vec, pattern: str, replacement: str) -> Vec:
    """Replace ALL regex matches (AstGSub)."""
    pat = re.compile(pattern)
    return _map_vec(vec, lambda s: pat.sub(replacement, s))


def nchar(vec: Vec) -> Vec:
    """Per-row string length as a numeric column (AstStrLength)."""
    if vec.type == T_CAT:
        lens = np.asarray([len(lbl) for lbl in (vec.domain or [])],
                          np.float64)
        codes = vec.to_numpy()
        out = np.where(codes >= 0, lens[np.clip(codes, 0, None)], np.nan)
        return Vec.from_numpy(out)
    if vec.type == T_STR:
        out = np.asarray([np.nan if v is None else float(len(str(v)))
                          for v in vec.host_data[: vec.nrows]])
        return Vec.from_numpy(out)
    raise TypeError(f"nchar on {vec.type} column")


def strsplit(vec: Vec, pattern: str) -> Frame:
    """Split each value into columns C1..Ck (AstStrSplit)."""
    pat = re.compile(pattern)
    if vec.type == T_CAT:
        vals = vec.decoded()
    else:
        vals = vec.host_data[: vec.nrows]
    parts = [pat.split(str(v)) if v is not None else [] for v in vals]
    k = max((len(p) for p in parts), default=0)
    cols = {}
    for j in range(k):
        cols[f"C{j+1}"] = np.array(
            [p[j] if j < len(p) else None for p in parts], dtype=object)
    out_vecs = []
    names = []
    for name, arr in cols.items():
        names.append(name)
        out_vecs.append(Vec(None, T_STR, len(arr), host_data=arr))
    return Frame(names, out_vecs)


def countmatches(vec: Vec, pattern: str) -> Vec:
    """Occurrences of the regex per row (AstCountMatches)."""
    pat = re.compile(pattern)
    if vec.type == T_CAT:
        cnt = np.asarray([float(len(pat.findall(lbl)))
                          for lbl in (vec.domain or [])])
        codes = vec.to_numpy()
        out = np.where(codes >= 0, cnt[np.clip(codes, 0, None)], np.nan)
        return Vec.from_numpy(out)
    out = np.asarray([np.nan if v is None else float(len(pat.findall(str(v))))
                      for v in vec.host_data[: vec.nrows]])
    return Vec.from_numpy(out)
