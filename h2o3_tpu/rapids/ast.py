"""Rapids AST: parse + evaluate the Lisp-style expression language.

Reference: ``water/rapids/Rapids.java:29`` (parser) and the Ast* op classes
under ``water/rapids/ast/prims`` — clients (h2o-py/h2o/expr.py:27) build
``(op arg ...)`` strings lazily and POST them to /99/Rapids; the server
parses and evaluates against DKV frames.

The evaluator here maps ops onto the device-side munging engine (ops.py)
and fused jnp arithmetic; numbers/strings/lists follow the reference's
literal syntax (``[1 2 3]`` number lists, ``["a" "b"]`` string lists,
``'col'`` quoted strings).  Temporary results are assigned DKV keys via
(tmp= ...) / (assign ...) exactly like the reference session protocol.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM
from ..runtime import dkv
from . import ops


# ------------------------------------------------------------------ parser
class _Tok:
    def __init__(self, text: str):
        self.text = text
        self.i = 0

    def peek(self) -> str:
        while self.i < len(self.text) and self.text[self.i].isspace():
            self.i += 1
        return self.text[self.i] if self.i < len(self.text) else ""

    def next_token(self) -> str:
        c = self.peek()
        if c in "()[]{}":
            self.i += 1
            return c
        if c in "'\"":
            q = c
            j = self.i + 1
            out = []
            while j < len(self.text) and self.text[j] != q:
                if self.text[j] == "\\" and j + 1 < len(self.text):
                    j += 1                 # backslash escape (h2o-py _quote)
                out.append(self.text[j])
                j += 1
            self.i = j + 1
            return ("str", "".join(out))
        j = self.i
        while j < len(self.text) and not self.text[j].isspace() \
                and self.text[j] not in "()[]{}":
            j += 1
        tok = self.text[self.i: j]
        self.i = j
        return tok


def parse(text: str):
    """Rapids text -> nested python lists (strings/floats/markers)."""
    tok = _Tok(text)

    def read():
        t = tok.next_token()
        if t == "(":
            out = []
            while tok.peek() != ")":
                if tok.peek() == "":
                    raise ValueError("unbalanced (")
                out.append(read())
            tok.next_token()
            return out
        if t == "[":
            out = ["__list__"]
            while tok.peek() != "]":
                if tok.peek() == "":
                    raise ValueError("unbalanced [")
                out.append(read())
            tok.next_token()
            return out
        if t == "{":
            # AstFunction syntax: { id1 id2 . body }  (AstFunction.java:63)
            ids = []
            while True:
                nxt = read()
                if nxt == ".":
                    break
                if not isinstance(nxt, str):
                    raise ValueError(f"lambda formal must be an id: {nxt!r}")
                ids.append(nxt)
            body = read()
            if tok.next_token() != "}":
                raise ValueError("unbalanced {")
            return ["__lambda__", ids, body]
        if t in (")", "]", "}"):
            raise ValueError(f"unexpected {t}")
        if isinstance(t, tuple):
            return ("str", t[1])
        try:
            return float(t)
        except ValueError:
            return t

    out = read()
    if tok.peek():
        raise ValueError(f"trailing input: {tok.text[tok.i:]}")
    return out


# --------------------------------------------------------------- evaluator
def _vecframe(v, name="x") -> Frame:
    return Frame([name], [v]) if isinstance(v, Vec) else v


def _numeric(fr: Frame) -> jnp.ndarray:
    """[padded, C] numeric view of all columns (cats as codes)."""
    return jnp.stack([v.numeric_data() for v in fr.vecs], axis=1)


def _binop(op, l, r):
    """Elementwise arithmetic over frames/vecs/scalars — fused on device."""
    if not isinstance(l, (Frame, Vec)) and not isinstance(r, (Frame, Vec)):
        import operator as _o
        fn = {"+": _o.add, "-": _o.sub, "*": _o.mul, "/": _o.truediv,
              "^": _o.pow, "%": _o.mod, "intDiv": _o.floordiv,
              "<": _o.lt, "<=": _o.le, ">": _o.gt, ">=": _o.ge,
              "==": _o.eq, "!=": _o.ne,
              "&": lambda a, b: bool(a) and bool(b),
              "|": lambda a, b: bool(a) or bool(b)}[op]
        return float(fn(float(l), float(r)))

    def arr(x):
        if isinstance(x, Frame):
            return _numeric(x)
        if isinstance(x, Vec):
            return x.numeric_data()[:, None]
        return x
    la, ra = arr(l), arr(r)
    fn = {
        "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply,
        "/": jnp.divide, "^": jnp.power, "%": jnp.mod,
        "intDiv": jnp.floor_divide,
        "<": jnp.less, "<=": jnp.less_equal, ">": jnp.greater,
        ">=": jnp.greater_equal, "==": jnp.equal, "!=": jnp.not_equal,
        "&": jnp.logical_and, "|": jnp.logical_or,
    }[op]
    out = fn(la, ra)
    out = out.astype(jnp.float32)
    ref = l if isinstance(l, (Frame, Vec)) else r
    nrows = ref.nrows
    names = ref.names if isinstance(ref, Frame) else ["x"]
    if out.ndim == 1:
        out = out[:, None]
    return Frame([f"{n}" for n in names[: out.shape[1]]],
                 [Vec(out[:, j], T_NUM, nrows) for j in range(out.shape[1])])


_UNARY = {
    "abs": jnp.abs, "log": jnp.log, "log10": jnp.log10, "log2": jnp.log2,
    "log1p": jnp.log1p, "exp": jnp.exp, "expm1": jnp.expm1,
    "sqrt": jnp.sqrt, "floor": jnp.floor, "ceiling": jnp.ceil,
    "round": jnp.round, "trunc": jnp.trunc, "sign": jnp.sign,
    "cos": jnp.cos, "sin": jnp.sin, "tan": jnp.tan, "acos": jnp.arccos,
    "asin": jnp.arcsin, "atan": jnp.arctan, "cosh": jnp.cosh,
    "sinh": jnp.sinh, "tanh": jnp.tanh, "not": jnp.logical_not,
    "is.na": jnp.isnan,
}

_STRING = {
    "toupper": "toupper", "tolower": "tolower", "trim": "trim",
    "lstrip": "lstrip", "rstrip": "rstrip", "substring": "substring",
    "replacefirst": "sub", "replaceall": "gsub", "nchar": "nchar",
    "countmatches": "countmatches",
}

_AGG = {
    "sum": jnp.nansum, "mean": jnp.nanmean, "max": jnp.nanmax,
    "min": jnp.nanmin, "sd": lambda x: jnp.nanstd(x, ddof=1),
    "var": lambda x: jnp.nanvar(x, ddof=1), "median": jnp.nanmedian,
}
_AGG["cor"] = None  # matrix-only: handled before the scalar reduction


class Lambda:
    """A Rapids function value — ``{ ids . body }`` (AstFunction.java:16)."""

    def __init__(self, ids: List[str], body):
        self.ids = list(ids)
        self.body = body

    def __repr__(self):
        return f"<lambda ({' '.join(self.ids)})>"


class Session:
    """One Rapids session: evaluates ASTs against the DKV."""

    def __init__(self):
        self._env: List[dict] = []       # lexical frames, innermost last

    def eval(self, text: str):
        return self._ev(parse(text))

    # -- helpers
    def _frame(self, key: str) -> Frame:
        fr = dkv.get(key)
        if fr is None:
            raise KeyError(f"no frame {key!r}")
        return fr

    def call(self, lam: Lambda, vals: List) -> Any:
        """Apply a lambda: bind formals, evaluate the body."""
        self._env.append(dict(zip(lam.ids, vals)))
        try:
            return self._ev(lam.body)
        finally:
            self._env.pop()

    def _ev(self, node) -> Any:
        if isinstance(node, float):
            return node
        if isinstance(node, tuple) and node[0] == "str":
            return node[1]
        if isinstance(node, str):
            # boolean tokens (Rapids.java parses these as 1/0)
            if node in ("TRUE", "True", "true"):
                return 1.0
            if node in ("FALSE", "False", "false"):
                return 0.0
            if node in ("NA", "NaN", "nan"):
                return float("nan")
            # lexical binding (lambda formal), then DKV key
            for frame in reversed(self._env):
                if node in frame:
                    return frame[node]
            return self._frame(node)
        if not isinstance(node, list):
            raise ValueError(f"bad node {node!r}")
        if node and node[0] == "__list__":
            return [self._ev(x) for x in node[1:]]
        if node and node[0] == "__lambda__":
            return Lambda(node[1], node[2])
        op, *args = node
        if isinstance(op, list):
            # immediate application: ({x . body} arg ...)
            fn = self._ev(op)
            if not isinstance(fn, Lambda):
                raise ValueError(f"cannot apply non-function {fn!r}")
            return self.call(fn, [self._ev(a) for a in args])
        return self._apply(op, args)

    def _apply(self, op: str, args: List) -> Any:
        ev = self._ev
        if op in ("tmp=", "assign"):
            key = args[0] if isinstance(args[0], str) else ev(args[0])
            val = ev(args[1])
            if isinstance(val, Vec):
                val = _vecframe(val)
            if isinstance(val, Frame):
                val = Frame(val.names, val.vecs, key=key)
            else:
                dkv.put(key, val)
            return val
        if op == "rm":
            dkv.remove(args[0] if isinstance(args[0], str) else ev(args[0]))
            return None
        if op in ("+", "-", "*", "/", "^", "%", "intDiv", "<", "<=", ">",
                  ">=", "==", "!=", "&", "|"):
            return _binop(op, ev(args[0]), ev(args[1]))
        if op in _UNARY:
            fr = _vecframe(ev(args[0]))
            X = _numeric(fr)
            out = _UNARY[op](X).astype(jnp.float32)
            return Frame(fr.names, [Vec(out[:, j], T_NUM, fr.nrows)
                                    for j in range(out.shape[1])])
        if op in _AGG:
            if op in ("var", "cor"):
                # frame form -> covariance/correlation MATRIX
                # (AstVariance); single column falls through to the
                # scalar reduction.  Optional args: y frame (cross
                # block via cbind) and the use mode string.
                probe = ev(args[0])
                rest = [ev(a) for a in args[1:]]
                y = next((r for r in rest if isinstance(r, Frame)), None)
                use = next((r for r in rest if isinstance(r, str)),
                           "complete.obs")
                if use == "all.obs":
                    use = "complete.obs"
                if isinstance(probe, Frame) and (probe.ncols > 1
                                                 or y is not None):
                    if y is not None and y is not probe:
                        joint = ops.cbind(
                            probe, y.rename(
                                {n: f"__y_{n}" for n in y.names}))
                        res = (ops.var if op == "var" else ops.cor)(
                            joint, use=use)
                        M = res["matrix"][:probe.ncols, probe.ncols:]
                        return Frame(y.names,
                                     [Vec.from_numpy(M[:, j], T_NUM)
                                      for j in range(M.shape[1])])
                    res = (ops.var if op == "var" else ops.cor)(
                        probe, use=use)
                    M = res["matrix"]
                    return Frame(res["columns"],
                                 [Vec.from_numpy(M[:, j], T_NUM)
                                  for j in range(M.shape[1])])
                if op == "cor":
                    raise ValueError("cor needs a multi-column frame")
                args = [probe] + list(args[1:])
            fr = _vecframe(ev(args[0]) if not isinstance(args[0], (Frame, Vec))
                           else args[0])
            X = _numeric(fr)[: None]
            mask = jnp.arange(X.shape[0]) < fr.nrows
            Xv = jnp.where(mask[:, None], X, jnp.nan)
            return float(_AGG[op](Xv))
        if op == "cols" or op == "cols_py":
            fr = ev(args[0])
            sel = ev(args[1])
            return fr[self._col_names(fr, sel)]
        if op == "rows":
            fr = ev(args[0])
            sel = ev(args[1])
            if isinstance(sel, Frame):           # boolean mask frame
                return ops.filter_rows(fr, sel.vecs[0])
            idx = np.asarray(sel, dtype=np.int64)
            return fr.rows(idx)
        if op == "sort":
            fr = ev(args[0])
            cols = self._col_names(fr, ev(args[1]))
            asc = True
            if len(args) > 2:
                a = ev(args[2])
                asc = [bool(x) for x in a] if isinstance(a, list) else bool(a)
            return ops.sort(fr, cols, ascending=asc)
        if op == "merge":
            left, right = ev(args[0]), ev(args[1])
            all_left = bool(ev(args[2])) if len(args) > 2 else False
            by = self._col_names(left, ev(args[3])) if len(args) > 3 and \
                args[3] is not None else \
                [c for c in left.names if c in right.names]
            return ops.merge(left, right, by,
                             how="left" if all_left else "inner")
        if op == "GB" or op == "group_by":
            # (GB frame [by...] agg col na agg col na ...) — AstGroup triples
            fr = ev(args[0])
            by = self._col_names(fr, ev(args[1]))
            aggs: dict = {}
            rest = args[2:]
            for i in range(0, len(rest) - 2, 3):
                fn = rest[i] if isinstance(rest[i], str) else ev(rest[i])
                col = self._col_names(fr, ev(rest[i + 1]))[0]
                aggs.setdefault(col, []).append(
                    {"nrow": "count"}.get(fn, fn))
            return ops.group_by(fr, by, aggs)
        if op == "rbind":
            return ops.rbind(*[ev(a) for a in args])
        if op == "cbind":
            return ops.cbind(*[_vecframe(ev(a)) for a in args])
        if op == "unique":
            fr = _vecframe(ev(args[0]))
            vals = ops.unique(fr.vecs[0])
            return Frame.from_numpy({fr.names[0]: vals})
        if op == "table":
            fr = _vecframe(ev(args[0]))
            t = ops.table(fr.vecs[0])
            return Frame.from_numpy({
                fr.names[0]: np.asarray(list(t.keys()), object),
                "Count": np.asarray(list(t.values()), np.float64)})
        if op == "ifelse":
            c, yes, no = ev(args[0]), ev(args[1]), ev(args[2])
            cv = c.vecs[0] if isinstance(c, Frame) else c
            yv = yes.vecs[0] if isinstance(yes, Frame) else yes
            nv = no.vecs[0] if isinstance(no, Frame) else no
            return _vecframe(ops.ifelse(cv, yv, nv))
        if op == "hist":
            fr = _vecframe(ev(args[0]))
            breaks = int(ev(args[1])) if len(args) > 1 else 20
            counts, edges = ops.hist(fr.vecs[0], breaks)
            return Frame.from_numpy({"breaks": edges[1:],
                                     "counts": counts.astype(np.float64)})
        if op == "nrow":
            return float(ev(args[0]).nrows)
        if op == "ncol":
            return float(ev(args[0]).ncols)
        if op == "colnames=":
            fr = ev(args[0])
            names = ev(args[2])
            names = names if isinstance(names, list) else [names]
            idx = ev(args[1])
            idx = [int(i) for i in (idx if isinstance(idx, list) else [idx])]
            mapping = {fr.names[i]: str(n) for i, n in zip(idx, names)}
            return fr.rename(mapping)
        if op == "as.factor":
            fr = _vecframe(ev(args[0]))
            out = []
            for v in fr.vecs:
                if v.type == T_CAT:
                    out.append(v)
                else:
                    x = v.to_numpy()
                    out.append(Vec.from_numpy(
                        np.asarray([("" if np.isnan(u) else str(u))
                                    for u in x], dtype=object), T_CAT))
            return Frame(fr.names, out)
        if op == "as.numeric":
            fr = _vecframe(ev(args[0]))
            X = _numeric(fr)
            return Frame(fr.names, [Vec(X[:, j], T_NUM, fr.nrows)
                                    for j in range(X.shape[1])])
        if op == "quantile":
            from ..models.quantile import quantile
            fr = ev(args[0])
            probs = [float(p) for p in ev(args[1])]
            return quantile(fr, probs)
        if op in _STRING:
            from . import strings as _str
            from ..frame.vec import T_STR
            from ..frame.vec import T_CAT as _TC
            fn = getattr(_str, _STRING[op])
            vals = [ev(a) for a in args]
            # h2o-py sends replacefirst/replaceall as (pattern,
            # replacement, frame, ignore_case); everything else frame-first
            fi = next(i for i, v in enumerate(vals)
                      if isinstance(v, (Frame, Vec)))
            target = vals[fi]
            extra = [v for i, v in enumerate(vals) if i != fi]
            if extra and isinstance(extra[-1], float) and \
                    op in ("replacefirst", "replaceall"):
                extra = extra[:-1]            # ignore_case flag: unused
            # Rapids numeric tokens are floats; string fns take ints
            extra = [int(v) if isinstance(v, float) and
                     float(v).is_integer() else v for v in extra]
            if isinstance(target, Vec):
                return _vecframe(fn(target, *extra))
            # frame form: transform every string column, preserve names
            # (AstToUpper & co. apply per string column)
            vecs = [fn(v, *extra) if v.type in (T_STR, _TC) else v
                    for v in target.vecs]
            return Frame(target.names, vecs)
        if op == "scale":
            fr = ev(args[0])
            center = ev(args[1]) if len(args) > 1 else True
            sc = ev(args[2]) if len(args) > 2 else True
            if isinstance(center, list) or isinstance(sc, list):
                raise NotImplementedError(
                    "scale: per-column center/scale lists not supported; "
                    "pass booleans")
            return ops.scale(fr, center=bool(center), scale_=bool(sc))
        if op == "apply":
            return self._apply_margin(args)
        if op == "ddply":
            return self._ddply(args)
        if op == "cut":
            fr = _vecframe(ev(args[0]))
            breaks = [float(b) for b in ev(args[1])]
            labels = ev(args[2]) if len(args) > 2 and args[2] is not None \
                else None
            if isinstance(labels, list) and not labels:
                labels = None
            include_lowest = bool(ev(args[3])) if len(args) > 3 else False
            right = bool(ev(args[4])) if len(args) > 4 else True
            digits = int(ev(args[5])) if len(args) > 5 else 3
            del digits                   # label precision: numpy repr used
            return _vecframe(ops.cut(
                fr.vecs[0], breaks, labels=labels,
                include_lowest=include_lowest, right=right))
        from .prims import PRIMS
        if op in PRIMS:
            return PRIMS[op](self, args)
        if op in ("h2o.impute", "impute"):
            fr = ev(args[0])
            col = ev(args[1])
            method = ev(args[2]) if len(args) > 2 else "mean"
            combine = ev(args[3]) if len(args) > 3 else "interpolate"
            if isinstance(col, float) and int(col) == -1:
                # h2o-py sentinel: impute every numeric column with NAs
                for name in fr.names:
                    v = fr.vec(name)
                    if v.is_numeric and v.rollups().nmissing:
                        fr = ops.impute(fr, name, method=method,
                                        combine_method=combine)
                return fr
            if not isinstance(col, str):
                col = fr.names[int(col)]
            return ops.impute(fr, col, method=method,
                              combine_method=combine)
        raise ValueError(f"unknown rapids op {op!r}")

    def _apply_margin(self, args) -> Any:
        """(apply frame margin fun) — AstApply.  margin 2 = per column
        (the fun sees each single-column frame); margin 1 = per row,
        evaluated VECTORIZED: the fun's body runs once with the formal
        bound to the whole frame, which is exact for elementwise bodies
        (the h2o-py lambda pattern); a bare reducer name ("mean", "sum",
        ...) reduces row-wise."""
        ev = self._ev
        fr = ev(args[0])
        margin = int(ev(args[1]))
        fun = ev(args[2])
        import jax.numpy as _jnp
        if isinstance(fun, str) or isinstance(fun, float):
            name = str(fun)
            fns = {"mean": jnp.nanmean, "sum": jnp.nansum,
                   "max": jnp.nanmax, "min": jnp.nanmin,
                   "median": jnp.nanmedian,
                   "sd": lambda x, axis: jnp.nanstd(x, axis=axis, ddof=1),
                   "var": lambda x, axis: jnp.nanvar(x, axis=axis, ddof=1)}
            if name not in fns:
                raise ValueError(f"apply: unknown function {name!r}")
            X = _numeric(fr)
            mask = jnp.arange(X.shape[0]) < fr.nrows
            Xv = jnp.where(mask[:, None], X, jnp.nan)
            if margin == 1:              # per row
                out = fns[name](Xv, axis=1)
                return Frame(["C1"], [Vec(out.astype(_jnp.float32),
                                          T_NUM, fr.nrows)])
            out = fns[name](Xv, axis=0)[None, :]
            return Frame(list(fr.names),
                         [Vec(out[:, j].astype(_jnp.float32), T_NUM, 1)
                          for j in range(out.shape[1])])
        if not isinstance(fun, Lambda):
            raise ValueError(f"apply: not a function: {fun!r}")
        if margin == 1:
            res = self.call(fun, [fr])
            return _vecframe(res) if isinstance(res, (Frame, Vec)) else res
        outs = []
        for name in fr.names:
            res = self.call(fun, [fr[[name]]])
            if isinstance(res, (int, float)):
                res = Frame([name], [Vec.from_numpy(
                    np.asarray([float(res)]), T_NUM)])
            outs.append(_vecframe(res, name))
        return ops.cbind(*outs)

    def _ddply(self, args) -> Any:
        """(ddply frame [group_cols] fun) — AstDdply: per-group lambda."""
        ev = self._ev
        fr = ev(args[0])
        by = self._col_names(fr, ev(args[1]))
        fun = ev(args[2])
        if not isinstance(fun, Lambda):
            raise ValueError("ddply needs a function argument")
        from .prims import _decoded
        keys = [_decoded(fr.vec(c))[: fr.nrows] for c in by]
        key_strs = np.asarray([tuple(str(k[i]) for k in keys)
                               for i in range(fr.nrows)], object)
        uniq, inverse = np.unique(
            np.asarray(["\x00".join(t) for t in key_strs], object),
            return_inverse=True)
        rows_out: List[list] = []
        for g, label in enumerate(uniq):
            idx = np.flatnonzero(inverse == g)
            sub = fr.rows(idx)
            res = self.call(fun, [sub])
            if isinstance(res, Frame):
                vals = [float(np.asarray(v.to_numpy(), np.float64)[0])
                        for v in res.vecs]
            elif isinstance(res, list):
                vals = [float(x) for x in res]
            else:
                vals = [float(res)]
            rows_out.append(list(label.split("\x00")) + vals)
        nvals = len(rows_out[0]) - len(by) if rows_out else 0
        cols: dict = {}
        for j, c in enumerate(by):
            src = fr.vec(c)
            col = np.asarray([r[j] for r in rows_out], object)
            if src.type not in (T_CAT,):
                col = np.asarray([float(x) for x in col])
            cols[c] = col
        for v in range(nvals):
            cols[f"ddply_C{v + 1}"] = np.asarray(
                [r[len(by) + v] for r in rows_out])
        return Frame.from_numpy(cols)

    def _col_names(self, fr: Frame, sel) -> List[str]:
        if isinstance(sel, str):
            return [sel]
        if isinstance(sel, float):
            return [fr.names[int(sel)]]
        out = []
        for s in sel:
            out.append(s if isinstance(s, str) else fr.names[int(s)])
        return out


_session: Optional[Session] = None


def rapids(text: str):
    """Evaluate a Rapids expression — h2o.rapids / POST /99/Rapids analog."""
    global _session
    if _session is None:
        _session = Session()
    return _session.eval(text)
