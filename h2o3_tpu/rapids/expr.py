"""Lazy Rapids expression DAG — the h2o-py ``ExprNode``/``H2OFrame`` analog.

Reference: ``h2o-py/h2o/expr.py:27-34`` — client-side frames are lazy AST
nodes; operations build ``(op args...)`` strings which only execute (via
/99/Rapids) when results are demanded, and materialized results are cached
under session-temp DKV keys.

``LazyFrame`` wraps either a DKV key or an unevaluated AST.  Arithmetic,
comparison, slicing, sort/merge/group-by compose lazily; ``.frame()`` /
``.collect()`` force evaluation through a ``Backend`` — in-process
(ast.rapids) or remote (client.H2OConnection posts to /99/Rapids).
"""

from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Union

import numpy as np

_TMP = itertools.count()


class Backend:
    """Evaluation target for lazy expressions."""

    def rapids(self, text: str):
        raise NotImplementedError

    def frame_by_key(self, key: str):
        raise NotImplementedError


class LocalBackend(Backend):
    def rapids(self, text: str):
        from .ast import rapids
        return rapids(text)

    def frame_by_key(self, key: str):
        from ..runtime import dkv
        return dkv.get(key)


def _quote(s: str) -> str:
    return "'" + str(s).replace("'", "\\'") + "'"


def _lit(v) -> str:
    if isinstance(v, LazyFrame):
        return v.ast()
    if isinstance(v, str):
        return _quote(v)
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + " ".join(_lit(x) for x in v) + "]"
    return repr(float(v)) if isinstance(v, float) else repr(v)


class LazyFrame:
    """A deferred frame: either a DKV key or an AST over other frames."""

    def __init__(self, ast_or_key: str, backend: Optional[Backend] = None,
                 is_key: bool = False):
        self._ast = ast_or_key
        self._is_key = is_key
        self._backend = backend or LocalBackend()
        self._cached_key: Optional[str] = None

    # ------------------------------------------------------------ plumbing
    @staticmethod
    def from_key(key: str, backend: Optional[Backend] = None) -> "LazyFrame":
        return LazyFrame(key, backend, is_key=True)

    def ast(self) -> str:
        if self._cached_key is not None:
            return self._cached_key
        return self._ast

    def _op(self, op: str, *args) -> "LazyFrame":
        parts = " ".join(_lit(a) for a in args)
        return LazyFrame(f"({op} {self.ast()}{' ' if parts else ''}{parts})",
                         self._backend)

    # ----------------------------------------------------------- execution
    def execute(self) -> "LazyFrame":
        """Force evaluation into a session temp key (h2o-py _eager)."""
        if self._is_key or self._cached_key is not None:
            return self
        key = f"rapids_tmp_{next(_TMP)}"
        self._backend.rapids(f"(tmp= {key} {self._ast})")
        self._cached_key = key
        return self

    def frame(self):
        """Materialize to a concrete Frame (local backends)."""
        if self._is_key:
            return self._backend.frame_by_key(self._ast)
        self.execute()
        return self._backend.frame_by_key(self._cached_key)

    def collect(self) -> np.ndarray:
        return self.frame().to_numpy()

    def scalar(self) -> float:
        """Evaluate an aggregate expression to a number."""
        out = self._backend.rapids(self._ast)
        return float(out)

    # ---------------------------------------------------------- operations
    def __add__(self, o):
        return self._op("+", o)

    def __radd__(self, o):
        return LazyFrame(f"(+ {_lit(o)} {self.ast()})", self._backend)

    def __sub__(self, o):
        return self._op("-", o)

    def __mul__(self, o):
        return self._op("*", o)

    def __truediv__(self, o):
        return self._op("/", o)

    def __pow__(self, o):
        return self._op("^", o)

    def __lt__(self, o):
        return self._op("<", o)

    def __le__(self, o):
        return self._op("<=", o)

    def __gt__(self, o):
        return self._op(">", o)

    def __ge__(self, o):
        return self._op(">=", o)

    def __eq__(self, o):                         # noqa: A003
        return self._op("==", o)

    def __ne__(self, o):
        return self._op("!=", o)

    def __and__(self, o):
        return self._op("&", o)

    def __or__(self, o):
        return self._op("|", o)

    def __getitem__(self, sel) -> "LazyFrame":
        if isinstance(sel, LazyFrame):           # boolean row mask
            return LazyFrame(f"(rows {self.ast()} {sel.ast()})",
                             self._backend)
        if isinstance(sel, str):
            return self._op("cols", [sel])
        if isinstance(sel, (list, tuple)):
            return self._op("cols", list(sel))
        raise TypeError(f"bad selector {sel!r}")

    def log(self):
        return self._op("log")

    def exp(self):
        return self._op("exp")

    def abs(self):                               # noqa: A003
        return self._op("abs")

    def sqrt(self):
        return self._op("sqrt")

    def isna(self):
        return self._op("is.na")

    def ifelse(self, yes, no):
        return self._op("ifelse", yes, no)

    def sum(self):                               # noqa: A003
        return self._op("sum").scalar()

    def mean(self):
        return self._op("mean").scalar()

    def max(self):                               # noqa: A003
        return self._op("max").scalar()

    def min(self):                               # noqa: A003
        return self._op("min").scalar()

    def sd(self):
        return self._op("sd").scalar()

    def median(self):
        return self._op("median").scalar()

    def nrow(self) -> int:
        return int(self._op("nrow").scalar())

    def ncol(self) -> int:
        return int(self._op("ncol").scalar())

    def sort(self, by: Union[str, Sequence[str]],
             ascending=True) -> "LazyFrame":
        by = [by] if isinstance(by, str) else list(by)
        asc = [ascending] * len(by) if isinstance(ascending, bool) \
            else list(ascending)
        return self._op("sort", by, [1 if a else 0 for a in asc])

    def merge(self, other: "LazyFrame", by: Union[str, Sequence[str]],
              all_left: bool = False) -> "LazyFrame":
        by = [by] if isinstance(by, str) else list(by)
        return self._op("merge", other, all_left, by)

    def group_by(self, by: Union[str, Sequence[str]],
                 **aggs: Union[str, Sequence[str]]) -> "LazyFrame":
        """group_by(by, col=\"mean\", other_col=[\"sum\", \"max\"])."""
        by = [by] if isinstance(by, str) else list(by)
        parts: List[str] = []
        for col, fns in aggs.items():
            for fn in ([fns] if isinstance(fns, str) else fns):
                parts += [fn, _quote(col), _quote("all")]
        return LazyFrame(
            f"(GB {self.ast()} {_lit(by)} {' '.join(parts)})", self._backend)

    def rbind(self, other: "LazyFrame") -> "LazyFrame":
        return self._op("rbind", other)

    # -------------------------------------------------- string verbs
    def toupper(self) -> "LazyFrame":
        return self._op("toupper")

    def tolower(self) -> "LazyFrame":
        return self._op("tolower")

    def trim(self) -> "LazyFrame":
        return self._op("trim")

    def nchar(self) -> "LazyFrame":
        return self._op("nchar")

    def substring(self, start: int, end=None) -> "LazyFrame":
        return self._op("substring", start) if end is None else             self._op("substring", start, end)

    def sub(self, pattern: str, replacement: str) -> "LazyFrame":
        """Replace first match (client arg order, like h2o-py)."""
        return LazyFrame(f"(replacefirst {_lit(pattern)} "
                         f"{_lit(replacement)} {self.ast()} FALSE)",
                         self._backend)

    def gsub(self, pattern: str, replacement: str) -> "LazyFrame":
        return LazyFrame(f"(replaceall {_lit(pattern)} "
                         f"{_lit(replacement)} {self.ast()} FALSE)",
                         self._backend)

    def countmatches(self, pattern: str) -> "LazyFrame":
        return self._op("countmatches", pattern)

    # -------------------------------------------------- stats verbs
    def scale(self, center: bool = True, scale: bool = True) -> "LazyFrame":
        return self._op("scale", center, scale)

    def impute(self, column, method: str = "mean") -> "LazyFrame":
        return self._op("h2o.impute", column, method)

    def var(self, use: str = "complete.obs"):
        """Covariance matrix Frame for multi-column frames; a float
        (like sd()/mean()) when the frame has a single column."""
        out = self._backend.rapids(f'(var {self.ast()} {_quote(use)})')
        return out if not isinstance(out, (int, float)) else float(out)

    def cor(self, use: str = "complete.obs"):
        return self._backend.rapids(f'(cor {self.ast()} {_quote(use)})')

    def cbind(self, other: "LazyFrame") -> "LazyFrame":
        return self._op("cbind", other)

    def unique(self) -> "LazyFrame":
        return self._op("unique")

    def asfactor(self) -> "LazyFrame":
        return self._op("as.factor")

    def asnumeric(self) -> "LazyFrame":
        return self._op("as.numeric")

    def __repr__(self):
        return f"<LazyFrame {self.ast()[:120]}>"


def lazy(frame_or_key, backend: Optional[Backend] = None) -> LazyFrame:
    """Wrap a Frame (by key) or key string as a lazy expression root."""
    key = frame_or_key if isinstance(frame_or_key, str) \
        else frame_or_key.key
    if key is None:
        from ..runtime import dkv
        key = dkv.make_key("frame")
        dkv.put(key, frame_or_key)
        frame_or_key.key = key
    return LazyFrame.from_key(key, backend)
