"""Device-side munging primitives: lexicographic rank, gather joins, row moves.

Reference semantics: ``water/rapids/RadixOrder.java`` (distributed MSB radix
sort over 100M rows) and ``water/rapids/BinaryMerge.java`` (per-MSB-bucket
binary merge with row expansion).  TPU redesign: XLA's sort network replaces
the radix passes; join matching and duplicate-row expansion are computed with
dense-rank + segment tables + prefix sums entirely on device.  The only host
syncs are O(1) scalars (output row counts).  Per-row binary searches
(``searchsorted``) are avoided on purpose — they lower to log(N) dependent
gathers per row, which is the slowest access pattern on TPU; every lookup here
is either a sort, a cumsum, or a single flat gather.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from ..frame.vec import Vec, T_CAT, T_NUM, T_TIME
from ..runtime.cluster import cluster, put_sharded, fetch

_INF = jnp.float32(np.inf)


def sort_key(vec: Vec) -> jax.Array:
    """Float32 sort key for one column: NA (and padding) map to +inf."""
    if vec.type == T_CAT:
        codes = vec.data.astype(jnp.float32)
        return jnp.where(vec.data < 0, _INF, codes)
    return jnp.where(jnp.isnan(vec.data), _INF, vec.data)


def lex_order(keys: Sequence[jax.Array],
              ascending: Optional[Sequence[bool]] = None) -> jax.Array:
    """Row order sorting lexicographically by ``keys`` (first key primary).

    Successive stable argsorts, least-significant key first — the classic
    LSD construction.  +inf (NA/padding) stays last under either direction.
    """
    n = keys[0].shape[0]
    asc = [True] * len(keys) if ascending is None else list(ascending)
    order = jnp.arange(n, dtype=jnp.int32)
    for key, a in reversed(list(zip(keys, asc))):
        k = jnp.where(jnp.isnan(key), _INF, key)
        if not a:
            k = jnp.where(jnp.isinf(k) & (k > 0), k, -k)
        order = order[jnp.argsort(k[order], stable=True)]
    return order


def dense_rank(keys: Sequence[jax.Array]) -> jax.Array:
    """Lexicographic dense rank (0-based) of rows over the key columns.

    Equal rows get equal ranks; all-NA rows (keys pre-mapped to +inf)
    collapse into the single top rank.  One sort + one scatter, no hashing.
    """
    order = lex_order(keys)
    skeys = [jnp.where(jnp.isnan(k), _INF, k)[order] for k in keys]
    neq = jnp.zeros(order.shape[0] - 1, dtype=bool)
    for s in skeys:
        neq = neq | (s[1:] != s[:-1])
    boundary = jnp.concatenate([jnp.zeros(1, jnp.int32), neq.astype(jnp.int32)])
    rank_sorted = jnp.cumsum(boundary)
    return jnp.zeros_like(rank_sorted).at[order].set(rank_sorted)


def gather_rows(frame: Frame, order: jax.Array, n_out: int,
                na_mask: Optional[jax.Array] = None) -> Frame:
    """New Frame whose row j is ``frame`` row ``order[j]`` (device gather).

    ``order`` may be longer/shorter than the output padding; rows at j >=
    n_out become NA padding.  ``na_mask`` additionally forces NA output rows
    (the unmatched side of a left join).  String/UUID/TIME columns gather
    host-side (they keep exact host payloads); everything else stays on
    device.
    """
    cl = cluster()
    p_out = cl.pad_rows(n_out)
    if order.shape[0] < p_out:
        order = jnp.concatenate(
            [order, jnp.zeros(p_out - order.shape[0], order.dtype)])
    idx = jnp.clip(order[:p_out], 0, max(frame.padded_rows - 1, 0))
    live = jnp.arange(p_out) < n_out
    if na_mask is not None:
        mask = na_mask[:p_out] if na_mask.shape[0] >= p_out else \
            jnp.concatenate([na_mask,
                             jnp.zeros(p_out - na_mask.shape[0], bool)])
        live = live & ~mask
    host_idx = None
    host_na = None
    vecs = []
    for v in frame.vecs:
        if v.data is None or v.type == T_TIME:
            if host_idx is None:
                host_idx = np.asarray(fetch(idx))[:n_out]
                host_na = ~np.asarray(fetch(live))[:n_out]
            payload = v.host_data[: len(v.host_data)]
            col = payload[np.clip(host_idx, 0, len(payload) - 1)]
            if host_na.any():
                col = np.array(col, copy=True)
                col[host_na] = np.nan if v.type == T_TIME else None
            vecs.append(Vec.from_numpy(col, v.type))
        elif v.type == T_CAT:
            g = jnp.where(live, v.data[idx], -1)
            vecs.append(Vec(put_sharded(g, cl.row_sharding), T_CAT, n_out,
                            domain=v.domain))
        else:
            g = jnp.where(live, v.data[idx], jnp.nan)
            vecs.append(Vec(put_sharded(g, cl.row_sharding), v.type, n_out))
    return Frame(frame.names, vecs)


def expand_starts(starts: jax.Array, counts: jax.Array,
                  p_out: int) -> jax.Array:
    """Map output position j -> source row i with starts[i] <= j < starts[i]+counts[i].

    The inverse of a ragged expansion, computed as scatter + cumulative max
    (rows with count 0 never own positions).  Requires starts ascending.
    """
    nonzero = counts > 0
    pos = jnp.where(nonzero, starts, p_out)  # park empty rows out of range
    pos = jnp.clip(pos, 0, p_out)
    src = jnp.arange(starts.shape[0], dtype=jnp.int32)
    owner = jnp.full(p_out + 1, -1, jnp.int32).at[pos].max(
        jnp.where(nonzero, src, -1))[:p_out]
    return jax.lax.associative_scan(jnp.maximum, owner)
