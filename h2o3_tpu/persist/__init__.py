"""Persist SPI: pluggable storage backends behind URI schemes.

Reference: ``water/persist/PersistManager.java`` routes every import/export
through a scheme-keyed registry of Persist implementations (PersistFS,
PersistGcs in h2o-persist-gcs, PersistS3, PersistHdfs, PersistHTTP); the
data plane reads raw byte ranges, the control plane lists/globs keys.

TPU-native redesign: the storage layer has no device concerns at all, so
the SPI is a small host-side protocol (open_read/open_write/list/exists/
delete).  The GCS backend is first (TPU-VMs live next to GCS, SURVEY.md §7
step 9): it uses ``google.cloud.storage`` when installed and otherwise a
"mock root" mapping (``gcs://bucket/key`` -> ``$H2O3_TPU_GCS_ROOT/bucket/
key``) so the full import/export surface stays testable offline.  S3/HDFS
get the same mock treatment; HTTP is read-only via urllib.
"""

from __future__ import annotations

import glob as _glob
import io
import os
import shutil
import urllib.request
from typing import BinaryIO, Dict, List, Optional, Tuple

__all__ = ["get_backend", "register", "split_uri", "open_read",
           "open_write", "list_uris", "exists", "delete", "PersistBackend"]


class PersistBackend:
    """One storage scheme — the water.persist.Persist analog."""

    scheme: str = ""

    def open_read(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def open_write(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def list(self, pattern: str) -> List[str]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def _uri(self, path: str) -> str:
        return f"{self.scheme}://{path}" if self.scheme else path


class LocalPersist(PersistBackend):
    """Plain filesystem (PersistFS analog); also handles file:// URIs."""

    scheme = ""

    def open_read(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def open_write(self, path: str) -> BinaryIO:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")

    def list(self, pattern: str) -> List[str]:
        if os.path.isdir(pattern):
            pattern = os.path.join(pattern, "*")
        return sorted(p for p in _glob.glob(pattern) if os.path.isfile(p))

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


class MockableCloudPersist(PersistBackend):
    """Cloud object store backend with an offline mock root.

    Real client libraries are used when importable; otherwise paths map
    onto ``$H2O3_TPU_{SCHEME}_ROOT`` (default /tmp/h2o3_tpu_{scheme}) so
    integration flows run without cloud credentials — the reference's
    PersistGcs tests use the same trick with a fake GCS server.
    """

    def __init__(self, scheme: str):
        self.scheme = scheme
        self._local = LocalPersist()

    @property
    def _root(self) -> Optional[str]:
        """Mock root dir; set H2O3_TPU_{SCHEME}_ROOT to activate the mock."""
        return os.environ.get(f"H2O3_TPU_{self.scheme.upper()}_ROOT")

    def _client_open(self, path: str, mode: str):
        if self.scheme in ("gcs", "gs"):
            from google.cloud import storage  # needs creds at call time
            bucket_name, _, key = path.partition("/")
            blob = storage.Client().bucket(bucket_name).blob(key)
            if mode == "rb":
                return io.BytesIO(blob.download_as_bytes())
            return _BlobWriter(blob)
        raise NotImplementedError(
            f"scheme {self.scheme!r} has no live client in this build; "
            f"set H2O3_TPU_{self.scheme.upper()}_ROOT to use the offline "
            f"mock mapping")

    def _map(self, path: str) -> str:
        return os.path.join(self._root, path)

    def open_read(self, path: str) -> BinaryIO:
        if self._root is not None:
            return self._local.open_read(self._map(path))
        return self._client_open(path, "rb")

    def open_write(self, path: str) -> BinaryIO:
        if self._root is not None:
            return self._local.open_write(self._map(path))
        return self._client_open(path, "wb")

    def list(self, pattern: str) -> List[str]:
        if self._root is not None:
            root = self._root
            out = self._local.list(self._map(pattern))
            return [f"{self.scheme}://{os.path.relpath(p, root)}"
                    for p in out]
        if self.scheme in ("gcs", "gs"):  # pragma: no cover - needs creds
            from google.cloud import storage
            bucket_name, _, prefix = pattern.partition("/")
            prefix = prefix.split("*", 1)[0]
            blobs = storage.Client().list_blobs(bucket_name, prefix=prefix)
            return [f"{self.scheme}://{bucket_name}/{b.name}" for b in blobs]
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        if self._root is not None:
            return self._local.exists(self._map(path))
        try:
            self.open_read(path).close()
            return True
        except Exception:
            return False

    def delete(self, path: str) -> None:
        if self._root is not None:
            self._local.delete(self._map(path))
        else:  # pragma: no cover - needs creds
            from google.cloud import storage
            bucket_name, _, key = path.partition("/")
            storage.Client().bucket(bucket_name).blob(key).delete()


class _BlobWriter(io.BytesIO):  # pragma: no cover - needs real GCS
    def __init__(self, blob):
        super().__init__()
        self._blob = blob

    def close(self):
        self._blob.upload_from_string(self.getvalue())
        super().close()


class HTTPPersist(PersistBackend):
    """Read-only HTTP(S) source (PersistHTTP analog)."""

    def __init__(self, scheme: str):
        self.scheme = scheme

    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(
            urllib.request.urlopen(f"{self.scheme}://{path}").read())

    def list(self, pattern: str) -> List[str]:
        return [f"{self.scheme}://{pattern}"]

    def exists(self, path: str) -> bool:
        try:
            self.open_read(path).close()
            return True
        except Exception:
            return False


_REGISTRY: Dict[str, PersistBackend] = {
    "": LocalPersist(),
    "file": LocalPersist(),
    "gcs": MockableCloudPersist("gcs"),
    "gs": MockableCloudPersist("gs"),
    "s3": MockableCloudPersist("s3"),
    "hdfs": MockableCloudPersist("hdfs"),
    "http": HTTPPersist("http"),
    "https": HTTPPersist("https"),
}


def register(scheme: str, backend: PersistBackend) -> None:
    """Install a custom backend — the PersistManager extension point."""
    _REGISTRY[scheme] = backend


def split_uri(uri: str) -> Tuple[PersistBackend, str]:
    scheme, sep, rest = uri.partition("://")
    if not sep:
        return _REGISTRY[""], uri
    if scheme == "file":
        return _REGISTRY[""], rest if rest.startswith("/") else "/" + rest
    be = _REGISTRY.get(scheme)
    if be is None:
        raise ValueError(f"no persist backend for scheme {scheme!r} "
                         f"(have {sorted(k for k in _REGISTRY if k)})")
    return be, rest


def get_backend(uri: str) -> PersistBackend:
    return split_uri(uri)[0]


def open_read(uri: str) -> BinaryIO:
    be, path = split_uri(uri)
    return be.open_read(path)


def open_write(uri: str) -> BinaryIO:
    be, path = split_uri(uri)
    return be.open_write(path)


def list_uris(pattern: str) -> List[str]:
    be, path = split_uri(pattern)
    return be.list(path)


def exists(uri: str) -> bool:
    be, path = split_uri(uri)
    return be.exists(path)


def delete(uri: str) -> None:
    be, path = split_uri(uri)
    be.delete(path)
