"""Persist SPI: pluggable storage backends behind URI schemes.

Reference: ``water/persist/PersistManager.java`` routes every import/export
through a scheme-keyed registry of Persist implementations (PersistFS,
PersistGcs in h2o-persist-gcs, PersistS3, PersistHdfs, PersistHTTP); the
data plane reads raw byte ranges, the control plane lists/globs keys.

TPU-native redesign: the storage layer has no device concerns at all, so
the SPI is a small host-side protocol (open_read/open_write/read_range/
size/list/exists/delete).  Real backends:

- GCS (``gs://``/``gcs://``): ``google.cloud.storage`` SDK — range reads,
  streaming resumable writes; honors ``STORAGE_EMULATOR_HOST``
  (integration-tested against an in-process fake GCS server).
- S3 (``s3://``): native REST + SigV4 (no boto3 in this image) — range
  reads, multipart streaming writes; custom endpoints via
  ``H2O3_TPU_S3_ENDPOINT`` (minio / fakes / interop).
- HDFS (``hdfs://``): WebHDFS protocol against
  ``H2O3_TPU_HDFS_NAMENODE`` or ``hdfs://host:port/path`` URIs.
- HTTP(S): read-only via urllib.

TEST-ONLY escape hatch: setting ``H2O3_TPU_{GCS,S3,HDFS}_ROOT`` remaps a
scheme onto a local directory (``gcs://bucket/key`` ->
``$ROOT/bucket/key``).  That exercises the SPI, not the backend — CI
integration tests use the protocol fakes instead.
"""

from __future__ import annotations

import glob as _glob
import io
import os
import shutil
import urllib.request
from typing import BinaryIO, Dict, List, Optional, Tuple

__all__ = ["get_backend", "register", "split_uri", "open_read",
           "open_write", "list_uris", "exists", "delete", "PersistBackend"]


class PersistBackend:
    """One storage scheme — the water.persist.Persist analog."""

    scheme: str = ""

    def open_read(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def open_write(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def list(self, pattern: str) -> List[str]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        """Byte-range read; default reads the object and slices."""
        with self.open_read(path) as f:
            f.seek(offset)
            return f.read(length)

    def size(self, path: str) -> int:
        with self.open_read(path) as f:
            f.seek(0, os.SEEK_END)
            return f.tell()

    def _uri(self, path: str) -> str:
        return f"{self.scheme}://{path}" if self.scheme else path


class LocalPersist(PersistBackend):
    """Plain filesystem (PersistFS analog); also handles file:// URIs."""

    scheme = ""

    def open_read(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def open_write(self, path: str) -> BinaryIO:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")

    def list(self, pattern: str) -> List[str]:
        if os.path.isdir(pattern):
            pattern = os.path.join(pattern, "*")
        return sorted(p for p in _glob.glob(pattern) if os.path.isfile(p))

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


class CloudPersist(PersistBackend):
    """Scheme dispatcher: real protocol backend, or the TEST-ONLY mock
    root when ``H2O3_TPU_{SCHEME}_ROOT`` is set (exercises the SPI without
    network; CI uses the protocol fakes instead — see module docstring)."""

    def __init__(self, scheme: str, real_factory):
        self.scheme = scheme
        self._local = LocalPersist()
        self._real_factory = real_factory
        self._real = None

    @property
    def _root(self) -> Optional[str]:
        return os.environ.get(f"H2O3_TPU_{self.scheme.upper()}_ROOT")

    def real(self):
        if self._real is None:
            self._real = self._real_factory()
        return self._real

    def _map(self, path: str) -> str:
        return os.path.join(self._root, path)

    def open_read(self, path: str) -> BinaryIO:
        if self._root is not None:
            return self._local.open_read(self._map(path))
        return self.real().open_read(path)

    def open_write(self, path: str) -> BinaryIO:
        if self._root is not None:
            return self._local.open_write(self._map(path))
        return self.real().open_write(path)

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        if self._root is not None:
            return super().read_range(path, offset, length)
        return self.real().read_range(path, offset, length)

    def size(self, path: str) -> int:
        if self._root is not None:
            return os.path.getsize(self._map(path))
        return self.real().size(path)

    def list(self, pattern: str) -> List[str]:
        if self._root is not None:
            root = self._root
            out = self._local.list(self._map(pattern))
            return [f"{self.scheme}://{os.path.relpath(p, root)}"
                    for p in out]
        return self.real().list(pattern)

    def exists(self, path: str) -> bool:
        if self._root is not None:
            return self._local.exists(self._map(path))
        return self.real().exists(path)

    def delete(self, path: str) -> None:
        if self._root is not None:
            self._local.delete(self._map(path))
        else:
            self.real().delete(path)


class HTTPPersist(PersistBackend):
    """Read-only HTTP(S) source (PersistHTTP analog)."""

    def __init__(self, scheme: str):
        self.scheme = scheme

    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(
            urllib.request.urlopen(f"{self.scheme}://{path}").read())

    def list(self, pattern: str) -> List[str]:
        return [f"{self.scheme}://{pattern}"]

    def exists(self, path: str) -> bool:
        try:
            self.open_read(path).close()
            return True
        except Exception:
            return False


def _gcs(scheme):
    def make():
        from .gcs import GcsPersist
        return GcsPersist(scheme)
    return make


def _s3():
    from .s3 import S3Persist
    return S3Persist()


def _hdfs():
    from .hdfs import WebHDFSPersist
    return WebHDFSPersist()


_REGISTRY: Dict[str, PersistBackend] = {
    "": LocalPersist(),
    "file": LocalPersist(),
    "gcs": CloudPersist("gcs", _gcs("gcs")),
    "gs": CloudPersist("gs", _gcs("gs")),
    "s3": CloudPersist("s3", _s3),
    "hdfs": CloudPersist("hdfs", _hdfs),
    "http": HTTPPersist("http"),
    "https": HTTPPersist("https"),
}


def register(scheme: str, backend: PersistBackend) -> None:
    """Install a custom backend — the PersistManager extension point."""
    _REGISTRY[scheme] = backend


def split_uri(uri: str) -> Tuple[PersistBackend, str]:
    scheme, sep, rest = uri.partition("://")
    if not sep:
        return _REGISTRY[""], uri
    if scheme == "file":
        return _REGISTRY[""], rest if rest.startswith("/") else "/" + rest
    be = _REGISTRY.get(scheme)
    if be is None:
        raise ValueError(f"no persist backend for scheme {scheme!r} "
                         f"(have {sorted(k for k in _REGISTRY if k)})")
    return be, rest


def get_backend(uri: str) -> PersistBackend:
    return split_uri(uri)[0]


def open_read(uri: str) -> BinaryIO:
    be, path = split_uri(uri)
    return be.open_read(path)


def open_write(uri: str) -> BinaryIO:
    be, path = split_uri(uri)
    return be.open_write(path)


def list_uris(pattern: str) -> List[str]:
    be, path = split_uri(pattern)
    return be.list(path)


def exists(uri: str) -> bool:
    be, path = split_uri(uri)
    return be.exists(path)


def delete(uri: str) -> None:
    be, path = split_uri(uri)
    be.delete(path)
