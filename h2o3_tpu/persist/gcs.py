"""GCS persist backend — the h2o-persist-gcs PersistGcs analog, real SDK.

Reference: ``h2o-persist-gcs/src/main/java/water/persist/PersistGcs.java`` —
SDK-backed range reads, streaming channel writes, prefix listing.

Uses ``google.cloud.storage`` (baked into TPU-VM images).  When
``STORAGE_EMULATOR_HOST`` is set the client runs anonymously against the
emulator — integration tests spin up an in-process fake GCS server and
exercise this exact code path (no mock-root shortcuts).
"""

from __future__ import annotations

import fnmatch
import io
import os
import threading
from typing import BinaryIO, List, Optional


class GcsPersist:
    """Real-SDK GCS backend (``gs://`` / ``gcs://``)."""

    def __init__(self, scheme: str = "gs"):
        self.scheme = scheme
        self._client = None
        self._lock = threading.Lock()

    # One client per backend: construction is expensive (auth discovery)
    # and clients are thread-safe.
    def client(self):
        with self._lock:
            if self._client is None:
                from google.cloud import storage
                if os.environ.get("STORAGE_EMULATOR_HOST"):
                    from google.auth.credentials import AnonymousCredentials
                    self._client = storage.Client(
                        credentials=AnonymousCredentials(),
                        project=os.environ.get("GOOGLE_CLOUD_PROJECT",
                                               "h2o3-tpu-test"))
                else:                      # pragma: no cover - needs creds
                    self._client = storage.Client()
            return self._client

    def reset(self) -> None:
        """Forget the cached client (tests flip emulator env vars)."""
        with self._lock:
            self._client = None

    def _blob(self, path: str):
        bucket_name, _, key = path.partition("/")
        return self.client().bucket(bucket_name).blob(key)

    # ------------------------------------------------------------------ SPI
    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(self._blob(path).download_as_bytes())

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        """SDK range read (PersistGcs.load reads chunk byte ranges)."""
        if length <= 0:
            return b""
        return self._blob(path).download_as_bytes(
            start=offset, end=offset + length - 1)

    def size(self, path: str) -> int:
        blob = self._blob(path)
        blob.reload()
        return int(blob.size or 0)

    def open_write(self, path: str) -> BinaryIO:
        """Streaming resumable upload (the SDK's BlobWriter channel).

        checksum=None: emulators/fakes rarely echo crc32c metadata and the
        SDK hard-fails on its absence; GCS still integrity-checks per
        request at the HTTP layer."""
        blob = self._blob(path)
        try:
            return blob.open("wb", ignore_flush=True, checksum=None)
        except TypeError:              # older SDK without ignore_flush
            return blob.open("wb", checksum=None)

    def list(self, pattern: str) -> List[str]:
        bucket_name, _, keypat = pattern.partition("/")
        prefix = keypat.split("*", 1)[0].split("?", 1)[0]
        names = [b.name for b in
                 self.client().list_blobs(bucket_name, prefix=prefix)]
        if any(c in keypat for c in "*?[") :
            names = [n for n in names if fnmatch.fnmatch(n, keypat)]
        elif keypat:
            # bare prefix: a directory-ish listing
            names = [n for n in names
                     if n == keypat or n.startswith(keypat.rstrip("/") + "/")]
        return [f"{self.scheme}://{bucket_name}/{n}" for n in sorted(names)]

    def exists(self, path: str) -> bool:
        try:
            return bool(self._blob(path).exists())
        except Exception:               # noqa: BLE001 — treat as absent
            return False

    def delete(self, path: str) -> None:
        self._blob(path).delete()
