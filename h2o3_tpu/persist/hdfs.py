"""HDFS persist backend — the h2o-persist-hdfs analog over WebHDFS.

Reference: ``h2o-persist-hdfs`` wraps the Hadoop FileSystem API (a JVM
dependency); the TPU rebuild speaks the WebHDFS REST protocol instead
(https://hadoop.apache.org/docs/stable/hadoop-project-dist/hadoop-hdfs/WebHDFS.html)
— no Hadoop client needed, works against any namenode with webhdfs
enabled.  Namenode from ``H2O3_TPU_HDFS_NAMENODE`` (e.g.
``http://namenode:9870``); ``hdfs://host:port/path`` URIs override it.

Protocol notes: CREATE and OPEN are two-step (namenode 307-redirects to a
datanode); the write path PUTs the redirect target explicitly since
urllib only auto-follows redirects for GET.
"""

from __future__ import annotations

import fnmatch
import io
import json
import os
import posixpath
import urllib.error
import urllib.parse
import urllib.request
from typing import BinaryIO, List, Optional, Tuple


def _namenode() -> Optional[str]:
    return os.environ.get("H2O3_TPU_HDFS_NAMENODE") or None


class WebHDFSPersist:
    """WebHDFS-protocol backend (``hdfs://``)."""

    scheme = "hdfs"

    def _base(self, path: str) -> Tuple[str, str]:
        """Split an ``hdfs://`` remainder into (namenode base, fs path)."""
        if "/" in path and ":" in path.split("/", 1)[0]:
            host, _, rest = path.partition("/")
            return f"http://{host}", "/" + rest
        nn = _namenode()
        if not nn:
            raise ValueError(
                "hdfs:// needs H2O3_TPU_HDFS_NAMENODE (http://host:port) "
                "or an hdfs://host:port/path URI")
        return nn.rstrip("/"), "/" + path.lstrip("/")

    @staticmethod
    def _url_at(base: str, fspath: str, op: str, **params) -> str:
        q = urllib.parse.urlencode({"op": op, **{
            k: v for k, v in params.items() if v is not None}})
        user = os.environ.get("H2O3_TPU_HDFS_USER")
        if user:
            q += f"&user.name={urllib.parse.quote(user)}"
        return f"{base}/webhdfs/v1{urllib.parse.quote(fspath)}?{q}"

    def _url(self, path: str, op: str, **params) -> str:
        base, fspath = self._base(path)
        return self._url_at(base, fspath, op, **params)

    # ------------------------------------------------------------------ SPI
    def open_read(self, path: str) -> BinaryIO:
        with urllib.request.urlopen(self._url(path, "OPEN")) as r:
            return io.BytesIO(r.read())

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        if length <= 0:
            return b""
        url = self._url(path, "OPEN", offset=offset, length=length)
        with urllib.request.urlopen(url) as r:
            return r.read()

    def size(self, path: str) -> int:
        with urllib.request.urlopen(self._url(path, "GETFILESTATUS")) as r:
            return int(json.loads(r.read())["FileStatus"]["length"])

    def open_write(self, path: str) -> BinaryIO:
        return _HDFSWriter(self, path)

    def _create(self, path: str, data: bytes) -> None:
        url = self._url(path, "CREATE", overwrite="true")
        req = urllib.request.Request(url, method="PUT")

        class _NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **k):
                return None

        opener = urllib.request.build_opener(_NoRedirect)
        try:
            resp = opener.open(req)
            location = resp.headers.get("Location")
        except urllib.error.HTTPError as e:
            if e.code in (301, 302, 307):
                location = e.headers.get("Location")
            else:
                raise
        if not location:
            raise IOError(f"webhdfs CREATE gave no redirect for {path}")
        put = urllib.request.Request(location, data=data, method="PUT")
        put.add_header("Content-Type", "application/octet-stream")
        urllib.request.urlopen(put).read()

    def list(self, pattern: str) -> List[str]:
        base, fspath = self._base(pattern)
        leaf = posixpath.basename(fspath)
        is_glob = any(c in leaf for c in "*?[")
        probe = posixpath.dirname(fspath) if is_glob else fspath
        url = self._url_at(base, probe, "LISTSTATUS")
        try:
            with urllib.request.urlopen(url) as r:
                statuses = json.loads(r.read())[
                    "FileStatuses"]["FileStatus"]
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return []
            raise
        host = base.split("://", 1)[-1]
        out = []
        for st in statuses:
            if st.get("type") == "DIR":
                continue
            suffix = st.get("pathSuffix")
            full = posixpath.join(probe, suffix) if suffix else probe
            name = suffix or posixpath.basename(probe)
            if is_glob and not fnmatch.fnmatch(name, leaf):
                continue
            out.append(f"hdfs://{host}{full}")
        return sorted(out)

    def exists(self, path: str) -> bool:
        try:
            urllib.request.urlopen(
                self._url(path, "GETFILESTATUS")).read()
            return True
        except Exception:               # noqa: BLE001 — 404 et al: absent
            return False

    def delete(self, path: str) -> None:
        req = urllib.request.Request(
            self._url(path, "DELETE", recursive="true"), method="DELETE")
        urllib.request.urlopen(req).read()


class _HDFSWriter(io.BytesIO):
    def __init__(self, backend: WebHDFSPersist, path: str):
        super().__init__()
        self._be = backend
        self._path = path

    def close(self) -> None:
        if not self.closed:
            self._be._create(self._path, self.getvalue())
            super().close()
