"""S3 persist backend — the h2o-persist-s3 PersistS3 analog, native REST.

Reference: ``h2o-persist-s3/src/main/java/water/persist/PersistS3.java`` —
SDK-backed range reads and multipart uploads.

boto3 is not in this image, so this speaks the S3 REST protocol directly
over urllib with AWS Signature V4: GET (with Range), PUT, DELETE, HEAD,
ListObjectsV2, and the CreateMultipartUpload/UploadPart/Complete flow for
large streaming writes.  Endpoint resolution:

- ``H2O3_TPU_S3_ENDPOINT`` / ``AWS_ENDPOINT_URL`` — custom endpoint
  (minio, the test fake, GCS-interop...), path-style addressing.
- otherwise ``https://{bucket}.s3.{region}.amazonaws.com``.

Credentials from ``AWS_ACCESS_KEY_ID``/``AWS_SECRET_ACCESS_KEY`` (+
``AWS_SESSION_TOKEN``); requests go unsigned when absent (public buckets /
auth-free emulators).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import io
import os
import urllib.error
import urllib.parse
import urllib.request
from typing import BinaryIO, Dict, List, Optional, Tuple
from xml.etree import ElementTree

_MULTIPART_CHUNK = 8 * 1024 * 1024


def _endpoint() -> Optional[str]:
    return (os.environ.get("H2O3_TPU_S3_ENDPOINT")
            or os.environ.get("AWS_ENDPOINT_URL") or None)


def _region() -> str:
    return os.environ.get("AWS_REGION",
                          os.environ.get("AWS_DEFAULT_REGION", "us-east-1"))


def _sign_v4(method: str, url: str, headers: Dict[str, str],
             payload_hash: str) -> Dict[str, str]:
    """AWS Signature Version 4 (the subset S3 object ops need)."""
    access = os.environ.get("AWS_ACCESS_KEY_ID")
    secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if not access or not secret:
        return headers                      # unsigned (emulator / public)
    region = _region()
    parsed = urllib.parse.urlsplit(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amzdate = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    headers = dict(headers)
    headers["x-amz-date"] = amzdate
    headers["x-amz-content-sha256"] = payload_hash
    token = os.environ.get("AWS_SESSION_TOKEN")
    if token:
        headers["x-amz-security-token"] = token
    headers.setdefault("host", parsed.netloc)
    lower_map = {h.lower(): h for h in headers}
    signed = sorted(lower_map)
    canonical_headers = "".join(
        f"{k}:{headers[lower_map[k]].strip()}\n" for k in signed)
    signed_headers = ";".join(signed)
    query = "&".join(sorted(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in urllib.parse.parse_qsl(parsed.query,
                                           keep_blank_values=True)))
    canonical = "\n".join([
        method, urllib.parse.quote(parsed.path or "/"), query,
        canonical_headers, signed_headers, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amzdate, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={sig}")
    return headers


class S3Persist:
    """Native-REST S3 backend (``s3://``)."""

    scheme = "s3"

    def _url(self, bucket: str, key: str = "", query: str = "") -> str:
        ep = _endpoint()
        key_q = urllib.parse.quote(key)
        if ep:
            url = f"{ep.rstrip('/')}/{bucket}"
        else:                              # pragma: no cover - live AWS
            url = f"https://{bucket}.s3.{_region()}.amazonaws.com"
        if key:
            url += f"/{key_q}"
        if query:
            url += f"?{query}"
        return url

    def _request(self, method: str, url: str, data: bytes = b"",
                 headers: Optional[Dict[str, str]] = None) -> Tuple[bytes,
                                                                    dict]:
        payload_hash = hashlib.sha256(data).hexdigest()
        headers = _sign_v4(method, url, dict(headers or {}), payload_hash)
        req = urllib.request.Request(url, data=data if data else None,
                                     method=method, headers=headers)
        with urllib.request.urlopen(req) as resp:
            return resp.read(), dict(resp.headers)

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        bucket, _, key = path.partition("/")
        return bucket, key

    # ------------------------------------------------------------------ SPI
    def open_read(self, path: str) -> BinaryIO:
        bucket, key = self._split(path)
        body, _ = self._request("GET", self._url(bucket, key))
        return io.BytesIO(body)

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        if length <= 0:
            return b""
        bucket, key = self._split(path)
        body, _ = self._request(
            "GET", self._url(bucket, key),
            headers={"Range": f"bytes={offset}-{offset + length - 1}"})
        return body

    def size(self, path: str) -> int:
        bucket, key = self._split(path)
        _, headers = self._request("HEAD", self._url(bucket, key))
        return int(headers.get("Content-Length", 0))

    def open_write(self, path: str) -> BinaryIO:
        return _S3Writer(self, path)

    def list(self, pattern: str) -> List[str]:
        import fnmatch
        bucket, keypat = self._split(pattern)
        prefix = keypat.split("*", 1)[0].split("?", 1)[0]
        names: List[str] = []
        token = None
        while True:                      # ListObjectsV2 pages at 1000 keys
            q = "list-type=2&prefix=" + urllib.parse.quote(prefix, safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(
                    token, safe="")
            body, _ = self._request("GET", self._url(bucket, query=q))
            root = ElementTree.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag.split("}", 1)[0] + "}"
            names += [c.findtext(f"{ns}Key")
                      for c in root.iter(f"{ns}Contents")]
            if root.findtext(f"{ns}IsTruncated") != "true":
                break
            token = root.findtext(f"{ns}NextContinuationToken")
            if not token:
                break
        names = [n for n in names if n]
        if any(c in keypat for c in "*?["):
            names = [n for n in names if fnmatch.fnmatch(n, keypat)]
        elif keypat:
            names = [n for n in names
                     if n == keypat or n.startswith(keypat.rstrip("/") + "/")]
        return [f"s3://{bucket}/{n}" for n in sorted(names)]

    def exists(self, path: str) -> bool:
        bucket, key = self._split(path)
        try:
            self._request("HEAD", self._url(bucket, key))
            return True
        except urllib.error.HTTPError:
            return False
        except Exception:               # noqa: BLE001 — unreachable: absent
            return False

    def delete(self, path: str) -> None:
        bucket, key = self._split(path)
        try:
            self._request("DELETE", self._url(bucket, key))
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise


class _S3Writer(io.RawIOBase):
    """Streaming writer: single PUT for small objects, multipart beyond
    the 8 MB chunk threshold (PersistS3's multipart contract)."""

    def __init__(self, backend: S3Persist, path: str):
        super().__init__()
        self._be = backend
        self._bucket, self._key = backend._split(path)
        self._buf = bytearray()
        self._upload_id: Optional[str] = None
        self._etags: List[str] = []

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf.extend(b)
        try:
            while len(self._buf) >= _MULTIPART_CHUNK:
                self._flush_part(bytes(self._buf[:_MULTIPART_CHUNK]))
                del self._buf[:_MULTIPART_CHUNK]
        except BaseException:
            self._abort()
            raise
        return len(b)

    def _abort(self) -> None:
        """AbortMultipartUpload — never leave invisible billed parts."""
        if self._upload_id is None:
            return
        try:
            q = f"uploadId={urllib.parse.quote(self._upload_id)}"
            self._be._request(
                "DELETE", self._be._url(self._bucket, self._key, q))
        except Exception:               # noqa: BLE001 — abort best-effort
            pass
        self._upload_id = None

    def _flush_part(self, chunk: bytes) -> None:
        be = self._be
        if self._upload_id is None:
            body, _ = be._request(
                "POST", be._url(self._bucket, self._key, "uploads"))
            root = ElementTree.fromstring(body)
            ns = root.tag.split("}", 1)[0] + "}" if root.tag.startswith(
                "{") else ""
            self._upload_id = root.findtext(f"{ns}UploadId")
        n = len(self._etags) + 1
        q = f"partNumber={n}&uploadId={urllib.parse.quote(self._upload_id)}"
        _, headers = be._request(
            "PUT", be._url(self._bucket, self._key, q), data=chunk)
        self._etags.append(headers.get("ETag", f'"{n}"'))

    def close(self) -> None:
        if self.closed:
            return
        be = self._be
        try:
            if self._upload_id is None:
                be._request("PUT", be._url(self._bucket, self._key),
                            data=bytes(self._buf))
            else:
                if self._buf:
                    self._flush_part(bytes(self._buf))
                    self._buf.clear()
                parts = "".join(
                    f"<Part><PartNumber>{i + 1}</PartNumber>"
                    f"<ETag>{etag}</ETag></Part>"
                    for i, etag in enumerate(self._etags))
                xml = (f"<CompleteMultipartUpload>{parts}"
                       f"</CompleteMultipartUpload>").encode()
                q = f"uploadId={urllib.parse.quote(self._upload_id)}"
                be._request("POST", be._url(self._bucket, self._key, q),
                            data=xml)
        except BaseException:
            self._abort()
            raise
        super().close()
