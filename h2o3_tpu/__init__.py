"""h2o3_tpu — a TPU-native distributed ML platform with H2O-3's capabilities.

Brand-new design (not a port): frames are row-sharded ``jax.Array``s over a
device mesh, whole-dataset algorithms are jit-compiled SPMD programs with XLA
collectives in place of the reference's MRTask RPC tree, and tree histograms
target the MXU/VPU instead of CUDA ``gpu_hist``.  See SURVEY.md for the
reference analysis and the layer-by-layer mapping.

Module-level API mirrors the ``h2o`` Python package (h2o-py/h2o/h2o.py):
``init``, ``import_file``, ``upload_string``, ``get_frame``, ``remove`` …
"""

from .runtime.cluster import init, cluster, shutdown
from .runtime.scope import Scope
from .runtime import dkv
from . import persist
from . import explain
from .frame.frame import Frame
from .frame.vec import Vec
from .frame.parse import (import_file, parse_csv, parse_files,
                          parse_svmlight, parse_arff, export_file,
                          upload_string, from_pandas, H2OFrame)
from .frame.sql import import_sql_table, import_sql_select
from .frame.hive import import_hive_table, import_hive_metadata
from .frame.create import (create_frame, insert_missing_values, interaction,
                           tabulate, dct_transform)
from .datasets import load_dataset
from .export.mojo import import_mojo
from .ingest import StreamingFrame


def stream_file(path: str, destination_frame=None, **kw) -> StreamingFrame:
    """Start a streaming ingest of a local CSV/parquet file: rows land on
    a background thread while training consumes the watermark prefix.
    See docs/operations.md "Streaming ingest & warm-start"."""
    return StreamingFrame(path, destination_frame=destination_frame,
                          **kw).start()


def save_model(model, path: str) -> str:
    """h2o.save_model analog — any persist URI works."""
    return model.save(path)


def load_model(path: str):
    """h2o.load_model analog."""
    from .models.base import Model
    return Model.load(path)

__version__ = "0.1.0"


def get_frame(key: str) -> Frame:
    f = dkv.get(key)
    if f is None:
        raise KeyError(f"no frame under key {key!r}")
    return f


def get_model(key: str):
    m = dkv.get(key)
    if m is None:
        raise KeyError(f"no model under key {key!r}")
    return m


def ls():
    """List all DKV keys — analog of h2o.ls()."""
    return dkv.keys()


def remove(key: str) -> None:
    dkv.remove(key)


def remove_all() -> None:
    dkv.clear()


def cluster_status() -> dict:
    return cluster().describe()


_pre_quiet_level = None


def no_progress() -> None:
    """h2o.no_progress analog: quiet the package's INFO chatter (jobs
    record progress in the DKV rather than logging, so this raises the
    'h2o3_tpu' logger to WARNING — spill/extension notices included)."""
    global _pre_quiet_level
    import logging
    lg = logging.getLogger("h2o3_tpu")
    if _pre_quiet_level is None:
        _pre_quiet_level = lg.level
    lg.setLevel(logging.WARNING)


def show_progress() -> None:
    """h2o.show_progress analog: restore the level no_progress saved."""
    global _pre_quiet_level
    import logging
    if _pre_quiet_level is not None:
        logging.getLogger("h2o3_tpu").setLevel(_pre_quiet_level)
        _pre_quiet_level = None


def assign(frame: Frame, key: str) -> Frame:
    """h2o.assign analog: REBIND the frame to ``key`` — the old DKV
    binding is released, matching h2o-py's in-place id change."""
    old = frame.key
    frame.key = key
    dkv.put(key, frame)
    if old and old != key:
        dkv.remove(old)
    return frame


def deep_copy(frame: Frame, key: str) -> Frame:
    """h2o.deep_copy analog: an independently-bound copy.

    Device payloads are IMMUTABLE jax.Arrays, so they are shared —
    only fresh Vec wrappers (independent spill/rollup/LRU state) and
    copies of the mutable host-side object arrays are made; spilled
    columns stay spilled rather than being pulled back onto HBM.
    """
    import numpy as np
    from .frame.vec import Vec, T_STR, T_UUID
    vecs = []
    for v in frame.vecs:
        if v.type in (T_STR, T_UUID):
            vecs.append(Vec(None, v.type, v.nrows,
                            host_data=np.array(v.host_data, dtype=object)))
            continue
        nv = Vec(v._device, v.type, v.nrows, domain=v.domain,
                 host_data=None if v.host_data is None
                 else np.array(v.host_data),
                 time_base=v.time_base)
        if v._spill is not None:
            nv._spill = v._spill          # host copy shared: numpy is
            nv._device = None             # only rebound, never mutated
        vecs.append(nv)
    return Frame(frame.names, vecs, key=key)


def download_mojo(model, path: str, format: str = "portable") -> str:
    """h2o.download_mojo analog.  ``format="portable"`` writes this
    framework's standalone artifact (export/mojo.py); ``format="h2o"``
    writes the reference's own MOJO zip format (export/h2o_mojo_writer),
    scoreable by reference genmodel consumers."""
    if format == "h2o":
        from .export.h2o_mojo_writer import write_h2o_mojo
        return write_h2o_mojo(model, path)
    from .export.mojo import export_mojo
    return export_mojo(model, path)


def download_pojo(model, path: str, class_name=None) -> str:
    """h2o.download_pojo analog — dependency-free Java scoring source
    (export/pojo.py; TreeJCodeGen)."""
    from .export.pojo import export_pojo
    return export_pojo(model, path, class_name=class_name)
