"""Per-piece chip profiling harness — PROFILE.md's methodology as code.

Round-2 lessons, encoded so a chip session starts productive instead of
re-deriving them (PROFILE.md "measurement methodology"):
 - per-dispatch tunnel overhead is ~4 ms: every piece is timed as a
   ``lax.fori_loop`` of REPS dependent invocations inside ONE jit, then
   divided — the carry feeds back into an operand so XLA cannot CSE or
   reorder the calls;
 - ``block_until_ready`` does not synchronize over the tunnel: the sync
   point is a tiny real device->host fetch;
 - operand layouts: inputs are produced on device (iota/prng) so pallas
   custom-call layout constraints don't charge a relayout to the kernel.

Prints one JSON line per piece.  Shape mirrors bench.py's airlines-10M
workload; H2O3_PIECES_ROWS overrides for smoke runs.

Usage (chip): python bench_pieces.py
CPU smoke:    JAX_PLATFORMS=cpu H2O3_PIECES_ROWS=100000 python bench_pieces.py
"""

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("H2O3_PIECES_ROWS", 10_000_000))
REPS = int(os.environ.get("H2O3_PIECES_REPS", 20))
BIN_COUNTS = (21, 12, 7, 256, 256, 22, 256, 256)
F, NBINS = 8, 256
B = NBINS + 1


def main():
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax
    import jax.numpy as jnp

    import h2o3_tpu
    cl = h2o3_tpu.init()
    platform = jax.devices()[0].platform
    n = N_ROWS - (N_ROWS % (512 * cl.n_row_shards))

    from h2o3_tpu.models.tree.hist import (make_varbin_hist_fn,
                                           make_hist_fn, offset_codes,
                                           best_splits)

    def emit(piece, ms, **extra):
        print(json.dumps({"piece": piece, "ms": round(ms, 3),
                          "platform": platform, "rows": n, **extra}),
              flush=True)

    def sync(x):
        np.asarray(jax.device_get(jnp.ravel(x)[:1]))

    def timed(fn_build, *args):
        """fn_build(acc, *args) -> new scalar acc; time REPS dependent
        iterations inside one jit."""

        @jax.jit
        def reps(*a):
            def body(i, acc):
                return fn_build(acc, *a)
            return jax.lax.fori_loop(0, REPS, body, jnp.float32(0.0))

        out = reps(*args)          # compile + warmup
        sync(out)
        out = reps(*args)          # absorb first-exec anomaly
        sync(out)
        t0 = time.perf_counter()
        out = reps(*args)
        sync(out)
        return (time.perf_counter() - t0) / REPS * 1e3

    # device-generated inputs (no host transfer, producer-fused layouts)
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    codes = jnp.stack([
        jax.random.randint(ks[f], (n,), 0, min(bc, NBINS), dtype=jnp.int32)
        for f, bc in enumerate(BIN_COUNTS)], axis=0)
    gcodes = offset_codes(codes, BIN_COUNTS, NBINS)
    g = jax.random.normal(ks[0], (n,), jnp.float32)
    h = jnp.abs(jax.random.normal(ks[1], (n,), jnp.float32)) + 0.1
    w = jnp.ones((n,), jnp.float32)

    # --- histogram levels: varbin (bench path) vs uniform
    # off-TPU smoke: interpret-mode pallas (slow but same code path)
    force = "" if platform == "tpu" else "pallas_interpret"
    for L in (1, 2, 4, 8, 16, 32):
        leaf = jax.random.randint(ks[2], (n,), 0, L, dtype=jnp.int32)
        fn = make_varbin_hist_fn(L, F, BIN_COUNTS, B, n, force_impl=force)

        def run_vb(acc, gc, lf, gg, hh, ww, _fn=fn):
            H = _fn(gc, lf, gg + acc * 0.0, hh, ww)
            return H[0, 0, 0, 0] * 1e-30

        emit(f"varbin_hist_L{L}", timed(run_vb, gcodes, leaf, g, h, w),
             kernel="varbin+int16+bf16")
    for L in (1, 32):
        leaf = jax.random.randint(ks[3], (n,), 0, L, dtype=jnp.int32)
        fn = make_hist_fn(L, F, B, n)

        def run_u(acc, cc, lf, gg, hh, ww, _fn=fn):
            H = _fn(cc, lf, gg + acc * 0.0, hh, ww)
            return H[0, 0, 0, 0] * 1e-30

        emit(f"uniform_hist_L{L}", timed(run_u, codes, leaf, g, h, w))

    # --- split search on a realistic histogram
    leaf32 = jax.random.randint(ks[4], (n,), 0, 32, dtype=jnp.int32)
    H = make_varbin_hist_fn(32, F, BIN_COUNTS, B, n, force_impl=force)(
        gcodes, leaf32, g, h, w)

    def run_split(acc, Hh):
        out = best_splits(Hh + acc * 0.0, NBINS, 1.0, 1.0, 0.0)
        return out[3].reshape(-1)[0].astype(jnp.float32) * 1e-30

    emit("best_splits_L32", timed(run_split, H))

    # --- whole-ensemble scoring (50 trees, depth 6)
    from h2o3_tpu.models.tree.shared import StackedTrees, traverse
    T, depth = 50, 6
    rng = np.random.default_rng(0)
    levels = []
    for d in range(depth):
        width = 2 ** d
        levels.append((
            jnp.asarray(rng.integers(0, F, (T, width)), jnp.int32),
            jnp.asarray(rng.normal(size=(T, width)), jnp.float32),
            jnp.asarray(rng.random((T, width)) < 0.5),
            jnp.ones((T, width), bool)))
    values = jnp.asarray(rng.normal(size=(T, 2 ** depth)) * 0.1,
                         jnp.float32)
    X = jax.random.normal(ks[5], (n, F), jnp.float32)

    def run_traverse(acc, Xx):
        s = traverse(levels, values, Xx + acc * 0.0)
        return s[0] * 1e-30

    t_ms = timed(run_traverse, X)
    emit("traverse_50trees_d6", t_ms,
         trees_per_sec_scoring=round(T / (t_ms / 1e3), 1))

    # --- rapids sort / merge (device)
    from h2o3_tpu.rapids import sort as _sort  # noqa: F401 — warm import
    keys_col = jax.random.randint(ks[6], (n,), 0, n, dtype=jnp.int32)

    def run_sort(acc, kk):
        out = jnp.sort(kk + acc.astype(jnp.int32) * 0)
        return out[0].astype(jnp.float32) * 1e-30

    emit("device_sort", timed(run_sort, keys_col))

    # --- projected end-to-end: one tree = 6 varbin levels + partition
    print(json.dumps({"piece": "NOTE",
                      "note": "tree total ~= sum(varbin_hist_L{1..32}) "
                              "+ 6x partition (~1.6ms) + split search; "
                              "see PROFILE.md round-2 table"}), flush=True)


def parse_piece():
    """Standalone ingest bench: bench.py's 568 MB parse line (same file,
    same warmup methodology) without the ~1091 s full suite.

    Usage:      python bench_pieces.py parse
    CPU smoke:  JAX_PLATFORMS=cpu H2O3_BENCH_ROWS=100000 \\
                python bench_pieces.py parse

    Prints one JSON line with MB/s, vs_baseline (reference: 580 MB in
    4.9 s on 5 nodes), and the pipeline's per-stage wall times
    (mmap / scan / tokenize / device / decode / vec).
    """
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import tempfile

    import h2o3_tpu
    import bench
    from h2o3_tpu.frame.parse import parse_csv, last_parse_stats
    h2o3_tpu.init()
    dt, mb = bench.bench_parse(parse_csv, tempfile.gettempdir())
    print(json.dumps({
        "piece": "parse", "sec": round(dt, 3), "mb": round(mb, 1),
        "mb_per_sec": round(mb / dt, 1),
        "vs_baseline": round(
            (bench.REFERENCE_PARSE_S * mb / bench.REFERENCE_PARSE_MB) / dt,
            2),
        "stages": dict(last_parse_stats)}), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "parse":
        parse_piece()
    else:
        main()
